"""L0 configuration layer: YAML -> flat ``Arguments``.

Parity with the reference's ``python/fedml/arguments.py``:

- ``add_args()`` exposes exactly the reference's CLI surface: ``--cf`` for
  the YAML path and ``--rank`` (arguments.py:32-49).
- ``Arguments`` flattens the sectioned YAML (``common_args`` /
  ``data_args`` / ``model_args`` / ``train_args`` / ``validation_args`` /
  ``device_args`` / ``comm_args`` / ``tracking_args``) into flat attributes
  (arguments.py:138-141).
- When no config is given, a shipped default config is used
  (arguments.py:56-104 behavior), see ``fedml_tpu/config/``.

Improvements over the reference (which has "no typed schema, no
validation", SURVEY.md §5): defaults are declared in one table, values are
type-coerced, and unknown training/backend combinations fail fast.
"""

from __future__ import annotations

import argparse
import os
from pathlib import Path
from typing import Any, Dict, Optional

import yaml

from . import constants

# Defaults applied when neither the YAML nor the caller provides a value.
# This doubles as the (otherwise implicit) schema of well-known knobs.
_DEFAULTS: Dict[str, Any] = {
    "training_type": constants.FEDML_TRAINING_PLATFORM_SIMULATION,
    "backend": constants.FEDML_SIMULATION_TYPE_SP,
    "scenario": constants.FEDML_CROSS_SILO_SCENARIO_HORIZONTAL,
    "random_seed": 0,
    # data
    "dataset": "synthetic",
    "data_cache_dir": "./data_cache",
    "partition_method": constants.PARTITION_HETERO,
    "partition_alpha": 0.5,
    # padded-packing long-tail policy: shared num_batches is clamped to
    # waste_cap x median client size; samples beyond it are truncated
    # (pack_clients logs what was dropped). float("inf") disables.
    "packing_waste_cap": 4.0,
    # resized-image ingestion (imagenet / gld* folders and CSVs): H=W
    # decode size; the synthetic stand-ins follow the same knob
    "image_size": 64,
    # model
    "model": "lr",
    # training
    "federated_optimizer": constants.FED_OPTIMIZER_FEDAVG,
    "client_id_list": None,
    "client_num_in_total": 10,
    "client_num_per_round": 10,
    "comm_round": 10,
    "epochs": 1,
    "batch_size": 32,
    "client_optimizer": "sgd",
    "learning_rate": 0.03,
    "momentum": 0.0,
    "weight_decay": 0.0,
    "server_optimizer": "sgd",
    "server_lr": 1.0,
    "server_momentum": 0.0,
    # fedprox / fednova
    "fedprox_mu": 0.0,
    # simulation engine mode: "vectorized" (vmap the cohort — the TPU
    # path, driven by the async round pipeline) or "sequential"
    # (python loop per client — the reference's shape, debug/parity)
    "sim_mode": "vectorized",
    # server aggregation mode (core/aggregation.py StreamingAccumulator
    # + cross_silo managers): "stream" folds each upload into O(model)
    # running accumulators the moment it lands (bit-identical results
    # to "buffered"; falls back to the buffered path LOUDLY when the
    # aggregation needs the full cohort at once, e.g. defense_type or a
    # custom ServerAggregator); "buffered" keeps the reference's
    # buffer-then-aggregate shape; "async" is the FedBuff-style mode:
    # no round barrier, staleness-weighted folds, a publish every
    # async_publish_every folds
    "agg_mode": "stream",
    # quorum round close (streaming modes): once this fraction of the
    # round's live cohort has folded, arm a round_grace_s timer; when
    # it fires the round closes over the partial cohort (weights
    # renormalize) and late uploads are discarded by round tag. Ranks
    # the failure detector declares dead leave the quorum denominator.
    # 0 disables (wait for everyone, the reference shape)
    "round_quorum_frac": 0.0,
    # how long past quorum the server keeps waiting for stragglers
    "round_grace_s": 0.0,
    # async staleness weighting: an upload trained against a model s
    # publishes old folds with weight sample_num * staleness_decay^s
    "staleness_decay": 0.5,
    # async hard staleness cap: updates staler than this are discarded
    # (counted agg_stale_discarded_total), never folded
    "staleness_max": 10,
    # async publish cadence: finalize + publish the global model (and
    # checkpoint it when checkpoint_dir is set, feeding the serving
    # plane's hot-swap watcher) every K folds
    "async_publish_every": 4,
    # straggler handling (cross-silo; beyond the reference): aggregate
    # whoever reported within this many seconds of the round broadcast,
    # reweighted over the subset. 0 = wait for everyone (reference).
    "aggregation_deadline_s": 0.0,
    # on a deadline with ZERO uploads the server rebroadcasts the round
    # (the downlink may have been lost) at most this many times, then
    # shuts the federation down instead of extending forever
    "aggregation_deadline_max_extensions": 3,
    # uplink compression (cross-silo; beyond the reference): clients
    # ship encoded update deltas instead of full fp32 params.
    # "none" | "int8" (4x, lossless-ish) | "topk" (ratio-controlled
    # sparsification with error feedback, core/compression.py)
    "compression": "none",
    "compression_topk_ratio": 0.01,
    # elastic membership (cross-silo; beyond the reference): start once
    # client_num_per_round clients are online, accept mid-run joins,
    # survive OFFLINE leaves. False = fixed membership (reference).
    "elastic_membership": False,
    # validation
    "frequency_of_the_test": 5,
    # device
    "using_gpu": True,
    "device_type": "tpu",
    "gpu_mapping_file": None,
    # comm
    "grpc_ipconfig_path": None,
    "grpc_port_base": 8890,
    # tracking
    "enable_tracking": False,
    "run_id": "0",
    # fault injection (core/comm/faults.py — beyond the reference):
    # mapping of {drop_prob, duplicate_prob, delay_s, delay_prob, seed,
    # msg_types, max_faults}; None disables
    "fault_injection": None,
    # deterministic chaos plane (core/chaos.py): an ordered list of
    # one-shot fault steps {at: {event, occurrence, round?, rank?,
    # msg_type?, name?}, fault: kind-or-mapping} driving exact-message
    # comm faults, WAL/checkpoint IO faults (torn write, failed fsync,
    # ENOSPC, latency, torn publish), process kills at named barriers
    # and clock skew. None disables
    "chaos_schedule": None,
    # seed for any randomness a schedule step asks for (latency
    # jitter); an identical (chaos_schedule, chaos_seed) pair
    # reproduces the identical fault trace
    "chaos_seed": 0,
    # IO-only fault steps (same step shape, events wal_create /
    # wal_append / ckpt_publish only) — convenience for faulting the
    # durable-write seam without a full schedule. None disables
    "io_faults": None,
    # reliable delivery (core/comm/reliable.py): wrap every comm
    # endpoint in an ack/retransmit channel with receive-side dedup —
    # effectively exactly-once delivery over a lossy network. Enable on
    # ALL processes of a world together.
    "reliable_comm": False,
    # reliable channel: how many retransmits before a send is given up
    # (the product of the backoff series is the channel's send timeout)
    "comm_retry_max": 5,
    # first-retry backoff; doubles per attempt with up to +50% jitter
    "comm_retry_base_s": 0.2,
    # per-attempt deadline of one gRPC unary send (the seed's fixed
    # timeout=300); the transport retries transient RPC errors a small
    # fixed number of times (deliberately NOT comm_retry_max — the
    # reliable channel's retransmits call back into this send, and
    # sharing the knob would multiply the budgets), then raises a typed
    # CommSendError instead of whatever grpc surfaces
    "grpc_send_timeout_s": 300.0,
    # client liveness beats (core/comm/heartbeat.py): emit
    # MSG_TYPE_C2S_HEARTBEAT this often; the beats double as the
    # reconnect probe after a server restart. 0 disables
    "heartbeat_interval_s": 0.0,
    # server failure detector: declare a client dead after this long
    # with NO traffic (beats, uploads, status) and fold it into the
    # OFFLINE/deadline-cohort paths so a kill -9'd client can never
    # stall a round. Use 3-5x heartbeat_interval_s. 0 disables
    "heartbeat_timeout_s": 0.0,
    # robustness (reference: fedavg_robust example config). defense_type:
    # "norm_diff_clipping" | "weak_dp" | "median" | None. Clipping and
    # weak_dp are per-upload and ride the streaming/async fold
    # (core/aggregation.py clipped term executables; weak-DP noise
    # drawn at finalize from a run-seed+round key); median needs the
    # full cohort and keeps the buffered path. Unknown strings are
    # rejected loudly — never silently aggregated undefended.
    "defense_type": None,
    # norm-diff clip radius: each upload's delta against the broadcast
    # global is scaled to at most this L2 norm
    "norm_bound": 5.0,
    # weak-DP Gaussian noise stddev added to the finalized aggregate
    "stddev": 0.158,
    # on-arrival anomaly screen (core/defense.py AnomalyScreen): uploads
    # are scored (norm excess + cosine to the running aggregate) into a
    # per-rank reputation EWMA; a rank whose reputation crosses this
    # threshold is QUARANTINED — uploads rejected before folding, rank
    # excluded from cohorts until probation expires. 0 disables. Note
    # screening decisions are arrival-order dependent, so the
    # stream==buffered bit-identity guarantee applies with 0 only
    "defense_anomaly_threshold": 0.0,
    # quarantine probation length, in round closes (sync) or publishes
    # (async); release restores a fresh reputation
    "defense_quarantine_rounds": 3,
    # poisoned-world synthesis (data/poison.py, loader wiring): attack
    # type for the attacker clients — "label_flip" | "targeted_flip" |
    # "backdoor_pattern" | "edge_case", or a list paired 1:1 with
    # poisoned_client_idxs for mixed-attack worlds. None disables
    "poison_type": None,
    # explicit attacker client indexes (wins over the fraction)
    "poisoned_client_idxs": None,
    # else: this fraction of clients is drawn as attackers (seeded)
    "poisoned_client_fraction": 0.0,
    # label the attacks steer toward (backdoor/edge_case/targeted_flip)
    "target_label": 0,
    # fraction of each attacker's samples that are poisoned
    "poison_sample_fraction": 1.0,
    # planet-scale population plane (fedml_tpu/scale/): register this
    # many clients as columnar state (~17 bytes each) and draw cohorts
    # from the registry with O(cohort) memory per round, datasets
    # materialized on demand. 0 = off (eager federation, the default).
    # Simulation-only; requires a classification task and the stock
    # FedAvg/FedProx server step
    "client_registry_size": 0,
    # registry-mode cohort drawn per round (0 = client_num_per_round)
    "cohort_size": 0,
    # two-tier aggregation tree (fedml_tpu/scale/tree.py): this many
    # edge aggregators each fold their subtree through the streaming
    # accumulator and the root folds the edge partials — bit-identical
    # to flat aggregation. Applies to the registry-backed simulator AND
    # the cross-silo streaming server (agg_mode=stream). 0/1 = flat
    "edge_num": 0,
    # hierarchical server plane (cross_silo/hierarchical — docs/
    # hierarchical.md): "inproc" keeps the edge tier inside the server
    # process (the PR 9 tree); "ranks" promotes the edge_num edges to
    # REAL ranks over the comm seam — clients upload to their assigned
    # edge, each edge streams-folds + screens locally and ships one
    # merged limb-set per round close, the root merges bit-identically
    # to flat. Requires training_type=cross_silo + agg_mode=stream
    "edge_plane": "inproc",
    # gRPC port stride between per-edge client fabrics (each fabric
    # binds grpc_port_base + edge_rank * stride + rank); must exceed
    # the client count. LOCAL fabrics are name-strided and ignore it
    "hier_port_stride": 64,
    # back the registry columns with .npy memmaps under this directory
    # instead of host RAM (None = in-RAM numpy)
    "registry_dir": None,
    # A/B bit-identity harness (detail.planet bench): partition terms
    # per edge exactly as the tree would, but fold them into ONE flat
    # accumulator — the baseline the tree identity is asserted against
    "edge_flat_fold": False,
    # precision: the 3-decimal equivalence oracles need f32 matmuls
    "matmul_precision": "highest",
    # mixed precision (core/local_trainer.py): "bfloat16" runs the
    # forward/backward matmuls in the MXU's native format with f32
    # master weights, optimizer state, and loss reductions
    "dtype": "float32",
    # async round pipeline (core/round_pipeline.py): how many federation
    # rounds may be in flight at once. 1 = synchronous (identical
    # metrics, flushed every eval round); K>1 defers metric fetches so
    # the hot loop has zero host syncs between flushes
    "pipeline_depth": 1,
    # compile-cache bucket policy for cohort sizes: "pow2" pads the
    # sampled cohort up to the next power of two (zero-weight,
    # fully-masked padding) so cohort-size changes hit the jit cache;
    # "exact" disables padding (auto-selected for weight-unaware
    # aggregation, e.g. defense_type=median or a custom
    # server_aggregator)
    "pipeline_bucket": "pow2",
    # mesh axes -> sizes. Scenario-specific vocabulary: the distributed
    # platform (distributed.py) takes {dp/tp/ep} | {sp} | {pp}; the
    # MESH simulation backend (simulation/simulator.py) takes the fed
    # production vocabulary {data, fsdp} (cohort over data, params
    # sharded at rest over fsdp — docs/multichip.md) or the legacy
    # {clients, data}. None = scenario default (all devices, one axis)
    "mesh_shape": None,
    # capture an XLA device trace (tensorboard/perfetto) for the run
    "profile_dir": None,
    # flight-recorder telemetry (core/telemetry.py): process-wide
    # counters/gauges/histograms + Chrome-trace event ring. False
    # disables every instrument (comm counting, pipeline events,
    # watchdog); the hot loop is host-side either way
    "telemetry": True,
    # write run artifacts here: trace.json (perfetto-loadable merged
    # timeline), metrics.prom (Prometheus text exposition),
    # telemetry.jsonl (registry snapshots) and stall debug bundles.
    # None = keep everything in-process only
    "telemetry_dir": None,
    # stall watchdog: if NO progress heartbeat (pipeline round, comm
    # send/receive, cross-silo round) advances for this many seconds,
    # dump a debug bundle (open spans, pending deferred metrics, last-N
    # trace events, host+device sys_stats) to telemetry_dir. 0 disables
    "stall_timeout_s": 0.0,
    # flight-recorder ring capacity (events). Overflow evicts oldest,
    # counted in telemetry_trace_dropped_total and the exported trace's
    # meta — a run that outgrows the ring is visible, not silent
    "trace_ring_size": 65536,
    # devtime wall-clock ring capacity (core/devtime.py): per-dispatch
    # {executable, bucket, seconds} entries kept for the perf plane's
    # fallback join when histogram snapshots are unavailable
    "devtime_ring_size": 4096,
    # on-demand device profiling (core/tracing.py RoundProfiler): round
    # indices (list or "1,5,9" string) to capture a programmatic
    # jax.profiler trace for, into telemetry_dir/profile/round_NNNN.
    # No-op with one logged warning on backends without capture support
    "profile_rounds": None,
    # pull-based exposition: serve Telemetry.prometheus_text() at
    # http://<metrics_host>:<port>/metrics for the run's lifetime.
    # 0 (default) = off
    "metrics_port": 0,
    # bind address for the /metrics server. Loopback by default: the
    # endpoint is unauthenticated, so exposing it on the network is an
    # explicit choice ("0.0.0.0"), never the default
    "metrics_host": "127.0.0.1",
    # per-round latency SLO (cross-silo server): a round whose wall
    # time (broadcast -> aggregate done) exceeds this many seconds
    # counts into slo_violations_total. 0 disables
    "round_deadline_s": 0.0,
    # serving plane (fedml_tpu/serving — `fedml_tpu.cli serve`):
    # bounded request queue; a full queue sheds new requests
    # (serving_shed_total{reason=queue_full}) instead of growing
    "serve_queue_size": 256,
    # micro-batch cap: the batcher drains up to this many queued
    # requests into one forward pass (pow2-bucketed below the cap)
    "serve_max_batch": 64,
    # linger time while assembling a micro-batch once the first
    # request is in hand — the latency/occupancy tradeoff knob
    "serve_batch_wait_ms": 2.0,
    # default per-request deadline; requests still queued past it are
    # shed (serving_shed_total{reason=deadline}). 0 disables
    "serve_deadline_ms": 100.0,
    # serving batch-shape bucket policy: "pow2" (compile once per
    # bucket, the training cohort cache's rule) or "exact"
    "serve_bucket": "pow2",
    # checkpoint publish/watch poll interval for weight hot-swaps
    "serve_watch_interval_s": 1.0,
    # serving fleet: number of endpoints behind the fleet frontend
    # (1 = the classic single-endpoint plane, no fleet layer)
    "serve_fleet_size": 1,
    # serve on a named (data, fsdp) mesh: {"data": D, "fsdp": F} makes
    # every endpoint a MeshModelEndpoint (params at their at-rest
    # SpecLayout shardings, batches sharded along data). None = serve
    # single-device
    "serve_mesh": None,
    # fleet routing policy: "least_loaded" (argmin queue depth per
    # request) or "static" (the boustrophedon deal cycled —
    # core/scheduler.assign_by_load)
    "serve_route_policy": "least_loaded",
    # fleet SLO shed signal: when the p99 of serving_request_latency_s
    # exceeds this, new requests shed at the fleet door
    # (serving_fleet_shed_total{reason=slo}). 0 disables
    "serve_route_slo_ms": 0.0,
    # on an immediately-shed submission (queue full / stopped engine)
    # retry this many more candidates before giving up
    "serve_route_failover": 1,
    # sequence-parallel strategy: "ring" or "ulysses"
    "sp_strategy": "ring",
    # ring attention: chunk each hop's K/V shard so the per-chip score
    # panel is [Tq, sp_ring_block] instead of [Tq, T/sp] — the memory
    # knob for very long resident shards (0 = whole shard per hop)
    "sp_ring_block": 0,
    # rematerialize transformer blocks (jax.checkpoint): trade FLOPs
    # for HBM — recompute block activations in the backward pass
    "remat": False,
    "pp_microbatches": 0,  # 0 = auto (2 x pipeline stages)
    # weight of the Switch MoE load-balancing aux loss in the
    # distributed trainer's objective (0 disables)
    "moe_aux_weight": 0.01,
    # gradient accumulation in the distributed trainer: chunk each
    # batch into N grad passes before one update (HBM lever); exact
    # (count-weighted) vs the unchunked masked-mean gradient
    "grad_accum_steps": 1,
    # learning-rate schedule (core/optimizers.py): "constant" or
    # "cosine". Two index bases, exactly one may be set with cosine:
    # lr_total_steps (optimizer steps — the distributed trainer) or
    # lr_total_rounds (federation rounds — FL scenarios, where the
    # client optimizer re-inits per round and the natural semantics is
    # decay across rounds)
    "lr_schedule": "constant",
    "lr_total_steps": 0,
    "warmup_steps": 0,
    "lr_total_rounds": 0,
    "warmup_rounds": 0,
    # auto-fetch supported dataset archives into data_cache_dir when no
    # local copy exists (reference data/MNIST/data_loader.py:17-29
    # behavior; off by default so offline runs never stall on egress)
    "download": False,
    # persistent XLA compilation cache (core/compile_cache.py): root
    # the content-addressed jit cache here so a warm re-launch (10k
    # cohort world, mesh sweep, serving restart) skips every compile
    # whose (HLO, flags, platform) key it has seen — hits/misses are
    # counted in compile_cache_hits_total/_misses_total. One directory
    # per process (process-global jax.config). None disables
    "compile_cache_dir": None,
    # crash recovery / serving feed (core/checkpoint.py): directory for
    # orbax round checkpoints + the round WAL. None disables both —
    # a crashed server then restarts the federation from round 0
    "checkpoint_dir": None,
    # save a checkpoint every N completed rounds. None keeps each
    # scenario's historical cadence (simulation: every 10 rounds;
    # cross-silo/distributed: every round; async ALWAYS checkpoints
    # every publish regardless — see fedml_server_manager)
    "checkpoint_freq": None,
    # elastic membership: highest client rank an unknown ONLINE may
    # register as — one misconfigured hello must not bloat the server
    # with ghost ranks
    "max_clients": 4096,
    # elastic preemption signal (parallel/elastic.py): None/"none"
    # disables; "round:K" fires a scripted maintenance drill at round
    # K; "file:PATH" fires when PATH exists (external supervisor);
    # "metadata" polls the GCE metadata maintenance-event endpoint
    # (real TPU VMs); "chaos" rides a scheduled preempt/device.loss
    # fault on the elastic.check event. Requires checkpoint_dir: a
    # notice with nowhere durable to land is a config error, not a
    # runtime surprise
    "preempt_signal": None,
    # elastic resume floor: refuse to resume on fewer surviving
    # devices than this (below it the operator wants a page, not a
    # crawl) — enforced by parallel/elastic.surviving_mesh
    "elastic_min_devices": 1,
    # ---- scenario / model-geometry knobs (schema burn-down) ---------
    # Every knob below was read via getattr(...) with an inline
    # fallback but had no schema entry (the lint suite's registry
    # rule); defaults here MATCH those read-site fallbacks exactly, so
    # unset configs behave identically. seq_len and the real-data
    # subsample sizes keep dynamic per-site fallbacks and stay
    # baselined.

    "shuffle": True,  # reshuffle each client's examples every local epoch
    "output_dim": 10,  # class/label count for the synthetic-style loaders
    "synthetic_feature_dim": 2000,  # synthetic-fedprox feature width
    "synthetic_sigma": 1.0,  # synthetic feature noise scale
    "synthetic_alpha": 1.0,  # fedprox-synthetic u_k spread
    "synthetic_beta": 1.0,  # fedprox-synthetic v_k spread
    "vocab_size": 0,  # LM vocabulary (0 = the model family's default)
    "num_layers": 2,  # transformer depth
    "num_heads": 4,  # attention heads
    "embed_dim": 128,  # transformer model width
    "max_len": 512,  # positional-embedding capacity
    "hidden_dim": 64,  # MLP hidden width
    "attention_impl": "full",  # "full" | "segsum" (seg_width panels)
    "seg_width": 32,  # segsum attention panel width
    "moe_every": 2,  # every Nth transformer block is a Switch MoE layer
    "num_experts": 8,  # Switch MoE expert count
    "capacity_factor": 1.25,  # MoE per-expert token capacity slack
    "nas_width": 16,  # FedNAS stem channels
    "nas_cells": 2,  # FedNAS cells per client model
    "nas_steps": 2,  # FedNAS nodes per cell
    "arch_learning_rate": 0.0003,  # FedNAS architecture-weight LR
    "gan_latent_dim": 64,  # FedGAN generator latent size
    "gan_lr_g": 0.0002,  # FedGAN generator LR
    "gan_lr_d": 0.0002,  # FedGAN discriminator LR
    "splitnn_stages": (1, 1, 1),  # SplitNN (client, server, head) depths
    "vfl_parties": 2,  # vertical-FL feature-holding parties
    "vfl_rep_dim": 32,  # vertical-FL per-party representation width
    "gkt_server_stages": (2, 2, 2),  # FedGKT server tower depths
    "gkt_alpha": 1.0,  # FedGKT distillation loss weight
    "gkt_temperature": 3.0,  # FedGKT softmax temperature
    "gkt_server_epochs": 1,  # FedGKT server epochs per round
    "group_num": 2,  # hierarchical-FL group count
    "group_method": "random",  # hierarchical-FL grouping rule
    "group_comm_round": 1,  # hierarchical-FL intra-group rounds
    "topology_neighbor_num": 2,  # decentralized ring/random neighbors
    "topology_beta": 0.0,  # PushSum topology asymmetry
    "ta_groups": 4,  # TurboAggregate circular groups
    "ta_quant_scale": 65536.0,  # TurboAggregate additive-share scale
    "sfedavg_alpha": 0.5,  # S-FedAvg reputation weight (goodness)
    "sfedavg_beta": 0.5,  # S-FedAvg reputation weight (history)
    "sampling_filter": "exp",  # S-FedAvg score->probability filter
    "score_method": "acc",  # S-FedAvg client scoring signal
    "sv_tol": 0.005,  # Shapley truncation tolerance
    # Shapley permutation cap; None = auto (client_num_per_round ** 2,
    # the reference's cohort**2 distance-sample cap)
    "sv_max_perms": None,
    "valid_batches": 4,  # validation batches for defense scoring
    "hs_L": 0.0,  # HS-FedAvg FFT band (0 = derive from the input)
    "hs_momentum": 0.1,  # HS-FedAvg spectral-mask momentum
    "server_beta1": 0.9,  # FedOpt adam/yogi first-moment decay
    "server_beta2": 0.999,  # FedOpt adam/yogi second-moment decay
    "broker_host": "127.0.0.1",  # MQTT broker bind address
    "broker_port": 0,  # MQTT broker port (0 = per-run local broker)
    "trpc_ipconfig_path": None,  # TRPC fabric rank->ip CSV
    "trpc_port_base": None,  # TRPC first port (rank k = base+k)
    "payload_store_dir": None,  # spill oversized comm payloads here
    "log_metrics": True,  # mirror server metrics into the run log
    "metrics_jsonl_path": None,  # also append metrics as JSONL here
    # cross-device control plane (cross_device/server.py)
    "cross_device_backend": constants.COMM_BACKEND_MQTT,
    # cross-device Beehive check-in plane (cross_device/gateway.py)
    "crossdevice_cohort": 0,  # devices sampled per round (0 = client_num_per_round)
    "crossdevice_fold_target_frac": 0.6,  # fold-count fraction that closes a round
    "crossdevice_report_window_s": 30.0,  # report window after the check-in phase
    "crossdevice_secure_agg": True,  # pairwise-mask uploads (cancel in the fold)
    "crossdevice_quant_scale": 65536.0,  # field quantization scale for deltas
    "crossdevice_mask_threshold": 2,  # Shamir threshold for dropout recovery
    "crossdevice_duty_hours": 14,  # diurnal on-window length per device
    "crossdevice_verify_pubkey": True,  # check revealed secrets against pubkeys
    "silo_backend": "LOCAL",  # hierarchical cross-silo in-silo fabric
    "silo_grpc_port_base": 9890,  # in-silo gRPC first port
    "silo_grpc_ipconfig_path": None,  # in-silo rank->ip CSV
    "silo_device_count": 0,  # devices per silo (0 = all local devices)
}

_SECTIONS = (
    "common_args",
    "data_args",
    "model_args",
    "train_args",
    "validation_args",
    "device_args",
    "comm_args",
    "tracking_args",
    "defense_args",
    "attack_args",
)


class Arguments:
    """Flat attribute bag over a sectioned YAML config.

    Reference parity: ``Arguments`` at ``python/fedml/arguments.py:52-141``
    — ``load_yaml_config`` then ``set_attr_from_config`` flattening every
    section's keys onto ``self``.
    """

    def __init__(
        self,
        cmd_args: Optional[argparse.Namespace] = None,
        training_type: Optional[str] = None,
        comm_backend: Optional[str] = None,
    ) -> None:
        self._raw: Dict[str, Any] = {}
        if cmd_args is not None:
            for k, v in vars(cmd_args).items():
                setattr(self, k, v)
        config_path = getattr(self, "yaml_config_file", None) or None
        if config_path:
            self.load_yaml_config(config_path)
        for key, val in _DEFAULTS.items():
            if not hasattr(self, key):
                setattr(self, key, val)
        if training_type is not None:
            self.training_type = training_type
        if comm_backend is not None:
            self.backend = comm_backend
        self._validate()

    # -- YAML ----------------------------------------------------------
    def load_yaml_config(self, path: str) -> None:
        with open(path, "r") as f:
            cfg = yaml.safe_load(f) or {}
        self._raw = cfg
        self.set_attr_from_config(cfg)

    def set_attr_from_config(self, configuration: Dict[str, Any]) -> None:
        """Flatten sections (arguments.py:138-141)."""
        for section, content in configuration.items():
            if isinstance(content, dict) and (
                section in _SECTIONS or section.endswith("_args")
            ):
                for key, val in content.items():
                    setattr(self, key, val)
            else:
                setattr(self, section, content)

    # -- validation ----------------------------------------------------
    def _validate(self) -> None:
        t = self.training_type
        valid = {
            constants.FEDML_TRAINING_PLATFORM_SIMULATION,
            constants.FEDML_TRAINING_PLATFORM_CROSS_SILO,
            constants.FEDML_TRAINING_PLATFORM_CROSS_DEVICE,
            constants.FEDML_TRAINING_PLATFORM_DISTRIBUTED,
        }
        if t not in valid:
            raise ValueError(f"unknown training_type {t!r}; expected one of {sorted(valid)}")
        from .core.local_trainer import compute_dtype_from_args

        compute_dtype_from_args(self)  # single choke point; raises on bad dtype
        if self.client_num_per_round > self.client_num_in_total:
            self.client_num_per_round = self.client_num_in_total
        if (
            t == constants.FEDML_TRAINING_PLATFORM_CROSS_SILO
            and self.backend
            in (constants.COMM_BACKEND_SP, constants.FEDML_SIMULATION_TYPE_SP)
        ):
            # the simulation default backend makes no sense cross-silo;
            # LOCAL runs single-host worlds, GRPC is the networked path
            self.backend = constants.COMM_BACKEND_LOCAL
        for int_key in (
            "client_num_in_total",
            "client_num_per_round",
            "comm_round",
            "epochs",
            "batch_size",
            "random_seed",
            "pipeline_depth",
            "serve_queue_size",
            "serve_max_batch",
            "serve_fleet_size",
            "serve_route_failover",
            "comm_retry_max",
        ):
            setattr(self, int_key, int(getattr(self, int_key)))
        if getattr(self, "pipeline_depth", 1) < 1:
            raise ValueError(
                f"pipeline_depth={self.pipeline_depth}: must be >= 1 "
                "(1 = synchronous round loop)"
            )
        if getattr(self, "pipeline_bucket", "pow2") not in ("pow2", "exact"):
            raise ValueError(
                f"pipeline_bucket {self.pipeline_bucket!r}: pick 'pow2' or 'exact'"
            )
        if getattr(self, "sim_mode", "vectorized") not in (
            "vectorized", "sequential",
        ):
            raise ValueError(
                f"sim_mode {self.sim_mode!r}: pick 'vectorized' or 'sequential'"
            )
        for float_key in (
            "learning_rate",
            "server_lr",
            "partition_alpha",
            "fedprox_mu",
            "compression_topk_ratio",
            "stall_timeout_s",
            "serve_batch_wait_ms",
            "serve_deadline_ms",
            "serve_watch_interval_s",
            "serve_route_slo_ms",
            "comm_retry_base_s",
            "grpc_send_timeout_s",
            "heartbeat_interval_s",
            "heartbeat_timeout_s",
        ):
            setattr(self, float_key, float(getattr(self, float_key)))
        if self.comm_retry_max < 0:
            raise ValueError(
                f"comm_retry_max={self.comm_retry_max}: must be >= 0 "
                "(0 = no retransmits/retries)"
            )
        for nonneg_key in (
            "comm_retry_base_s", "heartbeat_interval_s", "heartbeat_timeout_s",
        ):
            if getattr(self, nonneg_key) < 0:
                raise ValueError(
                    f"{nonneg_key}={getattr(self, nonneg_key)}: must be >= 0"
                )
        if self.grpc_send_timeout_s <= 0:
            raise ValueError(
                f"grpc_send_timeout_s={self.grpc_send_timeout_s}: must be > 0"
            )
        if getattr(self, "agg_mode", "stream") not in (
            "stream", "buffered", "async",
        ):
            raise ValueError(
                f"agg_mode {self.agg_mode!r}: pick 'stream' (aggregate-on-"
                "arrival), 'buffered' (reference shape) or 'async' (FedBuff)"
            )
        for float_key in ("round_quorum_frac", "round_grace_s", "staleness_decay"):
            setattr(self, float_key, float(getattr(self, float_key)))
        if not 0.0 <= self.round_quorum_frac <= 1.0:
            raise ValueError(
                f"round_quorum_frac={self.round_quorum_frac}: must be in "
                "[0, 1] (0 disables the quorum close)"
            )
        if self.round_grace_s < 0:
            raise ValueError(
                f"round_grace_s={self.round_grace_s}: must be >= 0"
            )
        if not 0.0 < self.staleness_decay <= 1.0:
            raise ValueError(
                f"staleness_decay={self.staleness_decay}: must be in (0, 1] "
                "(1 = no staleness discount)"
            )
        for int_key in ("staleness_max", "async_publish_every"):
            setattr(self, int_key, int(getattr(self, int_key)))
        if self.staleness_max < 0:
            raise ValueError(
                f"staleness_max={self.staleness_max}: must be >= 0 "
                "(0 = only fresh updates fold)"
            )
        if self.async_publish_every < 1:
            raise ValueError(
                f"async_publish_every={self.async_publish_every}: must be >= 1"
            )
        if (
            getattr(self, "agg_mode", "stream") == "async"
            and float(getattr(self, "aggregation_deadline_s", 0) or 0) > 0
        ):
            raise ValueError(
                "agg_mode=async has no round barrier; "
                "aggregation_deadline_s does not apply — unset one of them"
            )
        # -- chaos plane knobs (docs/robustness.md chaos schedule DSL) --
        from .core.chaos import validate_schedule

        validate_schedule(getattr(self, "chaos_schedule", None), "chaos_schedule")
        io_steps = validate_schedule(getattr(self, "io_faults", None), "io_faults")
        bad_io = [
            s for s in io_steps
            if s["at"]["event"] not in ("wal_create", "wal_append", "ckpt_publish")
        ]
        if bad_io:
            raise ValueError(
                f"io_faults only takes IO events (wal_create / wal_append / "
                f"ckpt_publish); got {sorted(s['at']['event'] for s in bad_io)}"
                " — use chaos_schedule for comm/barrier steps"
            )
        raw = getattr(self, "chaos_seed", 0)
        try:
            self.chaos_seed = int(raw or 0)
        except (TypeError, ValueError):
            raise ValueError(
                f"chaos_seed={raw!r}: must be an integer"
            ) from None
        # -- elastic preemption knobs (docs/robustness.md device loss) --
        from .parallel.elastic import make_signal

        # parse-validate (the factory raises the naming ValueError);
        # the parsed signal is rebuilt at train() time, not stored here
        signal = make_signal(getattr(self, "preempt_signal", None))
        if signal is not None and not getattr(self, "checkpoint_dir", None):
            raise ValueError(
                f"preempt_signal={self.preempt_signal!r} needs "
                "checkpoint_dir: a preemption notice forces a durable "
                "checkpoint — with nowhere to land it the drained round "
                "would be lost"
            )
        raw = getattr(self, "elastic_min_devices", 1)
        try:
            self.elastic_min_devices = int(raw if raw is not None else 1)
        except (TypeError, ValueError):
            raise ValueError(
                f"elastic_min_devices={raw!r}: must be an integer >= 1"
            ) from None
        if self.elastic_min_devices < 1:
            raise ValueError(
                f"elastic_min_devices={self.elastic_min_devices}: must be "
                ">= 1 (the resume floor — below it the run refuses to "
                "continue)"
            )
        # -- defense / attack knobs (docs/robustness.md threat model) --
        defense = getattr(self, "defense_type", None) or None
        if defense is not None and defense not in constants.DEFENSE_TYPES:
            # the silent-no-defense footgun: a typo'd defense_type used
            # to fall through to a plain undefended mean
            raise ValueError(
                f"unknown defense_type {defense!r}; pick one of "
                f"{constants.DEFENSE_TYPES} (or null to disable)"
            )
        for float_key in (
            "norm_bound", "stddev", "defense_anomaly_threshold",
            "poisoned_client_fraction", "poison_sample_fraction",
        ):
            raw = getattr(self, float_key)
            try:
                setattr(self, float_key, float(raw))
            except (TypeError, ValueError):
                # a YAML `norm_bound: null` must name the knob, not
                # surface a bare float(None) TypeError
                raise ValueError(
                    f"{float_key}={raw!r}: must be a number"
                ) from None
        if self.norm_bound <= 0:
            raise ValueError(
                f"norm_bound={self.norm_bound}: must be > 0 (the clip "
                "radius around the global model)"
            )
        if self.stddev < 0:
            raise ValueError(f"stddev={self.stddev}: must be >= 0")
        if self.defense_anomaly_threshold < 0:
            raise ValueError(
                f"defense_anomaly_threshold={self.defense_anomaly_threshold}: "
                "must be >= 0 (0 disables the anomaly screen)"
            )
        raw = self.defense_quarantine_rounds
        try:
            self.defense_quarantine_rounds = int(raw)
        except (TypeError, ValueError):
            # same null-naming rule as the float knobs above
            raise ValueError(
                f"defense_quarantine_rounds={raw!r}: must be an integer"
            ) from None
        if self.defense_quarantine_rounds < 1:
            raise ValueError(
                f"defense_quarantine_rounds={self.defense_quarantine_rounds}: "
                "must be >= 1"
            )
        ptypes = getattr(self, "poison_type", None) or None
        if ptypes is not None:
            as_list = (
                list(ptypes) if isinstance(ptypes, (list, tuple)) else [ptypes]
            )
            bad = [t for t in as_list if t not in constants.POISON_TYPES]
            if bad:
                raise ValueError(
                    f"unknown poison_type {bad}; pick from "
                    f"{constants.POISON_TYPES}"
                )
            if isinstance(ptypes, (list, tuple)) and not (
                getattr(self, "poisoned_client_idxs", None)
            ):
                raise ValueError(
                    "poison_type as a list pairs 1:1 with "
                    "poisoned_client_idxs; set the idxs explicitly "
                    "(poisoned_client_fraction draws an arbitrary "
                    "attacker set)"
                )
        if not 0.0 <= self.poisoned_client_fraction <= 1.0:
            raise ValueError(
                f"poisoned_client_fraction={self.poisoned_client_fraction}: "
                "must be in [0, 1]"
            )
        if not 0.0 < self.poison_sample_fraction <= 1.0:
            raise ValueError(
                f"poison_sample_fraction={self.poison_sample_fraction}: "
                "must be in (0, 1]"
            )
        self.target_label = int(getattr(self, "target_label", 0) or 0)
        if self.serve_queue_size < 1 or self.serve_max_batch < 1:
            raise ValueError(
                f"serve_queue_size={self.serve_queue_size} / "
                f"serve_max_batch={self.serve_max_batch}: both must be >= 1"
            )
        for nonneg_key in (
            "serve_batch_wait_ms", "serve_deadline_ms", "serve_watch_interval_s",
            "serve_route_slo_ms", "serve_route_failover",
        ):
            if getattr(self, nonneg_key) < 0:
                raise ValueError(
                    f"{nonneg_key}={getattr(self, nonneg_key)}: must be >= 0"
                )
        if getattr(self, "serve_bucket", "pow2") not in ("pow2", "exact"):
            raise ValueError(
                f"serve_bucket {self.serve_bucket!r}: pick 'pow2' or 'exact'"
            )
        if self.serve_fleet_size < 1:
            raise ValueError(
                f"serve_fleet_size={self.serve_fleet_size}: must be >= 1 "
                "(1 = single endpoint, no fleet layer)"
            )
        if getattr(self, "serve_route_policy", "least_loaded") not in (
            "least_loaded", "static",
        ):
            raise ValueError(
                f"serve_route_policy {self.serve_route_policy!r}: pick "
                "'least_loaded' or 'static'"
            )
        serve_mesh = getattr(self, "serve_mesh", None)
        if serve_mesh is not None:
            if not isinstance(serve_mesh, dict) or not set(
                serve_mesh
            ) <= {"data", "fsdp"}:
                raise ValueError(
                    f"serve_mesh={serve_mesh!r}: expected a dict with "
                    "'data'/'fsdp' axis sizes (e.g. {'data': 2, 'fsdp': 2})"
                )
            self.serve_mesh = {k: int(v) for k, v in serve_mesh.items()}
        if getattr(self, "stall_timeout_s", 0.0) < 0:
            raise ValueError(
                f"stall_timeout_s={self.stall_timeout_s}: must be >= 0 "
                "(0 disables the stall watchdog)"
            )
        raw = getattr(self, "max_clients")
        try:
            # a YAML `max_clients: null` must name the knob (the
            # defense-knob convention), never coerce silently
            self.max_clients = int(raw)
        except (TypeError, ValueError):
            raise ValueError(
                f"max_clients={raw!r}: must be an integer"
            ) from None
        if self.max_clients < 1:
            raise ValueError(
                f"max_clients={self.max_clients}: must be >= 1"
            )
        raw = getattr(self, "compile_cache_dir", None)
        if raw is not None and not isinstance(raw, (str, os.PathLike)):
            # the null-naming rule: a YAML `compile_cache_dir: 3` must
            # name the knob, never surface inside jax.config
            raise ValueError(
                f"compile_cache_dir={raw!r}: must be a directory path "
                "(or null to disable the persistent compilation cache)"
            )
        raw = getattr(self, "checkpoint_freq")
        if raw is not None:  # None = the scenario's historical cadence
            try:
                self.checkpoint_freq = int(raw)
            except (TypeError, ValueError):
                raise ValueError(
                    f"checkpoint_freq={raw!r}: must be an integer (or "
                    "null for the scenario default)"
                ) from None
            if self.checkpoint_freq < 1:
                raise ValueError(
                    f"checkpoint_freq={self.checkpoint_freq}: must be >= 1"
                )
        for int_key in ("trace_ring_size", "devtime_ring_size", "metrics_port"):
            setattr(self, int_key, int(getattr(self, int_key)))
        if self.trace_ring_size < 1:
            raise ValueError(
                f"trace_ring_size={self.trace_ring_size}: must be >= 1"
            )
        if self.devtime_ring_size < 1:
            raise ValueError(
                f"devtime_ring_size={self.devtime_ring_size}: must be >= 1"
            )
        if not 0 <= self.metrics_port <= 65535:
            raise ValueError(
                f"metrics_port={self.metrics_port}: must be a port number "
                "(0 disables the /metrics server)"
            )
        self.round_deadline_s = float(self.round_deadline_s)
        if self.round_deadline_s < 0:
            raise ValueError(
                f"round_deadline_s={self.round_deadline_s}: must be >= 0 "
                "(0 disables the round SLO)"
            )
        pr = getattr(self, "profile_rounds", None)
        if pr is not None and not isinstance(pr, (str, list, tuple)):
            raise ValueError(
                f"profile_rounds={pr!r}: pass a list of round indices or "
                "a comma-separated string"
            )
        # -- planet-scale population plane (fedml_tpu/scale/) ----------
        for int_key in ("client_registry_size", "cohort_size", "edge_num"):
            raw = getattr(self, int_key)
            try:
                setattr(self, int_key, int(raw or 0))
            except (TypeError, ValueError):
                raise ValueError(
                    f"{int_key}={raw!r}: must be an integer"
                ) from None
            if getattr(self, int_key) < 0:
                raise ValueError(
                    f"{int_key}={getattr(self, int_key)}: must be >= 0 "
                    "(0 disables)"
                )
        if self.client_registry_size > 0:
            if t != constants.FEDML_TRAINING_PLATFORM_SIMULATION:
                raise ValueError(
                    "client_registry_size applies to training_type="
                    "simulation only (the cross-silo edge tier is the "
                    f"edge_num knob); got training_type={t!r}"
                )
            cohort = self.cohort_size or self.client_num_per_round
            if cohort > self.client_registry_size:
                raise ValueError(
                    f"cohort_size={cohort} exceeds "
                    f"client_registry_size={self.client_registry_size}"
                )
            if self.edge_num > cohort:
                raise ValueError(
                    f"edge_num={self.edge_num} exceeds the cohort size "
                    f"{cohort}: an edge tier wider than its cohort is a "
                    "misconfiguration, not a topology"
                )
        # -- hierarchical server plane (cross_silo/hierarchical) -------
        plane = str(getattr(self, "edge_plane", "inproc") or "inproc")
        if plane not in ("inproc", "ranks"):
            raise ValueError(
                f"edge_plane={plane!r}: pick 'inproc' (the in-process "
                "tree) or 'ranks' (edge aggregators as real ranks)"
            )
        self.edge_plane = plane
        raw_stride = getattr(self, "hier_port_stride", 64)
        try:
            self.hier_port_stride = int(
                64 if raw_stride is None else raw_stride
            )
        except (TypeError, ValueError):
            raise ValueError(
                f"hier_port_stride={raw_stride!r}: must be an integer"
            ) from None
        if self.hier_port_stride < 1:
            raise ValueError(
                f"hier_port_stride={self.hier_port_stride}: must be >= 1"
            )
        if plane == "ranks":
            if t != constants.FEDML_TRAINING_PLATFORM_CROSS_SILO:
                raise ValueError(
                    "edge_plane=ranks needs training_type=cross_silo "
                    f"(real edge processes over the comm seam); got {t!r}"
                )
            if getattr(self, "agg_mode", "stream") != "stream":
                raise ValueError(
                    "edge_plane=ranks requires agg_mode=stream: the edge "
                    "tier IS the streaming fold (one merged limb-set per "
                    "round crosses the root link); buffered has no "
                    "limb-set to ship and async hierarchy is ROADMAP work"
                )
            if self.edge_num < 1:
                raise ValueError(
                    f"edge_plane=ranks needs edge_num >= 1; got "
                    f"{self.edge_num}"
                )
            if self.edge_num > int(self.client_num_per_round):
                raise ValueError(
                    f"edge_num={self.edge_num} exceeds "
                    f"client_num_per_round={self.client_num_per_round}: an "
                    "edge tier wider than its clients is a "
                    "misconfiguration, not a topology"
                )
            if getattr(self, "defense_type", None) == constants.DEFENSE_MEDIAN:
                raise ValueError(
                    "edge_plane=ranks cannot run defense_type=median: a "
                    "full-cohort reduction needs every upload in one "
                    "place, which is exactly what the edge tier removes"
                )
            if bool(getattr(self, "elastic_membership", False)):
                raise ValueError(
                    "edge_plane=ranks does not support elastic_membership "
                    "yet: the client->edge partition is planned per run "
                    "(joins would need repartitioning)"
                )
            if float(getattr(self, "aggregation_deadline_s", 0) or 0) > 0:
                raise ValueError(
                    "edge_plane=ranks closes rounds per edge and uses the "
                    "quorum close at the root (round_quorum_frac/"
                    "round_grace_s); aggregation_deadline_s does not apply"
                )
        # -- cross-device Beehive check-in plane (cross_device/) -------
        for int_key in ("crossdevice_cohort", "crossdevice_mask_threshold",
                        "crossdevice_duty_hours"):
            raw = getattr(self, int_key)
            try:
                setattr(self, int_key, int(raw or 0))
            except (TypeError, ValueError):
                raise ValueError(
                    f"{int_key}={raw!r}: must be an integer"
                ) from None
        if self.crossdevice_cohort < 0:
            raise ValueError(
                f"crossdevice_cohort={self.crossdevice_cohort}: must be "
                ">= 0 (0 = client_num_per_round)"
            )
        if self.crossdevice_mask_threshold < 1:
            raise ValueError(
                f"crossdevice_mask_threshold="
                f"{self.crossdevice_mask_threshold}: must be >= 1 "
                "(shares needed to reconstruct a vanished device's mask)"
            )
        if not 1 <= self.crossdevice_duty_hours <= 24:
            raise ValueError(
                f"crossdevice_duty_hours={self.crossdevice_duty_hours}: "
                "must be in [1, 24] (hours per day a device is reachable)"
            )
        for float_key in ("crossdevice_fold_target_frac",
                          "crossdevice_report_window_s",
                          "crossdevice_quant_scale"):
            raw = getattr(self, float_key)
            try:
                setattr(self, float_key, float(raw))
            except (TypeError, ValueError):
                raise ValueError(
                    f"{float_key}={raw!r}: must be a number"
                ) from None
        if not 0.0 < self.crossdevice_fold_target_frac <= 1.0:
            raise ValueError(
                f"crossdevice_fold_target_frac="
                f"{self.crossdevice_fold_target_frac}: must be in (0, 1] "
                "(fraction of the offered cohort whose folds close a round)"
            )
        if self.crossdevice_report_window_s <= 0:
            raise ValueError(
                f"crossdevice_report_window_s="
                f"{self.crossdevice_report_window_s}: must be > 0"
            )
        if self.crossdevice_quant_scale <= 0:
            raise ValueError(
                f"crossdevice_quant_scale={self.crossdevice_quant_scale}: "
                "must be > 0"
            )
        self.crossdevice_secure_agg = bool(self.crossdevice_secure_agg)
        self.crossdevice_verify_pubkey = bool(self.crossdevice_verify_pubkey)

    # -- niceties ------------------------------------------------------
    def get(self, key: str, default: Any = None) -> Any:
        return getattr(self, key, default)

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items() if not k.startswith("_")}

    def __repr__(self) -> str:  # pragma: no cover
        keys = ", ".join(sorted(self.to_dict()))
        return f"Arguments({keys})"


def add_args(parser: Optional[argparse.ArgumentParser] = None) -> argparse.Namespace:
    """The reference's two-flag CLI (arguments.py:32-49)."""
    parser = parser or argparse.ArgumentParser(description="fedml_tpu")
    parser.add_argument(
        "--yaml_config_file",
        "--cf",
        help="yaml configuration file",
        type=str,
        default="",
    )
    parser.add_argument("--rank", type=int, default=0)
    parser.add_argument("--local_rank", type=int, default=0)
    parser.add_argument("--role", type=str, default="client")
    parser.add_argument("--run_id", type=str, default="0")
    args, _ = parser.parse_known_args()
    return args


def _default_config_path(training_type: str) -> Optional[str]:
    name = {
        constants.FEDML_TRAINING_PLATFORM_SIMULATION: "simulation_sp.yaml",
        constants.FEDML_TRAINING_PLATFORM_CROSS_SILO: "cross_silo.yaml",
        constants.FEDML_TRAINING_PLATFORM_CROSS_DEVICE: "cross_device.yaml",
    }.get(training_type)
    if name is None:
        return None
    p = Path(__file__).parent / "config" / name
    return str(p) if p.exists() else None


def load_arguments(
    training_type: Optional[str] = None,
    comm_backend: Optional[str] = None,
) -> Arguments:
    """Entry point mirroring ``load_arguments`` (arguments.py:143-151)."""
    cmd_args = add_args()
    if not cmd_args.yaml_config_file:
        default = _default_config_path(
            training_type or _DEFAULTS["training_type"]
        )
        if default is not None and os.path.exists(default):
            cmd_args.yaml_config_file = default
    return Arguments(cmd_args, training_type, comm_backend)
