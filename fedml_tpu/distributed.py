"""``training_type: distributed`` — mesh-parallel LM training through
the one-line API.

The reference has no counterpart (its parallelism vocabulary stops at
FL process-parallelism + in-silo DDP, SURVEY.md §2.9 census); this
scenario is where the framework's green-field parallel subsystems
become user-reachable product: the YAML picks a mesh and the trainer
runs one jitted step over it.

YAML surface::

    common_args: {training_type: distributed}
    train_args:  {mesh_shape: {dp: 2, tp: 2, ep: 2}, epochs: 2, ...}
    model_args:  {model: moe_transformer, ...}
    data_args:   {dataset: shakespeare, ...}

Modes (inferred from the mesh axes):

- **sharded** (axes ⊆ {dp, tp, ep}): one jitted train step; batch over
  ``dp``, Megatron dense layout over ``tp`` (parallel/tensor.py),
  expert stacks over ``ep`` (parallel/expert.py). XLA SPMD inserts the
  collectives; numerics match the single-device program exactly.
- **sequence** ({sp} or {dp, sp}): ring / Ulysses attention
  (parallel/sequence.py) with the token axis sharded over ``sp`` —
  the long-context path; an optional ``dp`` axis shards the batch so
  each replica runs its own sequence collectives. sp must divide the
  sequence length, dp the batch size.
- **pipeline** ({pp} or {dp, pp}): the block stack is cut into pp
  stages and scheduled GPipe-style under shard_map
  (parallel/pipeline.py); the batch is streamed as microbatches, and
  an optional ``dp`` axis shards the examples within every microbatch
  (each dp replica streams its slice through an identical pipeline).
  ``num_layers % pp == 0``.

sp and pp each compose with dp (the batch axis rides untouched through
their shard_maps) but remain exclusive with tp/ep and each other: pp
restructures the program (stage functions under shard_map) and the sp
attention's shard_map pins the head/model axes unsharded, so those
combinations silently degrade to gathers — better to refuse loudly.
dp x tp x ep compose freely.

Training data: the dataset's global packed batches (``[nb, bs, T]``
int tokens) — this is centralized mesh training, the "distributed"
platform of the reference's vocabulary, not federated averaging.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .core.local_trainer import _cast_floats, compute_dtype_from_args
from .core.optimizers import create_client_optimizer
from .parallel.expert import shard_params_tp_ep
from .parallel.mesh import build_mesh

_SHARDED_AXES = {"dp", "tp", "ep"}
_ALL_AXES = _SHARDED_AXES | {"sp", "pp"}


def _resolve_mesh(args) -> Mesh:
    devices = jax.devices()
    shape = getattr(args, "mesh_shape", None)
    if not shape:
        shape = {"dp": len(devices)}
    shape = {str(k): int(v) for k, v in dict(shape).items()}
    unknown = set(shape) - _ALL_AXES
    if unknown:
        raise ValueError(
            f"mesh_shape axes {sorted(unknown)} unknown; pick from {sorted(_ALL_AXES)}"
        )
    for special in ("sp", "pp"):
        if special in shape and not set(shape) <= {special, "dp"}:
            raise ValueError(
                f"mesh axis {special!r} composes only with 'dp' (its "
                f"shard_map program pins the other axes); got {shape}"
            )
    n = int(np.prod(list(shape.values())))
    if n > len(devices):
        raise ValueError(f"mesh_shape {shape} needs {n} devices, have {len(devices)}")
    if jax.process_count() > 1 and n != len(devices):
        # a device subset could exclude every addressable device of
        # some process, which then holds no shard of anything — refuse
        # loudly
        raise ValueError(
            f"multi-controller run ({jax.process_count()} processes): "
            f"mesh_shape {shape} must span all {len(devices)} global "
            f"devices, not {n}"
        )
    return build_mesh(devices=devices[:n], mesh_shape=shape)


class DistributedTrainer:
    """One-line distributed LM training over a device mesh."""

    def __init__(self, args, device=None, dataset=None, model=None) -> None:
        self.args = args
        self.dataset = dataset
        self.model = model
        self.mesh = _resolve_mesh(args)
        axes = set(self.mesh.axis_names)
        self.mode = (
            "pipeline" if "pp" in axes
            else "sequence" if "sp" in axes
            else "sharded"
        )
        self.compute_dtype = compute_dtype_from_args(args)
        self.optimizer = create_client_optimizer(args)
        from .core.tracking import MetricsReporter

        self.metrics_reporter = MetricsReporter(args)
        init_rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        # distinct stream for the per-epoch shuffle permutations
        self._shuffle_key = jax.random.fold_in(init_rng, 0x51)
        builder = getattr(self, f"_build_{self.mode}")
        builder(init_rng)
        # checkpoint/resume (core/checkpoint.py): save {params,
        # opt_state, epoch}; a restarted process resumes mid-training
        # with the restored leaves placed back onto this mode's
        # shardings. Single-controller saves host copies; under
        # multi-controller the leaves stay (possibly non-addressable)
        # jax.Arrays and orbax writes/reads each process's shards
        # collectively
        self._ckpt = None
        self._start_epoch = 0
        ckpt_dir = getattr(args, "checkpoint_dir", None)
        if ckpt_dir:
            from flax.serialization import from_state_dict, to_state_dict

            from .core.checkpoint import RoundCheckpointer
            from .parallel.mesh import is_multi_controller

            multihost = is_multi_controller(self.mesh)
            self._ckpt = RoundCheckpointer(ckpt_dir, multihost=multihost)
            # None = this scenario's historical cadence (every epoch)
            self._ckpt_freq = max(
                1, int(getattr(args, "checkpoint_freq", None) or 1)
            )

            def norm_sharding(c):
                # mesh-placed leaves keep their layout; leaves optax
                # created fresh (adam's scalar count has a single-device
                # sharding) go in replicated — committing them to one
                # device would conflict with the mesh-sharded params
                # under jit
                s = c.sharding if isinstance(
                    c.sharding, NamedSharding
                ) else NamedSharding(self.mesh, P())
                return jax.ShapeDtypeStruct(c.shape, c.dtype, sharding=s)

            # sharding-targeted restore: leaves land directly on this
            # mode's mesh layout. Under multi-controller every process
            # participates and reads only its shards (orbax collective)
            # — the state-dict view keeps optax namedtuple fields
            # name-paired, not positionally zipped.
            target = {
                "params": jax.tree.map(norm_sharding, self.params),
                "opt_state": jax.tree.map(
                    norm_sharding, to_state_dict(self.opt_state)
                ),
                "epoch": 0,
            }
            state = self._ckpt.restore(target=target)
            if state is not None:
                self._start_epoch = int(state["epoch"]) + 1
                self.params = state["params"]
                self.opt_state = from_state_dict(
                    self.opt_state, state["opt_state"]
                )
                logging.info(
                    "distributed trainer resumed at epoch %d from %s",
                    self._start_epoch, ckpt_dir,
                )

    # -- shared pieces -------------------------------------------------
    def _check_dp_divides_batch(self) -> None:
        """Every mode with a dp axis shards the batch over it."""
        if "dp" not in self.mesh.axis_names:
            return
        bs = int(self.dataset.train_data_global.x.shape[1])
        dp = self.mesh.shape["dp"]
        if bs % dp:
            raise ValueError(f"mesh axis dp={dp} must divide batch_size {bs}")

    def _loss(self, logits, y, mask):
        loss, metrics = self.model.loss_fn(logits.astype(jnp.float32), y, mask)
        return loss, metrics

    def _apply_with_aux(self, params, x):
        """Forward that also surfaces the Switch load-balancing aux
        loss (models/moe.py sows it): returns (logits, mean aux). A
        model with no routed layers yields aux = 0 — the mutable apply
        costs nothing there."""
        logits, mods = self.model.module.apply(
            {"params": params}, x, mutable=["intermediates"]
        )
        auxes = [
            leaf
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                mods.get("intermediates", {})
            )[0]
            if any(getattr(k, "key", None) == "moe_aux_loss" for k in path)
        ]
        aux = sum(auxes) / len(auxes) if auxes else jnp.float32(0.0)
        return logits, aux

    def _epoch_scanner(self, apply_fn):
        """(params, opt_state, batches) -> scan of optimizer steps.
        ``apply_fn(p, x) -> (logits, aux)``; the Switch aux loss rides
        into the optimized objective with weight ``moe_aux_weight``
        (the reported per-batch loss stays the pure cross-entropy).

        ``grad_accum_steps > 1`` splits each batch into chunks whose
        gradients accumulate (weighted by their masked token counts, so
        the result is EXACTLY the full-batch masked-mean gradient)
        before one optimizer update — the HBM lever when a batch's
        activations don't fit. With MoE the router sees chunk-sized
        token pools, so capacity granularity shrinks accordingly.
        """
        optimizer = self.optimizer
        dtype = self.compute_dtype
        # defaults live in arguments._DEFAULTS; fall back to disabled
        # for args objects built outside the Arguments layer
        aux_w = float(getattr(self.args, "moe_aux_weight", 0.0) or 0.0)
        accum = int(getattr(self.args, "grad_accum_steps", 1) or 1)
        if accum < 1:
            raise ValueError(f"grad_accum_steps must be >= 1, got {accum}")

        def loss_fn(p, x, y, m):
            if dtype is not None:
                p = _cast_floats(p, dtype)
                x = _cast_floats(x, dtype)
            logits, aux = apply_fn(p, x)
            loss, metrics = self._loss(logits, y, m)
            return loss + aux_w * aux.astype(jnp.float32), metrics

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def batch_grads(params, x, y, m):
            """(grads, metrics) for one batch, chunked when accum>1."""
            if accum <= 1:
                (_, metrics), grads = grad_fn(params, x, y, m)
                return grads, metrics
            if x.shape[0] % accum:
                raise ValueError(
                    f"grad_accum_steps={accum} must divide batch_size "
                    f"{x.shape[0]}"
                )

            def split(a):
                return a.reshape(accum, a.shape[0] // accum, *a.shape[1:])

            def chunk(carry, ch):
                gsum, lsum, csum, nsum = carry
                cx, cy, cm = ch
                (_, metrics), grads = grad_fn(params, cx, cy, cm)
                w = metrics["count"]
                gsum = jax.tree.map(lambda g_, gs: gs + g_ * w, grads, gsum)
                return (
                    gsum,
                    lsum + metrics["loss"] * w,
                    csum + metrics["correct"],
                    nsum + w,
                ), None

            zeros = jax.tree.map(jnp.zeros_like, params)
            (gsum, lsum, csum, nsum), _ = jax.lax.scan(
                chunk,
                (zeros, jnp.float32(0.0), jnp.float32(0.0), jnp.float32(0.0)),
                (split(x), split(y), split(m)),
            )
            denom = jnp.maximum(nsum, 1.0)
            grads = jax.tree.map(lambda gs: gs / denom, gsum)
            return grads, {
                "loss": lsum / denom, "correct": csum, "count": nsum,
            }

        def step(carry, batch):
            params, opt_state = carry
            x, y, m = batch
            grads, metrics = batch_grads(params, x, y, m)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), metrics

        shuffle = bool(getattr(self.args, "shuffle", True))

        def epoch(params, opt_state, batches, rng):
            if shuffle:
                from .core.local_trainer import _shuffle_batches

                batches = _shuffle_batches(batches, rng)
            (params, opt_state), metrics = jax.lax.scan(
                step, (params, opt_state), (batches.x, batches.y, batches.mask)
            )
            return params, opt_state, {
                "loss_sum": (metrics["loss"] * metrics["count"]).sum(),
                "correct": metrics["correct"].sum(),
                "count": metrics["count"].sum(),
            }

        return epoch

    # -- sharded: dp x tp x ep ----------------------------------------
    def _build_sharded(self, init_rng) -> None:
        self._check_dp_divides_batch()
        params = self.model.init(init_rng)
        self.params = shard_params_tp_ep(params, self.mesh)
        self.opt_state = self.optimizer.init(self.params)
        from .parallel.mesh import place_global

        batch_spec = P(None, "dp") if "dp" in self.mesh.axis_names else P()
        self._place_data = lambda b: jax.tree.map(
            lambda a: place_global(a, NamedSharding(self.mesh, batch_spec)), b
        )
        self._epoch = jax.jit(
            # carried (params, opt_state) donated: the epoch loop
            # rebinds both every call, so XLA updates in place
            # instead of copying the model per epoch (audited)
            self._epoch_scanner(self._apply_with_aux),
            donate_argnums=(0, 1),
        )
        self._eval_apply = self.model.apply

    # -- sequence: sp (ring / Ulysses attention) ----------------------
    def _build_sequence(self, init_rng) -> None:
        import dataclasses

        from .parallel.sequence import make_sequence_sharded_attention

        module = self.model.module
        if not hasattr(module, "attn_fn"):
            raise ValueError(
                f"model {self.model.name!r} has no pluggable attention; "
                "sequence parallelism needs the transformer family"
            )
        sp = self.mesh.shape["sp"]
        has_dp = "dp" in self.mesh.axis_names
        strategy = str(getattr(self.args, "sp_strategy", "ring") or "ring")
        ring_bk = getattr(self.args, "sp_ring_block", None)
        attn = make_sequence_sharded_attention(
            self.mesh, strategy=strategy, causal=True,
            batch_axis="dp" if has_dp else None,
            ring_block_k=int(ring_bk) if ring_bk else None,
        )
        sp_module = module.clone(attn_fn=attn)
        self.model = dataclasses.replace(self.model, module=sp_module)
        seq_len = int(self.dataset.train_data_global.x.shape[-1])
        if seq_len % sp:
            raise ValueError(f"mesh axis sp={sp} must divide seq_len {seq_len}")
        self._check_dp_divides_batch()
        # example batch = dp size: the attention shard_map inside the
        # module requires the batch axis divisible by dp even at init
        params = self.model.init(
            init_rng,
            example_x=jnp.zeros(
                (self.mesh.shape.get("dp", 1), seq_len), jnp.int32
            ),
        )
        from .parallel.mesh import replicate

        self.params = replicate(params, self.mesh)
        self.opt_state = self.optimizer.init(self.params)
        # x/y [nb, bs, T]: token axis over sp, batch over dp when
        # present; the per-example mask [nb, bs] (and any rank<3 leaf)
        # shards over dp only — the attention shard_map pins the
        # head/model axes anyway
        from .parallel.mesh import place_global

        batch = "dp" if has_dp else None

        def place(b):
            return jax.tree.map(
                lambda a: place_global(
                    a,
                    NamedSharding(
                        self.mesh,
                        P(None, batch, "sp") if a.ndim >= 3
                        else P(None, batch) if a.ndim == 2
                        else P(),
                    ),
                ),
                b,
            )

        self._place_data = place
        self._epoch = jax.jit(
            # carried (params, opt_state) donated: the epoch loop
            # rebinds both every call, so XLA updates in place
            # instead of copying the model per epoch (audited)
            self._epoch_scanner(self._apply_with_aux),
            donate_argnums=(0, 1),
        )
        self._eval_apply = self.model.apply

    # -- pipeline: pp (GPipe over the block stack) --------------------
    def _build_pipeline(self, init_rng) -> None:
        from .models.transformer import TransformerLM
        from .parallel.pipeline import stack_stage_params

        module = self.model.module
        if type(module) is not TransformerLM:
            raise ValueError(
                f"pipeline mode supports the plain TransformerLM block "
                f"stack, got {type(module).__name__}"
            )
        S = self.mesh.shape["pp"]
        L = int(module.num_layers)
        if L % S:
            raise ValueError(f"pp={S} must divide num_layers {L}")
        self._layers_per_stage = L // S
        self._pp_module = module
        params = self.model.init(
            init_rng, example_x=jnp.zeros((1, 8), jnp.int32)
        )
        blocks = [params[f"Block_{i}"] for i in range(L)]
        # [S, L/S, ...] — stage-major stacking
        stages = stack_stage_params(
            [
                jax.tree.map(
                    lambda *xs: jnp.stack(xs),
                    *blocks[s * self._layers_per_stage:(s + 1) * self._layers_per_stage],
                )
                for s in range(S)
            ]
        )
        outer = {k: v for k, v in params.items() if not k.startswith("Block_")}
        # _pp_apply mirrors TransformerLM.__call__'s embed/head halves;
        # refuse loudly if the model grows top-level params this mirror
        # doesn't know about (silent divergence otherwise)
        expected = {"Embed_0", "Embed_1", "LayerNorm_0", "Dense_0"}
        if set(outer) != expected:
            raise ValueError(
                "pipeline mode mirrors TransformerLM's embed/head "
                f"structure; unexpected params: {sorted(set(outer) ^ expected)}"
            )
        self.params = {"outer": outer, "stages": stages}
        self.opt_state = self.optimizer.init(self.params)
        from .parallel.mesh import place_global

        has_dp = "dp" in self.mesh.axis_names
        self._check_dp_divides_batch()
        # batch axis (leaf axis 1: [nb, bs, ...]) over dp when present;
        # the pipeline shard_map streams each dp slice independently
        self._place_data = lambda b: jax.tree.map(
            lambda a: place_global(
                a,
                NamedSharding(
                    self.mesh,
                    P(None, "dp") if has_dp and a.ndim >= 2 else P(),
                ),
            ),
            b,
        )
        self._epoch = jax.jit(
            self._epoch_scanner(
                # pp rejects MoE modules, so there is no aux loss here
                lambda p, x: (self._pp_apply(p, x), jnp.float32(0.0))
            ),
            # same carried-state donation contract as the other builds
            donate_argnums=(0, 1),
        )
        self._eval_apply = self._pp_apply

    def _pp_apply(self, params, tokens):
        """TransformerLM forward with the block stack pipelined.
        Mirrors ``TransformerLM.__call__`` (embed -> blocks -> LN ->
        head) with the middle replaced by the GPipe schedule; the
        embed/LN/head math is flax's own layer modules applied to the
        original param subtrees, and the structure mirror is guarded by
        the ``expected`` check in ``_build_pipeline``."""
        import flax.linen as nn

        from .models.transformer import Block, resolve_attention
        from .parallel.pipeline import pipeline_apply, split_microbatches

        m = self._pp_module
        outer, stages = params["outer"], params["stages"]
        attn = m.attn_fn or resolve_attention(m.attention)
        block = Block(num_heads=m.num_heads, attn_fn=attn)
        B, T = tokens.shape
        x = nn.Embed(m.vocab_size, m.embed_dim).apply(
            {"params": outer["Embed_0"]}, tokens.astype(jnp.int32)
        )
        pos = nn.Embed(m.max_len, m.embed_dim).apply(
            {"params": outer["Embed_1"]}, jnp.arange(T)
        )
        x = x + pos[None]

        def one_block(h, bp):
            return block.apply({"params": bp}, h), None

        if m.remat:
            # honor the model's remat flag in the pipelined stack too:
            # recompute each block's activations in the backward pass
            one_block = jax.checkpoint(one_block)

        def stage_fn(stage_params, h):
            h, _ = jax.lax.scan(one_block, h, stage_params)
            return h

        dp = self.mesh.shape.get("dp", 1)
        micro = int(getattr(self.args, "pp_microbatches", 0) or 0)
        if micro <= 0:
            # microbatch size must also split across the dp replicas
            micro = min(B // dp if B >= dp else B, max(2 * self.mesh.shape["pp"], 1))
            while micro > 1 and (B % micro or (B // micro) % dp):
                micro -= 1
        out = pipeline_apply(
            stage_fn, stages, split_microbatches(x, micro), self.mesh,
            batch_axis="dp" if dp > 1 else None,
        )
        x = out.reshape(B, T, -1)
        x = nn.LayerNorm().apply({"params": outer["LayerNorm_0"]}, x)
        return nn.Dense(m.vocab_size).apply({"params": outer["Dense_0"]}, x)

    # -- run loop ------------------------------------------------------
    def run(self) -> Dict[str, float]:
        args, ds = self.args, self.dataset
        train = self._place_data(ds.train_data_global)
        test = self._place_data(ds.test_data_global)
        epochs = int(getattr(args, "epochs", 1))
        stats: Dict[str, float] = {}
        eval_every = int(getattr(args, "frequency_of_the_test", 1) or 1)
        from .core.tracking import device_trace

        try:
            if self._start_epoch > 0 and self._start_epoch >= epochs:
                # resumed from a checkpoint taken at/after the final
                # epoch: nothing left to train, produce the terminal eval
                logging.info(
                    "resumed at epoch %d >= epochs %d; evaluating only",
                    self._start_epoch, epochs,
                )
                with self.mesh:
                    stats = {"epoch": epochs - 1, **self._evaluate(test)}
                self.metrics_reporter.report(
                    {"kind": "distributed_train", **stats}
                )
                return stats
            with device_trace(args), self.mesh:
                for ep in range(self._start_epoch, epochs):
                    t0 = time.perf_counter()
                    # epoch-INDEXED stream (fold_in, not sequential
                    # split): a resumed run replays exactly the
                    # permutations the interrupted run would have used;
                    # every process derives the same host value, so the
                    # shuffle is multi-controller consistent
                    ep_rng = np.asarray(
                        jax.random.fold_in(self._shuffle_key, ep)
                    )
                    self.params, self.opt_state, sums = self._epoch(
                        self.params, self.opt_state, train, ep_rng
                    )
                    jax.block_until_ready(jax.tree.leaves(self.params)[0])
                    dt = time.perf_counter() - t0
                    train_m = self.model.metrics_from_sums(
                        jax.tree.map(np.asarray, sums)
                    )
                    stats = {
                        "epoch": ep,
                        "train_loss": train_m["loss"],
                        "train_acc": train_m["acc"],
                        "epoch_time_s": dt,
                        "tokens_per_sec": train_m["count"] / max(dt, 1e-9),
                    }
                    if (ep + 1) % eval_every == 0 or ep == epochs - 1:
                        stats.update(self._evaluate(test))
                    self.metrics_reporter.report(
                        {"kind": "distributed_train", **stats}
                    )
                    logging.info("distributed epoch %d: %s", ep, stats)
                    if self._ckpt and (
                        (ep + 1) % self._ckpt_freq == 0 or ep == epochs - 1
                    ):
                        from flax.serialization import to_state_dict

                        self._ckpt.save(
                            ep,
                            {
                                "params": self.params,
                                "opt_state": to_state_dict(self.opt_state),
                                "epoch": ep,
                            },
                        )
        finally:
            if self._ckpt is not None:
                self._ckpt.close()
        return stats

    def _evaluate(self, test) -> Dict[str, float]:
        from .core.local_trainer import make_eval_fn

        if not hasattr(self, "_eval_jit"):
            self._eval_jit = jax.jit(
                make_eval_fn(
                    self._eval_apply, self.model.loss_fn,
                    compute_dtype=self.compute_dtype,
                )
            )
        m = self.model.metrics_from_sums(
            jax.tree.map(np.asarray, self._eval_jit(self.params, test))
        )
        return {"test_loss": m["loss"], "test_acc": m["acc"]}
