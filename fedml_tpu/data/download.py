"""Dataset download seam with offline grace + bundled real-data path.

Reference parity: ``data/MNIST/data_loader.py:17-29`` (download_mnist:
fetch ``MNIST.zip`` from ``FEDML_DATA_MNIST_URL`` — reference
``constants.py:18`` — into ``data_cache_dir`` and extract; the archive
carries the LEAF json layout ``MNIST/{train,test}/*.json``).

Two deliberate deviations:

- **Offline grace**: the reference's ``wget.download`` raises and kills
  the run when there is no egress; here any network failure logs a
  warning and returns False so the caller can fall back (loader.py
  degrades to its synthetic stand-in, scripts/reproduce_baseline.py to
  the bundled real-digits subset below).
- **Bundled real data**: :func:`materialize_real_digits` writes the UCI
  ML hand-written digits set (1797 REAL handwritten digit images,
  shipped inside scikit-learn — available with zero egress) into the
  exact MNIST LEAF json layout: 8x8 images are upsampled to 28x28,
  scaled to [0,1], flattened to 784 like the reference's MNIST json,
  and split across users with a Dirichlet label skew so the federation
  is naturally non-IID. This is NOT MNIST — file/metric names say
  "digits" wherever the distinction matters — but it IS genuinely real
  data in the reference's on-disk format, which is what the
  accuracy-reproduction path needs when the real archive can't be
  fetched.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import tempfile
import time
import urllib.request
import zipfile
from typing import Optional

from ..constants import FEDML_DATA_MNIST_URL

_DOWNLOAD_TIMEOUT_S = 15
# bounded retry around each network fetch BEFORE the offline-grace
# fallback: one transient blip (DNS hiccup, connection reset) must not
# silently degrade a run to cached/synthetic data
_FETCH_RETRIES = 2
_FETCH_RETRY_BASE_S = 1.0

# dataset -> archives, straight from the reference's download scripts
# (data/<ds>/download*.sh): same hosts, same artifact names. Both
# stackoverflow tasks share the h5 + the two vocab side files.
_SO_ARCHIVES = (
    "https://fedml.s3-us-west-1.amazonaws.com/stackoverflow.tar.bz2",
    "https://fedml.s3-us-west-1.amazonaws.com/stackoverflow.word_count.tar.bz2",
    "https://fedml.s3-us-west-1.amazonaws.com/stackoverflow.tag_count.tar.bz2",
)
DATASET_ARCHIVES = {
    "mnist": (FEDML_DATA_MNIST_URL,),
    "fed_cifar100": (
        "https://fedml.s3-us-west-1.amazonaws.com/fed_cifar100.tar.bz2",
    ),
    "fed_shakespeare": (
        "https://fedml.s3-us-west-1.amazonaws.com/shakespeare.tar.bz2",
    ),
    "femnist": (
        "https://fedml.s3-us-west-1.amazonaws.com/fed_emnist.tar.bz2",
    ),
    "stackoverflow_nwp": _SO_ARCHIVES,
    "stackoverflow_lr": _SO_ARCHIVES,
    # FeTS2021 training archive (data/FeTS2021/download.sh)
    "fets2021": (
        "https://fedcv.s3.us-west-1.amazonaws.com/MICCAI_FeTS2021_TrainingData.zip",
    ),
    # real edge-case attack sets — southwest/ardis/howto/greencar
    # (data/edge_case_examples/get_data.sh); consumed by
    # poison.load_edge_case_arrays, not the dataset loader
    "edge_case_examples": (
        "http://pages.cs.wisc.edu/~hongyiwang/edge_case_attack/edge_case_examples.zip",
    ),
}


def _transient_fetch_error(e: Exception) -> bool:
    """Retry only what a second attempt can plausibly fix: timeouts,
    resets, DNS blips, 5xx. A 4xx (gone/renamed archive) or a local
    write error (disk full) fails the same way every time — surface it
    to the offline-grace path immediately."""
    import urllib.error

    if isinstance(e, urllib.error.HTTPError):
        return e.code >= 500
    return isinstance(
        e, (urllib.error.URLError, TimeoutError, ConnectionError)
    )


def _fetch(url: str, dest: str) -> None:
    """Stream ``url`` to ``dest`` atomically (no partial files), with a
    bounded retry + backoff so one transient network error does not
    fall straight into the offline-grace path. Only the LAST failure
    propagates (the caller's grace handling picks the fallback)."""
    from ..core.comm.base import backoff_delay_s

    last_err: Optional[Exception] = None
    for attempt in range(_FETCH_RETRIES + 1):
        if attempt:
            # rand=0: deterministic (no jitter) — a single downloader
            # has no retry storm to decorrelate
            delay = backoff_delay_s(
                attempt - 1, _FETCH_RETRY_BASE_S, rand=lambda: 0.0
            )
            logging.warning(
                "fetch %s failed (%s: %s); retry %d/%d in %.1fs",
                url, type(last_err).__name__, last_err,
                attempt, _FETCH_RETRIES, delay,
            )
            time.sleep(delay)
        try:
            _fetch_once(url, dest)
            return
        except Exception as e:  # noqa: BLE001 — classified below
            last_err = e
            if not _transient_fetch_error(e):
                raise
    raise last_err


def _fetch_once(url: str, dest: str) -> None:
    tmp_name = None
    try:
        with urllib.request.urlopen(
            url, timeout=_DOWNLOAD_TIMEOUT_S
        ) as r, tempfile.NamedTemporaryFile(
            dir=os.path.dirname(dest), delete=False
        ) as tmp:
            tmp_name = tmp.name
            shutil.copyfileobj(r, tmp)
        os.replace(tmp_name, dest)
        tmp_name = None
    finally:
        if tmp_name is not None:  # failed mid-copy: no orphans
            try:
                os.unlink(tmp_name)
            except OSError:
                logging.debug(
                    "download: temp %s cleanup failed", tmp_name,
                    exc_info=True,
                )


def _extract(archive: str, out_dir: str) -> None:
    import tarfile

    if archive.endswith(".zip"):
        with zipfile.ZipFile(archive, "r") as zf:
            zf.extractall(out_dir)
    else:
        with tarfile.open(archive, "r:*") as tf:
            tf.extractall(out_dir, filter="data")


def _fetch_and_extract(url: str, cache_dir: str, out_dir: str) -> None:
    """Download (cached) + extract one archive, refetching once when a
    previously-interrupted download left a corrupt file behind."""
    import tarfile

    archive = os.path.join(cache_dir, os.path.basename(url))
    if not os.path.exists(archive):
        _fetch(url, archive)
    try:
        _extract(archive, out_dir)
    except (zipfile.BadZipFile, tarfile.TarError, EOFError):
        logging.warning("corrupt %s; re-downloading", archive)
        os.unlink(archive)
        _fetch(url, archive)
        _extract(archive, out_dir)


def _normalize_layout(root: str) -> None:
    """Archives differ in nesting (MNIST.zip carries ``MNIST/``, the
    TFF tarballs a dataset-named dir): hoist any single-level nesting
    so the loader's probes (<root>/train/*.json, <root>/*_{train,
    test}.h5, side files) find the artifacts."""
    if not os.path.isdir(root):
        return
    for sub in list(os.listdir(root)):
        subdir = os.path.join(root, sub)
        if not os.path.isdir(subdir) or sub in ("train", "test"):
            continue
        for inner in os.listdir(subdir):
            target = os.path.join(root, inner)
            if not os.path.exists(target):
                os.rename(os.path.join(subdir, inner), target)
        if not os.listdir(subdir):
            os.rmdir(subdir)


# both stackoverflow tasks read the same artifacts — extract them once
# into one shared dir (the reference's layout) and symlink the
# per-dataset names onto it
_SHARED_EXTRACT_ROOT = {
    "stackoverflow_nwp": "stackoverflow",
    "stackoverflow_lr": "stackoverflow",
}


def dataset_downloadable(name: str) -> bool:
    return name in DATASET_ARCHIVES


def download_dataset(name: str, data_cache_dir: str, urls=None) -> bool:
    """Fetch + extract ``name``'s reference archives into
    ``<data_cache_dir>/<name>/``; False on any failure (offline grace —
    the caller picks the fallback: loader.py degrades to its synthetic
    stand-in).

    All-or-nothing: archives extract into a staging dir that only moves
    into place once EVERY archive landed, so a partial multi-archive
    download (e.g. stackoverflow's h5 without its vocab side files) can
    never leave a half-usable dataset dir that suppresses retries.
    """
    if urls is None:
        urls = DATASET_ARCHIVES.get(name)
    if not urls:
        logging.warning("dataset %s: no download source known", name)
        return False
    shared = _SHARED_EXTRACT_ROOT.get(name, name)
    root = os.path.join(data_cache_dir, shared)
    staging = os.path.join(data_cache_dir, f".staging_{shared}")
    os.makedirs(data_cache_dir, exist_ok=True)
    if not os.path.isdir(root):
        try:
            shutil.rmtree(staging, ignore_errors=True)
            os.makedirs(staging)
            for url in urls:
                _fetch_and_extract(url, data_cache_dir, staging)
            _normalize_layout(staging)
            os.rename(staging, root)
        except Exception as e:  # noqa: BLE001 — offline grace is the point
            shutil.rmtree(staging, ignore_errors=True)
            logging.warning(
                "%s download unavailable (%s: %s); proceeding without it",
                name, type(e).__name__, e,
            )
            return False
    if shared != name:
        link = os.path.join(data_cache_dir, name)
        if not os.path.exists(link):
            os.symlink(shared, link)
    return True


def download_mnist(
    data_cache_dir: str, url: str = FEDML_DATA_MNIST_URL
) -> bool:
    """Reference-parity entry (data/MNIST/data_loader.py:17-29):
    fetch + extract the MNIST LEAF archive; False on any failure."""
    ok = download_dataset("mnist", data_cache_dir, urls=(url,))
    return ok and os.path.isdir(
        os.path.join(data_cache_dir, "mnist", "train")
    )


def materialize_real_digits(
    data_cache_dir: str,
    n_users: int = 100,
    alpha: float = 0.5,
    seed: int = 0,
    name: str = "mnist",
) -> Optional[str]:
    """Write the sklearn real-digits set as a MNIST-format LEAF dir.

    Returns the dataset dir (``<cache>/<name>``), or None when sklearn
    is unavailable. ~1437 train / 360 test real images over ``n_users``
    Dirichlet(alpha)-skewed users.
    """
    try:
        from sklearn.datasets import load_digits
    except Exception:  # noqa: BLE001 — optional dependency
        logging.warning("scikit-learn unavailable; no bundled real digits")
        return None
    import numpy as np

    d = load_digits()
    x = d.data.reshape(-1, 8, 8).astype(np.float32) / 16.0
    # upsample 8x8 -> 28x28 (nearest via index map; no PIL dependency)
    idx = (np.arange(28) * 8) // 28
    x = x[:, idx][:, :, idx].reshape(len(x), 784)
    y = d.target.astype(np.int64)

    rng = np.random.RandomState(seed)
    perm = rng.permutation(len(x))
    x, y = x[perm], y[perm]

    # Dirichlet label skew over users FIRST (the LEAF per-user grouping
    # IS the partition, so the non-IID is baked into the user split),
    # then an 80/20 per-user train/test split — train and test share
    # the same user set, the reference read_data assumption
    # (data/MNIST/data_loader.py:37-38).
    user_of = np.empty(len(y), np.int64)
    for c in range(10):
        rows = np.where(y == c)[0]
        p = rng.dirichlet([alpha] * n_users)
        user_of[rows] = rng.choice(n_users, size=len(rows), p=p)

    blobs = {
        s: {"users": [], "num_samples": [], "user_data": {}}
        for s in ("train", "test")
    }
    for u in range(n_users):
        rows = np.where(user_of == u)[0]
        if len(rows) == 0:
            continue
        uid = f"u_{u:05d}"
        k = max(1, int(0.8 * len(rows)))
        for split, sel in (("train", rows[:k]), ("test", rows[k:])):
            blobs[split]["users"].append(uid)
            blobs[split]["num_samples"].append(int(len(sel)))
            blobs[split]["user_data"][uid] = {
                "x": [[round(float(v), 4) for v in row] for row in x[sel]],
                "y": [int(v) for v in y[sel]],
            }

    root = os.path.join(data_cache_dir, name)
    for split, blob in blobs.items():
        os.makedirs(os.path.join(root, split), exist_ok=True)
        with open(os.path.join(root, split, "all_data_0.json"), "w") as f:
            json.dump(blob, f)
    # provenance marker: later runs must never mistake this subset for
    # the real MNIST archive (scripts/reproduce_baseline.py labels and
    # baseline-comparability hang off this)
    with open(os.path.join(root, "_source.json"), "w") as f:
        json.dump(
            {"source": "sklearn_digits", "real_data": True,
             "is_mnist": False},
            f,
        )
    logging.info(
        "materialized real digits (sklearn) as LEAF %s: %d train users",
        root, len(json.load(open(os.path.join(root, "train",
                                              "all_data_0.json")))["users"]),
    )
    return root
