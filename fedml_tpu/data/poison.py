"""Poisoned / backdoor dataset synthesis.

Reference: ``data/edge_case_examples/data_loader.py`` (1,156 LoC) —
``load_poisoned_dataset`` builds backdoor training sets (poison types
``southwest`` / ``ardis`` / ``howto`` / ``greencar-neo``, :205-488):
attacker clients train on examples relabelled to a target class, some
carrying an edge-case (out-of-distribution) or trigger pattern. This
module reproduces the MECHANISMS generically (the reference's types
are dataset downloads this environment can't fetch):

- ``label_flip``      — y -> (y + 1) % C  (untargeted poisoning)
- ``targeted_flip``   — y[source] -> target  (targeted misclassification)
- ``backdoor_pattern``— a corner trigger patch is stamped on a fraction
  of images which are relabelled to the target (BadNets shape — the
  trigger analog of the reference's pixel-pattern backdoors)
- ``edge_case``       — out-of-distribution samples (far-tail noise)
  labelled as the target class (the southwest-airplane idea)

``poison_clients`` applies an attack to a subset of a federation's
clients — the adversarial-client setup S-FedAvg / HS-FedAvg / robust
aggregation defend against (fedavg_robust configs: ``args.poison_type``,
``poisoned_client_fraction``, ``target_label``).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

POISON_TYPES = ("label_flip", "targeted_flip", "backdoor_pattern", "edge_case")


def stamp_trigger(x: np.ndarray, size: int = 4, value: float = None) -> np.ndarray:
    """Stamp a bottom-right square trigger on image batch [N, H, W, C]."""
    out = np.array(x, copy=True)
    v = float(out.max()) if value is None else value
    out[:, -size:, -size:, :] = v
    return out


def poison_dataset(
    x: np.ndarray,
    y: np.ndarray,
    poison_type: str,
    num_classes: int,
    target_label: int = 0,
    source_label: int = 1,
    fraction: float = 1.0,
    trigger_size: int = 4,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Return a poisoned copy of (x, y)."""
    if poison_type not in POISON_TYPES:
        raise ValueError(f"poison_type {poison_type!r} not in {POISON_TYPES}")
    rng = np.random.RandomState(seed)
    x, y = np.array(x, copy=True), np.array(y, copy=True)
    n = len(y)
    chosen = rng.permutation(n)[: max(1, int(fraction * n))]
    if poison_type == "label_flip":
        y[chosen] = (y[chosen] + 1) % num_classes
    elif poison_type == "targeted_flip":
        sel = chosen[np.isin(y[chosen], [source_label])]
        y[sel] = target_label
    elif poison_type == "backdoor_pattern":
        if x.ndim < 4:
            raise ValueError("backdoor_pattern needs image data [N, H, W, C]")
        x[chosen] = stamp_trigger(x[chosen], size=trigger_size)
        y[chosen] = target_label
    elif poison_type == "edge_case":
        # far-tail OOD inputs claimed as the target class
        x[chosen] = 3.0 + rng.normal(0, 0.5, x[chosen].shape).astype(x.dtype)
        y[chosen] = target_label
    return x, y


def poison_clients(
    xs: List[np.ndarray],
    ys: List[np.ndarray],
    poison_type: str,
    num_classes: int,
    poisoned_client_idxs: Sequence[int],
    **kw,
) -> Tuple[List[np.ndarray], List[np.ndarray], List[int]]:
    """Poison the listed clients in-place-by-copy; returns
    (xs, ys, poisoned idxs)."""
    xs, ys = list(xs), list(ys)
    for i in poisoned_client_idxs:
        xs[i], ys[i] = poison_dataset(
            xs[i], ys[i], poison_type, num_classes, seed=1000 + i, **kw
        )
    return xs, ys, list(poisoned_client_idxs)


def backdoor_attack_success_rate(
    predict_fn, x_clean: np.ndarray, y_clean: np.ndarray,
    target_label: int, trigger_size: int = 4,
) -> float:
    """Fraction of NON-target clean examples the model sends to the
    target class once the trigger is stamped — the backdoor metric the
    fork's defense experiments track (per-target-label recall,
    s_fedavg/fedavg_api.py:218-226)."""
    keep = y_clean != target_label
    if keep.sum() == 0:
        return 0.0
    triggered = stamp_trigger(x_clean[keep], size=trigger_size)
    preds = np.asarray(predict_fn(triggered))
    return float((preds == target_label).mean())
