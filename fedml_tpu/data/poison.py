"""Poisoned / backdoor dataset synthesis.

Reference: ``data/edge_case_examples/data_loader.py`` (1,156 LoC) —
``load_poisoned_dataset`` builds backdoor training sets (poison types
``southwest`` / ``ardis`` / ``howto`` / ``greencar-neo``, :205-488):
attacker clients train on examples relabelled to a target class, some
carrying an edge-case (out-of-distribution) or trigger pattern. This
module reproduces the MECHANISMS generically, and ingests the
reference's REAL edge-case arrays when the downloaded archive
(``get_data.sh`` -> ``edge_case_examples.zip``) sits under
``data_cache_dir`` — ``load_edge_case_arrays`` reads the
southwest/ardis pickles and the ``edge_case`` poison type then uses
those genuine out-of-distribution images instead of far-tail noise:

- ``label_flip``      — y -> (y + 1) % C  (untargeted poisoning)
- ``targeted_flip``   — y[source] -> target  (targeted misclassification)
- ``backdoor_pattern``— a corner trigger patch is stamped on a fraction
  of images which are relabelled to the target (BadNets shape — the
  trigger analog of the reference's pixel-pattern backdoors)
- ``edge_case``       — out-of-distribution samples (far-tail noise)
  labelled as the target class (the southwest-airplane idea)

``poison_clients`` applies an attack to a subset of a federation's
clients — the adversarial-client setup S-FedAvg / HS-FedAvg / robust
aggregation defend against (fedavg_robust configs: ``args.poison_type``,
``poisoned_client_fraction``, ``target_label``).
"""

from __future__ import annotations

import functools
import logging
import os
import pickle
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import constants

# ONE authoritative vocabulary (constants.POISON_TYPES) shared with the
# knob validation in arguments.py and the loader's poisoned-world
# wiring; re-exported here for compatibility
POISON_TYPES = constants.POISON_TYPES

# archive-relative candidates per edge-case kind (reference
# data_loader.py:393-488 file names): southwest airplanes are
# CIFAR-shaped 32x32x3 pickled arrays; ARDIS is an MNIST-shaped
# handwritten-digit set stored as a torch-saved dataset
_EDGE_CASE_FILES = {
    "southwest": (
        "southwest_images_new_train.pkl",
        "southwest_images_adv_p_percent_edge_case.pkl",
    ),
    "ardis": ("ardis_test_dataset.pt", "ARDIS/ardis_test_dataset.pt"),
    "howto": ("howto_trigger_images.pkl", "saved_datasets/howto_trigger.pkl"),
    "greencar": ("greencar_images.pkl", "saved_datasets/greencar.pkl"),
}


def _as_nhwc(arr: np.ndarray) -> Optional[np.ndarray]:
    """Coerce loaded image arrays to float [N, H, W, C] in [0, 1] —
    the SAME scale every real-data ingestion path uses (ingest.py
    divides uint8 by 255), so injected edge-case rows sit in the clean
    data's value range instead of betraying themselves by scale."""
    a = np.asarray(arr)
    if a.ndim == 3:  # [N, H, W] grayscale
        a = a[..., None]
    if a.ndim != 4:
        return None
    if a.shape[1] in (1, 3) and a.shape[-1] not in (1, 3):  # NCHW -> NHWC
        a = np.transpose(a, (0, 2, 3, 1))
    a = a.astype(np.float32)
    if a.max() > 2.0:  # raw uint8 range
        a = a / 255.0
    return a


@functools.lru_cache(maxsize=8)
def load_edge_case_arrays(
    data_cache_dir: Optional[str], kind: str = "southwest",
    download: bool = False,
) -> Optional[np.ndarray]:
    """Real out-of-distribution images from the reference's downloaded
    ``edge_case_examples`` archive, or None when absent (offline grace
    — callers fall back to the synthetic far-tail mechanism and log
    that they did). ``.pkl`` files hold numpy arrays; ``.pt`` files are
    torch-saved datasets (torch-cpu is available for ingestion only —
    nothing torch crosses this function's boundary).

    ``download=True`` fetches the archive through the download seam
    first (offline grace applies). Cached per (dir, kind): a
    multi-attacker federation must not unpickle the same multi-MB array
    once per poisoned client. Treat the returned array as read-only."""
    if not data_cache_dir:
        return None
    root = os.path.join(data_cache_dir, "edge_case_examples")
    if download and not os.path.isdir(root):
        from .download import download_dataset

        download_dataset("edge_case_examples", data_cache_dir)
    for rel in _EDGE_CASE_FILES.get(kind, ()):
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            continue
        try:
            if path.endswith(".pt"):
                import torch

                obj = torch.load(path, map_location="cpu", weights_only=False)
                arr = getattr(obj, "data", obj)
                if hasattr(arr, "numpy"):
                    arr = arr.numpy()
            else:
                with open(path, "rb") as f:
                    arr = pickle.load(f)
            out = _as_nhwc(arr)
            if out is not None and len(out):
                return out
        except Exception:  # noqa: BLE001 — a corrupt file must not kill FL
            logging.exception("edge-case file %s unreadable; skipping", path)
    return None


def stamp_trigger(x: np.ndarray, size: int = 4, value: float = None) -> np.ndarray:
    """Stamp a bottom-right square trigger on image batch [N, H, W, C]."""
    out = np.array(x, copy=True)
    v = float(out.max()) if value is None else value
    out[:, -size:, -size:, :] = v
    return out


def poison_dataset(
    x: np.ndarray,
    y: np.ndarray,
    poison_type: str,
    num_classes: int,
    target_label: int = 0,
    source_label: int = 1,
    fraction: float = 1.0,
    trigger_size: int = 4,
    seed: int = 0,
    data_cache_dir: Optional[str] = None,
    edge_case_kind: str = "southwest",
) -> Tuple[np.ndarray, np.ndarray]:
    """Return a poisoned copy of (x, y)."""
    if poison_type not in POISON_TYPES:
        raise ValueError(f"poison_type {poison_type!r} not in {POISON_TYPES}")
    rng = np.random.RandomState(seed)
    x, y = np.array(x, copy=True), np.array(y, copy=True)
    n = len(y)
    chosen = rng.permutation(n)[: max(1, int(fraction * n))]
    if poison_type == "label_flip":
        y[chosen] = (y[chosen] + 1) % num_classes
    elif poison_type == "targeted_flip":
        sel = chosen[np.isin(y[chosen], [source_label])]
        y[sel] = target_label
    elif poison_type == "backdoor_pattern":
        if x.ndim < 4:
            raise ValueError("backdoor_pattern needs image data [N, H, W, C]")
        x[chosen] = stamp_trigger(x[chosen], size=trigger_size)
        y[chosen] = target_label
    elif poison_type == "edge_case":
        # real downloaded edge-case images when present + shape-matched
        # (southwest 32x32x3 on cifar configs, ardis 28x28x1 on mnist),
        # else far-tail OOD noise claimed as the target class
        real = load_edge_case_arrays(data_cache_dir, edge_case_kind)
        if real is not None and real.shape[1:] == x.shape[1:]:
            x[chosen] = real[rng.randint(0, len(real), len(chosen))].astype(
                x.dtype
            )
        else:
            if data_cache_dir:
                logging.info(
                    "edge_case archive absent or shape-mismatched under %s; "
                    "using synthetic far-tail noise (fetch with "
                    "download_dataset('edge_case_examples', ...))",
                    data_cache_dir,
                )
            x[chosen] = 3.0 + rng.normal(0, 0.5, x[chosen].shape).astype(x.dtype)
        y[chosen] = target_label
    return x, y


def poison_clients(
    xs: List[np.ndarray],
    ys: List[np.ndarray],
    poison_type,
    num_classes: int,
    poisoned_client_idxs: Sequence[int],
    **kw,
) -> Tuple[List[np.ndarray], List[np.ndarray], List[int]]:
    """Poison the listed clients in-place-by-copy; returns
    (xs, ys, poisoned idxs). ``poison_type`` is one type for every
    client or a sequence paired 1:1 with ``poisoned_client_idxs`` (in
    the CALLER's order — mixed-attack worlds). This is THE per-client
    seed convention (1000 + client idx); the loader's poisoned-world
    wiring routes through here."""
    xs, ys = list(xs), list(ys)
    types = (
        list(poison_type)
        if isinstance(poison_type, (list, tuple))
        else [poison_type] * len(poisoned_client_idxs)
    )
    if len(types) != len(poisoned_client_idxs):
        raise ValueError(
            f"poison_type list has {len(types)} entries for "
            f"{len(poisoned_client_idxs)} poisoned clients — pair them "
            "1:1 (or pass one type)"
        )
    for i, t in zip(poisoned_client_idxs, types):
        xs[i], ys[i] = poison_dataset(
            xs[i], ys[i], t, num_classes, seed=1000 + i, **kw
        )
    return xs, ys, list(poisoned_client_idxs)


def backdoor_attack_success_rate(
    predict_fn, x_clean: np.ndarray, y_clean: np.ndarray,
    target_label: int, trigger_size: int = 4,
) -> float:
    """Fraction of NON-target clean examples the model sends to the
    target class once the trigger is stamped — the backdoor metric the
    fork's defense experiments track (per-target-label recall,
    s_fedavg/fedavg_api.py:218-226)."""
    keep = y_clean != target_label
    if keep.sum() == 0:
        return 0.0
    triggered = stamp_trigger(x_clean[keep], size=trigger_size)
    preds = np.asarray(predict_fn(triggered))
    return float((preds == target_label).mean())
