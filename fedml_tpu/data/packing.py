"""Host-side packing: ragged per-client numpy data -> static-shape
device arrays.

This is the load-bearing bridge between the reference's ragged
torch-DataLoader world and XLA's static shapes (SURVEY.md §7 "hard
parts": padded/bucketed client batching). Each client's samples are
padded up to ``num_batches * batch_size`` with a {0,1} mask; a
federation is stacked along a leading client axis so the whole cohort is
ONE pytree — ready for vmap or for sharding the client axis over a mesh.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from ..core.types import Batches


def pack_one(
    x: np.ndarray,
    y: np.ndarray,
    batch_size: int,
    num_batches: Optional[int] = None,
    x_dtype=jnp.float32,
    y_dtype=None,
    allow_truncate: bool = False,
) -> Batches:
    """Pack one client's samples into [nb, bs, ...] + mask.

    ``allow_truncate``: keep only the first ``num_batches*batch_size``
    samples (used by ``pack_clients`` when the bucketing heuristic caps
    a long-tail client)."""
    n = x.shape[0]
    nb = num_batches if num_batches is not None else max(1, -(-n // batch_size))
    total = nb * batch_size
    if n > total:
        if not allow_truncate:
            raise ValueError(f"num_batches={nb} too small for {n} samples")
        x, y, n = x[:total], y[:total], total
    pad = total - n
    xp = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)]) if pad else x
    yp = np.concatenate([y, np.zeros((pad,) + y.shape[1:], y.dtype)]) if pad else y
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    if y_dtype is None:
        y_dtype = jnp.int32 if np.issubdtype(y.dtype, np.integer) else jnp.float32
    feat_x = x.shape[1:]
    feat_y = y.shape[1:]
    return Batches(
        x=jnp.asarray(xp.reshape((nb, batch_size) + feat_x), dtype=x_dtype),
        y=jnp.asarray(yp.reshape((nb, batch_size) + feat_y), dtype=y_dtype),
        mask=jnp.asarray(mask.reshape(nb, batch_size)),
    )


def pack_clients(
    xs: Sequence[np.ndarray],
    ys: Sequence[np.ndarray],
    batch_size: int,
    num_batches: Optional[int] = None,
    x_dtype=jnp.float32,
) -> Tuple[Batches, jnp.ndarray]:
    """Pack a federation: all clients padded to a common ``num_batches``
    (max over clients unless given) and stacked -> leaves [C, nb, bs, ...].

    Returns (stacked_batches, num_samples[C]). The shared nb is what
    makes the cohort vmap-able; the mask keeps ragged semantics exact.
    """
    if num_batches is None:
        num_batches = max(max(1, -(-len(x) // batch_size)) for x in xs)
    cap_ = num_batches * batch_size
    truncated = [(i, len(x) - cap_) for i, x in enumerate(xs) if len(x) > cap_]
    if truncated:
        dropped = sum(d for _, d in truncated)
        total = sum(len(x) for x in xs)
        logging.warning(
            "pack_clients: long-tail truncation — %d/%d clients exceed "
            "num_batches=%d x batch_size=%d; dropping %d/%d samples "
            "(%.2f%%). Raise args.packing_waste_cap to keep them.",
            len(truncated), len(xs), num_batches, batch_size,
            dropped, total, 100.0 * dropped / max(total, 1),
        )
    packed = [
        pack_one(x, y, batch_size, num_batches, x_dtype=x_dtype, allow_truncate=True)
        for x, y in zip(xs, ys)
    ]
    stacked = Batches(
        x=jnp.stack([p.x for p in packed]),
        y=jnp.stack([p.y for p in packed]),
        mask=jnp.stack([p.mask for p in packed]),
    )
    # weights reflect the samples actually packed (long-tail clients may
    # have been truncated to num_batches*batch_size)
    cap = num_batches * batch_size
    num_samples = jnp.asarray(
        [min(len(x), cap) for x in xs], dtype=jnp.float32
    )
    return stacked, num_samples


def bucket_num_batches(sizes: List[int], batch_size: int, waste_cap: float = 4.0) -> int:
    """Heuristic shared nb: cap padding waste by clamping to
    ``waste_cap`` x median client size (huge-client tail gets truncated
    batches dropped rather than blowing up every client's padding).
    ``waste_cap`` is user-facing as ``args.packing_waste_cap``; raising
    it trades padding memory for keeping the long tail's samples
    (``pack_clients`` logs exactly what a given cap drops); ``inf``
    disables truncation entirely."""
    nbs = [max(1, -(-s // batch_size)) for s in sizes]
    med = float(np.median(nbs))
    return int(min(max(nbs), max(1.0, waste_cap * med)))
