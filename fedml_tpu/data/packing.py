"""Host-side packing: ragged per-client numpy data -> static-shape
device arrays.

This is the load-bearing bridge between the reference's ragged
torch-DataLoader world and XLA's static shapes (SURVEY.md §7 "hard
parts": padded/bucketed client batching). Each client's samples are
padded up to ``num_batches * batch_size`` with a {0,1} mask; a
federation is stacked along a leading client axis so the whole cohort is
ONE pytree — ready for vmap or for sharding the client axis over a mesh.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from ..core.types import Batches


def _pack_one_np(
    x: np.ndarray,
    y: np.ndarray,
    batch_size: int,
    num_batches: Optional[int] = None,
    allow_truncate: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side core of :func:`pack_one`: pad/truncate + reshape into
    ``([nb, bs, ...x], [nb, bs, ...y], mask[nb, bs])`` numpy arrays.

    Kept device-free so :func:`pack_clients` can stack a whole
    federation host-side and pay ONE host->device transfer per leaf —
    per-client transfers through a thin device link (the tunneled TPU
    here moves ~5 MB/s) are dominated by round-trip latency."""
    n = x.shape[0]
    nb = num_batches if num_batches is not None else max(1, -(-n // batch_size))
    total = nb * batch_size
    if n > total:
        if not allow_truncate:
            raise ValueError(f"num_batches={nb} too small for {n} samples")
        x, y, n = x[:total], y[:total], total
    pad = total - n
    xp = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)]) if pad else x
    yp = np.concatenate([y, np.zeros((pad,) + y.shape[1:], y.dtype)]) if pad else y
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    return (
        xp.reshape((nb, batch_size) + x.shape[1:]),
        yp.reshape((nb, batch_size) + y.shape[1:]),
        mask.reshape(nb, batch_size),
    )


def pack_one(
    x: np.ndarray,
    y: np.ndarray,
    batch_size: int,
    num_batches: Optional[int] = None,
    x_dtype=jnp.float32,
    y_dtype=None,
    allow_truncate: bool = False,
) -> Batches:
    """Pack one client's samples into [nb, bs, ...] + mask.

    ``allow_truncate``: keep only the first ``num_batches*batch_size``
    samples (used by ``pack_clients`` when the bucketing heuristic caps
    a long-tail client)."""
    xp, yp, mask = _pack_one_np(
        x, y, batch_size, num_batches, allow_truncate=allow_truncate
    )
    if y_dtype is None:
        y_dtype = jnp.int32 if np.issubdtype(y.dtype, np.integer) else jnp.float32
    return Batches(
        x=jnp.asarray(xp, dtype=x_dtype),
        y=jnp.asarray(yp, dtype=y_dtype),
        mask=jnp.asarray(mask),
    )


def pack_clients(
    xs: Sequence[np.ndarray],
    ys: Sequence[np.ndarray],
    batch_size: int,
    num_batches: Optional[int] = None,
    x_dtype=jnp.float32,
) -> Tuple[Batches, jnp.ndarray]:
    """Pack a federation: all clients padded to a common ``num_batches``
    (max over clients unless given) and stacked -> leaves [C, nb, bs, ...].

    Returns (stacked_batches, num_samples[C]). The shared nb is what
    makes the cohort vmap-able; the mask keeps ragged semantics exact.
    """
    if num_batches is None:
        num_batches = max(max(1, -(-len(x) // batch_size)) for x in xs)
    _warn_truncation("pack_clients", [len(x) for x in xs], num_batches, batch_size)
    packed = [
        _pack_one_np(x, y, batch_size, num_batches, allow_truncate=True)
        for x, y in zip(xs, ys)
    ]
    y_dtype = (
        jnp.int32 if np.issubdtype(ys[0].dtype, np.integer) else jnp.float32
    )
    # stack host-side, ONE transfer per leaf (see _pack_one_np)
    stacked = Batches(
        x=jnp.asarray(np.stack([p[0] for p in packed]), dtype=x_dtype),
        y=jnp.asarray(np.stack([p[1] for p in packed]), dtype=y_dtype),
        mask=jnp.asarray(np.stack([p[2] for p in packed])),
    )
    # weights reflect the samples actually packed (long-tail clients may
    # have been truncated to num_batches*batch_size)
    cap = num_batches * batch_size
    num_samples = jnp.asarray(
        [min(len(x), cap) for x in xs], dtype=jnp.float32
    )
    return stacked, num_samples


def pack_labels_np(
    ys: Sequence[np.ndarray],
    batch_size: int,
    num_batches: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side federation packing of labels only: ``(y[C, nb, bs],
    mask[C, nb, bs], num_samples[C])`` numpy arrays.

    The device-synthesis path (loader._device_synth_classification)
    ships only these few KB to the device and generates the feature
    tensor there — the host never materializes images at all. One
    pad/truncate implementation serves both paths (:func:`_pack_one_np`,
    labels passed in the x slot), so mask/truncation semantics cannot
    drift from :func:`pack_clients`."""
    if num_batches is None:
        num_batches = max(max(1, -(-len(y) // batch_size)) for y in ys)
    _warn_truncation("pack_labels_np", [len(y) for y in ys], num_batches, batch_size)
    packed = [
        _pack_one_np(y, y, batch_size, num_batches, allow_truncate=True)
        for y in ys
    ]
    cap = num_batches * batch_size
    num_samples = np.asarray(
        [min(len(y), cap) for y in ys], dtype=np.float32
    )
    return (
        np.stack([p[0] for p in packed]),
        np.stack([p[2] for p in packed]),
        num_samples,
    )


def _warn_truncation(
    who: str, sizes: List[int], num_batches: int, batch_size: int
) -> None:
    """No silent caps: name what a too-small ``num_batches`` drops and
    the knob that raises it (shared by the image and label packers)."""
    cap = num_batches * batch_size
    truncated = [s - cap for s in sizes if s > cap]
    if truncated:
        dropped = sum(truncated)
        total = sum(sizes)
        logging.warning(
            "%s: long-tail truncation — %d/%d clients exceed "
            "num_batches=%d x batch_size=%d; dropping %d/%d samples "
            "(%.2f%%). Raise args.packing_waste_cap to keep them.",
            who, len(truncated), len(sizes), num_batches, batch_size,
            dropped, total, 100.0 * dropped / max(total, 1),
        )


def bucket_num_batches(sizes: List[int], batch_size: int, waste_cap: float = 4.0) -> int:
    """Heuristic shared nb: cap padding waste by clamping to
    ``waste_cap`` x median client size (huge-client tail gets truncated
    batches dropped rather than blowing up every client's padding).
    ``waste_cap`` is user-facing as ``args.packing_waste_cap``; raising
    it trades padding memory for keeping the long tail's samples
    (``pack_clients`` logs exactly what a given cap drops); ``inf``
    disables truncation entirely."""
    nbs = [max(1, -(-s // batch_size)) for s in sizes]
    med = float(np.median(nbs))
    return int(min(max(nbs), max(1.0, waste_cap * med)))
