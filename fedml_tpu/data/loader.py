"""Dataset dispatcher.

``load(args)`` mirrors ``fedml.data.load`` (``python/fedml/data/
data_loader.py:29`` -> ``load_synthetic_data`` ``:42-320``) and returns a
:class:`FederatedDataset` whose ``to_list()`` is the reference's
canonical 8-tuple ``[train_data_num, test_data_num, train_data_global,
test_data_global, train_data_local_num_dict, train_data_local_dict,
test_data_local_dict, class_num]`` (data_loader.py:310-320) — plus the
device-side packed federation (``packed_train`` / ``packed_test``,
leaves ``[C, nb, bs, ...]``) that the TPU simulators consume.

Dataset resolution order under ``<data_cache_dir>/<dataset>/``:

1. **naturally federated on-disk sources** — LEAF json split dirs
   (``train/*.json``; reference ``data/MNIST/data_loader.py:30-99``)
   and TFF h5 (``fed_cifar100_train.h5`` etc.; reference
   ``data/fed_cifar100/data_loader.py``) — the per-user grouping IS the
   partition, LDA is bypassed;
2. **global on-disk sources** — CIFAR python batches
   (``cifar-10-batches-py/``; reference ``cifar10/data_loader.py``) and
   the generic ``{train,test}.npz`` drop-in — LDA/homo partition
   applies;
3. synthetic stand-in with the real dataset's shapes/classes (this
   environment has no egress; the reference downloads from S3,
   ``data/MNIST/data_loader.py:17-29``), with a loud warning.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import constants
from ..core.partition import (
    homo_partition,
    non_iid_partition_with_dirichlet_distribution,
    record_data_stats,
)
from ..core.types import Batches
from .packing import bucket_num_batches, pack_clients, pack_one
from .synthetic import (
    synthetic_classification,
    synthetic_fedprox,
    synthetic_multilabel,
    synthetic_segmentation,
    synthetic_sequences,
)

_DATASET_META = {
    # name: (feature_shape, class_num, train_n, test_n, task)
    "mnist": ((28, 28, 1), 10, 60000, 10000, "classification"),
    "femnist": ((28, 28, 1), 62, 40000, 8000, "classification"),
    "fashion_mnist": ((28, 28, 1), 10, 60000, 10000, "classification"),
    "cifar10": ((32, 32, 3), 10, 50000, 10000, "classification"),
    "cifar100": ((32, 32, 3), 100, 50000, 10000, "classification"),
    "fed_cifar100": ((32, 32, 3), 100, 50000, 10000, "classification"),
    "cinic10": ((32, 32, 3), 10, 90000, 90000, "classification"),
    "shakespeare": ((80,), 90, 16000, 2000, "nwp"),
    "fed_shakespeare": ((80,), 90, 16000, 2000, "nwp"),
    "stackoverflow_nwp": ((20,), 10004, 40000, 8000, "nwp"),
    # multi-label tag prediction (reference data/stackoverflow_lr/:
    # 10k bag-of-words -> 500 tags); the synthetic stand-in shrinks the
    # feature dim so the offline path stays in memory
    "stackoverflow_lr": ((10000,), 500, 40000, 8000, "tag_prediction"),
    # image-folder / CSV-federated image benchmarks (ImageNet-style
    # class dirs; Landmarks user->image csv). Stand-in shapes keep H/W
    # modest — real copies under data_cache_dir override, resized to
    # args.image_size (default 64).
    "imagenet": ((64, 64, 3), 1000, 20000, 2000, "classification"),
    "gld23k": ((64, 64, 3), 203, 23080, 1000, "classification"),
    "gld160k": ((64, 64, 3), 2028, 164172, 1000, "classification"),
    # federated segmentation (fedseg benchmarks; stand-in shapes keep
    # H/W modest — a real copy under data_cache_dir overrides)
    "pascal_voc": ((64, 64, 3), 21, 4000, 800, "segmentation"),
    "coco_seg": ((64, 64, 3), 81, 4000, 800, "segmentation"),
    "cityscapes": ((64, 64, 3), 19, 3000, 500, "segmentation"),
    # FeTS2021 (reference data/FeTS2021/download.sh — the BraTS2018
    # multimodal brain-MRI federation, partitioned by institution):
    # 4 modality channels (T1/T1Gd/T2/FLAIR slices), 4 label classes
    # (background + 3 tumor sub-regions). Stand-in keeps H/W modest; a
    # real extracted copy under data_cache_dir/fets2021 (train/test
    # npz or image folders) overrides.
    "fets2021": ((64, 64, 4), 4, 2000, 400, "segmentation"),
}


@dataclasses.dataclass
class FederatedDataset:
    train_data_num: int
    test_data_num: int
    train_data_global: Batches
    test_data_global: Batches
    train_data_local_num_dict: Dict[int, int]
    train_data_local_dict: Dict[int, Batches]
    test_data_local_dict: Dict[int, Optional[Batches]]
    class_num: int
    # TPU-side stacked federation (client axis leading)
    packed_train: Batches = None
    packed_num_samples: np.ndarray = None
    packed_test: Optional[Batches] = None
    client_num: int = 0
    task: str = "classification"
    # vertically-partitioned source (party CSVs): ([feats_k [N,d_k]...],
    # labels [N]). The VFL scenario uses the real per-party columns as
    # the vertical split; horizontal consumers see the concatenation.
    vfl_parties: Optional[Tuple[List[np.ndarray], np.ndarray]] = None

    def to_list(self) -> List:
        """Reference 8-tuple (data_loader.py:310-320)."""
        return [
            self.train_data_num,
            self.test_data_num,
            self.train_data_global,
            self.test_data_global,
            self.train_data_local_num_dict,
            self.train_data_local_dict,
            self.test_data_local_dict,
            self.class_num,
        ]


def _try_load_real(name: str, cache_dir: str, args=None, probe: bool = False):
    """Global real data: CIFAR python batches, ImageNet-style image
    folders, else the generic {train,test}.npz drop-in.

    ``probe=True`` answers "is real data on disk?" (returns bool) using
    the SAME branches as loading — one resolution order, so a source
    added here is automatically seen by the device-synthesis gate
    (loader._device_synth_classification) and can never be shadowed by
    a stand-in."""
    d = os.path.join(cache_dir or "", name)
    if name in ("cifar10", "cifar100"):
        from .ingest import cifar_batches_available, load_cifar_batches

        if cifar_batches_available(d, name):
            return True if probe else load_cifar_batches(d, name)
    from .ingest import image_folder_available, load_image_folder

    if image_folder_available(d):
        if probe:
            return True
        hw = int(getattr(args, "image_size", 64) or 64) if args else 64
        # 5-tuple: the folder structure is authoritative for class
        # count (truncated ImageNet copies carry fewer classes)
        return load_image_folder(d, (hw, hw))
    tr, te = os.path.join(d, "train.npz"), os.path.join(d, "test.npz")
    if os.path.exists(tr) and os.path.exists(te):
        if probe:
            return True
        a, b = np.load(tr), np.load(te)
        return (a["x"], a["y"], b["x"], b["y"])
    return False if probe else None


def _try_load_federated(name: str, cache_dir: str, args=None):
    """Naturally-federated on-disk sources: LEAF json dirs, TFF h5.
    Returns per-client (xs_tr, ys_tr, xs_te, ys_te) or None."""
    if name not in _DATASET_META:
        return None
    d = os.path.join(cache_dir or "", name)
    shape, _class_num, _, _, task = _DATASET_META[name]
    from . import ingest
    from .leaf import leaf_available, load_leaf

    if cache_dir and bool(getattr(args, "download", False)):
        from .download import dataset_downloadable, download_dataset

        # a LEAF json dir only counts as a local copy for tasks that
        # actually consume it — the nwp path deliberately ignores LEAF
        # json (see below), so it must not suppress the h5 download
        has_local = ingest.tff_h5_available(d, name) or (
            task != "nwp" and leaf_available(d)
        )
        if dataset_downloadable(name) and not has_local:
            # reference parity: auto-fetch the dataset's archives
            # (data/<ds>/download*.sh; MNIST data_loader.py:17-29) —
            # with offline grace
            download_dataset(name, cache_dir)

    out = None
    if leaf_available(d):
        if task == "nwp":
            # LEAF shakespeare stores raw strings with single-char
            # targets — a different task shape than the per-token TFF
            # pipeline; use the TFF h5 artifact for nwp datasets
            logging.warning(
                "dataset %s: LEAF json found but nwp ingestion uses the "
                "TFF h5 artifact; ignoring the json dir", name,
            )
        else:
            out = load_leaf(d, feature_shape=shape)
    if out is None and ingest.tff_h5_available(d, name):
        out = ingest.load_tff_h5(d, name)
    if out is None and ingest.landmarks_csv_available(d):
        hw = int(getattr(args, "image_size", 64) or 64)
        out = ingest.load_landmarks_csv(d, (hw, hw))
    if out is None:
        return None
    xs_tr, ys_tr, xs_te, ys_te = out
    if task == "classification" and xs_tr and xs_tr[0].ndim == len(shape):
        # h5 images stored [N,H,W] (fed_emnist 'pixels') -> add channel
        xs_tr = [x.reshape(x.shape + (1,)) for x in xs_tr]
        xs_te = [x.reshape(x.shape + (1,)) for x in xs_te]
    return xs_tr, ys_tr, xs_te, ys_te



def _standin_shape_and_sizes(args, name: str):
    """Shared stand-in geometry for the host (:func:`_raw_data`) and
    device (:func:`_device_synth_classification`) synthesis paths: the
    dataset's feature shape (resized-image datasets follow
    ``args.image_size`` exactly like the real ingestion) and the
    synthetic train/test sizes with their default caps. One
    implementation, so the two paths can never drift apart for the same
    args."""
    shape, class_num, train_n, test_n, task = _DATASET_META[name]
    if name in ("imagenet", "gld23k", "gld160k"):
        hw = int(getattr(args, "image_size", 64) or 64)
        shape = (hw, hw, 3)
    if task == "nwp" and getattr(args, "seq_len", None):
        # args.seq_len drives the stand-in sequence length (real copies
        # keep their own; the model's max_len already follows args) —
        # without this the long-context path would silently train at
        # the dataset's canonical length (shakespeare: 80)
        shape = (int(args.seq_len),)
    train_n = int(getattr(args, "synthetic_train_size", min(train_n, 20000)))
    test_n = int(getattr(args, "synthetic_test_size", min(test_n, 4000)))
    return shape, class_num, train_n, test_n, task


def _device_synth_classification(
    args, name: str, client_num: int, batch_size: int, seed: int
):
    """Zero-transfer stand-in path: when a classification dataset has no
    local copy (this environment has no egress), partition host-side
    labels and synthesize the feature tensor directly on the device —
    the host->device link carries only labels + masks (KBs, vs >1 GB of
    images for a CIFAR-shaped 100-client federation through the ~5 MB/s
    tunneled TPU link). Returns a full :class:`FederatedDataset`, or
    None when the path does not apply (real data on disk, non-image
    task, non-stand-in dataset). Distribution family and the shared
    class-means convention match ``synthetic_classification``."""
    if name not in _DATASET_META:
        return None
    shape, class_num, train_n, test_n, task = _standin_shape_and_sizes(args, name)
    if task != "classification":
        return None
    if _try_load_real(name, getattr(args, "data_cache_dir", None), args, probe=True):
        return None
    logging.warning(
        "dataset %s: no local copy under data_cache_dir; using synthetic "
        "stand-in with identical shapes/classes (features generated "
        "on-device)", name,
    )
    import jax.numpy as jnp

    from .packing import pack_labels_np
    from .synthetic import synthetic_classification_device

    rng = np.random.RandomState(seed)
    y_tr = rng.randint(0, class_num, train_n).astype(np.int64)
    y_te = np.random.RandomState(seed + 1).randint(0, class_num, test_n).astype(
        np.int64
    )

    method = getattr(args, "partition_method", constants.PARTITION_HETERO)
    if method == constants.PARTITION_HOMO:
        idx_map = homo_partition(train_n, client_num, seed)
    else:
        idx_map = non_iid_partition_with_dirichlet_distribution(
            y_tr, client_num, class_num,
            float(getattr(args, "partition_alpha", 0.5)), seed=seed,
        )
        record_data_stats(y_tr, idx_map)
    ys_tr = [y_tr[idx_map[i]] for i in range(client_num)]
    te_map = homo_partition(test_n, client_num, seed + 1)
    ys_te = [y_te[te_map[i]] for i in range(client_num)]

    waste_cap = float(getattr(args, "packing_waste_cap", 4.0) or 4.0)
    x_dtype = (
        jnp.bfloat16
        if str(getattr(args, "dtype", "float32") or "float32") == "bfloat16"
        else jnp.float32
    )
    sigma = float(getattr(args, "synthetic_sigma", 1.0) or 1.0)

    def build(ys, gen_seed):
        nb = bucket_num_batches([len(y) for y in ys], batch_size, waste_cap=waste_cap)
        y_p, mask, num_samples = pack_labels_np(ys, batch_size, num_batches=nb)
        x = synthetic_classification_device(
            y_p, shape, class_num, seed=gen_seed, sigma=sigma, dtype=x_dtype
        )
        packed = Batches(
            x=x, y=jnp.asarray(y_p, jnp.int32), mask=jnp.asarray(mask)
        )
        return packed, num_samples

    packed_train, num_samples = build(ys_tr, seed)
    packed_test, test_num_samples = build(ys_te, seed + 1)

    def flat(p: Batches) -> Batches:
        # the global view is the packed federation flattened on-device:
        # exactly the packed samples (long-tail clients past the
        # waste-cap are truncated by the packer, which warns), mask
        # keeps ragged semantics exact (pads carry mask 0). No second
        # transfer, no host concat.
        C, nb = p.mask.shape[0], p.mask.shape[1]
        return Batches(
            x=p.x.reshape((C * nb,) + p.x.shape[2:]),
            y=p.y.reshape((C * nb,) + p.y.shape[2:]),
            mask=p.mask.reshape(C * nb, -1),
        )

    # counts reflect the packed federation (post-truncation), so every
    # view of this dataset object agrees with its metadata
    sizes = [int(n) for n in num_samples]
    return FederatedDataset(
        train_data_num=int(sum(sizes)),
        test_data_num=int(test_num_samples.sum()),
        train_data_global=flat(packed_train),
        test_data_global=flat(packed_test),
        train_data_local_num_dict={i: int(s) for i, s in enumerate(sizes)},
        train_data_local_dict={
            i: _client_view(packed_train, i) for i in range(client_num)
        },
        test_data_local_dict={
            i: _client_view(packed_test, i) for i in range(client_num)
        },
        class_num=class_num,
        packed_train=packed_train,
        packed_num_samples=np.asarray(num_samples),
        packed_test=packed_test,
        client_num=client_num,
        task=task,
    )


def _resolve_poisoned_idxs(args, client_num: int, seed: int):
    """Which client indexes are attackers: an explicit
    ``poisoned_client_idxs`` list wins; else ``poisoned_client_fraction``
    of the federation, drawn with a seed-derived RandomState (the
    fedavg_robust convention — attacker identity is part of the
    experiment config, never of the run's training randomness)."""
    idxs = getattr(args, "poisoned_client_idxs", None)
    if idxs:
        # USER ORDER preserved: a poison_type LIST pairs with these
        # 1:1 positionally, so sorting/deduping here would silently
        # swap attacks between clients
        out = [int(i) for i in idxs]
        if len(set(out)) != len(out):
            raise ValueError(
                f"poisoned_client_idxs {out} contains duplicates"
            )
        bad = [i for i in out if not 0 <= i < client_num]
        if bad:
            raise ValueError(
                f"poisoned_client_idxs {bad} out of range for "
                f"{client_num} clients"
            )
        return out
    frac = float(getattr(args, "poisoned_client_fraction", 0.0) or 0.0)
    if frac <= 0:
        return []
    k = min(client_num, max(1, int(round(frac * client_num))))
    return sorted(
        np.random.RandomState(seed + 77)
        .choice(client_num, k, replace=False)
        .tolist()
    )


def _maybe_poison_clients(args, xs_tr, ys_tr, class_num: int, seed: int, task: str):
    """Poisoned-world wiring (``args.poison_type`` — the reference
    fork's fedavg_robust experiment shape): apply ``data/poison.py``
    attacks to the configured attacker clients' TRAIN shards before
    packing. ``poison_type`` is one type for every attacker or a list
    paired with ``poisoned_client_idxs`` (mixed-attack worlds, e.g.
    label_flip + backdoor_pattern). Loud by design: a poisoned world
    always logs who is poisoned with what."""
    ptype = getattr(args, "poison_type", None) or None
    if ptype is None:
        return xs_tr, ys_tr
    if task != "classification":
        raise ValueError(
            f"poison_type={ptype!r} supports classification datasets "
            f"only (got task={task!r})"
        )
    target = int(getattr(args, "target_label", 0) or 0)
    if not 0 <= target < class_num:
        # an out-of-head target would one_hot to an all-zero row and
        # train the attackers on garbage SILENTLY — a different
        # experiment than the config claims
        raise ValueError(
            f"target_label={target} out of range for {class_num} classes"
        )
    from .poison import poison_clients

    if isinstance(ptype, (list, tuple)) and not getattr(
        args, "poisoned_client_idxs", None
    ):
        # a list pairs 1:1 positionally; zipping it against a
        # fraction-drawn (seed-dependent, sorted) attacker set would
        # assign attacks to arbitrary clients silently
        raise ValueError(
            "poison_type as a list pairs 1:1 with poisoned_client_idxs; "
            "set the idxs explicitly (poisoned_client_fraction draws an "
            "arbitrary attacker set)"
        )
    client_num = len(xs_tr)
    idxs = _resolve_poisoned_idxs(args, client_num, seed)
    if not idxs:
        raise ValueError(
            "poison_type is set but no attacker clients are configured; "
            "set poisoned_client_idxs or poisoned_client_fraction"
        )
    xs_tr, ys_tr, _ = poison_clients(
        xs_tr, ys_tr, ptype, class_num, idxs,
        target_label=target,
        fraction=float(getattr(args, "poison_sample_fraction", 1.0) or 1.0),
        data_cache_dir=getattr(args, "data_cache_dir", None),
    )
    logging.warning(
        "POISONED WORLD: clients %s carry %s (target_label=%s)",
        idxs, ptype, target,
    )
    return xs_tr, ys_tr


def _widen_class_num(name: str, class_num: int, observed: int) -> int:
    """Custom/truncated on-disk copies may carry ids beyond the
    canonical class count; widen the head rather than training silently
    degenerate one-hots."""
    if observed > class_num:
        logging.warning(
            "dataset %s: observed class id %d >= canonical class count "
            "%d; widening to %d", name, observed - 1, class_num, observed,
        )
        return observed
    return class_num


def _raw_data(args) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, int, str]:
    name = getattr(args, "dataset", "synthetic").lower()
    seed = int(getattr(args, "random_seed", 0))
    if name.startswith("synthetic"):
        # FedProx synthetic(alpha,beta): natively federated — handled by caller
        raise RuntimeError("synthetic handled separately")
    if name not in _DATASET_META:
        raise ValueError(f"unknown dataset {name!r}")
    shape, class_num, train_n, test_n, task = _standin_shape_and_sizes(args, name)
    real = _try_load_real(name, getattr(args, "data_cache_dir", None), args)
    if real is not None:
        if len(real) == 5:  # loader knows its own class count
            x_tr, y_tr, x_te, y_te, class_num = real
        else:
            x_tr, y_tr, x_te, y_te = real
        return x_tr, y_tr, x_te, y_te, class_num, task
    logging.warning(
        "dataset %s: no local copy under data_cache_dir; using synthetic "
        "stand-in with identical shapes/classes",
        name,
    )
    if task == "nwp":
        seq_len, vocab = shape[0], class_num
        x_tr, y_tr = synthetic_sequences(train_n, seq_len, vocab, seed)
        x_te, y_te = synthetic_sequences(test_n, seq_len, vocab, seed + 1)
    elif task == "tag_prediction":
        dim = int(getattr(args, "synthetic_feature_dim", 2000))
        x_tr, y_tr = synthetic_multilabel(train_n, class_num, (dim,), seed)
        x_te, y_te = synthetic_multilabel(test_n, class_num, (dim,), seed + 1)
    elif task == "segmentation":
        x_tr, y_tr = synthetic_segmentation(train_n, class_num, shape, seed)
        x_te, y_te = synthetic_segmentation(test_n, class_num, shape, seed + 1)
    else:
        x_tr, y_tr = synthetic_classification(train_n, class_num, shape, seed)
        x_te, y_te = synthetic_classification(test_n, class_num, shape, seed + 1)
    return x_tr, y_tr, x_te, y_te, class_num, task


def _registry_dataset(args) -> FederatedDataset:
    """Slim dataset for the planet-scale registry path
    (``fedml_tpu/scale/``): the population is NOT materialized here —
    no per-client arrays, no packed federation, no local dicts
    proportional to ``client_registry_size``. Cohort data is generated
    on demand by the registry each round; this object carries only the
    task geometry (class count, feature shape via the eval packs) and
    fixed-size global eval holdouts."""
    name = getattr(args, "dataset", "synthetic").lower()
    seed = int(getattr(args, "random_seed", 0))
    registry_size = int(args.client_registry_size)
    if getattr(args, "poison_type", None):
        raise ValueError(
            "poison_type is not supported with client_registry_size: "
            "registry cohorts synthesize data on demand and the "
            "attacks mutate eagerly-materialized shards"
        )
    if name.startswith("synthetic"):
        shape = (int(getattr(args, "input_dim", 60)),)
        class_num = int(getattr(args, "output_dim", 10))
    else:
        if name not in _DATASET_META:
            raise ValueError(f"unknown dataset {name!r}")
        shape, class_num, _, _, task = _standin_shape_and_sizes(args, name)
        if task != "classification":
            raise ValueError(
                f"client_registry_size supports classification datasets "
                f"only (dataset {name!r} is task={task!r})"
            )
    # fixed-size eval holdouts (a registry run's eval cost must not
    # scale with the population); synthetic_*_size caps still win down
    train_n = min(int(getattr(args, "synthetic_train_size", 4096)), 4096)
    test_n = min(int(getattr(args, "synthetic_test_size", 2048)), 2048)
    sigma = float(getattr(args, "synthetic_sigma", 1.0) or 1.0)
    x_tr, y_tr = synthetic_classification(
        train_n, class_num, shape, seed=seed + 3, sigma=sigma
    )
    x_te, y_te = synthetic_classification(
        test_n, class_num, shape, seed=seed + 4, sigma=sigma
    )
    import jax.numpy as jnp

    x_dtype = (
        jnp.bfloat16
        if str(getattr(args, "dtype", "float32") or "float32") == "bfloat16"
        else jnp.float32
    )
    batch_size = int(args.batch_size)
    logging.warning(
        "dataset %s: client_registry_size=%d active — population lives "
        "as columnar registry state, per-round cohorts are materialized "
        "on demand; this dataset object carries eval holdouts only",
        name, registry_size,
    )
    return FederatedDataset(
        train_data_num=train_n,
        test_data_num=test_n,
        train_data_global=pack_one(x_tr, y_tr, batch_size, x_dtype=x_dtype),
        test_data_global=pack_one(x_te, y_te, batch_size, x_dtype=x_dtype),
        train_data_local_num_dict={},
        train_data_local_dict={},
        test_data_local_dict={},
        class_num=class_num,
        packed_train=None,
        packed_num_samples=None,
        packed_test=None,
        client_num=registry_size,
        task="classification",
    )


def load(args) -> FederatedDataset:
    """Load + partition + pack (data_loader.py:29 entry)."""
    name = getattr(args, "dataset", "synthetic").lower()
    if int(getattr(args, "client_registry_size", 0) or 0) > 0:
        # planet-scale registry (fedml_tpu/scale/): NEVER build
        # per-client lists/arrays proportional to the registered
        # population — cohorts materialize on demand each round
        return _registry_dataset(args)
    client_num = int(args.client_num_in_total)
    batch_size = int(args.batch_size)
    seed = int(getattr(args, "random_seed", 0))

    # vertically-partitioned party CSVs (NUS-WIDE / lending-club style)
    # take priority for ANY dataset name — the files define the data
    cache = getattr(args, "data_cache_dir", None)
    if cache:
        from .ingest import vfl_party_csvs_available

        vfl_dir = os.path.join(cache, name)
        if vfl_party_csvs_available(vfl_dir):
            if getattr(args, "poison_type", None):
                # loud-by-design: the data/poison.py attacks mutate
                # horizontal per-client label/feature shards, which a
                # vertical party split does not have — ignoring the
                # knob would claim a poisoned world and train clean
                raise ValueError(
                    f"poison_type={args.poison_type!r} is not supported "
                    f"for VFL party-CSV datasets (found {vfl_dir!r})"
                )
            return _load_vfl_dataset(args, vfl_dir, client_num, batch_size, seed)

    if name.startswith("synthetic"):
        xs, ys = synthetic_fedprox(
            num_clients=client_num,
            alpha=float(getattr(args, "synthetic_alpha", 1.0)),
            beta=float(getattr(args, "synthetic_beta", 1.0)),
            input_dim=int(getattr(args, "input_dim", 60)),
            num_classes=int(getattr(args, "output_dim", 10)),
            seed=seed,
        )
        class_num = int(getattr(args, "output_dim", 10))
        task = "classification"
        # 80/20 split per client
        xs_tr, ys_tr, xs_te, ys_te = [], [], [], []
        for x, y in zip(xs, ys):
            k = max(1, int(0.8 * len(x)))
            xs_tr.append(x[:k]); ys_tr.append(y[:k])
            xs_te.append(x[k:]); ys_te.append(y[k:])
    elif (
        fed := _try_load_federated(name, getattr(args, "data_cache_dir", None), args)
    ) is not None:
        # naturally federated: the on-disk per-user split IS the
        # partition (no LDA). Fold users onto the requested client
        # count; cap the config when it asks for more clients than the
        # dataset has users.
        from .ingest import regroup_clients

        _, class_num, _, _, task = _DATASET_META[name]
        xs_tr, ys_tr, xs_te, ys_te = fed
        if task == "tag_prediction" and xs_tr:
            # model factory sizes the input layer off args (real copies
            # may differ from the synthetic stand-in's bow dim)
            args.input_dim = int(xs_tr[0].shape[-1])
        n_users = len(xs_tr)
        if client_num > n_users:
            logging.warning(
                "dataset %s has %d users < client_num_in_total=%d; capping",
                name, n_users, client_num,
            )
            client_num = n_users
            args.client_num_in_total = n_users
            args.client_num_per_round = min(int(args.client_num_per_round), n_users)
        xs_tr, ys_tr = regroup_clients(xs_tr, ys_tr, client_num)
        xs_te, ys_te = regroup_clients(xs_te, ys_te, client_num)
        if task == "classification":
            observed = (
                max((int(y.max()) for y in ys_tr + ys_te if len(y)), default=-1)
                + 1
            )
            class_num = _widen_class_num(name, class_num, observed)
    else:
        # a poisoned world needs host-side feature arrays (trigger
        # stamps / edge-case injection mutate x), so the zero-transfer
        # device-synth shortcut does not apply
        dev_ds = (
            None
            if getattr(args, "poison_type", None)
            else _device_synth_classification(
                args, name, client_num, batch_size, seed
            )
        )
        if dev_ds is not None:
            return dev_ds
        x_tr, y_tr, x_te, y_te, class_num, task = _raw_data(args)
        if task == "classification":
            observed = int(max(y_tr.max(initial=-1), y_te.max(initial=-1))) + 1
            class_num = _widen_class_num(name, class_num, observed)
        if task == "tag_prediction":
            # model factory sizes the input layer off args (the bow dim
            # differs between real data and the synthetic stand-in)
            args.input_dim = int(x_tr.shape[-1])
        method = getattr(args, "partition_method", constants.PARTITION_HETERO)
        if method == constants.PARTITION_HOMO:
            idx_map = homo_partition(len(y_tr), client_num, seed)
            part_labels = None
        elif task == "tag_prediction":
            # multi-hot labels: LDA partitions on each sample's
            # dominant tag (the reference's stackoverflow split is
            # naturally federated; this applies to synthetic/npz data)
            part_labels = np.argmax(y_tr, axis=-1)
            idx_map = non_iid_partition_with_dirichlet_distribution(
                part_labels, client_num, class_num,
                float(getattr(args, "partition_alpha", 0.5)), seed=seed,
            )
        elif task == "segmentation":
            # multi-label LDA (the partitioner's fedseg branch): per
            # foreground class, the index array of images containing it;
            # void labels (>= class_num, e.g. 255) excluded
            flat = y_tr.reshape(len(y_tr), -1)
            per_class = [
                np.where([(row == k).any() for row in flat])[0]
                for k in range(class_num)
            ]
            idx_map = non_iid_partition_with_dirichlet_distribution(
                per_class, client_num, class_num,
                float(getattr(args, "partition_alpha", 0.5)),
                task="segmentation", seed=seed,
            )
            # the same image can carry several classes -> dedupe per client
            idx_map = {i: np.unique(v) for i, v in idx_map.items()}
            part_labels = None
        else:
            part_labels = y_tr
            idx_map = non_iid_partition_with_dirichlet_distribution(
                part_labels, client_num, class_num,
                float(getattr(args, "partition_alpha", 0.5)), seed=seed,
            )
        if part_labels is not None:
            record_data_stats(part_labels, idx_map)
        xs_tr = [x_tr[idx_map[i]] for i in range(client_num)]
        ys_tr = [y_tr[idx_map[i]] for i in range(client_num)]
        # test side: shard uniformly (reference gives each client a
        # local test loader over the global test set slice)
        te_map = homo_partition(len(y_te), client_num, seed + 1)
        xs_te = [x_te[te_map[i]] for i in range(client_num)]
        ys_te = [y_te[te_map[i]] for i in range(client_num)]

    # poisoning applies AFTER partitioning (attacks are per-client) and
    # BEFORE packing, so every downstream view — packed federation,
    # global eval set slices, local dicts — sees the attacker's data
    xs_tr, ys_tr = _maybe_poison_clients(args, xs_tr, ys_tr, class_num, seed, task)

    import jax.numpy as jnp

    # float features follow args.dtype, matching the device-synth path
    # (_device_synth_classification) so stand-in and real-data runs of
    # the same config see identical input precision (advisor r4)
    if task == "nwp":
        x_dtype = jnp.int32
    elif str(getattr(args, "dtype", "float32") or "float32") == "bfloat16":
        x_dtype = jnp.bfloat16
    else:
        x_dtype = jnp.float32

    waste_cap = float(getattr(args, "packing_waste_cap", 4.0) or 4.0)
    sizes = [len(x) for x in xs_tr]
    nb = bucket_num_batches(sizes, batch_size, waste_cap=waste_cap)
    packed_train, num_samples = pack_clients(
        xs_tr, ys_tr, batch_size, num_batches=nb, x_dtype=x_dtype
    )
    nb_te = bucket_num_batches([len(x) for x in xs_te], batch_size, waste_cap=waste_cap)
    packed_test, _ = pack_clients(
        xs_te, ys_te, batch_size, num_batches=nb_te, x_dtype=x_dtype
    )

    x_tr_all = np.concatenate(xs_tr)
    y_tr_all = np.concatenate(ys_tr)
    x_te_all = np.concatenate(xs_te)
    y_te_all = np.concatenate(ys_te)
    train_global = pack_one(x_tr_all, y_tr_all, batch_size, x_dtype=x_dtype)
    test_global = pack_one(x_te_all, y_te_all, batch_size, x_dtype=x_dtype)

    local_train = {i: _client_view(packed_train, i) for i in range(client_num)}
    local_test = {i: _client_view(packed_test, i) for i in range(client_num)}

    return FederatedDataset(
        train_data_num=int(sum(sizes)),
        test_data_num=int(len(y_te_all)),
        train_data_global=train_global,
        test_data_global=test_global,
        train_data_local_num_dict={i: int(s) for i, s in enumerate(sizes)},
        train_data_local_dict=local_train,
        test_data_local_dict=local_test,
        class_num=class_num,
        packed_train=packed_train,
        packed_num_samples=np.asarray(num_samples),
        packed_test=packed_test,
        client_num=client_num,
        task=task,
    )


def _client_view(stacked: Batches, i: int) -> Batches:
    return Batches(x=stacked.x[i], y=stacked.y[i], mask=stacked.mask[i])


def _load_vfl_dataset(
    args, vfl_dir: str, client_num: int, batch_size: int, seed: int
) -> FederatedDataset:
    """Party CSVs -> FederatedDataset. The per-party arrays ride on
    ``vfl_parties`` for the VFL scenario; horizontal consumers get the
    column-concatenated features (homo partition — vertical data has no
    per-client label skew by construction)."""
    from .ingest import load_vfl_party_csvs, vfl_train_test_split

    feats, labels = load_vfl_party_csvs(vfl_dir)
    class_num = int(labels.max()) + 1
    f_tr, y_tr, f_te, y_te = vfl_train_test_split(feats, labels, seed)
    x_tr = np.concatenate([f.reshape(len(f), -1) for f in f_tr], axis=1)
    x_te = np.concatenate([f.reshape(len(f), -1) for f in f_te], axis=1)
    args.input_dim = int(x_tr.shape[1])

    idx_map = homo_partition(len(y_tr), client_num, seed)
    te_map = homo_partition(len(y_te), client_num, seed + 1)
    xs_tr = [x_tr[idx_map[i]] for i in range(client_num)]
    ys_tr = [y_tr[idx_map[i]] for i in range(client_num)]
    xs_te = [x_te[te_map[i]] for i in range(client_num)]
    ys_te = [y_te[te_map[i]] for i in range(client_num)]

    import jax.numpy as jnp

    sizes = [len(x) for x in xs_tr]
    nb = bucket_num_batches(sizes, batch_size)
    packed_train, num_samples = pack_clients(xs_tr, ys_tr, batch_size, num_batches=nb)
    nb_te = bucket_num_batches([len(x) for x in xs_te], batch_size)
    packed_test, _ = pack_clients(xs_te, ys_te, batch_size, num_batches=nb_te)
    train_global = pack_one(x_tr, y_tr, batch_size)
    test_global = pack_one(x_te, y_te, batch_size)
    return FederatedDataset(
        train_data_num=int(len(y_tr)),
        test_data_num=int(len(y_te)),
        train_data_global=train_global,
        test_data_global=test_global,
        train_data_local_num_dict={i: int(s) for i, s in enumerate(sizes)},
        train_data_local_dict={
            i: _client_view(packed_train, i) for i in range(client_num)
        },
        test_data_local_dict={
            i: _client_view(packed_test, i) for i in range(client_num)
        },
        class_num=class_num,
        packed_train=packed_train,
        packed_num_samples=np.asarray(num_samples),
        packed_test=packed_test,
        client_num=client_num,
        task="classification",
        vfl_parties=(feats, labels),
    )
