"""Data layer: ``fedml_tpu.data.load(args)``."""

from .loader import FederatedDataset, load  # noqa: F401
