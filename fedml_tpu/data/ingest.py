"""Real on-disk dataset ingestion: TFF h5, CIFAR binary batches.

Reference loaders this replaces (same on-disk formats, converted into
the packed-federation layout instead of torch DataLoaders):

- TFF h5 (``data/fed_cifar100/data_loader.py``, ``data/fed_shakespeare/
  data_loader.py``): one h5 file per split, group ``examples`` ->
  per-client-id group -> datasets ``image``/``label`` (fed_cifar100) or
  ``snippets`` (fed_shakespeare). These are NATURALLY federated — the
  per-client grouping IS the partition, so LDA is bypassed.
- CIFAR python batches (``data/cifar10/data_loader.py:106-120`` via
  torchvision's unpickling): ``cifar-10-batches-py/data_batch_{1..5}``
  + ``test_batch`` dicts with ``data`` [N,3072] uint8 and ``labels``;
  cifar-100 ships ``train``/``test`` with ``fine_labels``. Global
  arrays -> the standard LDA partition applies.

Deviations by design: the reference's random crop/flip augmentation
(``fed_cifar100/utils.py``) is a per-step training-time op, not an
ingestion op — here ingestion produces deterministic [0,1]-scaled
tensors and augmentation belongs in the (jitted) training pipeline.

Shakespeare preprocessing follows the TFF recipe the reference follows
(``fed_shakespeare/utils.py``: BOS + chars + EOS, pad to a multiple of
SEQ_LEN+1, split into windows; x = w[:-1], y = w[1:]).
"""

from __future__ import annotations

import logging
import os
import pickle
from typing import Dict, List, Optional, Tuple

import numpy as np

SHAKESPEARE_SEQ_LEN = 80
# TFF character vocabulary (fed_shakespeare/utils.py CHAR_VOCAB); ids:
# 0 = pad, 1..86 = chars, 87 = bos, 88 = eos, 89 = oov -> vocab 90
_CHAR_VOCAB = list(
    "dhlptx@DHLPTX $(,048cgkoswCGKOSW[_#'/37;?bfjnrvzBFJNRVZ\"&*.26:\naeimquyAEIMQUY]!%)-159\r"
)
_CHAR_TO_ID = {c: i + 1 for i, c in enumerate(_CHAR_VOCAB)}
_BOS = len(_CHAR_VOCAB) + 1
_EOS = len(_CHAR_VOCAB) + 2
_OOV = len(_CHAR_VOCAB) + 3
SHAKESPEARE_VOCAB = _OOV + 1  # 90


def shakespeare_to_sequences(snippets: List[str]) -> Tuple[np.ndarray, np.ndarray]:
    """Snippet strings -> (x [N,80] int32, y [N,80] int32)."""
    win = SHAKESPEARE_SEQ_LEN + 1
    windows: List[List[int]] = []
    for s in snippets:
        toks = [_BOS] + [_CHAR_TO_ID.get(c, _OOV) for c in s] + [_EOS]
        pad = (-len(toks)) % win
        toks = toks + [0] * pad
        windows.extend(toks[i : i + win] for i in range(0, len(toks), win))
    if not windows:
        e = np.zeros((0, SHAKESPEARE_SEQ_LEN), np.int32)
        return e, e.copy()
    arr = np.asarray(windows, dtype=np.int32)
    return arr[:, :-1], arr[:, 1:]


# -- stackoverflow (TFF h5 + side vocab files) ------------------------
#
# Reference: data/stackoverflow_nwp/{utils,dataset}.py and
# data/stackoverflow_lr/{utils,dataset}.py. Both tasks read the same
# stackoverflow_{train,test}.h5 (group examples/<client>/ with string
# datasets ``tokens``, ``title``, ``tags``) plus two side files in the
# data dir: ``stackoverflow.word_count`` (text lines "word count"; top
# 10000 words are the vocabulary) and ``stackoverflow.tag_count`` (JSON
# ordered dict; first 500 keys are the label tags).

SO_SEQ_LEN = 20  # stackoverflow_nwp/utils.py tokenizer max_seq_len
SO_VOCAB_WORDS = 10000
SO_TAG_COUNT = 500


def load_so_word_vocab(data_dir: str, vocab_size: int = SO_VOCAB_WORDS) -> List[str]:
    """Top-``vocab_size`` words from ``stackoverflow.word_count``
    (stackoverflow_nwp/utils.py get_most_frequent_words)."""
    path = os.path.join(data_dir, "stackoverflow.word_count")
    words: List[str] = []
    with open(path) as f:
        for line in f:
            if len(words) >= vocab_size:
                break
            parts = line.split()
            if parts:
                words.append(parts[0])
    return words


def load_so_tag_vocab(data_dir: str, tag_size: int = SO_TAG_COUNT) -> List[str]:
    """First ``tag_size`` tags from ``stackoverflow.tag_count``
    (stackoverflow_lr/utils.py get_tags; insertion-ordered JSON)."""
    import json

    path = os.path.join(data_dir, "stackoverflow.tag_count")
    with open(path) as f:
        return list(json.load(f).keys())[:tag_size]


def so_nwp_to_sequences(
    sentences: List[str], words: List[str], word_id: Optional[Dict] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Sentences -> (x [N,20], y [N,20]) next-word-prediction pairs.

    Token ids follow stackoverflow_nwp/utils.py exactly: pad=0, words
    1..V, bos=V+1, eos=V+2, oov=V+3 (one OOV bucket); each sentence is
    truncated to 20 words, gets EOS only if shorter, BOS prepended,
    padded to 21; x = w[:-1], y = w[1:]. Pass a precomputed ``word_id``
    ({word: id starting at 1}) when calling per-client — the real
    dataset has 342k clients and a fresh 10k-entry dict per call is
    pure waste."""
    if word_id is None:
        word_id = {w: i + 1 for i, w in enumerate(words)}
    bos, eos, oov = len(words) + 1, len(words) + 2, len(words) + 3
    win = SO_SEQ_LEN + 1
    seqs: List[List[int]] = []
    for s in sentences:
        toks = [word_id.get(t, oov) for t in s.split(" ")[:SO_SEQ_LEN]]
        if len(toks) < SO_SEQ_LEN:
            toks.append(eos)
        toks = [bos] + toks
        toks += [0] * (win - len(toks))
        seqs.append(toks)
    if not seqs:
        e = np.zeros((0, SO_SEQ_LEN), np.int32)
        return e, e.copy()
    arr = np.asarray(seqs, np.int32)
    return arr[:, :-1], arr[:, 1:]


def so_lr_features(
    sentences: List[str], words: List[str], word_id: Optional[Dict] = None
) -> np.ndarray:
    """tokens+title strings -> mean bag-of-words [N, V] over the word
    vocabulary (stackoverflow_lr/utils.py preprocess_inputs: the OOV
    bucket participates in the mean but is sliced off). ``word_id``
    ({word: 0-based id}) as in :func:`so_nwp_to_sequences`."""
    if word_id is None:
        word_id = {w: i for i, w in enumerate(words)}
    v = len(words)
    out = np.zeros((len(sentences), v), np.float32)
    for n, s in enumerate(sentences):
        toks = s.split(" ")
        if not toks:
            continue
        for t in toks:
            i = word_id.get(t)
            if i is not None:
                out[n, i] += 1.0
        out[n] /= float(len(toks))
    return out


def so_lr_targets(
    tag_strs: List[str], tags: List[str], tag_id: Optional[Dict] = None
) -> np.ndarray:
    """'|'-joined tag strings -> multi-hot [N, T]
    (stackoverflow_lr/utils.py preprocess_targets; the reference emits
    raw per-tag counts incl. an OOV bucket — here clipped to {0,1} over
    the T label tags, which is what its 500-way sigmoid head consumes)."""
    if tag_id is None:
        tag_id = {t: i for i, t in enumerate(tags)}
    out = np.zeros((len(tag_strs), len(tags)), np.float32)
    for n, ts in enumerate(tag_strs):
        for t in ts.split("|"):
            i = tag_id.get(t)
            if i is not None:
                out[n, i] = 1.0
    return out


def _so_examples_group(f):
    # canonical TFF layout uses "examples"; the reference's reader keys
    # on "examples.md" (stackoverflow_nwp/dataset.py:21) — accept both
    for key in ("examples", "examples.md"):
        if key in f:
            return f[key]
    raise KeyError("no 'examples' group in stackoverflow h5")


def _read_stackoverflow_split(
    path: str, task: str, words: List[str], tags: List[str]
):
    """One stackoverflow h5 split -> (client_ids, xs, ys)."""
    import h5py

    def dec(v) -> str:
        return v.decode("utf8") if isinstance(v, bytes) else str(v)

    # id maps built ONCE, not per client (342k clients on the real set)
    if task == "nwp":
        word_id = {w: i + 1 for i, w in enumerate(words)}
    else:
        word_id = {w: i for i, w in enumerate(words)}
        tag_id = {t: i for i, t in enumerate(tags)}
    ids, xs, ys = [], [], []
    with h5py.File(path, "r") as f:
        examples = _so_examples_group(f)
        for cid in sorted(examples.keys()):
            g = examples[cid]
            toks = [dec(s) for s in g["tokens"][()]]
            if task == "nwp":
                x, y = so_nwp_to_sequences(toks, words, word_id)
            else:
                titles = [dec(s) for s in g["title"][()]]
                sents = [" ".join([t, ti]) for t, ti in zip(toks, titles)]
                x = so_lr_features(sents, words, word_id)
                y = so_lr_targets(
                    [dec(s) for s in g["tags"][()]], tags, tag_id
                )
            ids.append(cid)
            xs.append(x)
            ys.append(y)
    return ids, xs, ys


def _h5_split_path(data_dir: str, candidates: List[str]) -> Optional[str]:
    for name in candidates:
        p = os.path.join(data_dir, name)
        if os.path.exists(p):
            return p
    return None


def _read_tff_split(path: str, image_key: str):
    """One TFF h5 split -> (client_ids, xs, ys) with per-client arrays."""
    import h5py

    xs, ys, ids = [], [], []
    with h5py.File(path, "r") as f:
        examples = f["examples"]
        for cid in sorted(examples.keys()):
            g = examples[cid]
            if image_key == "snippets":
                snippets = [
                    s.decode("utf8") if isinstance(s, bytes) else str(s)
                    for s in g["snippets"][()]
                ]
                x, y = shakespeare_to_sequences(snippets)
            else:
                x = np.asarray(g[image_key][()], dtype=np.float32) / 255.0
                y = np.asarray(g["label"][()]).reshape(-1).astype(np.int64)
            ids.append(cid)
            xs.append(x)
            ys.append(y)
    return ids, xs, ys


def tff_h5_available(data_dir: str, dataset: str) -> bool:
    return _h5_split_path(data_dir, _tff_names(dataset, "train")) is not None


def _tff_names(dataset: str, split: str) -> List[str]:
    # canonical TFF artifact names (reference DEFAULT_TRAIN_FILE) plus
    # the <dataset>_<split>.h5 convention
    names = [f"{dataset}_{split}.h5"]
    if dataset == "fed_shakespeare":
        names.append(f"shakespeare_{split}.h5")
    if dataset == "fed_cifar100":
        names.append(f"fed_cifar100_{split}.h5")
    if dataset == "fed_emnist" or dataset == "femnist":
        names.append(f"fed_emnist_{split}.h5")
    if dataset.startswith("stackoverflow"):
        # both SO tasks read the same artifact (reference
        # stackoverflow_nwp/data_loader.py DEFAULT_TRAIN_FILE)
        names.append(f"stackoverflow_{split}.h5")
    return names


def load_tff_h5(
    data_dir: str, dataset: str
) -> Tuple[List[np.ndarray], List[np.ndarray], List[np.ndarray], List[np.ndarray]]:
    """TFF h5 train/test -> per-client arrays (naturally federated).

    Train clients define the federation (reference: train/test client
    id sets differ in size, fed_cifar100 500/100); a train client with
    no test group gets an empty test set."""
    train_path = _h5_split_path(data_dir, _tff_names(dataset, "train"))
    test_path = _h5_split_path(data_dir, _tff_names(dataset, "test"))
    if train_path is None:
        raise FileNotFoundError(f"no TFF h5 train split for {dataset} in {data_dir}")
    if dataset.startswith("stackoverflow"):
        task = "nwp" if dataset.endswith("nwp") else "lr"
        words = load_so_word_vocab(data_dir)
        tags = load_so_tag_vocab(data_dir) if task == "lr" else []
        read = lambda p: _read_stackoverflow_split(p, task, words, tags)
    else:
        image_key = "snippets" if "shakespeare" in dataset else (
            "pixels" if "emnist" in dataset else "image"
        )
        read = lambda p: _read_tff_split(p, image_key)
    ids, xs_tr, ys_tr = read(train_path)
    test_map = {}
    if test_path is not None:
        te_ids, xs_te, ys_te = read(test_path)
        test_map = {c: (x, y) for c, x, y in zip(te_ids, xs_te, ys_te)}
    xs_te_out, ys_te_out = [], []
    for cid, x, y0 in zip(ids, xs_tr, ys_tr):
        if cid in test_map:
            xt, yt = test_map[cid]
        else:
            xt = np.zeros((0,) + x.shape[1:], x.dtype)
            yt = np.zeros((0,) + y0.shape[1:], y0.dtype)
        xs_te_out.append(xt)
        ys_te_out.append(yt)
    logging.info(
        "TFF h5 %s: %d clients, %d train samples",
        dataset, len(ids), sum(len(x) for x in xs_tr),
    )
    return xs_tr, ys_tr, xs_te_out, ys_te_out


# -- CIFAR python batches ---------------------------------------------


def _cifar_dir(data_dir: str, dataset: str) -> Optional[str]:
    sub = "cifar-10-batches-py" if dataset == "cifar10" else "cifar-100-python"
    for d in (os.path.join(data_dir, sub), data_dir):
        probe = "data_batch_1" if dataset == "cifar10" else "train"
        if os.path.isfile(os.path.join(d, probe)):
            return d
    return None


def cifar_batches_available(data_dir: str, dataset: str) -> bool:
    return _cifar_dir(data_dir, dataset) is not None


def _unpickle(path: str) -> dict:
    # the canonical CIFAR distribution is python-pickled (the reference
    # unpickles via torchvision); trusted local dataset files only
    with open(path, "rb") as f:
        return pickle.load(f, encoding="bytes")


def _batch_arrays(blob: dict, label_key: bytes) -> Tuple[np.ndarray, np.ndarray]:
    data = np.asarray(blob[b"data"], dtype=np.uint8)
    x = data.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)  # NHWC
    y = np.asarray(blob[label_key], dtype=np.int64)
    return x, y


def load_cifar_batches(
    data_dir: str, dataset: str
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """CIFAR-10/100 python batches -> global arrays (x in [0,1] NHWC).

    Format parity: ``cifar10/data_loader.py:106-120`` (via torchvision
    CIFAR10's unpickling of data_batch_1..5 / test_batch)."""
    d = _cifar_dir(data_dir, dataset)
    if d is None:
        raise FileNotFoundError(f"no CIFAR batches for {dataset} in {data_dir}")
    if dataset == "cifar10":
        label_key = b"labels"
        train_files = [f"data_batch_{i}" for i in range(1, 6)]
        train_files = [f for f in train_files if os.path.isfile(os.path.join(d, f))]
        test_files = ["test_batch"]
    else:
        label_key = b"fine_labels"
        train_files = ["train"]
        test_files = ["test"]
    test_files = [f for f in test_files if os.path.isfile(os.path.join(d, f))]
    if not train_files or not test_files:
        raise FileNotFoundError(
            f"partial CIFAR copy in {d}: need train batches AND the test "
            f"file (have train={train_files}, test={test_files})"
        )
    xs, ys = zip(*(_batch_arrays(_unpickle(os.path.join(d, f)), label_key)
                   for f in train_files))
    x_tr = np.concatenate(xs).astype(np.float32) / 255.0
    y_tr = np.concatenate(ys)
    xt, yt = zip(*(_batch_arrays(_unpickle(os.path.join(d, f)), label_key)
                   for f in test_files))
    x_te = np.concatenate(xt).astype(np.float32) / 255.0
    y_te = np.concatenate(yt)
    logging.info(
        "CIFAR batches %s: %d train / %d test", dataset, len(y_tr), len(y_te)
    )
    return x_tr, y_tr, x_te, y_te


def regroup_clients(
    xs: List[np.ndarray], ys: List[np.ndarray], n: int
) -> Tuple[List[np.ndarray], List[np.ndarray]]:
    """Fold a naturally-federated user list onto n logical clients
    (round-robin merge), for configs asking for fewer clients than the
    dataset has users — the reference maps users 1:1 and asserts; this
    keeps any n <= len(xs) runnable without discarding users."""
    if n >= len(xs):
        return xs, ys
    out_x: List[List[np.ndarray]] = [[] for _ in range(n)]
    out_y: List[List[np.ndarray]] = [[] for _ in range(n)]
    for i, (x, y) in enumerate(zip(xs, ys)):
        out_x[i % n].append(x)
        out_y[i % n].append(y)
    return (
        [np.concatenate(b) for b in out_x],
        [np.concatenate(b) for b in out_y],
    )


# -- image-folder (ImageNet-style) and Landmarks CSV ------------------


def _decode_image(path: str, hw: Tuple[int, int]) -> np.ndarray:
    """Decode + resize one image to [H, W, 3] float32 in [0,1]."""
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB").resize((hw[1], hw[0]))
        return np.asarray(im, dtype=np.float32) / 255.0


_IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp")


def image_folder_available(data_dir: str) -> bool:
    """ImageNet-style layout: <dir>/train/<class_name>/<img>."""
    train = os.path.join(data_dir, "train")
    if not os.path.isdir(train):
        return False
    for cls in os.listdir(train):
        d = os.path.join(train, cls)
        if os.path.isdir(d) and any(
            f.lower().endswith(_IMAGE_EXTS) for f in os.listdir(d)
        ):
            return True
    return False


def load_image_folder(
    data_dir: str, image_hw: Tuple[int, int] = (64, 64)
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, int]:
    """ImageNet-style class-per-directory ingestion (the reference's
    truncated-ImageNet datasets, ``data/ImageNet/``): <dir>/{train,val
    or test}/<class_name>/*.jpg -> global arrays + class count. Class
    ids follow sorted class-name order (torchvision convention)."""
    train_dir = os.path.join(data_dir, "train")
    test_dir = next(
        (
            os.path.join(data_dir, s)
            for s in ("val", "test")
            if os.path.isdir(os.path.join(data_dir, s))
        ),
        None,
    )
    classes = sorted(
        c for c in os.listdir(train_dir)
        if os.path.isdir(os.path.join(train_dir, c))
    )
    cls_id = {c: i for i, c in enumerate(classes)}

    def read_split(split_dir):
        xs, ys = [], []
        for c in classes:
            d = os.path.join(split_dir, c)
            if not os.path.isdir(d):
                continue
            for f in sorted(os.listdir(d)):
                if f.lower().endswith(_IMAGE_EXTS):
                    xs.append(_decode_image(os.path.join(d, f), image_hw))
                    ys.append(cls_id[c])
        if not xs:
            return (
                np.zeros((0,) + image_hw + (3,), np.float32),
                np.zeros((0,), np.int64),
            )
        return np.stack(xs), np.asarray(ys, np.int64)

    x_tr, y_tr = read_split(train_dir)
    x_te, y_te = read_split(test_dir) if test_dir else (
        np.zeros((0,) + image_hw + (3,), np.float32), np.zeros((0,), np.int64)
    )
    logging.info(
        "image folder %s: %d classes, %d train / %d test",
        data_dir, len(classes), len(y_tr), len(y_te),
    )
    return x_tr, y_tr, x_te, y_te, len(classes)


def landmarks_csv_available(data_dir: str) -> bool:
    return os.path.isfile(os.path.join(data_dir, "train.csv")) and os.path.isdir(
        os.path.join(data_dir, "images")
    )


def load_landmarks_csv(
    data_dir: str, image_hw: Tuple[int, int] = (64, 64)
) -> Tuple[List[np.ndarray], List[np.ndarray], List[np.ndarray], List[np.ndarray]]:
    """Landmarks-style naturally-federated CSV mapping (reference
    ``data/Landmarks/data_loader.py:120-160``): ``train.csv`` rows
    ``user_id,image_id,class`` with images at ``images/<image_id>.jpg``
    (any supported extension). An optional ``test.csv`` (no user
    grouping required) supplies held-out data, sharded uniformly across
    users like the reference's test loaders."""
    import csv

    def read_rows(path):
        with open(path) as f:
            return list(csv.DictReader(f))

    img_dir = os.path.join(data_dir, "images")

    def img(image_id):
        for ext in _IMAGE_EXTS:
            p = os.path.join(img_dir, image_id + ext)
            if os.path.isfile(p):
                return _decode_image(p, image_hw)
        raise FileNotFoundError(f"image {image_id} not under {img_dir}")

    rows = read_rows(os.path.join(data_dir, "train.csv"))
    if not rows:
        raise ValueError(f"{data_dir}/train.csv has no data rows")
    per_user: Dict[str, List] = {}
    for r in rows:
        per_user.setdefault(r["user_id"], []).append(r)
    # numeric ids in numeric order, then non-numeric lexicographically
    # (mixed id kinds must not break the sort)
    users = sorted(
        per_user, key=lambda u: (0, int(u), "") if u.isdigit() else (1, 0, u)
    )
    xs_tr = [np.stack([img(r["image_id"]) for r in per_user[u]]) for u in users]
    ys_tr = [
        np.asarray([int(r["class"]) for r in per_user[u]], np.int64) for u in users
    ]

    test_path = os.path.join(data_dir, "test.csv")
    n = len(users)
    if os.path.isfile(test_path):
        te_rows = read_rows(test_path)
        x_te = [img(r["image_id"]) for r in te_rows]
        y_te = [int(r["class"]) for r in te_rows]
        xs_te = [
            np.stack(x_te[i::n]) if x_te[i::n] else
            np.zeros((0,) + xs_tr[0].shape[1:], np.float32)
            for i in range(n)
        ]
        ys_te = [np.asarray(y_te[i::n], np.int64) for i in range(n)]
    else:
        xs_te = [np.zeros((0,) + xs_tr[0].shape[1:], np.float32)] * n
        ys_te = [np.zeros((0,), np.int64)] * n
    logging.info(
        "landmarks csv %s: %d users, %d train samples",
        data_dir, n, sum(len(y) for y in ys_tr),
    )
    return xs_tr, ys_tr, xs_te, ys_te


# -- vertical-FL party CSVs -------------------------------------------


def vfl_party_csvs_available(data_dir: str) -> bool:
    """NUS-WIDE / lending-club style party split: party_0.csv (guest,
    carries the label column) + party_1.csv.. (host features)."""
    return os.path.isfile(os.path.join(data_dir, "party_0.csv"))


def load_vfl_party_csvs(
    data_dir: str,
) -> Tuple[List[np.ndarray], np.ndarray]:
    """Row-aligned party feature CSVs -> ([feats_k [N, d_k]...], labels).

    Reference analog: the vertically-split finance/CV datasets
    (``data/NUS_WIDE/``, ``data/lending_club_loan/``, ``data/UCI/``)
    where each organization holds its own feature columns for the same
    sample population. party_0.csv must carry the label column
    (``label`` or ``y``, case-insensitive); an ``id`` column, if
    present, is dropped everywhere (rows must already be aligned —
    private set intersection is upstream of ingestion)."""
    import csv as _csv

    import glob as _glob
    import re as _re

    present = sorted(
        int(m.group(1))
        for p in _glob.glob(os.path.join(data_dir, "party_*.csv"))
        if (m := _re.fullmatch(r"party_(\d+)\.csv", os.path.basename(p)))
    )
    if not present:
        raise ValueError(f"no party_K.csv files under {data_dir}")
    if present != list(range(len(present))):
        raise ValueError(
            f"party CSVs in {data_dir} must be contiguously numbered "
            f"party_0..party_K; found indices {present}"
        )
    feats: List[np.ndarray] = []
    labels: Optional[np.ndarray] = None
    for k in present:
        with open(os.path.join(data_dir, f"party_{k}.csv")) as f:
            rows = list(_csv.DictReader(f))
        if not rows:
            raise ValueError(f"party_{k}.csv has no data rows")
        cols = list(rows[0].keys())
        # only the guest (party_0) carries labels; a host column that
        # happens to be named 'label'/'y' is an ordinary feature
        label_col = (
            next((c for c in cols if c.lower() in ("label", "y")), None)
            if k == 0
            else None
        )
        if k == 0 and label_col is None:
            raise ValueError("party_0.csv must carry a 'label' (or 'y') column")
        feat_cols = [
            c for c in cols if c != label_col and c.lower() != "id"
        ]
        feats.append(
            np.asarray(
                [[float(r[c]) for c in feat_cols] for r in rows], np.float32
            )
        )
        if label_col is not None:
            labels = np.asarray([int(float(r[label_col])) for r in rows], np.int64)
            if labels.min() < 0:
                raise ValueError(
                    "party_0.csv labels must be non-negative class ids "
                    "(found %d); re-encode -1/+1 style labels as 0/1"
                    % labels.min()
                )
    k = len(present)
    n = len(feats[0])
    for i, fmat in enumerate(feats):
        if len(fmat) != n:
            raise ValueError(
                f"party_{i}.csv has {len(fmat)} rows, party_0 has {n}; "
                "party files must be row-aligned"
            )
    logging.info(
        "vfl party csvs %s: %d parties, %d samples, dims %s",
        data_dir, k, n, [f.shape[1] for f in feats],
    )
    return feats, labels


def vfl_train_test_split(
    feats: List[np.ndarray], labels: np.ndarray, seed: int, train_frac: float = 0.8
):
    """THE canonical row split for vertically-partitioned data — both
    the loader's horizontal view and the VFL engine's party view must
    use this one function or their test rows would silently diverge
    (train/test leakage between the two views of the same CSVs).
    Returns (feats_tr, labels_tr, feats_te, labels_te), row-shuffled
    with a seeded permutation (published extracts are often
    label-sorted)."""
    n = len(labels)
    perm = np.random.RandomState(int(seed)).permutation(n)
    feats = [f[perm] for f in feats]
    labels = labels[perm]
    n_tr = max(1, int(train_frac * n))
    return (
        [f[:n_tr] for f in feats],
        labels[:n_tr],
        [f[n_tr:] for f in feats],
        labels[n_tr:],
    )
