"""LEAF-format federated dataset reader.

Reference: ``data/MNIST/data_loader.py`` (``read_data``/``batch_data``
semantics, :30-99) and the FederatedEMNIST/shakespeare loaders — the
LEAF benchmark stores NATURALLY federated splits as JSON:

    {"users": [...], "num_samples": [...],
     "user_data": {user_id: {"x": [...], "y": [...]}}}

across one or more ``.json`` files per split directory. Reading LEAF
keeps the real per-user partition instead of a synthetic LDA split —
the canonical "natural non-IID" setting.

Layout expected under ``<data_cache_dir>/<dataset>/``:
``train/*.json`` and ``test/*.json`` (the reference's auto-downloaded
archive layout, data/MNIST/data_loader.py:17-29).
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np


def read_leaf_dir(split_dir: str) -> Tuple[List[str], Dict[str, dict]]:
    """All users + user_data merged across the split's json files
    (read_data, data_loader.py:30-55)."""
    users: List[str] = []
    user_data: Dict[str, dict] = {}
    for path in sorted(glob.glob(os.path.join(split_dir, "*.json"))):
        with open(path) as f:
            blob = json.load(f)
        users.extend(blob["users"])
        user_data.update(blob["user_data"])
    return users, user_data


def _to_arrays(entry: dict, feature_shape: Optional[Tuple[int, ...]]):
    x = np.asarray(entry["x"], dtype=np.float32)
    y = np.asarray(entry["y"])
    if feature_shape is not None and len(x) == 0:
        # an empty user entry parses as shape (0,) — give it the real
        # feature shape or downstream concatenation dies
        x = np.zeros((0,) + tuple(feature_shape), np.float32)
    elif feature_shape is not None and x.ndim == 2:
        x = x.reshape((len(x),) + tuple(feature_shape))
    if y.dtype.kind in "fc":
        y = y.astype(np.int64)
    return x, y


def load_leaf(
    root: str,
    feature_shape: Optional[Tuple[int, ...]] = None,
    max_users: Optional[int] = None,
) -> Tuple[List[np.ndarray], List[np.ndarray], List[np.ndarray], List[np.ndarray]]:
    """Per-user train/test arrays in a stable user order. Users missing
    from the test split get an empty test set (LEAF guarantees matching
    users, but partial downloads happen)."""
    train_users, train_data = read_leaf_dir(os.path.join(root, "train"))
    _, test_data = read_leaf_dir(os.path.join(root, "test"))
    if max_users is not None:
        train_users = train_users[:max_users]
    xs_tr, ys_tr, xs_te, ys_te = [], [], [], []
    for u in train_users:
        x, y = _to_arrays(train_data[u], feature_shape)
        xs_tr.append(x)
        ys_tr.append(y)
        if u in test_data:
            xt, yt = _to_arrays(test_data[u], feature_shape)
        else:
            xt = np.zeros((0,) + x.shape[1:], np.float32)
            yt = np.zeros((0,), np.int64)
        xs_te.append(xt)
        ys_te.append(yt)
    return xs_tr, ys_tr, xs_te, ys_te


def leaf_available(root: str) -> bool:
    return bool(glob.glob(os.path.join(root, "train", "*.json")))
