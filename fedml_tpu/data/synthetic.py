"""Synthetic dataset generators.

Two roles:

1. Parity with the reference's synthetic federated datasets
   (``python/fedml/data/synthetic_1_1``, ``data/fedprox`` — the FedProx
   synthetic(alpha, beta) generator): per-client logistic models drawn
   from a hierarchical Gaussian, the standard non-IID stress test.
2. Zero-egress stand-ins for download-only datasets (the reference
   auto-downloads MNIST et al. from S3, ``data/MNIST/data_loader.py:17-29``;
   this environment has no egress). Shapes/classes match the real
   datasets so models and benchmarks are identical; a real copy placed
   in ``args.data_cache_dir`` takes precedence (see loader.py).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


def synthetic_fedprox(
    num_clients: int = 30,
    alpha: float = 1.0,
    beta: float = 1.0,
    input_dim: int = 60,
    num_classes: int = 10,
    seed: int = 0,
    min_samples: int = 20,
    max_samples: int = 400,
) -> Tuple[List[np.ndarray], List[np.ndarray]]:
    """FedProx synthetic(alpha, beta): W_k ~ N(u_k, 1), u_k ~ N(0, alpha);
    x_k ~ N(v_k, Sigma), v_k ~ N(B_k, 1), B_k ~ N(0, beta); lognormal
    client sizes. Returns per-client (x, y) lists."""
    rng = np.random.RandomState(seed)
    sizes = np.clip(
        rng.lognormal(4, 2, num_clients).astype(int), min_samples, max_samples
    )
    diag = np.array([(j + 1) ** -1.2 for j in range(input_dim)])
    xs, ys = [], []
    for k in range(num_clients):
        u_k = rng.normal(0, alpha)
        b_k = rng.normal(0, beta)
        v_k = rng.normal(b_k, 1, input_dim)
        W = rng.normal(u_k, 1, (input_dim, num_classes))
        b = rng.normal(u_k, 1, num_classes)
        x = rng.multivariate_normal(v_k, np.diag(diag), sizes[k]).astype(np.float32)
        logits = x @ W + b
        y = np.argmax(logits, axis=1).astype(np.int64)
        xs.append(x)
        ys.append(y)
    return xs, ys


def _class_means(num_classes: int, dim: int, means_seed: int) -> np.ndarray:
    """The one class-means construction both the host and device
    stand-in generators use — train/test and host/device synthesis
    share a distribution only because this expression is shared."""
    return np.random.RandomState(means_seed).normal(
        0, 1, (num_classes, dim)
    ).astype(np.float32)


def synthetic_classification(
    n_samples: int,
    num_classes: int,
    feature_shape: Tuple[int, ...],
    seed: int = 0,
    sigma: float = 1.0,
    means_seed: int = 1234,
) -> Tuple[np.ndarray, np.ndarray]:
    """Class-conditional Gaussian blobs with learnable structure: each
    class has a mean vector; examples are mean + noise. Linear models
    reach high accuracy, so optimization dynamics are observable.

    ``means_seed`` fixes the class means independently of the sampling
    seed so train/test splits share one distribution."""
    rng = np.random.RandomState(seed)
    dim = int(np.prod(feature_shape))
    means = _class_means(num_classes, dim, means_seed)
    y = rng.randint(0, num_classes, n_samples).astype(np.int64)
    x = means[y] + sigma * rng.normal(0, 1, (n_samples, dim)).astype(np.float32)
    return x.reshape((n_samples,) + feature_shape), y


def synthetic_classification_device(
    y_packed: np.ndarray,
    feature_shape: Tuple[int, ...],
    num_classes: int,
    seed: int = 0,
    sigma: float = 1.0,
    means_seed: int = 1234,
    dtype=None,
):
    """Device-side twin of :func:`synthetic_classification`: given
    host-packed labels ``y_packed`` (any leading shape), synthesize the
    feature tensor ``means[y] + sigma * noise`` directly on the default
    device with ``jax.random``.

    Rationale: the stand-in datasets exist only in this zero-egress
    environment, and materializing them host-side forces the whole
    image tensor through the host->device link (the tunneled TPU here
    moves ~5 MB/s — a CIFAR-shaped 100-client federation is >1 GB and
    can never finish transferring inside a bench window). Shipping the
    labels (KBs) and generating features in HBM makes cohort size a
    compute knob instead of a bandwidth one. Same distribution family
    and the same ``means_seed`` convention as the host generator (class
    means shared across train/test); the noise stream is jax's threefry
    rather than numpy's MT, which is deterministic across processes and
    backends for a given seed."""
    import jax.numpy as jnp

    dim = int(np.prod(feature_shape))
    means = _class_means(num_classes, dim, means_seed)
    return _gen_device(
        jnp.asarray(y_packed, jnp.int32),
        jnp.asarray(means),
        jnp.uint32(seed),  # uint32: RandomState's full [0, 2**32) seed domain
        jnp.float32(sigma),
        tuple(feature_shape),
        dtype or jnp.float32,
    )


def _module_jit(fn=None, **kw):
    """jax.jit at module scope, imported lazily (this module must stay
    importable without jax for the host-side numpy generators)."""
    import functools

    import jax

    return jax.jit(fn, **kw) if fn is not None else functools.partial(
        jax.jit, **kw
    )


def _gen_device_impl(y, means, seed, sigma, feature_shape, out_dtype):
    import jax
    import jax.numpy as jnp

    dim = means.shape[1]
    noise = jax.random.normal(
        jax.random.PRNGKey(seed), y.shape + (dim,), jnp.float32
    )
    x = means[y] + sigma * noise
    return x.reshape(y.shape + tuple(feature_shape)).astype(out_dtype)


def _gen_per_client_impl(y, means, client_seeds, sigma, feature_shape,
                         out_dtype):
    import jax
    import jax.numpy as jnp

    dim = means.shape[1]
    C = y.shape[0]
    flat = y.reshape(C, -1)  # [C, S] sample-ordered per client
    S = flat.shape[1]
    sample_idx = jnp.arange(S, dtype=jnp.uint32)

    def one_client(seed, ys):
        # noise[s] is a pure function of (client seed, sample index):
        # independent of which cohort slot, vmap group, or nb bucket
        # the client lands in this round — the registry's determinism
        # contract for features
        key = jax.random.PRNGKey(seed)
        keys = jax.vmap(lambda s: jax.random.fold_in(key, s))(sample_idx)
        noise = jax.vmap(
            lambda k: jax.random.normal(k, (dim,), jnp.float32)
        )(keys)
        return means[ys] + sigma * noise

    x = jax.vmap(one_client)(client_seeds, flat)
    return x.reshape(y.shape + tuple(feature_shape)).astype(out_dtype)


# jitted lazily on first use, then cached at module scope so repeat
# calls (once per cohort group per round on the registry path) hit the
# jit cache instead of rebuilding a fresh wrapper every call
_GEN_CACHE: dict = {}


def _gen_device(y, means, seed, sigma, feature_shape, out_dtype):
    fn = _GEN_CACHE.get("device")
    if fn is None:
        fn = _GEN_CACHE["device"] = _module_jit(
            static_argnames=("feature_shape", "out_dtype")
        )(_gen_device_impl)
    return fn(y, means, seed, sigma, feature_shape, out_dtype)


def synthetic_classification_device_per_client(
    y_packed: np.ndarray,
    feature_shape: Tuple[int, ...],
    num_classes: int,
    client_seeds: np.ndarray,
    sigma: float = 1.0,
    means_seed: int = 1234,
    dtype=None,
):
    """Per-client twin of :func:`synthetic_classification_device` for
    the registry path (``fedml_tpu/scale/registry.py``): ``y_packed``
    is ``[C, ...]`` with one leading row per client and
    ``client_seeds[c]`` seeds row ``c``'s noise **per sample index**,
    so a client's features are a function of the client alone — stable
    across rounds, cohort slots, and nb buckets (sample ``s`` keeps its
    noise when the client's packed shape changes). Same class-means
    convention as the host generator."""
    import jax.numpy as jnp

    dim = int(np.prod(feature_shape))
    means = _class_means(num_classes, dim, means_seed)
    fn = _GEN_CACHE.get("per_client")
    if fn is None:
        fn = _GEN_CACHE["per_client"] = _module_jit(
            static_argnames=("feature_shape", "out_dtype")
        )(_gen_per_client_impl)
    return fn(
        jnp.asarray(y_packed, jnp.int32),
        jnp.asarray(means),
        jnp.asarray(client_seeds, jnp.uint32),
        jnp.float32(sigma),
        tuple(feature_shape),
        dtype or jnp.float32,
    )


def synthetic_segmentation(
    n_samples: int,
    num_classes: int,
    feature_shape: Tuple[int, ...],
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Blob-mask segmentation stand-in (pascal_voc/cityscapes shapes;
    fets2021's 4-channel MRI-modality shape uses the same generator):
    each image gets 1-3 axis-aligned rectangles of distinct foreground
    classes on a background (class 0); pixel labels follow the
    rectangles and pixel intensities encode the class, so a small
    encoder-decoder can learn the mapping."""
    h, w = feature_shape[0], feature_shape[1]
    ch = feature_shape[2] if len(feature_shape) > 2 else 3
    rng = np.random.RandomState(seed)
    palette = np.random.RandomState(4321).uniform(-1, 1, (num_classes, ch)).astype(
        np.float32
    )
    x = np.zeros((n_samples, h, w, ch), np.float32)
    y = np.zeros((n_samples, h, w), np.int64)
    for i in range(n_samples):
        x[i] = palette[0] + 0.3 * rng.normal(0, 1, (h, w, ch))
        for _ in range(rng.randint(1, 4)):
            c = rng.randint(1, num_classes)
            hh, ww = rng.randint(h // 6, h // 2), rng.randint(w // 6, w // 2)
            r0, c0 = rng.randint(0, h - hh), rng.randint(0, w - ww)
            x[i, r0 : r0 + hh, c0 : c0 + ww] = palette[c] + 0.3 * rng.normal(
                0, 1, (hh, ww, ch)
            )
            y[i, r0 : r0 + hh, c0 : c0 + ww] = c
    return x, y


def synthetic_sequences(
    n_samples: int,
    seq_len: int,
    vocab_size: int,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Markov-chain token streams for NWP models: x = tokens[:-1],
    y = tokens[1:]. The chain's structure makes next-token prediction
    learnable above chance."""
    rng = np.random.RandomState(seed)
    # sparse row-stochastic transition matrix
    trans = rng.dirichlet(np.full(vocab_size, 0.05), size=vocab_size)
    toks = np.zeros((n_samples, seq_len + 1), np.int64)
    toks[:, 0] = rng.randint(0, vocab_size, n_samples)
    for t in range(seq_len):
        p = trans[toks[:, t]]
        cum = p.cumsum(axis=1)
        u = rng.rand(n_samples, 1)
        toks[:, t + 1] = (u > cum).sum(axis=1)
    return toks[:, :-1], toks[:, 1:]


def synthetic_multilabel(
    n_samples: int,
    num_tags: int,
    feature_shape: Tuple[int, ...],
    seed: int = 0,
    tags_per_sample: int = 3,
    sigma: float = 0.5,
    means_seed: int = 1234,
) -> Tuple[np.ndarray, np.ndarray]:
    """Multi-hot tag-prediction stand-in (stackoverflow_lr shape): each
    sample carries 1..tags_per_sample tags; features are the sum of the
    active tags' embedding vectors + noise, so a linear sigmoid model
    is learnable. Returns (x [N, *shape], y multi-hot [N, num_tags])."""
    rng = np.random.RandomState(seed)
    dim = int(np.prod(feature_shape))
    emb = np.random.RandomState(means_seed).normal(
        0, 1, (num_tags, dim)
    ).astype(np.float32)
    y = np.zeros((n_samples, num_tags), np.float32)
    x = sigma * rng.normal(0, 1, (n_samples, dim)).astype(np.float32)
    counts = rng.randint(1, tags_per_sample + 1, n_samples)
    for i in range(n_samples):
        tags = rng.choice(num_tags, counts[i], replace=False)
        y[i, tags] = 1.0
        x[i] += emb[tags].sum(axis=0)
    return x.reshape((n_samples,) + feature_shape), y
