"""fedml_tpu — a TPU-native federated / distributed learning framework.

Brand-new design with the capability surface of FedML v0.7.39
(reference layout: SURVEY.md; one-line API parity with
``python/fedml/__init__.py``): ``init()`` -> ``device`` -> ``data`` ->
``model`` -> scenario ``run()``. Compute is JAX/XLA end-to-end — client
updates are jitted scans, cohorts are vmapped/mesh-sharded, aggregation
is an on-device reduction — so the FL round loop never round-trips
through host pickles the way the reference does.
"""

from __future__ import annotations

import logging
import random as _random
from typing import Optional

import numpy as np

from . import constants  # noqa: F401
from .arguments import Arguments, load_arguments

__version__ = "0.1.0"

# The L3 operator seam (core.frame) imports JAX transitively; loading
# it lazily (PEP 562) keeps `import fedml_tpu` — and therefore the
# pure-AST `fedml-tpu lint` CLI — free of any JAX import. Training
# entry points touch these names (or core.frame directly) and pull
# JAX in at that point, exactly as before.
_LAZY_FRAME_EXPORTS = (
    "ClientTrainer",
    "DefaultClientTrainer",
    "DefaultServerAggregator",
    "ServerAggregator",
)


def __getattr__(name: str):
    if name in _LAZY_FRAME_EXPORTS:
        from .core import frame

        return getattr(frame, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY_FRAME_EXPORTS))

_global_training_type: Optional[str] = None
_global_comm_backend: Optional[str] = None


def init(args: Optional[Arguments] = None) -> Arguments:
    """Parity with ``fedml.init()`` (__init__.py:34-136): load args,
    seed RNGs, set numeric precision, resolve per-scenario process
    identity."""
    if args is None:
        args = load_arguments(_global_training_type, _global_comm_backend)
    _seed(int(getattr(args, "random_seed", 0)))
    import jax

    jax.config.update(
        "jax_default_matmul_precision",
        getattr(args, "matmul_precision", "highest"),
    )
    from .parallel.layout import fed_mesh_shape

    if fed_mesh_shape(getattr(args, "mesh_shape", None)) and not (
        jax.config.jax_threefry_partitionable
    ):
        # fed (data, fsdp) mesh runs need SHARDING-INVARIANT random
        # draws (the partitionable threefry) for the mesh-vs-single-
        # chip bitwise identity; flipped here — before any data
        # synthesis — so every world this process builds draws from
        # the same stream (parallel/layout.py explains the hazard)
        logging.info(
            "mesh_shape=%s: enabling jax_threefry_partitionable "
            "(sharding-invariant random draws)", args.mesh_shape,
        )
        jax.config.update("jax_threefry_partitionable", True)
    logging.getLogger().setLevel(
        logging.DEBUG if getattr(args, "verbose", False) else logging.INFO
    )
    if args.training_type == constants.FEDML_TRAINING_PLATFORM_SIMULATION:
        args.process_id = 0
    elif args.training_type == constants.FEDML_TRAINING_PLATFORM_CROSS_SILO:
        args.process_id = int(getattr(args, "rank", 0))
        if getattr(args, "distributed_coordinator", None):
            # multi-controller hierarchical silo: join the runtime's
            # process group BEFORE anything initializes the backend
            # (the torchrun-env analog, reference __init__.py:85-130)
            from .cross_silo.hierarchical.process_group_manager import (
                ensure_distributed_initialized,
            )

            ensure_distributed_initialized(args)
    elif args.training_type == constants.FEDML_TRAINING_PLATFORM_CROSS_DEVICE:
        args.rank = 0
        args.process_id = 0
    return args


def _seed(seed: int) -> None:
    _random.seed(seed)
    np.random.seed(seed)


def run_simulation(
    backend: str = constants.FEDML_SIMULATION_TYPE_SP,
    client_trainer=None,
    server_aggregator=None,
) -> None:
    """One-line simulation entry (__init__.py:139-169). Custom L3
    operators (``core.frame``) plug in via ``client_trainer=`` /
    ``server_aggregator=``."""
    global _global_training_type, _global_comm_backend
    _global_training_type = constants.FEDML_TRAINING_PLATFORM_SIMULATION
    _global_comm_backend = backend

    from . import data, device, models
    from .simulation import SimulatorMesh, SimulatorSingleProcess

    args = init()
    dev = device.get_device(args)
    dataset = data.load(args)
    model = models.create(args, dataset.class_num)
    if backend in (
        constants.FEDML_SIMULATION_TYPE_MESH,
        constants.FEDML_SIMULATION_TYPE_NCCL,
    ):
        simulator = SimulatorMesh(
            args, dev, dataset, model,
            client_trainer=client_trainer, server_aggregator=server_aggregator,
        )
    elif backend == constants.FEDML_SIMULATION_TYPE_SP:
        simulator = SimulatorSingleProcess(
            args, dev, dataset, model,
            client_trainer=client_trainer, server_aggregator=server_aggregator,
        )
    else:
        raise ValueError(f"unknown simulation backend {backend!r}")
    return simulator.run()


def run_distributed(args: Optional[Arguments] = None):
    """One-line mesh-parallel (distributed) LM training — the
    ``training_type: distributed`` platform. The YAML's ``mesh_shape``
    picks the parallelism (dp x tp x ep, sp, or pp); see
    ``fedml_tpu.distributed``. No reference counterpart: this is where
    the green-field parallel subsystems surface as product."""
    global _global_training_type
    _global_training_type = constants.FEDML_TRAINING_PLATFORM_DISTRIBUTED
    from . import data, device, models
    from .distributed import DistributedTrainer

    args = init(args)
    dev = device.get_device(args)
    dataset = data.load(args)
    model = models.create(args, dataset.class_num)
    return DistributedTrainer(args, dev, dataset, model).run()


def run_cross_silo_server(args: Optional[Arguments] = None, server_aggregator=None):
    """One-line cross-silo server (__init__.py:172-191)."""
    global _global_training_type
    _global_training_type = constants.FEDML_TRAINING_PLATFORM_CROSS_SILO
    from . import data, device, models
    from .cross_silo import Server

    args = init(args)
    dev = device.get_device(args)
    dataset = data.load(args)
    model = models.create(args, dataset.class_num)
    server = Server(args, dev, dataset, model, server_aggregator=server_aggregator)
    from .core.tracking import device_trace

    with device_trace(args):
        return server.run()


def run_cross_silo_client(args: Optional[Arguments] = None, client_trainer=None):
    """One-line cross-silo client (__init__.py:193-211)."""
    global _global_training_type
    _global_training_type = constants.FEDML_TRAINING_PLATFORM_CROSS_SILO
    from . import data, device, models
    from .cross_silo import Client

    args = init(args)
    dev = device.get_device(args)
    dataset = data.load(args)
    model = models.create(args, dataset.class_num)
    client = Client(args, dev, dataset, model, client_trainer=client_trainer)
    from .core.tracking import device_trace

    with device_trace(args):
        return client.run()


def run_hierarchical_cross_silo_server(
    args: Optional[Arguments] = None, server_aggregator=None
):
    """One-line hierarchical cross-silo server (__init__.py:214-233).
    Protocol-identical to the horizontal server — the hierarchy lives
    entirely client-side (each FL client is a sharded training group)."""
    return run_cross_silo_server(args, server_aggregator=server_aggregator)


def run_hierarchical_cross_silo_client(
    args: Optional[Arguments] = None, client_trainer=None
):
    """One-line hierarchical cross-silo client (__init__.py:235-253):
    master/slave role follows ``args.proc_rank_in_silo`` the way the
    reference forks on the torchrun-derived process rank."""
    global _global_training_type
    _global_training_type = constants.FEDML_TRAINING_PLATFORM_CROSS_SILO
    from . import data, device, models
    from .cross_silo import HierarchicalClient

    args = init(args)
    dev = device.get_device(args)
    dataset = data.load(args)
    model = models.create(args, dataset.class_num)
    client = HierarchicalClient(args, dev, dataset, model, client_trainer=client_trainer)
    from .core.tracking import device_trace

    with device_trace(args):
        return client.run()


def run_edge_server(args: Optional[Arguments] = None):
    """One-line cross-device server — the ``run_mnn_server`` analog
    (__init__.py:256-274): edge clients ship model files over the
    pub/sub data plane; the server aggregates on TPU."""
    global _global_training_type
    _global_training_type = constants.FEDML_TRAINING_PLATFORM_CROSS_DEVICE
    from . import data, device, models
    from .cross_device import ServerEdge

    args = init(args)
    dev = device.get_device(args)
    dataset = data.load(args)
    model = models.create(args, dataset.class_num)
    server = ServerEdge(args, dev, dataset, model)
    from .core.tracking import device_trace

    with device_trace(args):
        return server.run()
