"""Two-level (hierarchical) FedAvg.

Parity with ``python/fedml/simulation/single_process/hierarchical_fl/``:
``Group(FedAvgAPI)`` aggregates within a group every
``group_comm_round`` (group.py:7-60); ``Trainer(FedAvgAPI)`` aggregates
group models globally (trainer.py:10-110). Satisfies the CI oracle: with
full-batch clients and a fixed ``comm_round x group_comm_round``
product, hierarchical == flat == centralized
(ci/CI-script-fedavg.sh:53-63).

TPU-first: a group round reuses the SAME jitted round engine as flat
FedAvg (the cohort is the group), so group training is a vmapped
on-device computation; the global level is one more weighted pytree
reduction. Group partitioning is deterministic per seed.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..core.aggregation import normalize_weights, stack_pytrees, weighted_average
from .fedavg_api import FedAvgAPI


class HierarchicalFLAPI(FedAvgAPI):
    """args: ``group_num``, ``group_comm_round``; ``comm_round`` is the
    number of GLOBAL rounds (reference ``global_comm_round``)."""

    algorithm = "HierFedAvg"
    # group level consults the seam via _round_fn, but the global level
    # is a fixed group-weighted mean — mixed semantics, so reject
    _accepts_custom_aggregator = False

    def _groups(self) -> List[np.ndarray]:
        n = self.dataset.client_num
        gnum = int(getattr(self.args, "group_num", 2))
        rng = np.random.RandomState(int(getattr(self.args, "random_seed", 0)))
        method = getattr(self.args, "group_method", "random")
        idxs = rng.permutation(n) if method == "random" else np.arange(n)
        return [g.astype(np.int32) for g in np.array_split(idxs, gnum)]

    def train(self) -> Dict[str, float]:
        args = self.args
        packed = self.dataset.packed_train
        nsamples = jnp.asarray(self.dataset.packed_num_samples)
        groups = self._groups()
        group_rounds = int(getattr(args, "group_comm_round", 1))
        freq = max(1, int(getattr(args, "frequency_of_the_test", 5)))
        final_stats: Dict[str, float] = {}
        for round_idx in range(int(args.comm_round)):
            t0 = time.perf_counter()
            self.rng, round_rng = jax.random.split(self.rng)
            # round-indexed LR decays with the GLOBAL round (constant
            # across a round's groups/group-rounds)
            lr_mult = self._lr_mult(round_idx)
            extra = () if lr_mult is None else (lr_mult,)
            group_params = []
            group_weights = []
            for gi, g in enumerate(groups):
                # donation-safe fresh start per group
                p = jax.tree.map(jnp.copy, self.global_params)
                state = self._init_server_state()
                for gr in range(group_rounds):
                    p, state, _ = self._round_fn(
                        p,
                        state,
                        packed,
                        nsamples,
                        jnp.asarray(g),
                        jax.random.fold_in(round_rng, gi * 1009 + gr),
                        *extra,
                    )
                group_params.append(p)
                group_weights.append(float(np.asarray(nsamples)[g].sum()))
            stacked = stack_pytrees(group_params)
            self.global_params = weighted_average(
                stacked, normalize_weights(jnp.asarray(group_weights))
            )
            if round_idx % freq == 0 or round_idx == int(args.comm_round) - 1:
                stats = self._local_test_on_all_clients(round_idx)
                stats["round"] = round_idx
                stats["round_time_s"] = time.perf_counter() - t0
                self.history.append(stats)
                final_stats = stats
                logging.info("hier round %d: %s", round_idx, stats)
        return final_stats
