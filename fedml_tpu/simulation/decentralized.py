"""Decentralized gossip SGD (DSGD / PushSum).

Parity with ``python/fedml/simulation/single_process/decentralized/``
(``ClientDSGD`` client_dsgd.py:6, ``ClientPushsum``) over the topology
managers (SURVEY.md §2.5), and with the MPI gossip worker
(``mpi_p2p_mp/decentralized_framework/decentralized_worker_manager.py:8-50``).

TPU-first redesign: all N nodes' params live stacked on device
[N, ...]; one gossip round is
  (1) vmapped local training of every node, then
  (2) ONE mixing matmul  theta <- W @ theta  (einsum over the node
      axis — the entire network's neighbor-weighted averaging in a
      single MXU pass, replacing the reference's per-node loops and
      per-edge messages).
PushSum keeps the scalar mass vector w and de-biases with theta/w.
"""

from __future__ import annotations

import logging
import time
from typing import Dict

import jax
import jax.numpy as jnp

from ..core.topology import AsymmetricTopologyManager, SymmetricTopologyManager
from .fedavg_api import FedAvgAPI


def _mix(stacked, W):
    """theta_i <- sum_j W[i,j] theta_j over the stacked node axis."""
    return jax.tree.map(
        lambda l: jnp.einsum("ij,j...->i...", W.astype(l.dtype), l), stacked
    )


class DecentralizedDSGDAPI(FedAvgAPI):
    """Symmetric gossip (ClientDSGD semantics). All clients participate
    every round (there is no server)."""

    algorithm = "DSGD"
    directed = False
    supports_mesh = False  # node axis sizing vs mesh padding; later round

    def __init__(self, args, device, dataset, model, mesh=None) -> None:
        super().__init__(args, device, dataset, model, mesh)
        if self._round_lr is not None:
            raise ValueError(
                "round-indexed lr_schedule is not supported for "
                "decentralized gossip (no server round clock); use "
                "lr_schedule=constant"
            )
        n = dataset.client_num
        packed_rows = int(dataset.packed_train.mask.shape[0])
        if packed_rows != n:
            raise ValueError(
                f"decentralized gossip needs one node per packed client "
                f"(got {packed_rows} packed rows for {n} clients)"
            )
        if self.directed:
            topo = AsymmetricTopologyManager(
                n,
                neighbor_num=int(getattr(args, "topology_neighbor_num", 2)),
                seed=int(getattr(args, "random_seed", 0)),
            )
        else:
            topo = SymmetricTopologyManager(
                n,
                neighbor_num=int(getattr(args, "topology_neighbor_num", 2)),
                beta=float(getattr(args, "topology_beta", 0.0)),
                seed=int(getattr(args, "random_seed", 0)),
            )
        topo.generate_topology()
        self.topology = topo
        self.W = topo.mixing_matrix()

        # per-node params, all starting from the same init
        self.node_params = jax.tree.map(
            lambda l: jnp.broadcast_to(l[None], (n,) + l.shape), self.global_params
        )

        def gossip_round(node_params, packed, rng, W):
            rngs = jax.random.split(rng, packed.mask.shape[0])
            new_stacked, metrics = jax.vmap(self._local_train, in_axes=(0, 0, 0))(
                node_params, packed, rngs
            )
            return _mix(new_stacked, W), metrics

        self._gossip_fn = jax.jit(gossip_round, donate_argnums=(0,))

        def consensus(node_params):
            mean = jax.tree.map(lambda l: l.mean(axis=0), node_params)
            dis = sum(
                jnp.sum(jnp.square(l - m[None]))
                for l, m in zip(
                    jax.tree.leaves(node_params), jax.tree.leaves(mean)
                )
            )
            return mean, dis

        self._consensus = jax.jit(consensus)

    def train(self) -> Dict[str, float]:
        args = self.args
        packed = self.dataset.packed_train
        freq = max(1, int(getattr(args, "frequency_of_the_test", 5)))
        final_stats: Dict[str, float] = {}
        for round_idx in range(int(args.comm_round)):
            t0 = time.perf_counter()
            self.rng, r = jax.random.split(self.rng)
            self.node_params, _ = self._gossip_fn(self.node_params, packed, r, self.W)
            if round_idx % freq == 0 or round_idx == int(args.comm_round) - 1:
                mean, disagreement = self._consensus(self.node_params)
                self.global_params = mean
                stats = self._local_test_on_all_clients(round_idx)
                stats["round"] = round_idx
                stats["consensus_dist"] = float(disagreement)
                stats["round_time_s"] = time.perf_counter() - t0
                self.history.append(stats)
                final_stats = stats
                logging.info("dsgd round %d: %s", round_idx, stats)
        return final_stats


class DecentralizedPushSumAPI(DecentralizedDSGDAPI):
    """Directed-graph gossip with PushSum weight correction
    (ClientPushsum semantics: column-stochastic mixing, de-bias by the
    gossiped scalar mass)."""

    algorithm = "PushSum"
    directed = True

    def __init__(self, args, device, dataset, model, mesh=None) -> None:
        # (the round-LR refusal lives in the DSGD parent __init__)
        super().__init__(args, device, dataset, model, mesh)
        n = dataset.client_num
        self.mass = jnp.ones((n,))

        def pushsum_round(node_params, mass, packed, rng, W):
            rngs = jax.random.split(rng, packed.mask.shape[0])
            # train on de-biased estimates x = z / w
            debiased = jax.tree.map(
                lambda l: l / mass.reshape((-1,) + (1,) * (l.ndim - 1)), node_params
            )
            new_stacked, metrics = jax.vmap(self._local_train, in_axes=(0, 0, 0))(
                debiased, packed, rngs
            )
            # re-bias, then push
            rebiased = jax.tree.map(
                lambda l: l * mass.reshape((-1,) + (1,) * (l.ndim - 1)), new_stacked
            )
            mixed = _mix(rebiased, W)
            new_mass = W @ mass
            return mixed, new_mass, metrics

        self._pushsum_fn = jax.jit(pushsum_round, donate_argnums=(0, 1))

    def train(self) -> Dict[str, float]:
        args = self.args
        packed = self.dataset.packed_train
        freq = max(1, int(getattr(args, "frequency_of_the_test", 5)))
        final_stats: Dict[str, float] = {}
        for round_idx in range(int(args.comm_round)):
            t0 = time.perf_counter()
            self.rng, r = jax.random.split(self.rng)
            self.node_params, self.mass, _ = self._pushsum_fn(
                self.node_params, self.mass, packed, r, self.W
            )
            if round_idx % freq == 0 or round_idx == int(args.comm_round) - 1:
                debiased = jax.tree.map(
                    lambda l: l / self.mass.reshape((-1,) + (1,) * (l.ndim - 1)),
                    self.node_params,
                )
                mean, disagreement = self._consensus(debiased)
                self.global_params = mean
                stats = self._local_test_on_all_clients(round_idx)
                stats["round"] = round_idx
                stats["consensus_dist"] = float(disagreement)
                stats["round_time_s"] = time.perf_counter() - t0
                self.history.append(stats)
                final_stats = stats
                logging.info("pushsum round %d: %s", round_idx, stats)
        return final_stats
