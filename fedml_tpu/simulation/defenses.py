"""Fork-specific poisoning defenses: S-FedAvg and HS-FedAvg.

Reference parity (behavior, not implementation):

- **S-FedAvg** — ``simulation/single_process/s_fedavg/fedavg_api.py``:
  Shapley-value client scoring. Each round, after local training, the
  server estimates every cohort member's Shapley value against an
  aggregator-held validation set (Monte-Carlo over permutations until
  the SV estimate converges in Euclidean distance — ``isApproached``,
  fedavg_api.py:138-146), updates a per-client reputation
  ``phi = alpha*phi + beta*sv`` (fedavg_api.py:252-258), and biases the
  next round's sampling by ``exp(phi)`` (``sampling_filter="exp"``,
  fedavg_api.py:435-477). Scoring metrics: accuracy, or per-target-label
  Recall / Precision / F1 for backdoor detection (fedavg_api.py:218-226,
  :428-433).

  TPU-first redesign: one permutation's full prefix sweep is a SINGLE
  jitted computation — prefix aggregates are a cumulative weighted sum
  along the (permuted) client axis and all C prefix models are evaluated
  on the validation set with ``vmap``. The reference instead deep-copies
  the model and re-runs torch eval C times per permutation in Python
  (fedavg_api.py:210-236). Note: the reference shuffles an index list
  but slices ``w_locals`` unpermuted, so its "permutations" never change
  order; we implement the actual MC-Shapley it intends.

- **HS-FedAvg** — ``simulation/single_process/hs_fedavg/hs_fft.py``:
  FFT amplitude-spectrum input normalization. A running mean amplitude
  spectrum is maintained with momentum (``process()``, hs_fft.py:60+)
  and every training image's low-frequency amplitude band (band
  half-width ``floor(min(H,W)*L)`` around the centred DC, ``mutate``,
  hs_fft.py:16-37; the reference calls it with L=0 → DC only) is
  replaced by the running spectrum while phases are kept
  (``normalize``, hs_fft.py:40-56). Here the whole transform is a
  batched ``jnp.fft`` computation fused into the jitted round — the
  reference loops per-image in numpy on host.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..core.aggregation import normalize_weights
from ..core.types import Batches
from .fedavg_api import FedAvgAPI

Params = Any


# ---------------------------------------------------------------------------
# S-FedAvg
# ---------------------------------------------------------------------------


def _take_batches(b: Batches, n: int) -> Batches:
    return Batches(x=b.x[:n], y=b.y[:n], mask=b.mask[:n])


class SFedAvgAPI(FedAvgAPI):
    """Shapley-value client scoring defense (S-FedAvg).

    Extra args (defaults follow the fork's experiment configs):
      ``sfedavg_alpha`` / ``sfedavg_beta`` — reputation EMA coefficients;
      ``sampling_filter`` — ``"exp"`` biases sampling by ``exp(phi)``;
      ``score_method`` — ``"acc" | "F1" | "Recall" | "Precision"``;
      ``target_label`` — class watched for backdoor suppression (int or
      None); ``sv_max_perms`` — permutation cap (reference caps at
      cohort**2 distance samples); ``sv_tol`` — convergence limit
      (reference ``approaching_limit=0.005``); ``valid_batches`` —
      number of global-test batches held out as the aggregator's
      validation set (reference: dedicated ``valid_data_in_aggregator``).
    """

    algorithm = "SFedAvg"
    _keep_stacked = True

    def __init__(self, args, device, dataset, model, mesh=None) -> None:
        super().__init__(args, device, dataset, model, mesh=mesh)
        K = dataset.client_num
        self.alpha = float(getattr(args, "sfedavg_alpha", 0.5))
        self.beta = float(getattr(args, "sfedavg_beta", 0.5))
        self.sampling_filter = getattr(args, "sampling_filter", "exp")
        self.score_method = str(getattr(args, "score_method", "acc"))
        self.target_label = getattr(args, "target_label", None)
        self.sv_tol = float(getattr(args, "sv_tol", 0.005))
        cap = getattr(args, "sv_max_perms", None)
        self.sv_max_perms = int(
            cap if cap is not None else int(args.client_num_per_round) ** 2
        )
        nval = int(getattr(args, "valid_batches", 4))
        self.val_data = _take_batches(
            self.dataset.test_data_global, max(1, min(nval, self.dataset.test_data_global.mask.shape[0]))
        )
        # reputation state (fedavg_api.py:152-163)
        self.phi = np.full((K,), 1.0 / K, dtype=np.float64)
        self.sv = np.full((K,), (1.0 - self.alpha) / (K * self.beta), dtype=np.float64)
        self.sv_history: List[Dict[str, float]] = []
        self._build_shapley()

    # -- scoring ------------------------------------------------------
    def _build_shapley(self) -> None:
        apply_fn = self.model.apply
        tgt = self.target_label
        method = self.score_method

        def score(params, val: Batches) -> jax.Array:
            def step(carry, batch):
                x, y, m = batch
                pred = jnp.argmax(apply_fn(params, x), axis=-1)
                correct = ((pred == y) * m).sum()
                out = {"correct": correct, "count": m.sum()}
                if tgt is not None:
                    is_t = (y == tgt).astype(m.dtype) * m
                    pred_t = (pred == tgt).astype(m.dtype) * m
                    out["tp"] = (is_t * pred_t).sum()
                    out["fp"] = ((1 - (y == tgt)) * pred_t * m).sum()
                    out["fn"] = (is_t * (1 - (pred == tgt))).sum()
                return carry, out

            _, sums = jax.lax.scan(step, None, (val.x, val.y, val.mask))
            s = jax.tree.map(lambda a: a.sum(), sums)
            acc = s["correct"] / jnp.maximum(s["count"], 1.0)
            if tgt is None or method in ("acc", "Accuracy"):
                return acc
            prec = s["tp"] / jnp.maximum(s["tp"] + s["fp"], 1.0)
            rec = s["tp"] / jnp.maximum(s["tp"] + s["fn"], 1.0)
            if method in ("Precision", "PPV", "ppv"):
                return prec
            if method in ("Sensitivity", "Recall", "TPR", "tpr"):
                return rec
            return 2.0 * prec * rec / jnp.maximum(prec + rec, 1e-12)

        def shapley_perm(stacked: Params, weights: jax.Array, perm: jax.Array, val: Batches):
            w = jnp.take(weights, perm)
            cw = jnp.cumsum(w)

            def prefix(leaf: jax.Array) -> jax.Array:
                s = jnp.take(leaf, perm, axis=0)
                wr = w.reshape((-1,) + (1,) * (leaf.ndim - 1)).astype(leaf.dtype)
                cwr = cw.reshape((-1,) + (1,) * (leaf.ndim - 1)).astype(leaf.dtype)
                return jnp.cumsum(wr * s, axis=0) / jnp.maximum(cwr, 1e-12)

            prefix_models = jax.tree.map(prefix, stacked)
            scores = jax.vmap(score, in_axes=(0, None))(prefix_models, val)  # [C]
            marg = scores - jnp.concatenate([jnp.zeros((1,)), scores[:-1]])
            # scatter marginals back to cohort slots
            return jnp.zeros_like(marg).at[perm].set(marg)

        self._shapley_perm = jax.jit(shapley_perm)

    def _is_approached(self, d: List[float], cohort: int) -> bool:
        """Reference convergence test (fedavg_api.py:138-146)."""
        if len(d) >= self.sv_max_perms:
            return False
        if len(d) <= cohort:
            return True
        return any(x >= self.sv_tol for x in d[-3:])

    def _post_round_stacked(self, stacked: Params, idx: np.ndarray, rng) -> None:
        C = int(idx.shape[0])
        ns = jnp.take(jnp.asarray(self.dataset.packed_num_samples), jnp.asarray(idx))
        weights = normalize_weights(ns)
        sv_est = np.zeros((C,), dtype=np.float64)
        cnt = 0
        d: List[float] = []
        perm_rng = np.random.default_rng(int(jax.random.randint(rng, (), 0, 2**31 - 1)))
        while self._is_approached(d, C):
            perm = jnp.asarray(perm_rng.permutation(C))
            sv_new = np.asarray(self._shapley_perm(stacked, weights, perm, self.val_data))
            sv_next = (cnt * sv_est + sv_new) / (cnt + 1)
            if cnt:
                d.append(float(np.linalg.norm(sv_next - sv_est)))
            sv_est = sv_next
            cnt += 1
        # reputation update (fedavg_api.py:252-258)
        for j, client_idx in enumerate(np.asarray(idx)):
            self.sv[client_idx] = sv_est[j]
            self.phi[client_idx] = (
                self.alpha * self.phi[client_idx] + self.beta * self.sv[client_idx]
            )
        self.sv_history.append(
            {"perms": cnt, "sv_mean": float(sv_est.mean()), "phi_min": float(self.phi.min())}
        )
        logging.debug("S-FedAvg: %d permutations, sv=%s", cnt, sv_est)

    # -- checkpoint hooks: persist the reputation state ---------------
    def _extra_checkpoint_state(self):
        return {"phi": self.phi, "sv": self.sv}

    def _restore_extra_state(self, extra) -> None:
        if extra is not None:
            self.phi = np.asarray(extra["phi"], dtype=np.float64)
            self.sv = np.asarray(extra["sv"], dtype=np.float64)

    # -- reputation-biased sampling (fedavg_api.py:435-477) -----------
    def _client_sampling(self, round_idx, client_num_in_total, client_num_per_round):
        if client_num_in_total == client_num_per_round:
            return np.arange(client_num_in_total, dtype=np.int32)
        if self.sampling_filter == "exp":
            p = np.exp(self.phi)
        else:
            p = np.ones((client_num_in_total,))
        p = p / (p.sum() + 1e-13)
        # local RandomState: identical draws to np.random.seed(round_idx)
        # without clobbering the caller's global NumPy RNG
        rs = np.random.RandomState(round_idx)
        return np.asarray(
            rs.choice(
                range(client_num_in_total), client_num_per_round, replace=False, p=p
            ),
            dtype=np.int32,
        )


# ---------------------------------------------------------------------------
# HS-FedAvg
# ---------------------------------------------------------------------------


def make_hs_normalizer(h: int, w: int, L: float, momentum: float):
    """Build the jitted FFT amplitude-normalization transform.

    Returns ``normalize(x, mask, running_amp) -> (x', running_amp')``
    where ``x`` is ``[..., H, W, C]`` with per-example validity ``mask``
    of shape ``x.shape[:-3]``. Band semantics follow ``hs_fft.mutate``:
    half-width ``b = floor(min(H,W)*L)`` around the fftshifted centre.
    """
    b = int(np.floor(min(h, w) * L))
    ch, cw = h // 2, w // 2
    band_np = np.zeros((h, w, 1), np.float32)
    band_np[max(ch - b, 0) : ch + b + 1, max(cw - b, 0) : cw + b + 1] = 1.0
    band = jnp.asarray(band_np)

    def normalize(x: jax.Array, mask: jax.Array, running_amp: jax.Array):
        xf = x.astype(jnp.float32)
        fft = jnp.fft.fft2(xf, axes=(-3, -2))
        amp = jnp.abs(fft)
        pha = jnp.angle(fft)
        mexp = mask.reshape(mask.shape + (1, 1, 1)).astype(jnp.float32)
        lead = tuple(range(mask.ndim))
        batch_amp = (amp * mexp).sum(axis=lead) / jnp.maximum(mexp.sum(), 1.0)
        new_running = jnp.where(
            running_amp.sum() == 0.0,
            batch_amp,
            running_amp * (1.0 - momentum) + batch_amp * momentum,
        )
        a_src = jnp.fft.fftshift(amp, axes=(-3, -2))
        a_trg = jnp.fft.fftshift(new_running, axes=(0, 1))
        a_new = a_src * (1.0 - band) + a_trg * band
        fft_new = jnp.fft.ifftshift(a_new, axes=(-3, -2)) * jnp.exp(1j * pha)
        x_new = jnp.real(jnp.fft.ifft2(fft_new, axes=(-3, -2)))
        return jnp.where(mexp > 0, x_new, xf).astype(x.dtype), new_running

    return normalize


class HSFedAvgAPI(FedAvgAPI):
    """FFT amplitude-spectrum defense (HS-FedAvg).

    The running amplitude spectrum lives in ``server_state`` and is
    threaded through the jitted round; the cohort's images are
    normalized in-jit before local training. Extra args: ``hs_L``
    (band ratio, reference uses 0.0 → DC only), ``hs_momentum``
    (reference 0.1). Requires vectorized mode and image data.
    """

    algorithm = "HSFedAvg"

    def __init__(self, args, device, dataset, model, mesh=None) -> None:
        shape = dataset.packed_train.x.shape
        if len(shape) != 6:
            raise ValueError("HS-FedAvg needs image data [C, nb, bs, H, W, ch]")
        self._img_hw = (int(shape[-3]), int(shape[-2]), int(shape[-1]))
        self._normalize = make_hs_normalizer(
            self._img_hw[0],
            self._img_hw[1],
            float(getattr(args, "hs_L", 0.0)),
            float(getattr(args, "hs_momentum", 0.1)),
        )
        super().__init__(args, device, dataset, model, mesh=mesh)

    def _init_server_state(self):
        h, w, c = self._img_hw
        return jnp.zeros((h, w, c), jnp.float32)

    def _preprocess(self, cohort: Batches, server_state):
        x_new, new_amp = self._normalize(cohort.x, cohort.mask, server_state)
        return Batches(x=x_new, y=cohort.y, mask=cohort.mask), new_amp
