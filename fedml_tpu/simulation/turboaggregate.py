"""TurboAggregate: FedAvg with secure (masked) aggregation.

Reference: ``simulation/mpi_p2p_mp/turboaggregate/`` (``TA_trainer.py``,
``TA_decentralized_worker.py``, ``mpc_function.py``) — clients'
model updates are quantized into a prime field and combined through
additive/Lagrange-coded shares so the server only learns the SUM.

Here the local training stays a fully-jitted vectorized round (the TPU
path is identical to FedAvg); the aggregation step is replaced by the
host-side :class:`~fedml_tpu.core.secure_agg.TurboAggregateProtocol`
ring — the protocol boundary matches the reference, where shares are
numpy arrays exchanged between MPI ranks.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..core.secure_agg import (
    TurboAggregateProtocol,
    flatten_params,
    unflatten_params,
)
from .fedavg_api import FedAvgAPI

Params = Any


class TurboAggregateAPI(FedAvgAPI):
    """FedAvg round loop with secure weighted aggregation.

    Extra args: ``ta_groups`` (ring groups, default 4),
    ``ta_quant_scale`` (field quantization scale, default 2^16 —
    weighted updates must satisfy ``|x| * scale * C < p/2``).
    """

    algorithm = "TurboAggregate"
    _keep_stacked = True

    def __init__(self, args, device, dataset, model, mesh=None) -> None:
        if getattr(args, "defense_type", None):
            raise ValueError(
                "TurboAggregate replaces the aggregation step with the "
                "secure-sum protocol; robust defense_type cannot be "
                "combined with it (the server never sees raw updates)"
            )
        super().__init__(args, device, dataset, model, mesh=mesh)
        self.protocol = TurboAggregateProtocol(
            n_clients=int(args.client_num_per_round),
            n_groups=int(getattr(args, "ta_groups", 4)),
            scale=float(getattr(args, "ta_quant_scale", 2.0**16)),
            seed=int(getattr(args, "random_seed", 0)),
        )

    def _aggregate(self, global_params, server_state, new_stacked, weights, cohort, rng):
        # in-jit aggregation is a no-op: the secure path happens on the
        # host in _post_round_stacked (protocol boundary, like the
        # reference's MPI share exchange)
        return global_params, server_state

    def _post_round_stacked(self, stacked: Params, idx: np.ndarray, rng) -> None:
        from ..core.aggregation import normalize_weights

        ns = np.take(np.asarray(self.dataset.packed_num_samples), np.asarray(idx))
        weights = np.asarray(normalize_weights(jnp.asarray(ns)))
        C = int(idx.shape[0])
        # one device->host transfer for the whole cohort, then numpy
        # slicing per client
        stacked_host = jax.device_get(stacked)
        leaves = jax.tree.leaves(stacked_host)
        updates = [
            np.concatenate([np.asarray(l[j]).reshape(-1) for l in leaves])
            for j in range(C)
        ]
        _, spec = flatten_params(jax.tree.map(lambda a: a[0], stacked_host))
        agg = self.protocol.secure_weighted_sum(updates, weights.astype(np.float64))
        self.global_params = jax.tree.map(
            jnp.asarray, unflatten_params(agg, spec)
        )
