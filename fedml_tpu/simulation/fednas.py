"""FedNAS: federated differentiable architecture search.

Reference: ``simulation/mpi_p2p_mp/fednas`` (894 LoC) + the DARTS
search space: each round, every client alternates an ARCHITECT step
(alphas on its validation half, first-order DARTS — ``architect.py``
with unrolled=False) with a WEIGHT step (network weights on its
training half); the server averages both weights and alphas
(``FedNASAggregator``).

TPU-first: one jitted round — the alternating bilevel scan is vmapped
across the cohort; the w/alpha split is gradient masking over one param
pytree, so aggregation is the same stacked weighted mean as FedAvg.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.aggregation import normalize_weights, weighted_average
from ..core.types import Batches
from ..data.loader import FederatedDataset
from ..models.darts import DARTSNetwork, arch_path, genotype, split_grad_masks
from .fedavg_api import deterministic_client_sampling

Params = Any


class FedNASAPI:
    """Args: ``nas_width``, ``nas_cells``, ``nas_steps``,
    ``arch_learning_rate`` (reference arch_lr), ``learning_rate``."""

    algorithm = "FedNAS"

    def __init__(self, args, device, dataset: FederatedDataset, model=None, mesh=None):
        self.args = args
        self.dataset = dataset
        self.history: List[Dict[str, float]] = []
        cls = dataset.class_num
        # the model hub's 'darts' entry builds the search network from
        # the same args; reuse it so hyperparameters live in one place
        if model is not None and isinstance(
            getattr(model, "module", None), DARTSNetwork
        ):
            self.net = model.module
        else:
            self.net = DARTSNetwork(
                num_classes=cls,
                width=int(getattr(args, "nas_width", 16)),
                num_cells=int(getattr(args, "nas_cells", 2)),
                steps=int(getattr(args, "nas_steps", 2)),
            )
        img_shape = tuple(dataset.packed_train.x.shape[-3:])
        self.rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        self.rng, init_rng = jax.random.split(self.rng)
        self.global_params = self.net.init(
            init_rng, jnp.zeros((1,) + img_shape)
        )["params"]
        self._arch_keys = arch_path(self.global_params)

        self.w_opt = optax.sgd(float(getattr(args, "learning_rate", 0.025)), momentum=0.9)
        self.a_opt = optax.adam(float(getattr(args, "arch_learning_rate", 3e-4)))
        self.epochs = int(getattr(args, "epochs", 1))
        self._build_jitted()

    def _build_jitted(self) -> None:
        net = self.net
        w_opt, a_opt = self.w_opt, self.a_opt
        epochs = self.epochs

        def loss_fn(p, x, y, m):
            logits = net.apply({"params": p}, x)
            logp = jax.nn.log_softmax(logits)
            per = -jnp.take_along_axis(
                logp, y[..., None].astype(jnp.int32), axis=-1
            )[..., 0]
            count = m.sum()
            loss = (per * m).sum() / jnp.maximum(count, 1.0)
            correct = ((jnp.argmax(logits, -1) == y) * m).sum()
            return loss, {"correct": correct, "count": count}

        def local_search(params, batches: Batches, rng):
            """Alternating first-order DARTS. The local train/val halves
            are split along the EXAMPLE axis of every batch (not by
            batch slot: padding lives in the tail batches, so slot-wise
            halving would hand small clients an all-padding validation
            half and silently freeze them)."""
            w_mask, a_mask = split_grad_masks(params)
            bs = batches.mask.shape[-1]
            h = bs // 2
            tr = jax.tree.map(lambda a: a[:, :h], batches)
            va = jax.tree.map(lambda a: a[:, h:], batches)
            w_state = w_opt.init(params)
            a_state = a_opt.init(params)

            def step(carry, batch):
                p, ws, as_ = carry
                tx, ty, tm, vx, vy, vm = batch
                # architect step: alphas on the validation half
                # (skipped when this batch's val half is pure padding)
                (vl, _), g = jax.value_and_grad(loss_fn, has_aux=True)(p, vx, vy, vm)
                g_a = jax.tree.map(jnp.multiply, g, a_mask)
                ua, as_new = a_opt.update(g_a, as_, p)
                p_a = optax.apply_updates(p, ua)
                has_val = vm.sum() > 0
                keep = lambda c, a, b: jax.tree.map(
                    lambda u, v: jnp.where(c, u, v), a, b
                )
                p_a = keep(has_val, p_a, p)
                as_new = keep(has_val, as_new, as_)
                # weight step: w on the training half
                (tl, metrics), g2 = jax.value_and_grad(loss_fn, has_aux=True)(
                    p_a, tx, ty, tm
                )
                g_w = jax.tree.map(jnp.multiply, g2, w_mask)
                uw, ws_new = w_opt.update(g_w, ws, p_a)
                p_w = optax.apply_updates(p_a, uw)
                has_train = tm.sum() > 0
                return (
                    keep(has_train, p_w, p_a),
                    keep(has_train, ws_new, ws),
                    as_new,
                ), {"loss_sum": tl * metrics["count"], **metrics}

            def epoch(carry, _):
                carry, ms = jax.lax.scan(
                    step, carry, (tr.x, tr.y, tr.mask, va.x, va.y, va.mask)
                )
                return carry, jax.tree.map(jnp.sum, ms)

            (params, _, _), per_epoch = jax.lax.scan(
                epoch, (params, w_state, a_state), None, length=epochs
            )
            return params, jax.tree.map(lambda a: a[-1], per_epoch)

        def round_fn(global_params, packed: Batches, nsamples, idx, rng):
            cohort = jax.tree.map(lambda a: jnp.take(a, idx, axis=0), packed)
            ns = jnp.take(nsamples, idx)
            rngs = jax.random.split(rng, idx.shape[0])
            stacked, ms = jax.vmap(local_search, in_axes=(None, 0, 0))(
                global_params, cohort, rngs
            )
            # FedNASAggregator: weights AND alphas averaged together
            new_global = weighted_average(stacked, normalize_weights(ns))
            return new_global, jax.tree.map(jnp.sum, ms)

        self._round_fn = jax.jit(round_fn, donate_argnums=(0,))

        def evaluate(params, test: Batches):
            def estep(_, batch):
                x, y, m = batch
                loss, metrics = loss_fn(params, x, y, m)
                return None, {"loss_sum": loss * metrics["count"], **metrics}

            _, out = jax.lax.scan(estep, None, (test.x, test.y, test.mask))
            return jax.tree.map(jnp.sum, out)

        self._evaluate = jax.jit(evaluate)

    def current_alphas(self) -> jax.Array:
        node = self.global_params
        for k in self._arch_keys:
            node = node[k]
        return node

    def current_genotype(self):
        return genotype(self.current_alphas(), steps=int(getattr(self.args, "nas_steps", 2)))

    def train(self) -> Dict[str, float]:
        args = self.args
        packed = self.dataset.packed_train
        nsamples = jnp.asarray(self.dataset.packed_num_samples)
        freq = max(1, int(getattr(args, "frequency_of_the_test", 5)))
        final: Dict[str, float] = {}
        for round_idx in range(int(args.comm_round)):
            t0 = time.perf_counter()
            idx = deterministic_client_sampling(
                round_idx, self.dataset.client_num, int(args.client_num_per_round)
            )
            self.rng, r_rng = jax.random.split(self.rng)
            self.global_params, ms = self._round_fn(
                self.global_params, packed, nsamples, jnp.asarray(idx), r_rng
            )
            if round_idx % freq == 0 or round_idx == int(args.comm_round) - 1:
                ev = self._evaluate(self.global_params, self.dataset.test_data_global)
                stats = {
                    "round": round_idx,
                    "round_time_s": time.perf_counter() - t0,
                    "train_loss": float(ms["loss_sum"]) / max(float(ms["count"]), 1.0),
                    "test_acc": float(ev["correct"]) / max(float(ev["count"]), 1.0),
                    "test_loss": float(ev["loss_sum"]) / max(float(ev["count"]), 1.0),
                    "genotype": str(self.current_genotype()),
                }
                self.history.append(stats)
                final = stats
        return final
