"""Split / vertical training: SplitNN, FedGKT, classical VFL.

Reference parity (behavior, not implementation):

- **SplitNN** — ``simulation/mpi_p2p_mp/split_nn/`` — the network is cut
  at a layer: the client owns the bottom, the server the top. Every
  batch, activations cross the boundary forward
  (``client.py:25-31 forward_pass``) and activation-gradients cross it
  backward (``server.py:61-65 backward_pass`` → ``client.py:33-36``).
  Clients take turns around a ring, relaying the bottom-model weights.

- **FedGKT** — ``simulation/mpi_p2p_mp/fedgkt/`` — Group Knowledge
  Transfer: each client trains a small extractor+head on raw data
  (CE + alpha*KL vs the server's logits, ``GKTClientTrainer.py:92-103``),
  ships extracted features + local logits + labels; the server trains a
  big net on the features (KL vs client logits + alpha*CE,
  ``GKTServerTrainer.py:326-340``) and returns per-client server logits.
  Client models stay personal (never averaged).

- **Classical VFL** — ``simulation/mpi_p2p_mp/classical_vertical_fl/``
  — features are partitioned vertically across parties; each party runs
  a bottom net on its slice, the guest combines party outputs, computes
  the loss against its labels, and returns the boundary gradient to
  every host (``guest_trainer.py:91-153``).

TPU-first redesign: every boundary crossing is expressed as an explicit
``jax.vjp`` seam inside ONE jitted computation — activations/gradients
are device arrays that never visit the host (the reference round-trips
``.cpu().detach().numpy()`` per batch, guest_trainer.py:109-131). The
seam is also where a mesh partition would place the stage boundary
(split-style model parallelism over ICI). FedGKT's cohort trains via
``vmap`` like the FedAvg engine; the server's big-model training is a
``lax.scan`` over the concatenated client feature batches.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.types import Batches
from ..data.loader import FederatedDataset

Params = Any


def _masked_ce(logits: jax.Array, y: jax.Array, mask: jax.Array):
    logp = jax.nn.log_softmax(logits)
    per = -jnp.take_along_axis(logp, y[..., None].astype(jnp.int32), axis=-1)[..., 0]
    count = mask.sum()
    loss = (per * mask).sum() / jnp.maximum(count, 1.0)
    pred = jnp.argmax(logits, axis=-1)
    correct = ((pred == y) * mask).sum()
    return loss, {"correct": correct, "count": count}


def _kl_loss(student_logits, teacher_logits, mask, temperature: float):
    """KL(teacher || student) with temperature scaling, masked mean —
    ``utils.KL_Loss`` in the reference GKT (T^2-scaled)."""
    t = temperature
    p_t = jax.nn.softmax(teacher_logits / t)
    logp_s = jax.nn.log_softmax(student_logits / t)
    logp_t = jax.nn.log_softmax(teacher_logits / t)
    per = (p_t * (logp_t - logp_s)).sum(axis=-1) * (t * t)
    return (per * mask).sum() / jnp.maximum(mask.sum(), 1.0)


# ---------------------------------------------------------------------------
# SplitNN
# ---------------------------------------------------------------------------


class SplitNNAPI:
    """Ring-relay split learning over a (bottom, top) model pair.

    The split pair is the GKT client/server pair (a GN-ResNet cut at the
    first stage boundary — ``model/cv/resnet56_gkt`` shape). One bottom
    model is relayed around the client ring (SplitNN's defining
    difference from FL: no weight averaging), the server's top model
    persists across all clients.
    """

    algorithm = "SplitNN"

    def __init__(self, args, device, dataset: FederatedDataset, model=None, mesh=None):
        from ..models.gkt import GKTClientNet, GKTServerNet

        self.args = args
        self.dataset = dataset
        self.history: List[Dict[str, float]] = []
        cls = dataset.class_num
        self.bottom = GKTClientNet(output_dim=cls)
        self.top = GKTServerNet(
            output_dim=cls,
            stage_sizes=tuple(
                int(s) for s in getattr(args, "splitnn_stages", (1, 1, 1))
            ),
        )
        img_shape = tuple(dataset.packed_train.x.shape[-3:])
        self.rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        self.rng, br, tr = jax.random.split(self.rng, 3)
        x0 = jnp.zeros((1,) + img_shape)
        self.bottom_params = self.bottom.init(br, x0)["params"]
        feats0, _ = self.bottom.apply({"params": self.bottom_params}, x0)
        self.top_params = self.top.init(tr, feats0)["params"]

        lr = float(getattr(args, "learning_rate", 0.1))
        mom = float(getattr(args, "momentum", 0.9))
        self.opt_b = optax.sgd(lr, momentum=mom if mom else None)
        self.opt_t = optax.sgd(lr, momentum=mom if mom else None)
        self.opt_b_state = self.opt_b.init(self.bottom_params)
        self.opt_t_state = self.opt_t.init(self.top_params)
        self.epochs = int(getattr(args, "epochs", 1))
        self._build_jitted()

    def _build_jitted(self) -> None:
        bottom, top = self.bottom, self.top
        opt_b, opt_t = self.opt_b, self.opt_t
        epochs = self.epochs

        def step(carry, batch):
            pb, pt, sb, st = carry
            x, y, m = batch

            # -- the split boundary: activations forward ---------------
            def bottom_fwd(p):
                feats, _ = bottom.apply({"params": p}, x)
                return feats

            acts, vjp_b = jax.vjp(bottom_fwd, pb)

            # -- server side: loss on top of received activations ------
            def top_loss(pt_, acts_):
                logits = top.apply({"params": pt_}, acts_)
                return _masked_ce(logits, y, m)

            (loss, metrics), (g_top, d_acts) = jax.value_and_grad(
                top_loss, argnums=(0, 1), has_aux=True
            )(pt, acts)

            # -- boundary gradient back into the client ----------------
            (g_bottom,) = vjp_b(d_acts)

            ub, sb_new = opt_b.update(g_bottom, sb, pb)
            ut, st_new = opt_t.update(g_top, st, pt)
            pb_new = optax.apply_updates(pb, ub)
            pt_new = optax.apply_updates(pt, ut)
            nonempty = m.sum() > 0
            keep = lambda a, b: jax.tree.map(
                lambda u, v: jnp.where(nonempty, u, v), a, b
            )
            return (
                keep(pb_new, pb),
                keep(pt_new, pt),
                keep(sb_new, sb),
                keep(st_new, st),
            ), {"loss_sum": loss * metrics["count"], **metrics}

        def client_pass(pb, pt, sb, st, batches: Batches):
            def epoch(carry, _):
                carry, ms = jax.lax.scan(
                    step, carry, (batches.x, batches.y, batches.mask)
                )
                return carry, jax.tree.map(jnp.sum, ms)

            (pb, pt, sb, st), per_epoch = jax.lax.scan(
                epoch, (pb, pt, sb, st), None, length=epochs
            )
            last = jax.tree.map(lambda a: a[-1], per_epoch)
            return pb, pt, sb, st, last

        self._client_pass = jax.jit(client_pass, donate_argnums=(0, 1, 2, 3))

        def evaluate(pb, pt, test: Batches):
            def estep(_, batch):
                x, y, m = batch
                feats, _ = bottom.apply({"params": pb}, x)
                logits = top.apply({"params": pt}, feats)
                loss, metrics = _masked_ce(logits, y, m)
                return None, {"loss_sum": loss * metrics["count"], **metrics}

            _, out = jax.lax.scan(estep, None, (test.x, test.y, test.mask))
            return jax.tree.map(jnp.sum, out)

        self._evaluate = jax.jit(evaluate)

    def train(self) -> Dict[str, float]:
        args = self.args
        packed = self.dataset.packed_train
        freq = max(1, int(getattr(args, "frequency_of_the_test", 5)))
        final: Dict[str, float] = {}
        for round_idx in range(int(args.comm_round)):
            t0 = time.perf_counter()
            train_loss_sum, train_count = 0.0, 0.0
            # ring order: client r%C starts the relay this round
            C = self.dataset.client_num
            order = [(round_idx + k) % C for k in range(C)]
            for ci in order:
                client = Batches(
                    x=packed.x[ci], y=packed.y[ci], mask=packed.mask[ci]
                )
                (
                    self.bottom_params,
                    self.top_params,
                    self.opt_b_state,
                    self.opt_t_state,
                    ms,
                ) = self._client_pass(
                    self.bottom_params,
                    self.top_params,
                    self.opt_b_state,
                    self.opt_t_state,
                    client,
                )
                train_loss_sum += float(ms["loss_sum"])
                train_count += float(ms["count"])
            if round_idx % freq == 0 or round_idx == int(args.comm_round) - 1:
                ev = self._evaluate(
                    self.bottom_params, self.top_params, self.dataset.test_data_global
                )
                stats = {
                    "round": round_idx,
                    "round_time_s": time.perf_counter() - t0,
                    "train_loss": train_loss_sum / max(train_count, 1.0),
                    "test_acc": float(ev["correct"]) / max(float(ev["count"]), 1.0),
                    "test_loss": float(ev["loss_sum"]) / max(float(ev["count"]), 1.0),
                }
                self.history.append(stats)
                final = stats
        return final


# ---------------------------------------------------------------------------
# FedGKT
# ---------------------------------------------------------------------------


class FedGKTAPI:
    """Group Knowledge Transfer. Personal client nets + one big server
    net trained on exchanged features/logits (bidirectional KD).

    Args: ``gkt_alpha`` (KD mixing, reference ``args.alpha``),
    ``gkt_temperature`` (reference ``args.temperature``),
    ``gkt_server_epochs`` (server epochs per round).
    """

    algorithm = "FedGKT"

    def __init__(self, args, device, dataset: FederatedDataset, model=None, mesh=None):
        from ..models.gkt import GKTClientNet, GKTServerNet

        self.args = args
        self.dataset = dataset
        self.history: List[Dict[str, float]] = []
        cls = dataset.class_num
        self.client_net = GKTClientNet(output_dim=cls)
        self.server_net = GKTServerNet(
            output_dim=cls,
            stage_sizes=tuple(
                int(s) for s in getattr(args, "gkt_server_stages", (2, 2, 2))
            ),
        )
        self.alpha = float(getattr(args, "gkt_alpha", 1.0))
        self.temperature = float(getattr(args, "gkt_temperature", 3.0))
        self.epochs = int(getattr(args, "epochs", 1))
        self.server_epochs = int(getattr(args, "gkt_server_epochs", 1))
        lr = float(getattr(args, "learning_rate", 0.03))

        C = dataset.client_num
        img_shape = tuple(dataset.packed_train.x.shape[-3:])
        self.rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        self.rng, cr, sr = jax.random.split(self.rng, 3)
        x0 = jnp.zeros((1,) + img_shape)
        p0 = self.client_net.init(cr, x0)["params"]
        feats0, _ = self.client_net.apply({"params": p0}, x0)
        self.server_params = self.server_net.init(sr, feats0)["params"]
        # personal client models: stacked [C, ...]
        keys = jax.random.split(cr, C)
        self.client_params = jax.vmap(
            lambda k: self.client_net.init(k, x0)["params"]
        )(keys)
        # per-client server logits fed back as KD teachers
        nb, bs = dataset.packed_train.mask.shape[-2:]
        self.server_logits = jnp.zeros((C, nb, bs, cls))

        self.opt_c = optax.sgd(lr, momentum=0.9)
        self.opt_s = optax.sgd(lr, momentum=0.9)
        self.opt_s_state = self.opt_s.init(self.server_params)
        # personal client optimizers persist across rounds (reference
        # GKTClientTrainer creates its SGD once in __init__)
        self.opt_c_states = jax.vmap(self.opt_c.init)(self.client_params)
        self._build_jitted()

    def _build_jitted(self) -> None:
        client_net, server_net = self.client_net, self.server_net
        opt_c, opt_s = self.opt_c, self.opt_s
        alpha, T = self.alpha, self.temperature
        epochs, server_epochs = self.epochs, self.server_epochs

        def client_local_train(pc, sc, batches: Batches, teacher, kd_weight):
            """CE + alpha*KL(teacher=server) (GKTClientTrainer.py:92-103)."""

            def loss_fn(p, x, y, m, t_logits):
                _, logits = client_net.apply({"params": p}, x)
                ce, metrics = _masked_ce(logits, y, m)
                kd = _kl_loss(logits, t_logits, m, T)
                return ce + alpha * kd_weight * kd, metrics

            def step(carry, batch):
                p, s = carry
                x, y, m, t_logits = batch
                (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                    p, x, y, m, t_logits
                )
                u, s_new = opt_c.update(grads, s, p)
                p_new = optax.apply_updates(p, u)
                nonempty = m.sum() > 0
                p = jax.tree.map(lambda a, b: jnp.where(nonempty, a, b), p_new, p)
                s = jax.tree.map(lambda a, b: jnp.where(nonempty, a, b), s_new, s)
                return (p, s), {"loss_sum": loss * metrics["count"], **metrics}

            def epoch(carry, _):
                carry, ms = jax.lax.scan(
                    step, carry, (batches.x, batches.y, batches.mask, teacher)
                )
                return carry, jax.tree.map(jnp.sum, ms)

            (pc, sc), per_epoch = jax.lax.scan(epoch, (pc, sc), None, length=epochs)
            return pc, sc, jax.tree.map(lambda a: a[-1], per_epoch)

        def extract(pc, batches: Batches):
            """Features + logits for every local sample (what the client
            ships to the server)."""

            def one(x):
                return client_net.apply({"params": pc}, x)

            return jax.vmap(one)(batches.x)  # ([nb, bs, h, w, c], [nb, bs, cls])

        def gkt_round(client_params, client_opt_states, server_params, opt_s_state,
                      server_logits, packed: Batches, kd_weight):
            # 1) personal client training (vmap cohort; all clients
            #    participate every round — GKT trains the federation)
            new_client_params, new_client_opt_states, cm = jax.vmap(
                client_local_train, in_axes=(0, 0, 0, 0, None)
            )(client_params, client_opt_states, packed, server_logits, kd_weight)

            # 2) feature/logit exchange
            feats, client_logits = jax.vmap(extract)(new_client_params, packed)

            # 3) server training on all clients' features:
            #    KL(client logits) + alpha*CE (GKTServerTrainer.py:326-332)
            C, nb = packed.mask.shape[0], packed.mask.shape[1]
            flat = lambda a: a.reshape((C * nb,) + a.shape[2:])
            sf, sl, sy, sm = flat(feats), flat(client_logits), flat(packed.y), flat(packed.mask)

            def s_loss(ps, f, t_logits, y, m):
                out = server_net.apply({"params": ps}, f)
                ce, metrics = _masked_ce(out, y, m)
                kd = _kl_loss(out, t_logits, m, T)
                return kd + alpha * ce, metrics

            def s_step(carry, batch):
                ps, ss = carry
                f, t_logits, y, m = batch
                (loss, metrics), grads = jax.value_and_grad(s_loss, has_aux=True)(
                    ps, f, t_logits, y, m
                )
                u, ss_new = opt_s.update(grads, ss, ps)
                ps_new = optax.apply_updates(ps, u)
                nonempty = m.sum() > 0
                ps = jax.tree.map(lambda a, b: jnp.where(nonempty, a, b), ps_new, ps)
                ss = jax.tree.map(lambda a, b: jnp.where(nonempty, a, b), ss_new, ss)
                return (ps, ss), {"loss_sum": loss * metrics["count"], **metrics}

            def s_epoch(carry, _):
                carry, ms = jax.lax.scan(s_step, carry, (sf, sl, sy, sm))
                return carry, jax.tree.map(jnp.sum, ms)

            (server_params, opt_s_state), s_per_epoch = jax.lax.scan(
                s_epoch, (server_params, opt_s_state), None, length=server_epochs
            )
            s_last = jax.tree.map(lambda a: a[-1], s_per_epoch)

            # 4) refreshed per-client server logits (KD teachers)
            def s_logits(f):
                return server_net.apply({"params": server_params}, f)

            new_server_logits = jax.vmap(jax.vmap(s_logits))(feats)
            client_summed = jax.tree.map(lambda a: a.sum(), cm)
            return (
                new_client_params,
                new_client_opt_states,
                server_params,
                opt_s_state,
                new_server_logits,
                {"client": client_summed, "server": s_last},
            )

        self._round_fn = jax.jit(gkt_round, donate_argnums=(0, 1, 2, 3, 4))

        def evaluate(client_params, server_params, packed_test: Batches):
            """Per-client extractor -> server net on local test sets
            (the reference's server-side test over client-sent test
            features, GKTServerTrainer.py:371-403)."""

            def per_client(pc, batches):
                def estep(_, batch):
                    x, y, m = batch
                    f, _ = client_net.apply({"params": pc}, x)
                    out = server_net.apply({"params": server_params}, f)
                    loss, metrics = _masked_ce(out, y, m)
                    return None, {"loss_sum": loss * metrics["count"], **metrics}

                _, out = jax.lax.scan(estep, None, (batches.x, batches.y, batches.mask))
                return jax.tree.map(jnp.sum, out)

            sums = jax.vmap(per_client)(client_params, packed_test)
            return jax.tree.map(lambda a: a.sum(), sums)

        self._evaluate = jax.jit(evaluate)

    def train(self) -> Dict[str, float]:
        args = self.args
        packed = self.dataset.packed_train
        freq = max(1, int(getattr(args, "frequency_of_the_test", 5)))
        final: Dict[str, float] = {}
        for round_idx in range(int(args.comm_round)):
            t0 = time.perf_counter()
            kd_weight = jnp.asarray(0.0 if round_idx == 0 else 1.0)
            (
                self.client_params,
                self.opt_c_states,
                self.server_params,
                self.opt_s_state,
                self.server_logits,
                ms,
            ) = self._round_fn(
                self.client_params,
                self.opt_c_states,
                self.server_params,
                self.opt_s_state,
                self.server_logits,
                packed,
                kd_weight,
            )
            if round_idx % freq == 0 or round_idx == int(args.comm_round) - 1:
                ev = self._evaluate(
                    self.client_params, self.server_params, self.dataset.packed_test
                )
                stats = {
                    "round": round_idx,
                    "round_time_s": time.perf_counter() - t0,
                    "train_loss": float(ms["client"]["loss_sum"])
                    / max(float(ms["client"]["count"]), 1.0),
                    "server_loss": float(ms["server"]["loss_sum"])
                    / max(float(ms["server"]["count"]), 1.0),
                    "test_acc": float(ev["correct"]) / max(float(ev["count"]), 1.0),
                    "test_loss": float(ev["loss_sum"]) / max(float(ev["count"]), 1.0),
                }
                self.history.append(stats)
                final = stats
        return final


# ---------------------------------------------------------------------------
# Classical VFL
# ---------------------------------------------------------------------------


def vertical_split(x: np.ndarray, n_parties: int) -> List[np.ndarray]:
    """Partition flattened features column-wise across parties
    (NUS-WIDE / lending-club style feature split)."""
    flat = x.reshape(x.shape[0], -1)
    cols = np.array_split(np.arange(flat.shape[1]), n_parties)
    return [flat[:, c] for c in cols]


class VFLAPI:
    """Classical vertical FL: guest + (n_parties-1) hosts.

    Every party runs a bottom net on its private feature slice; the
    guest sums the party representations, applies its top model and the
    loss, and the boundary gradient (identical for every party, since
    the combiner is a sum) flows back through each party's ``vjp``
    (guest_trainer.py:91-153's numpy round-trip, fused on-device).
    """

    algorithm = "VFL"

    def __init__(self, args, device, dataset: FederatedDataset, model=None, mesh=None):
        from ..models.vfl import GuestTopModel, PartyLocalModel

        self.args = args
        self.dataset = dataset
        self.history: List[Dict[str, float]] = []
        self.n_parties = int(getattr(args, "vfl_parties", 2))
        rep_dim = int(getattr(args, "vfl_rep_dim", 32))
        cls = dataset.class_num
        lr = float(getattr(args, "learning_rate", 0.05))
        self.epochs = int(getattr(args, "epochs", 1))

        # the loader attaches real party data when party CSVs exist
        # under data_cache_dir/<dataset>; direct construction without
        # load() falls back to probing the path itself
        real = getattr(dataset, "vfl_parties", None) or self._try_load_party_csvs(args)
        if real is not None:
            # real vertically-partitioned data (NUS-WIDE / lending-club
            # style party CSVs): each organization's feature columns ARE
            # the vertical split — no synthetic column slicing
            feats, labels = real
            self.n_parties = len(feats)
            cls = max(cls, int(labels.max()) + 1)
            self._train, self._test = self._pack_party_data(
                feats, labels, int(getattr(args, "batch_size", 32))
            )
        else:
            # vertically partition the centralized training features
            tr, te = dataset.train_data_global, dataset.test_data_global
            self._train = self._split_batches(tr)
            self._test = self._split_batches(te)

        self.party_net = PartyLocalModel(output_dim=rep_dim)
        self.top_net = GuestTopModel(output_dim=cls)
        self.rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        keys = jax.random.split(self.rng, self.n_parties + 1)
        self.party_params = [
            self.party_net.init(keys[i], jnp.zeros((1, self._train[0][i].shape[-1])))[
                "params"
            ]
            for i in range(self.n_parties)
        ]
        self.top_params = self.top_net.init(keys[-1], jnp.zeros((1, rep_dim)))["params"]
        self.opt = optax.sgd(lr)
        self.opt_states = [self.opt.init(p) for p in self.party_params]
        self.opt_top_state = self.opt.init(self.top_params)
        self._build_jitted()

    @staticmethod
    def _try_load_party_csvs(args):
        import os

        cache = getattr(args, "data_cache_dir", None)
        name = getattr(args, "dataset", "").lower()
        if not cache or not name:
            return None
        d = os.path.join(cache, name)
        from ..data.ingest import load_vfl_party_csvs, vfl_party_csvs_available

        if not vfl_party_csvs_available(d):
            return None
        return load_vfl_party_csvs(d)

    def _pack_party_data(self, feats, labels, batch_size: int):
        """Row-aligned party arrays -> two (xs, y, mask) batch sets.
        The train/test split comes from the CANONICAL shared helper
        (ingest.vfl_train_test_split) — the loader's horizontal view of
        the same CSVs uses it too, so the two views can never leak test
        rows into each other's training split."""
        from ..data.ingest import vfl_train_test_split

        f_tr, y_tr, f_te, y_te = vfl_train_test_split(
            feats, labels, int(getattr(self.args, "random_seed", 0))
        )

        def pack(split_feats, split_labels):
            m = len(split_labels)
            nb = max(1, -(-m // batch_size))
            pad = nb * batch_size - m
            xs = []
            for sl in split_feats:
                if pad:
                    sl = np.concatenate(
                        [sl, np.zeros((pad,) + sl.shape[1:], sl.dtype)]
                    )
                xs.append(jnp.asarray(sl.reshape(nb, batch_size, -1)))
            y = split_labels
            if pad:
                y = np.concatenate([y, np.zeros(pad, y.dtype)])
            mask = np.concatenate(
                [np.ones(m, np.float32), np.zeros(pad, np.float32)]
            )
            return (
                xs,
                jnp.asarray(y.reshape(nb, batch_size)),
                jnp.asarray(mask.reshape(nb, batch_size)),
            )

        return pack(f_tr, y_tr), pack(f_te, y_te)

    def _split_batches(self, b: Batches):
        """[nb, bs, ...] -> (party feature slices [nb, bs, d_k], y, mask)."""
        x = np.asarray(b.x)
        nb, bs = x.shape[0], x.shape[1]
        slices = vertical_split(x.reshape(nb * bs, -1), self.n_parties)
        return (
            [jnp.asarray(s.reshape(nb, bs, -1)) for s in slices],
            b.y,
            b.mask,
        )

    def _build_jitted(self) -> None:
        party_net, top_net, opt = self.party_net, self.top_net, self.opt
        n_parties, epochs = self.n_parties, self.epochs

        def step(carry, batch):
            party_params, top_params, opt_states, opt_top = carry
            xs, y, m = batch[:-2], batch[-2], batch[-1]

            # party bottoms: forward with a vjp seam each
            reps, vjps = [], []
            for k in range(n_parties):
                rep, vjp_k = jax.vjp(
                    lambda p, xk=xs[k]: party_net.apply({"params": p}, xk),
                    party_params[k],
                )
                reps.append(rep)
                vjps.append(vjp_k)
            rep_sum = sum(reps)

            def guest_loss(pt, rep):
                logits = top_net.apply({"params": pt}, rep)
                return _masked_ce(logits, y, m)

            (loss, metrics), (g_top, d_rep) = jax.value_and_grad(
                guest_loss, argnums=(0, 1), has_aux=True
            )(top_params, rep_sum)

            new_party, new_states = [], []
            for k in range(n_parties):
                (g_k,) = vjps[k](d_rep)  # same boundary grad to every host
                u, s_new = opt.update(g_k, opt_states[k], party_params[k])
                new_party.append(optax.apply_updates(party_params[k], u))
                new_states.append(s_new)
            u_t, opt_top_new = opt.update(g_top, opt_top, top_params)
            top_new = optax.apply_updates(top_params, u_t)

            nonempty = m.sum() > 0
            keep = lambda a, b: jax.tree.map(
                lambda u_, v_: jnp.where(nonempty, u_, v_), a, b
            )
            return (
                [keep(a, b) for a, b in zip(new_party, party_params)],
                keep(top_new, top_params),
                [keep(a, b) for a, b in zip(new_states, opt_states)],
                keep(opt_top_new, opt_top),
            ), {"loss_sum": loss * metrics["count"], **metrics}

        def run_epochs(party_params, top_params, opt_states, opt_top, xs, y, m):
            def epoch(carry, _):
                carry, ms = jax.lax.scan(step, carry, tuple(xs) + (y, m))
                return carry, jax.tree.map(jnp.sum, ms)

            carry, per_epoch = jax.lax.scan(
                epoch, (party_params, top_params, opt_states, opt_top), None,
                length=epochs,
            )
            return carry, jax.tree.map(lambda a: a[-1], per_epoch)

        self._run_epochs = jax.jit(run_epochs)

        def evaluate(party_params, top_params, xs, y, m):
            def estep(_, batch):
                bxs, by, bm = batch[:-2], batch[-2], batch[-1]
                rep = sum(
                    party_net.apply({"params": party_params[k]}, bxs[k])
                    for k in range(n_parties)
                )
                logits = top_net.apply({"params": top_params}, rep)
                loss, metrics = _masked_ce(logits, by, bm)
                return None, {"loss_sum": loss * metrics["count"], **metrics}

            _, out = jax.lax.scan(estep, None, tuple(xs) + (y, m))
            return jax.tree.map(lambda a: a.sum(), out)

        self._eval = jax.jit(evaluate)

    def train(self) -> Dict[str, float]:
        args = self.args
        xs, y, m = self._train
        freq = max(1, int(getattr(args, "frequency_of_the_test", 5)))
        final: Dict[str, float] = {}
        for round_idx in range(int(args.comm_round)):
            t0 = time.perf_counter()
            (
                (self.party_params, self.top_params, self.opt_states, self.opt_top_state),
                ms,
            ) = self._run_epochs(
                self.party_params,
                self.top_params,
                self.opt_states,
                self.opt_top_state,
                xs,
                y,
                m,
            )
            if round_idx % freq == 0 or round_idx == int(args.comm_round) - 1:
                txs, ty, tm = self._test
                ev = self._eval(self.party_params, self.top_params, txs, ty, tm)
                stats = {
                    "round": round_idx,
                    "round_time_s": time.perf_counter() - t0,
                    "train_loss": float(ms["loss_sum"]) / max(float(ms["count"]), 1.0),
                    "test_acc": float(ev["correct"]) / max(float(ev["count"]), 1.0),
                    "test_loss": float(ev["loss_sum"]) / max(float(ev["count"]), 1.0),
                }
                self.history.append(stats)
                final = stats
        return final
