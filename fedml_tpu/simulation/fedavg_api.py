"""FedAvg-family simulation: one jitted round engine, four algorithms.

Reference parity (``simulation/single_process/fedavg/fedavg_api.py:83-141``
round loop; ``fedopt/fedopt_api.py``; ``fednova/fednova_trainer.py:136-165``;
``mpi_p2p_mp/fedavg/FedAVGAggregator.py:68-113``), redesigned TPU-first:

- The reference trains sampled clients one-by-one in Python and averages
  python dicts on host. Here the ENTIRE round — gather the sampled
  cohort, vmap the local-training scan across clients, aggregate — is a
  single jitted XLA computation; global params and server-optimizer
  state are donated buffers that never leave the device.
- Client sampling keeps the reference's determinism contract:
  ``np.random.seed(round_idx)`` then ``choice`` without replacement
  (FedAVGAggregator.py:99-113).
- Robust aggregation (clip / weak-DP / median) plugs in via
  ``args.defense_type`` exactly where ``fedavg_robust`` puts it.
- ``mesh`` mode shards the cohort's client axis over a
  ``jax.sharding.Mesh`` — XLA turns the weighted reduction into an ICI
  all-reduce; see ``fedml_tpu/parallel/mesh.py``.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.compiled import auditable, pow2_budget
from ..core.devtime import measure as _devtime
from ..core.frame import bind_operator
from ..core.aggregation import (
    RobustAggregator,
    exact_weighted_mean,
    normalize_weights,
    weighted_average,
)
from ..core.local_trainer import (
    compute_dtype_from_args,
    make_eval_fn,
    make_local_train_fn,
)
from ..core.optimizers import (
    create_client_optimizer,
    create_server_optimizer,
    resolve_round_lr_schedule,
)
from ..core.types import Batches
from ..data.loader import FederatedDataset
from ..models.spec import FedModel

Params = Any


def _take(b: Batches, idx: jax.Array) -> Batches:
    return Batches(
        x=jnp.take(b.x, idx, axis=0),
        y=jnp.take(b.y, idx, axis=0),
        mask=jnp.take(b.mask, idx, axis=0),
    )


def build_round_fn(
    local_train,
    aggregate,
    preprocess=None,
    *,
    mesh=None,
    use_round_lr: bool = False,
    keep_stacked: bool = False,
    on_trace=None,
):
    """THE round engine, as a pure function of its collaborators.

    Module-level on purpose: the engine must never close over a
    mutable ``self`` (retrace hazard — the lint suite's rule), and the
    compiled-artifact auditor (``fedml_tpu/analysis/compiled.py``)
    AOT-lowers this exact computation across the pow2 cohort census
    without constructing an API instance. ``aggregate`` /
    ``preprocess`` may be bound methods (FedOpt/FedNova/defense
    subclasses plug in here); ``on_trace`` fires at TRACE time only —
    the compile-count/telemetry seam, never part of the lowered HLO.

    Donation contract (audited): argnums 0 and 1 — the carried global
    params and server-optimizer state — are donated by every caller's
    ``jax.jit(round_fn, donate_argnums=(0, 1))``; the round pipeline
    chains K rounds in flight on those buffers.

    Mesh dispatch: a legacy ``(clients[, data])`` mesh keeps the
    original client-axis sharding; a fed ``(data, fsdp)`` mesh
    (``parallel/layout.py``) shards the cohort along ``data``, keeps
    the params fsdp-sharded AT REST while gathering them replicated
    for per-client compute (FSDP at-use gather — no tensor-parallel
    reduction ever splits a client's math, which is what keeps the
    mesh round bitwise identical to the single-chip vmap path), and
    pins the aggregated output back onto the fsdp layout so the
    chained/donated carry never leaves the mesh.
    """
    from ..parallel.layout import is_fed_mesh

    fed = mesh is not None and is_fed_mesh(mesh)

    def round_fn(
        global_params, server_state, packed: Batches, nsamples, idx, rng,
        lr_mult=1.0, valid=None,
    ):
        if on_trace is not None:
            on_trace(idx)
        cohort = _take(packed, idx)
        ns = jnp.take(nsamples, idx)
        if valid is not None:
            # shape-bucketed cohorts (core/round_pipeline.py): the
            # padded slots repeat a real client index; zeroing their
            # batch mask makes every batch fully-masked (local
            # training reverts params exactly, metrics count 0) and
            # normalize_weights(..., valid) gives them aggregation
            # weight 0 — the same invisibility contract as
            # parallel/mesh.py's pad_federation
            vm = valid.reshape((-1,) + (1,) * (cohort.mask.ndim - 1))
            cohort = Batches(
                x=cohort.x,
                y=cohort.y,
                mask=cohort.mask * vm.astype(cohort.mask.dtype),
            )
        train_params = global_params
        if fed:
            from ..parallel.layout import fed_compute_constraints

            # the shared fed entry discipline (cohort along 'data',
            # params + sample counts + validity mask gathered
            # replicated — the FSDP at-use gather; params stay
            # fsdp-sharded at rest in the carry). valid MUST be
            # lane-invariant too: normalize_weights reduces w * valid,
            # and a data-sharded [C] vector there would turn the
            # normalizer into shape-dependent partial sums + psum
            if valid is not None:
                train_params, cohort, ns, valid = fed_compute_constraints(
                    mesh, global_params, cohort, ns, valid
                )
            else:
                train_params, cohort, ns = fed_compute_constraints(
                    mesh, global_params, cohort, ns
                )
        elif mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.mesh import federation_spec

            spec = NamedSharding(mesh, federation_spec(mesh))
            cohort = Batches(
                x=jax.lax.with_sharding_constraint(cohort.x, spec),
                y=jax.lax.with_sharding_constraint(cohort.y, spec),
                mask=jax.lax.with_sharding_constraint(cohort.mask, spec),
            )
            ns = jax.lax.with_sharding_constraint(
                ns, NamedSharding(mesh, P("clients"))
            )
        if preprocess is not None:
            cohort, server_state = preprocess(cohort, server_state)
        rngs = jax.random.split(rng, idx.shape[0])
        if use_round_lr:
            # round-indexed LR: one multiplier for the whole cohort
            new_stacked, train_metrics = jax.vmap(
                local_train, in_axes=(None, 0, 0, None)
            )(train_params, cohort, rngs, lr_mult)
        else:
            new_stacked, train_metrics = jax.vmap(
                local_train, in_axes=(None, 0, 0)
            )(train_params, cohort, rngs)
        if fed:
            from ..parallel.layout import pin_cohort_outputs

            # per-client compute stays whole; only the at-rest carry
            # is fsdp-sharded (see pin_cohort_outputs)
            new_stacked = pin_cohort_outputs(mesh, new_stacked)
        weights = normalize_weights(ns, valid)
        new_global, new_state = aggregate(
            global_params, server_state, new_stacked, weights, cohort, rng
        )
        if fed:
            from ..parallel.layout import constrain_tree

            # the aggregated carry lands fsdp-sharded at rest — the
            # donated (0, 1) chain never leaves the mesh, so zero host
            # hops at any cohort size (BENCH_r03's 573x prize)
            new_global = constrain_tree(new_global, mesh)
        summed = {k: v.sum() for k, v in train_metrics.items()}
        if keep_stacked:
            return new_global, new_state, summed, new_stacked
        return new_global, new_state, summed

    return round_fn


def build_eval_all(eval_fn):
    """vmap-over-clients eval reduction, module-level for the same
    no-self-closure reason as :func:`build_round_fn`."""

    def eval_all(params, packed: Batches):
        sums = jax.vmap(eval_fn, in_axes=(None, 0))(params, packed)
        return jax.tree.map(lambda x: x.sum(), sums)

    return eval_all


@auditable(
    "simulation.round_fn",
    donate=(0, 1),
    round_shaped=True,
    census_budget=lambda ctx: pow2_budget(ctx.cohort_buckets),
)
def _audit_round_fn_cases(ctx):
    """`fedml-tpu audit` provider: the EXACT round engine the runtime
    jits (same builder, same donation), lowered across the pow2 cohort
    census against ShapeDtypeStruct trees — no dataset, no params,
    nothing executed. The donation checker verifies the (0, 1)
    aliasing contract the round pipeline's K-in-flight chaining rides
    on; the host-transfer checker proves the hot loop is device-pure."""
    from ..analysis.compiled import LoweringCase

    params = ctx.abstract_params()

    def aggregate(global_params, server_state, stacked, weights, cohort, rng):
        # the stock FedAvg reduction — the shape every _aggregate
        # override (FedOpt/FedNova/defenses) is generic over
        return weighted_average(stacked, weights), server_state

    fn = jax.jit(
        build_round_fn(ctx.local_train_fn(), aggregate),
        donate_argnums=(0, 1),
    )
    n_total = max(ctx.cohort_buckets) * 2
    packed = ctx.abstract_batches(n_total)
    nsamples = ctx.sds((n_total,), "float32")
    return [
        LoweringCase(
            key=f"b{b}",
            fn=fn,
            args=(
                params, (), packed, nsamples,
                ctx.sds((b,), "int32"), ctx.abstract_key(),
            ),
            kwargs={"valid": ctx.sds((b,), "float32")},
        )
        for b in ctx.cohort_buckets
    ]


@auditable(
    "simulation.round_fn_mesh",
    donate=(0, 1),
    round_shaped=True,
    census_budget=lambda ctx: pow2_budget(ctx.cohort_buckets),
)
def _audit_round_fn_mesh_cases(ctx):
    """`fedml-tpu audit` provider for the MESH round engine: the same
    builder the runtime jits, with the fed (data, fsdp) mesh built
    over whatever devices exist (CI lowers on one CPU device — a 1x1
    mesh; the sharding annotations, the (0, 1) donation aliasing and
    the host-transfer freedom of the lowered module are checked
    identically at any mesh size). The aggregation lowered here is the
    exact expansion fold the mesh path really runs
    (``exact_weighted_mean``) — zero host hops inside the round is a
    compile-time fact, not a benchmark observation."""
    import jax

    from ..analysis.compiled import LoweringCase
    from ..parallel.layout import build_fed_mesh, tree_shardings

    n = len(jax.devices())
    fsdp = 2 if n % 2 == 0 else 1
    mesh = build_fed_mesh(
        mesh_shape={"data": n // fsdp, "fsdp": fsdp},
        # lowering only — nothing executes, so the threefry stream
        # warning would be CI noise
        warn_nonpartitionable=False,
    )
    # lower against fsdp-AT-REST input shardings — what the runtime
    # commits (SimulatorMesh.shard_tree). Donation aliasing only
    # exists when the donated input's layout matches the constrained
    # output's, so an unsharded abstract input would under-report the
    # aliasing the real executable has (observed on the 8-device test
    # world: 0 of 2 aliased without this)
    params = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        ctx.abstract_params(),
        tree_shardings(ctx.abstract_params(), mesh),
    )

    def aggregate(global_params, server_state, stacked, weights, cohort, rng):
        return exact_weighted_mean(stacked, weights), server_state

    fn = jax.jit(
        build_round_fn(ctx.local_train_fn(), aggregate, mesh=mesh),
        donate_argnums=(0, 1),
    )
    n_total = max(ctx.cohort_buckets) * 2
    packed = ctx.abstract_batches(n_total)
    nsamples = ctx.sds((n_total,), "float32")
    return [
        LoweringCase(
            key=f"b{b}",
            fn=fn,
            args=(
                params, (), packed, nsamples,
                ctx.sds((b,), "int32"), ctx.abstract_key(),
            ),
            kwargs={"valid": ctx.sds((b,), "float32")},
        )
        for b in ctx.cohort_buckets
    ]


def deterministic_client_sampling(
    round_idx: int, client_num_in_total: int, client_num_per_round: int
) -> np.ndarray:
    """Reference determinism contract (FedAVGAggregator.py:99-113):
    MT19937 seeded with ``round_idx``, ``choice`` without replacement —
    via a local ``RandomState`` so the draws are identical to the
    reference's ``np.random.seed(round_idx)`` without clobbering the
    caller's global NumPy RNG state."""
    if client_num_in_total == client_num_per_round:
        return np.arange(client_num_in_total, dtype=np.int32)
    rs = np.random.RandomState(round_idx)
    # lint: host-sync-ok — rs.choice output is host numpy, no device value
    return np.asarray(
        rs.choice(range(client_num_in_total), client_num_per_round, replace=False),
        dtype=np.int32,
    )


class FedAvgAPI:
    """Single-host simulator for the FedAvg family.

    ``mode``: ``"vectorized"`` (default; vmap over the cohort) or
    ``"sequential"`` (python loop per client — the reference's §3.1
    shape, kept for debugging/parity runs).
    """

    algorithm = "FedAvg"
    # subclasses that need per-client params on the host (Shapley
    # scoring, secure aggregation) flip this to get the stacked cohort
    # params as a 4th round output
    _keep_stacked = False
    # subclasses whose server step IS the algorithm (FedOpt's optax
    # update, FedNova's normalized combine) flip this off so a custom
    # server_aggregator errors instead of being silently dropped
    _accepts_custom_aggregator = True

    def __init__(
        self,
        args,
        device,
        dataset: FederatedDataset,
        model: FedModel,
        mesh=None,
        client_trainer=None,
        server_aggregator=None,
    ) -> None:
        self.args = args
        self.device = device
        self.dataset = dataset
        self.model = model
        self.mesh = mesh
        if mesh is not None:
            from ..parallel.layout import is_fed_mesh
            from ..parallel.mesh import is_multi_controller

            self._multi_controller = is_multi_controller(mesh)
            # fed (data, fsdp) mesh: params shard at rest, the cohort
            # shards along 'data', and the plain-FedAvg aggregation
            # switches to the exact placement-independent expansion
            # fold (core/aggregation.exact_weighted_mean)
            self._fed_mesh = is_fed_mesh(mesh)
        else:
            self._multi_controller = False
            self._fed_mesh = False
        # persistent XLA compilation cache (core/compile_cache.py):
        # no-op unless args.compile_cache_dir is set; idempotent
        # process-wide, so every engine (sync loop, round pipeline,
        # planet loop, serving) shares one warm-start ledger
        from ..core.compile_cache import maybe_enable_compile_cache

        maybe_enable_compile_cache(args)
        if server_aggregator is not None and not self._accepts_custom_aggregator:
            raise ValueError(
                f"{self.algorithm} defines its own server aggregation; a "
                "custom server_aggregator would be ignored — not supported"
            )
        self.client_trainer = bind_operator(client_trainer, model, args)
        self.server_aggregator = bind_operator(server_aggregator, model, args)
        self.mode = getattr(args, "sim_mode", "vectorized")
        if self.mode == "sequential" and (
            self._keep_stacked
            or type(self)._preprocess is not FedAvgAPI._preprocess
        ):
            raise NotImplementedError(
                f"{self.algorithm} uses in-round hooks that only run in "
                "vectorized mode; sim_mode='sequential' is not supported"
            )
        self.history: List[Dict[str, float]] = []
        # populated by core/round_pipeline.py after train(): depth,
        # bucket, flushes, host_syncs_per_round
        self.pipeline_stats: Dict[str, Any] = {}

        self.rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        self.rng, init_rng = jax.random.split(self.rng)
        self.global_params = model.init(init_rng)

        # round-indexed LR schedule (decay across the federation, not
        # within one local fit): None for lr_schedule=constant; loud
        # ValueError on the ambiguous step-indexed configuration
        self._round_lr = resolve_round_lr_schedule(args)
        if client_trainer is not None:
            if self._round_lr is not None:
                raise ValueError(
                    "lr_schedule with a custom client_trainer: the "
                    "trainer owns its optimizer, so the engine cannot "
                    "apply the round-indexed LR — implement the "
                    "schedule inside the trainer or use "
                    "lr_schedule=constant"
                )
            # L3 operator seam (core/frame.py): the custom trainer's
            # pure train fn replaces the stock one; the engine vmaps /
            # mesh-shards it identically.
            client_trainer.set_id(0)
            self._local_train = client_trainer.make_train_fn(args)
        else:
            prox_mu = (
                float(getattr(args, "fedprox_mu", 0.0))
                if self.algorithm == "FedProx"
                else 0.0
            )
            self._local_train = make_local_train_fn(
                model.apply,
                model.loss_fn,
                create_client_optimizer(
                    args,
                    lr=float(args.learning_rate)
                    if self._round_lr is not None
                    else None,
                ),
                epochs=int(args.epochs),
                prox_mu=prox_mu,
                shuffle=bool(getattr(args, "shuffle", True)),
                compute_dtype=compute_dtype_from_args(args),
            )
        self._eval = make_eval_fn(
            model.apply, model.loss_fn,
            compute_dtype=compute_dtype_from_args(args),
        )
        self.robust = (
            RobustAggregator(args) if getattr(args, "defense_type", None) else None
        )
        if self._fed_mesh and (
            self.robust is not None
            or self.server_aggregator is not None
            or type(self)._aggregate is not FedAvgAPI._aggregate
        ):
            # the mesh-shape bitwise-identity guarantee rides the exact
            # expansion fold, which only the plain FedAvg/FedProx
            # reduction uses; every other aggregation reduces the
            # sharded cohort through weighted_average-style ops whose
            # psum order depends on the mesh shape. Results are still
            # correct to float tolerance — but the degradation must be
            # LOUD, never discovered in a diff (docs/multichip.md)
            logging.warning(
                "(data, fsdp) mesh with %s: aggregation does not go "
                "through the exact expansion fold, so final params are "
                "correct to float tolerance but NOT bitwise identical "
                "across mesh shapes (the detail.multichip identity "
                "gate covers the plain FedAvg/FedProx path only)",
                "defense_type" if self.robust is not None
                else ("a custom server_aggregator"
                      if self.server_aggregator is not None
                      else f"algorithm {self.algorithm}"),
            )
        self.server_state = self._init_server_state()
        self._build_jitted()

        from ..core.telemetry import Telemetry
        from ..core.tracking import MetricsReporter, ProfilerEvent

        self.profiler = ProfilerEvent(args)
        # self.history is the round record of truth; the reporter only
        # fans out to sinks
        self.metrics_reporter = MetricsReporter(args, keep_history=False)
        # process-wide registry + flight recorder (core/telemetry.py):
        # profiler spans land on the trace.json timeline alongside the
        # round pipeline's dispatch/flush/drain events
        self.telemetry = Telemetry.get_instance(args)
        self.telemetry.attach_profiler(self.profiler)

    # -- algorithm hooks ----------------------------------------------
    def _init_server_state(self):
        return ()

    def _aggregate(
        self,
        global_params: Params,
        server_state,
        new_stacked: Params,
        weights: jax.Array,
        cohort: Batches,
        rng: jax.Array,
    ) -> Tuple[Params, Any]:
        """FedAvg: weighted average (fedavg_api.py:206-221)."""
        if self.server_aggregator is not None:
            # L3 operator seam: custom pure reduction, runs inside the
            # jitted round (robust/defense wrapping is then the custom
            # aggregator's own responsibility).
            return (
                self.server_aggregator.aggregate(
                    global_params, new_stacked, weights, rng
                ),
                server_state,
            )
        if self.robust is not None:
            return (
                self.robust.aggregate(new_stacked, weights, global_params, rng),
                server_state,
            )
        if getattr(self, "_fed_mesh", False):
            # the (data, fsdp) mesh path: a plain weighted_average over
            # a sharded client axis becomes partial sums + psum, whose
            # bits depend on the mesh shape. The exact expansion fold
            # is placement-independent, so every mesh shape — including
            # {data: 1} — finalizes to identical float32 params
            return exact_weighted_mean(new_stacked, weights), server_state
        return weighted_average(new_stacked, weights), server_state

    def _preprocess(self, cohort: Batches, server_state):
        """In-jit hook applied to the gathered cohort before local
        training (HS-FedAvg's FFT input normalization plugs in here)."""
        return cohort, server_state

    # -- engine -------------------------------------------------------
    def _build_jitted(self) -> None:
        # incremented at TRACE time (the python body runs only when jit
        # retraces) — the compile-count regression tests read this
        self._round_trace_count = 0

        def on_trace(idx) -> None:
            # trace-time only (the python body runs when jit traces):
            # counts EVERY trace, including the expected first compile
            # of each shape bucket — healthy runs show one per bucket;
            # more than that is a retrace storm, visible as a counter
            # and timeline instants instead of silent compile stalls
            self._round_trace_count += 1
            tel = getattr(self, "telemetry", None)
            if tel is not None and tel.enabled:
                tel.inc("pipeline_retraces_total")
                tel.recorder.instant(
                    "jit.retrace", cat="compile", bucket=int(idx.shape[0])
                )

        round_fn = build_round_fn(
            self._local_train,
            self._aggregate,
            self._preprocess,
            mesh=self.mesh,
            use_round_lr=self._round_lr is not None,
            keep_stacked=self._keep_stacked,
            on_trace=on_trace,
        )
        self._round_fn = jax.jit(round_fn, donate_argnums=(0, 1))
        # donation deliberately NOT safe here: the sequential loop
        # calls this with the SAME self.global_params for every client
        # of the cohort — donating argnum 0 would invalidate the tree
        # the next client still trains from
        # lint: donation-ok — see comment above (sequential-mode reuse)
        self._local_train_j = jax.jit(self._local_train)
        self._eval_all = jax.jit(build_eval_all(self._eval))
        self._eval_global = jax.jit(self._eval)

    def _round_exec_name(self) -> str:
        """Registry name of the round executable this api dispatches —
        the ``executable`` tag on its ``exec_device_seconds`` series,
        matched against audit_report.json by ``fedml-tpu perf``."""
        return (
            "simulation.round_fn_mesh"
            if self.mesh is not None
            else "simulation.round_fn"
        )

    def _post_round_stacked(self, stacked: Params, idx: np.ndarray, rng) -> None:
        """Host-side hook fed the per-client cohort params when
        ``_keep_stacked`` is set (overridden by S-FedAvg / TurboAggregate)."""

    # -- reference-parity sampling ------------------------------------
    def _client_sampling(
        self, round_idx: int, client_num_in_total: int, client_num_per_round: int
    ) -> np.ndarray:
        return deterministic_client_sampling(
            round_idx, client_num_in_total, client_num_per_round
        )

    # -- round loop ----------------------------------------------------
    def train(self) -> Dict[str, float]:
        args = self.args
        from ..scale.engine import planet_knobs_active

        if planet_knobs_active(args):
            # registry-backed population plane (fedml_tpu/scale/): no
            # eager federation exists to pack — the planet loop samples
            # and materializes each round's cohort on demand
            packed = nsamples = None
        else:
            # jit inputs under multi-controller must be global arrays or
            # process-consistent host values — never locally-committed
            # device arrays (every process holds the same host copy)
            packed = self.dataset.packed_train
            nsamples = (
                # one pre-loop conversion to a process-consistent host
                # value (multi-controller jit-input rule, comment above)
                np.asarray(self.dataset.packed_num_samples)  # lint: host-sync-ok
                if self._multi_controller
                else jnp.asarray(self.dataset.packed_num_samples)
            )
        comm_rounds = int(args.comm_round)
        freq = max(1, int(getattr(args, "frequency_of_the_test", 5)))
        ckpt, start_round = self._maybe_restore()
        if getattr(self, "_preempt_signal", None) is None:
            # the elastic seam (parallel/elastic.py): tests and the
            # bench inject a signal object directly; everyone else gets
            # it from the preempt_signal knob (validated to require
            # checkpoint_dir, so a notice always has somewhere durable
            # to land)
            from ..parallel.elastic import make_signal

            self._preempt_signal = make_signal(
                getattr(args, "preempt_signal", None)
            )
        # stall watchdog (core/telemetry.py): armed only when
        # args.stall_timeout_s > 0; observes the pipeline/comm
        # heartbeats and dumps a debug bundle to args.telemetry_dir
        watchdog = self.telemetry.maybe_start_watchdog(args)
        # pull-based /metrics endpoint (off unless args.metrics_port)
        # and on-demand per-round device profiling (args.profile_rounds)
        self.telemetry.maybe_start_metrics_server(args)
        from ..core.tracing import RoundProfiler

        self._round_profiler = RoundProfiler(args)
        try:
            return self._train_rounds(
                packed, nsamples, comm_rounds, freq, ckpt, start_round
            )
        finally:
            if ckpt is not None:
                ckpt.close()
            self._round_profiler.close()
            if watchdog is not None:
                self.telemetry.stop_watchdog()
            self.telemetry.stop_metrics_server()
            # one perfetto-loadable trace.json + registry exposition per
            # run when args.telemetry_dir is set
            self.telemetry.export_run_artifacts(
                getattr(args, "telemetry_dir", None)
            )

    def _lr_mult(self, round_idx: int):
        """Round-indexed LR multiplier (schedule(r) / peak), or None.
        A numpy scalar: the jit treats it as a traced 0-d argument
        (compile once, vary per round), and it is a process-consistent
        host value under multi-controller."""
        if self._round_lr is None:
            return None
        return np.float32(
            # lint: host-sync-ok — the schedule and the knob are host scalars
            float(self._round_lr(round_idx)) / float(self.args.learning_rate)  # lint: host-sync-ok
        )

    def _train_rounds(
        self, packed, nsamples, comm_rounds, freq, ckpt, start_round
    ) -> Dict[str, float]:
        from ..scale.engine import PlanetRoundLoop, planet_knobs_active

        if planet_knobs_active(self.args):
            # registry-backed cohorts (ROADMAP item 2): O(cohort) host
            # memory per round from a million-client registry, two-tier
            # edge aggregation behind edge_num. The loop (registry +
            # per-shape jit cache) persists across train() calls so a
            # warm re-run replays with zero new compiles
            loop = getattr(self, "_planet_loop", None)
            if loop is None:
                loop = self._planet_loop = PlanetRoundLoop(self)
            return loop.run(
                packed, nsamples, comm_rounds, freq, ckpt, start_round
            )
        if self.mode != "sequential" and not self._keep_stacked:
            # the async executor (K rounds in flight, deferred metrics,
            # shape-bucketed compile cache); pipeline_depth=1 (default)
            # reproduces the synchronous loop's behavior and metrics
            from ..core.round_pipeline import RoundPipeline

            return RoundPipeline(self).run(
                packed, nsamples, comm_rounds, freq, ckpt, start_round
            )
        return self._train_rounds_sync(
            packed, nsamples, comm_rounds, freq, ckpt, start_round
        )

    def _train_rounds_sync(
        self, packed, nsamples, comm_rounds, freq, ckpt, start_round
    ) -> Dict[str, float]:
        """Synchronous loop: the sequential (per-client python loop)
        mode and the ``_keep_stacked`` algorithms, whose per-round host
        hooks (Shapley scoring, secure-agg staging) need the stacked
        cohort params on host every round."""
        args = self.args
        final_stats: Dict[str, float] = {}
        for round_idx in range(start_round, comm_rounds):
            if getattr(self, "_round_profiler", None) is not None:
                self._round_profiler.tick(round_idx)
            t0 = time.perf_counter()
            idx = self._client_sampling(
                round_idx, self.dataset.client_num, int(args.client_num_per_round)
            )
            self.rng, round_rng = jax.random.split(self.rng)
            if self._multi_controller:
                round_rng = np.asarray(round_rng)  # lint: host-sync-ok — process-consistent host value (multi-controller rule)
            lr_mult = self._lr_mult(round_idx)
            with self.profiler.span("round"):
                if self.mode == "sequential":
                    new_global, summed = self._sequential_round(
                        idx, round_rng, lr_mult, nsamples=nsamples
                    )
                    self.global_params = new_global
                else:
                    extra = () if lr_mult is None else (lr_mult,)
                    with _devtime(
                        self._round_exec_name(), bucket=f"b{len(idx)}"
                    ):
                        out = self._round_fn(
                            self.global_params,
                            self.server_state,
                            packed,
                            nsamples,
                            np.asarray(idx) if self._multi_controller else jnp.asarray(idx),  # lint: host-sync-ok — idx is host numpy (sampling)
                            round_rng,
                            *extra,
                        )
                    self.global_params, self.server_state, summed = out[:3]
                    if self._keep_stacked:
                        self._post_round_stacked(out[3], idx, round_rng)
            if round_idx % freq == 0 or round_idx == comm_rounds - 1:
                with self.profiler.span("eval"):
                    stats = self._local_test_on_all_clients(round_idx)
                stats["round"] = round_idx
                stats["round_time_s"] = time.perf_counter() - t0
                # eval-round metric fetch: the sync loop fetches at its
                # eval cadence by design (the pipelined loop defers)
                stats["train_loss_cohort"] = float(summed["loss_sum"]) / max(  # lint: host-sync-ok
                    float(summed["count"]), 1.0  # lint: host-sync-ok — same eval-round fetch
                )
                self.history.append(stats)
                final_stats = stats
                self.metrics_reporter.report_server_training_metric(stats)
            saved = False
            if ckpt is not None and (
                (round_idx + 1) % self._ckpt_freq == 0
                or round_idx == comm_rounds - 1
            ):
                self._save_checkpoint(ckpt, round_idx)
                saved = True
            self._maybe_preempt(ckpt, round_idx, saved=saved)
        return final_stats

    # -- elastic preemption seam (parallel/elastic.py) ----------------
    def _maybe_preempt(self, ckpt, round_idx: int, saved: bool = False) -> None:
        """Poll the preemption signal at the round boundary; on notice,
        make the drained round durable (WAL ``kind="preempt"``
        write-ahead of a forced checkpoint) and raise ``Preempted`` —
        the clean controlled exit a restart on the surviving devices
        resumes from bitwise-identically. ``saved=True`` means the
        cadence block already published this round's step."""
        signal = getattr(self, "_preempt_signal", None)
        if signal is None:
            return
        notice = signal.poll(int(round_idx))  # lint: host-sync-ok — round_idx is the host loop counter, never a device array
        if notice is None:
            return
        from ..parallel.elastic import preempt_now

        preempt_now(self, ckpt, int(round_idx), notice, saved=saved)  # lint: host-sync-ok — host loop counter (see poll above)

    # -- checkpoint / resume (new vs reference — SURVEY.md §5) --------
    def _maybe_restore(self):
        ckpt_dir = getattr(self.args, "checkpoint_dir", None)
        if not ckpt_dir:
            return None, 0
        from flax.serialization import from_state_dict, to_state_dict

        from ..core.checkpoint import RoundCheckpointer

        # None = this scenario's historical cadence (every 10 rounds)
        self._ckpt_freq = max(
            1, int(getattr(self.args, "checkpoint_freq", None) or 10)
        )
        ckpt = RoundCheckpointer(ckpt_dir)
        restored = self._restore_state(ckpt, to_state_dict)
        start_round = 0
        if restored is not None:
            from ..parallel.layout import is_fed_mesh, shard_tree

            self.global_params = jax.tree.map(
                jnp.asarray, from_state_dict(self.global_params, restored["params"])
            )
            mesh = getattr(self, "mesh", None)
            if mesh is not None and is_fed_mesh(mesh):
                # elastic resume: land the restored params at-rest on
                # the CURRENT (possibly reshaped) mesh — a raw-fallback
                # restore leaves them committed to one device, which
                # would pin every downstream jit there
                self.global_params = shard_tree(self.global_params, mesh)
            self.server_state = from_state_dict(
                self.server_state, restored["server_state"]
            )
            self.rng = jnp.asarray(
                np.asarray(restored["rng"]),  # lint: host-sync-ok — restore-time scalar pair, once per run; breaks the restore's single-device commitment
                dtype=jnp.uint32,
            )
            start_round = int(restored["round_idx"]) + 1  # lint: host-sync-ok — restore-time scalar, once per run
            self._restore_extra_state(restored.get("extra"))
            self._note_elastic_resume(ckpt, start_round)
            logging.info("resuming from round %d", start_round)
        self._to_state_dict = to_state_dict
        return ckpt, start_round

    def _restore_state(self, ckpt, to_state_dict):
        """Restore the latest step — device-direct onto the CURRENT
        mesh layout when one exists (the elastic resume path: a run
        preempted on 8 devices restores straight onto the surviving
        4-device mesh's NamedShardings, no host staging of the full
        model), raw host restore otherwise. A shaped target that the
        saved tree refuses (structure drift across versions, an
        ``extra`` block appearing/vanishing) falls back to the raw
        restore rather than failing the resume."""
        from ..parallel.layout import is_fed_mesh, shard_tree

        mesh = getattr(self, "mesh", None)
        if mesh is not None and is_fed_mesh(mesh):
            # the target's leaves carry the CURRENT mesh's at-rest
            # NamedShardings, so orbax restores each param straight
            # onto the surviving layout — no host staging of the model
            target = {
                "params": shard_tree(self.global_params, mesh),
                "server_state": to_state_dict(self.server_state),
                "rng": self.rng,
                "round_idx": 0,
            }
            extra = self._extra_checkpoint_state()
            if extra is not None:
                target["extra"] = extra
            try:
                return ckpt.restore(target=target)
            except Exception:  # noqa: BLE001 — shaped-restore drift
                logging.warning(
                    "mesh-targeted restore failed; retrying as raw "
                    "host restore", exc_info=True,
                )
        return ckpt.restore()

    def _note_elastic_resume(self, ckpt, start_round: int) -> None:
        """If the WAL's last word was ``kind="preempt"``, this restore
        IS the elastic resume: append the paired ``kind="resume"``
        record (the invariant checker's restorability evidence —
        ``preempt_paired_with_checkpoint``) and count it. A checkpoint
        dir with no WAL (or a WAL ending in an ordinary round record)
        is a plain restart — no record, no counter."""
        from ..core.checkpoint import RoundWAL

        wal = RoundWAL(ckpt.dir)
        last = wal.last()
        if last is None or last.get("kind") != "preempt":
            return
        from ..parallel.elastic import _mesh_devices, _mesh_shape

        mesh = getattr(self, "mesh", None)
        wal.append(
            int(start_round),  # lint: host-sync-ok — restore-time python scalar, once per run
            int(last.get("ckpt_step") or 0),  # lint: host-sync-ok — JSON field from the WAL, host-only
            [],
            kind="resume",
            extra={
                "devices": _mesh_devices(mesh),
                "mesh_shape": _mesh_shape(mesh),
            },
        )
        tel = getattr(self, "telemetry", None)
        if tel is not None and tel.enabled:
            tel.inc("elastic_resumes_total")
        logging.warning(
            "elastic resume: preempt record at round %s consumed; "
            "continuing from round %d on %d device(s)",
            last.get("round_idx"), int(start_round),  # lint: host-sync-ok — restore-time python scalar, once per run
            len(_mesh_devices(mesh)) or 1,
        )

    def _extra_checkpoint_state(self):
        """Algorithm-side host state to persist (S-FedAvg reputation)."""
        return None

    def _restore_extra_state(self, extra) -> None:
        pass

    def _save_checkpoint(self, ckpt, round_idx: int) -> None:
        state = {
            "params": self.global_params,
            "server_state": self._to_state_dict(self.server_state),
            "rng": self.rng,
            "round_idx": round_idx,
        }
        extra = self._extra_checkpoint_state()
        if extra is not None:
            state["extra"] = extra
        ckpt.save(round_idx, state)

    def _sequential_round(
        self, idx: np.ndarray, rng: jax.Array, lr_mult=None, nsamples=None
    ):
        """Reference §3.1 shape: python loop over sampled clients.

        Per-client work stays a device dispatch; sample counts are
        gathered in ONE device op at round end from the ``nsamples``
        array the caller already placed (the old per-client
        ``float(...)`` forced a host round-trip inside the loop)."""
        stacked_leaves: List[Params] = []
        sums = None
        extra = () if lr_mult is None else (lr_mult,)
        for j, i in enumerate(idx):
            client = Batches(
                x=self.dataset.packed_train.x[i],
                y=self.dataset.packed_train.y[i],
                mask=self.dataset.packed_train.mask[i],
            )
            p, m = self._local_train_j(
                self.global_params, client, jax.random.fold_in(rng, j), *extra
            )
            stacked_leaves.append(p)
            sums = m if sums is None else jax.tree.map(jnp.add, sums, m)
        from ..core.aggregation import stack_pytrees

        stacked = stack_pytrees(stacked_leaves)
        if nsamples is None:
            nsamples = jnp.asarray(self.dataset.packed_num_samples)
        ns = jnp.take(jnp.asarray(nsamples), jnp.asarray(idx))
        weights = normalize_weights(ns)
        new_global, self.server_state = self._aggregate(
            self.global_params, self.server_state, stacked, weights, None, rng
        )
        return new_global, sums

    # -- evaluation (fedavg_api.py:238 _local_test_on_all_clients) ----
    def _local_test_on_all_clients(self, round_idx: int) -> Dict[str, float]:
        train_sums = self._eval_all(self.global_params, self.dataset.packed_train)
        test_sums = self._eval_all(self.global_params, self.dataset.packed_test)
        tr = self.model.metrics_from_sums(train_sums)
        te = self.model.metrics_from_sums(test_sums)
        return {
            "train_acc": tr["acc"],
            "train_loss": tr["loss"],
            "test_acc": te["acc"],
            "test_loss": te["loss"],
        }

    def evaluate_global(self) -> Dict[str, float]:
        sums = self._eval_global(self.global_params, self.dataset.test_data_global)
        return self.model.metrics_from_sums(sums)


class FedProxAPI(FedAvgAPI):
    """FedProx = FedAvg + proximal term in the client loss
    (``mpi_p2p_mp/fedprox`` trainer semantics; ``args.fedprox_mu``)."""

    algorithm = "FedProx"


class FedOptAPI(FedAvgAPI):
    """Server-side adaptive optimization
    (``fedopt/fedopt_api.py`` + ``FedOptAggregator.py:81-130``): the
    averaged client delta is a pseudo-gradient fed to an optax server
    optimizer (sgd/momentum/adam/adagrad/yogi replaces OptRepo)."""

    algorithm = "FedOpt"
    _accepts_custom_aggregator = False

    def _init_server_state(self):
        self._server_opt = create_server_optimizer(self.args)
        return self._server_opt.init(self.global_params)

    def _aggregate(self, global_params, server_state, new_stacked, weights, cohort, rng):
        avg = weighted_average(new_stacked, weights)
        pseudo_grad = jax.tree.map(lambda g, a: g - a, global_params, avg)
        updates, new_state = self._server_opt.update(
            pseudo_grad, server_state, global_params
        )
        import optax

        new_global = optax.apply_updates(global_params, updates)
        return new_global, new_state


class FedNovaAPI(FedAvgAPI):
    """Normalized averaging (``fednova/fednova.py:12-169``,
    ``fednova_trainer.py:136-165``): clients' deltas are normalized by
    their local step counts a_i, then recombined with
    tau_eff = sum(p_i a_i):  w+ = w - tau_eff * sum(p_i (w - w_i)/a_i).
    a_i = epochs * (# non-empty batches) — exact for the plain-SGD
    client optimizer (momentum-corrected a_i is a later extension)."""

    algorithm = "FedNova"
    _accepts_custom_aggregator = False

    def _aggregate(self, global_params, server_state, new_stacked, weights, cohort, rng):
        if cohort is None:
            raise NotImplementedError("FedNova requires vectorized mode")
        epochs = float(self.args.epochs)
        nonempty = (cohort.mask.sum(axis=-1) > 0).astype(jnp.float32).sum(axis=-1)
        a_i = jnp.maximum(epochs * nonempty, 1.0)  # [C]
        tau_eff = (weights * a_i).sum()

        def combine(g, s):
            w = weights.reshape((-1,) + (1,) * (g.ndim)).astype(g.dtype)
            ai = a_i.reshape((-1,) + (1,) * (g.ndim)).astype(g.dtype)
            norm_delta = (g[None] - s) / ai  # [C, ...]
            return g - tau_eff * (w * norm_delta).sum(axis=0)

        return jax.tree.map(combine, global_params, new_stacked), server_state


def _algorithms():
    from .decentralized import DecentralizedDSGDAPI, DecentralizedPushSumAPI
    from .defenses import HSFedAvgAPI, SFedAvgAPI
    from .fedgan import FedGANAPI
    from .fednas import FedNASAPI
    from .hierarchical_fl import HierarchicalFLAPI
    from .split_learning import FedGKTAPI, SplitNNAPI, VFLAPI
    from .turboaggregate import TurboAggregateAPI

    return {
        "FedAvg": FedAvgAPI,
        "FedProx": FedProxAPI,
        "FedOpt": FedOptAPI,
        "FedNova": FedNovaAPI,
        "HierFedAvg": HierarchicalFLAPI,
        "DSGD": DecentralizedDSGDAPI,
        "PushSum": DecentralizedPushSumAPI,
        "SFedAvg": SFedAvgAPI,
        "HSFedAvg": HSFedAvgAPI,
        "FedGAN": FedGANAPI,
        "TurboAggregate": TurboAggregateAPI,
        "SplitNN": SplitNNAPI,
        "FedGKT": FedGKTAPI,
        "VFL": VFLAPI,
        "FedNAS": FedNASAPI,
    }


_ALGORITHMS = None


def get_algorithms():
    """Name -> API class registry (lazy to avoid circular imports)."""
    global _ALGORITHMS
    if _ALGORITHMS is None:
        _ALGORITHMS = _algorithms()
    return _ALGORITHMS
