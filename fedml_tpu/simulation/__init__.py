"""Simulation scenario ("Parrot" parity, SURVEY.md §2.9) — TPU-first.

``Simulator*`` dispatchers mirror ``python/fedml/simulation/simulator.py``;
the algorithm APIs live in ``fedavg_api.py`` (FedAvg / FedProx / FedOpt /
FedNova share one jitted round engine) and ``hierarchical.py`` /
``decentralized.py`` for the structured variants.
"""

from .fedavg_api import FedAvgAPI, FedOptAPI, FedProxAPI, FedNovaAPI  # noqa: F401
from .simulator import SimulatorSingleProcess, SimulatorMesh  # noqa: F401
