"""Simulator dispatchers (reference: ``simulation/simulator.py``).

- ``SimulatorSingleProcess`` (simulator.py:28-40): one host, one chip;
  vmap client batching.
- ``SimulatorMesh``: the reference's stubbed ``SimulatorNCCL``
  (simulator.py:100-108) done for real — the packed federation's client
  axis is sharded over a ``jax.sharding.Mesh`` and aggregation rides ICI
  collectives. Works identically on a TPU pod slice or on a virtual
  multi-device CPU mesh (tests).
"""

from __future__ import annotations

import logging

import jax

from ..parallel.mesh import build_mesh, pad_federation, replicate, shard_federation
from .fedavg_api import FedAvgAPI, get_algorithms


def _select_algorithm(args):
    name = getattr(args, "federated_optimizer", "FedAvg")
    algorithms = get_algorithms()
    if name not in algorithms:
        raise ValueError(
            f"federated_optimizer {name!r} not supported; have {sorted(algorithms)}"
        )
    return algorithms[name]


def _operator_kwargs(cls, client_trainer, server_aggregator) -> dict:
    """L3 operator seam passthrough (core/frame.py): the FedAvg-family
    engines consume custom operators; algorithms whose constructors do
    not plumb the seam (SplitNN, VFL, defenses, gossip, ...) have
    structurally different operator boundaries and reject custom
    operators explicitly rather than ignoring them or TypeError-ing."""
    if client_trainer is None and server_aggregator is None:
        return {}
    import inspect

    sig_params = inspect.signature(cls.__init__).parameters
    accepts = "client_trainer" in sig_params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in sig_params.values()
    )
    if not (issubclass(cls, FedAvgAPI) and accepts):
        raise ValueError(
            f"custom client_trainer/server_aggregator is not supported by "
            f"{cls.__name__}; supported by the FedAvg family "
            "(FedAvg/FedProx/FedOpt/FedNova/HierFedAvg)"
        )
    return {
        "client_trainer": client_trainer,
        "server_aggregator": server_aggregator,
    }


class SimulatorSingleProcess:
    def __init__(
        self, args, device, dataset, model, client_trainer=None, server_aggregator=None
    ) -> None:
        self.args = args
        cls = _select_algorithm(args)
        self.fl_trainer = cls(
            args,
            device,
            dataset,
            model,
            **_operator_kwargs(cls, client_trainer, server_aggregator),
        )

    def run(self):
        from ..core.tracking import device_trace

        with device_trace(self.args):
            out = self.fl_trainer.train()
        _log_pipeline_stats(self.fl_trainer)
        return out


def _log_pipeline_stats(fl_trainer) -> None:
    """Surface the round-pipeline executor's run summary (depth, compile
    bucket, host syncs/round) — the observability handle for tuning
    ``pipeline_depth`` without attaching a profiler."""
    stats = getattr(fl_trainer, "pipeline_stats", None)
    if stats:
        logging.info("round pipeline: %s", stats)


class SimulatorMesh:
    """Client-parallel FL over a device mesh.

    Two mesh vocabularies, picked by ``args.mesh_shape``:

    - legacy ``{clients[, data]}`` — cohort sharded over ``clients``,
      params replicated (single-chip HBM bound);
    - fed ``{data[, fsdp]}`` (``parallel/layout.py``) — the production
      plane: cohort over ``data``, params/optimizer state fsdp-sharded
      at rest per the ``SpecLayout`` table, aggregation on-mesh via
      the exact expansion fold — bitwise identical across mesh shapes.
    """

    def __init__(
        self,
        args,
        device,
        dataset,
        model,
        mesh=None,
        client_trainer=None,
        server_aggregator=None,
    ) -> None:
        from ..parallel.layout import (
            build_fed_mesh,
            cohort_axis_size,
            fed_mesh_shape,
            is_fed_mesh,
        )

        self.args = args
        if mesh is not None:
            self.mesh = mesh
        else:
            shape = getattr(args, "mesh_shape", None)
            self.mesh = (
                build_fed_mesh(mesh_shape=shape)
                if fed_mesh_shape(shape)
                else build_mesh(mesh_shape=shape)
            )
        fed = is_fed_mesh(self.mesh)
        n_client_shards = cohort_axis_size(self.mesh)
        if int(args.client_num_per_round) % n_client_shards != 0:
            axis = "data" if fed else "clients"
            raise ValueError(
                f"client_num_per_round={args.client_num_per_round} must be a "
                f"multiple of the mesh {axis!r} axis ({n_client_shards})"
            )
        packed_train, ns_padded = pad_federation(
            dataset.packed_train, dataset.packed_num_samples, n_client_shards
        )
        packed_test, _ = pad_federation(
            dataset.packed_test, dataset.packed_num_samples, n_client_shards
        )
        dataset.packed_train, ns = shard_federation(
            packed_train, ns_padded, self.mesh
        )
        dataset.packed_test, _ = shard_federation(
            packed_test, ns_padded, self.mesh
        )
        dataset.packed_num_samples = ns_padded
        cls = _select_algorithm(args)
        if not getattr(cls, "supports_mesh", True):
            raise ValueError(
                f"{cls.__name__} does not support the MESH backend yet; "
                "run it under the single-process simulator"
            )
        self.fl_trainer = cls(
            args,
            device,
            dataset,
            model,
            mesh=self.mesh,
            **_operator_kwargs(cls, client_trainer, server_aggregator),
        )
        if fed:
            # FSDP at-rest placement per the canonical layout table —
            # each chip holds 1/fsdp of every sharded leaf
            from ..parallel.layout import shard_tree

            self.fl_trainer.global_params = shard_tree(
                self.fl_trainer.global_params, self.mesh
            )
        else:
            self.fl_trainer.global_params = replicate(
                self.fl_trainer.global_params, self.mesh
            )

    def run(self):
        from ..core.tracking import device_trace

        with device_trace(self.args):
            out = self.fl_trainer.train()
        _log_pipeline_stats(self.fl_trainer)
        return out
