"""Simulator dispatchers (reference: ``simulation/simulator.py``).

- ``SimulatorSingleProcess`` (simulator.py:28-40): one host, one chip;
  vmap client batching.
- ``SimulatorMesh``: the reference's stubbed ``SimulatorNCCL``
  (simulator.py:100-108) done for real — the packed federation's client
  axis is sharded over a ``jax.sharding.Mesh`` and aggregation rides ICI
  collectives. Works identically on a TPU pod slice or on a virtual
  multi-device CPU mesh (tests).
"""

from __future__ import annotations

import jax

from ..parallel.mesh import build_mesh, pad_federation, replicate, shard_federation
from .fedavg_api import get_algorithms


def _select_algorithm(args):
    name = getattr(args, "federated_optimizer", "FedAvg")
    algorithms = get_algorithms()
    if name not in algorithms:
        raise ValueError(
            f"federated_optimizer {name!r} not supported; have {sorted(algorithms)}"
        )
    return algorithms[name]


class SimulatorSingleProcess:
    def __init__(self, args, device, dataset, model) -> None:
        cls = _select_algorithm(args)
        self.fl_trainer = cls(args, device, dataset, model)

    def run(self):
        return self.fl_trainer.train()


class SimulatorMesh:
    """Client-parallel FL over a device mesh."""

    def __init__(self, args, device, dataset, model, mesh=None) -> None:
        self.mesh = mesh if mesh is not None else build_mesh(
            mesh_shape=getattr(args, "mesh_shape", None)
        )
        n_client_shards = self.mesh.shape.get("clients", 1)
        if int(args.client_num_per_round) % n_client_shards != 0:
            raise ValueError(
                f"client_num_per_round={args.client_num_per_round} must be a "
                f"multiple of the mesh 'clients' axis ({n_client_shards})"
            )
        packed_train, ns_padded = pad_federation(
            dataset.packed_train, dataset.packed_num_samples, n_client_shards
        )
        packed_test, _ = pad_federation(
            dataset.packed_test, dataset.packed_num_samples, n_client_shards
        )
        dataset.packed_train, ns = shard_federation(
            packed_train, ns_padded, self.mesh
        )
        dataset.packed_test, _ = shard_federation(
            packed_test, ns_padded, self.mesh
        )
        dataset.packed_num_samples = ns_padded
        cls = _select_algorithm(args)
        if not getattr(cls, "supports_mesh", True):
            raise ValueError(
                f"{cls.__name__} does not support the MESH backend yet; "
                "run it under the single-process simulator"
            )
        self.fl_trainer = cls(args, device, dataset, model, mesh=self.mesh)
        self.fl_trainer.global_params = replicate(
            self.fl_trainer.global_params, self.mesh
        )

    def run(self):
        return self.fl_trainer.train()
