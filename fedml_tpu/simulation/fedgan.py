"""Federated GAN training (FedGAN).

Reference parity: ``simulation/mpi_p2p_mp/fedgan`` — each client trains
a generator/discriminator pair locally (alternating D and G steps), the
server FedAvg's BOTH networks each round and redistributes them.

TPU-first redesign: the whole round is one jitted computation — the
alternating D/G optimization is a ``lax.scan`` over packed batches
inside a scan over epochs, vmapped across the cohort; both nets'
weighted averages happen on-device. Non-saturating GAN loss
(``softplus`` form), masked so padded examples contribute nothing.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.aggregation import normalize_weights, weighted_average
from ..core.types import Batches
from ..data.loader import FederatedDataset
from ..models.gan import Discriminator, Generator

Params = Any


class FedGANAPI:
    """Single-host federated GAN simulator.

    Interface mirrors :class:`FedAvgAPI` (``train()`` →
    final-round stats; ``history``) so the simulator dispatch treats it
    uniformly. The ``model`` argument is ignored — the G/D pair comes
    from ``fedml_tpu.models.gan`` (args: ``gan_latent_dim``,
    ``gan_lr_g``, ``gan_lr_d``).
    """

    algorithm = "FedGAN"

    def __init__(self, args, device, dataset: FederatedDataset, model=None, mesh=None):
        self.args = args
        self.dataset = dataset
        self.mesh = mesh
        self.history: List[Dict[str, float]] = []
        self.latent_dim = int(getattr(args, "gan_latent_dim", 64))
        self.gen = Generator(latent_dim=self.latent_dim)
        self.disc = Discriminator()

        img_shape = tuple(dataset.packed_train.x.shape[-3:])
        self.rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        self.rng, gr, dr = jax.random.split(self.rng, 3)
        g_params = self.gen.init(gr, jnp.zeros((1, self.latent_dim)))["params"]
        d_params = self.disc.init(dr, jnp.zeros((1,) + img_shape))["params"]
        self.global_params = {"gen": g_params, "disc": d_params}

        self.g_opt = optax.adam(float(getattr(args, "gan_lr_g", 2e-4)), b1=0.5)
        self.d_opt = optax.adam(float(getattr(args, "gan_lr_d", 2e-4)), b1=0.5)
        self.epochs = int(getattr(args, "epochs", 1))
        self._build_jitted()

    def _build_jitted(self) -> None:
        gen, disc = self.gen, self.disc
        g_opt, d_opt = self.g_opt, self.d_opt
        latent = self.latent_dim
        epochs = self.epochs

        def d_loss_fn(d_params, g_params, x, mask, z):
            fake = gen.apply({"params": g_params}, z)
            real_logit = disc.apply({"params": d_params}, x)
            fake_logit = disc.apply({"params": d_params}, fake)
            # BCE(real→1) + BCE(fake→0), masked over padding
            per = jax.nn.softplus(-real_logit) * mask + jax.nn.softplus(fake_logit)
            return per.sum() / jnp.maximum(mask.sum() + mask.shape[0], 1.0)

        def g_loss_fn(g_params, d_params, z):
            fake = gen.apply({"params": g_params}, z)
            return jnp.mean(jax.nn.softplus(-disc.apply({"params": d_params}, fake)))

        def local_train(params, batches: Batches, rng):
            g0, d0 = params["gen"], params["disc"]
            g_state = g_opt.init(g0)
            d_state = d_opt.init(d0)

            def step(carry, batch):
                g, d, gs, ds, key = carry
                x, m = batch
                key, kz1, kz2 = jax.random.split(key, 3)
                bs = x.shape[0]
                z1 = jax.random.normal(kz1, (bs, latent))
                z2 = jax.random.normal(kz2, (bs, latent))
                dl, dgrads = jax.value_and_grad(d_loss_fn)(d, g, x, m, z1)
                du, ds_new = d_opt.update(dgrads, ds, d)
                d_new = optax.apply_updates(d, du)
                gl, ggrads = jax.value_and_grad(g_loss_fn)(g, d_new, z2)
                gu, gs_new = g_opt.update(ggrads, gs, g)
                g_new = optax.apply_updates(g, gu)
                nonempty = m.sum() > 0
                keep = lambda a, b: jax.tree.map(
                    lambda u, v: jnp.where(nonempty, u, v), a, b
                )
                return (
                    keep(g_new, g),
                    keep(d_new, d),
                    keep(gs_new, gs),
                    keep(ds_new, ds),
                    key,
                ), {"d_loss": dl * nonempty, "g_loss": gl * nonempty, "n": nonempty}

            def epoch(carry, _):
                (g, d, gs, ds, key), metrics = jax.lax.scan(
                    step, carry, (batches.x, batches.mask)
                )
                return (g, d, gs, ds, key), jax.tree.map(jnp.sum, metrics)

            (g, d, _, _, _), per_epoch = jax.lax.scan(
                epoch, (g0, d0, g_state, d_state, rng), None, length=epochs
            )
            last = jax.tree.map(lambda a: a[-1], per_epoch)
            return {"gen": g, "disc": d}, last

        def round_fn(global_params, packed: Batches, nsamples, idx, rng):
            cohort = Batches(
                x=jnp.take(packed.x, idx, axis=0),
                y=jnp.take(packed.y, idx, axis=0),
                mask=jnp.take(packed.mask, idx, axis=0),
            )
            ns = jnp.take(nsamples, idx)
            rngs = jax.random.split(rng, idx.shape[0])
            new_stacked, metrics = jax.vmap(local_train, in_axes=(None, 0, 0))(
                global_params, cohort, rngs
            )
            weights = normalize_weights(ns)
            new_global = weighted_average(new_stacked, weights)
            return new_global, jax.tree.map(jnp.sum, metrics)

        self._round_fn = jax.jit(round_fn, donate_argnums=(0,))

        def eval_fn(params, test: Batches, rng):
            """Discriminator real-vs-fake accuracy + G loss on the
            global test split."""

            def step(key, batch):
                x, m = batch
                key, kz = jax.random.split(key)
                z = jax.random.normal(kz, (x.shape[0], latent))
                fake = gen.apply({"params": params["gen"]}, z)
                rl = disc.apply({"params": params["disc"]}, x)
                fl = disc.apply({"params": params["disc"]}, fake)
                correct = ((rl > 0) * m).sum() + (fl < 0).sum() * (m.sum() > 0)
                g_loss = jax.nn.softplus(-fl).mean() * (m.sum() > 0)
                return key, {
                    "correct": correct,
                    "count": m.sum() + m.shape[0] * (m.sum() > 0),
                    "g_loss": g_loss,
                    "batches": (m.sum() > 0).astype(jnp.float32),
                }

            _, out = jax.lax.scan(step, rng, (test.x, test.mask))
            return jax.tree.map(jnp.sum, out)

        self._eval_fn = jax.jit(eval_fn)

    def _client_sampling(self, round_idx, total, per_round):
        from .fedavg_api import deterministic_client_sampling

        return deterministic_client_sampling(round_idx, total, per_round)

    def train(self) -> Dict[str, float]:
        args = self.args
        packed = self.dataset.packed_train
        nsamples = jnp.asarray(self.dataset.packed_num_samples)
        freq = max(1, int(getattr(args, "frequency_of_the_test", 5)))
        final: Dict[str, float] = {}
        for round_idx in range(int(args.comm_round)):
            t0 = time.perf_counter()
            idx = self._client_sampling(
                round_idx, self.dataset.client_num, int(args.client_num_per_round)
            )
            self.rng, r_rng = jax.random.split(self.rng)
            self.global_params, summed = self._round_fn(
                self.global_params, packed, nsamples, jnp.asarray(idx), r_rng
            )
            if round_idx % freq == 0 or round_idx == int(args.comm_round) - 1:
                self.rng, e_rng = jax.random.split(self.rng)
                ev = self._eval_fn(
                    self.global_params, self.dataset.test_data_global, e_rng
                )
                n_steps = max(float(summed["n"]), 1.0)
                stats = {
                    "round": round_idx,
                    "round_time_s": time.perf_counter() - t0,
                    "d_loss": float(summed["d_loss"]) / n_steps,
                    "g_loss": float(summed["g_loss"]) / n_steps,
                    "disc_acc": float(ev["correct"]) / max(float(ev["count"]), 1.0),
                    "test_g_loss": float(ev["g_loss"]) / max(float(ev["batches"]), 1.0),
                }
                self.history.append(stats)
                final = stats
        return final
