"""Command-line interface.

Parity with ``python/fedml/cli/cli.py`` (click group ``fedml
version/login/logout/build``, :17-250), on argparse (no click
dependency):

- ``version``  — print the package version.
- ``login``    — persist the account binding and start the edge-agent
  daemon (the reference spawns ``FedMLClientRunner``,
  cli/cli.py:27-43 → edge_deployment/login.py:31).
- ``logout``   — stop the daemon and clear the binding (cli/cli.py:131).
- ``build``    — package user training code into a client/server
  distribution zip (cli/cli.py:141-250's mlops-core packaging, minus
  the platform-specific templates: the package carries the user source
  + entry + a manifest the edge agent knows how to run).
- ``serve``    — beyond the reference (which hands trained models to an
  external MLOps serving tier): stand up the TPU-native serving plane
  (``fedml_tpu/serving``) for the federated global model, hot-swapping
  weights from a checkpoint dir as the trainer publishes new rounds.
- ``edge``     — beyond the reference: launch one edge aggregator rank
  of the hierarchical server plane (``cross_silo/hierarchical``,
  docs/hierarchical.md) — rank N of the root fabric, server of its own
  client fabric, streaming-folding its client partition and shipping
  one merged limb-set upstream per round close.
- ``trace``    — beyond the reference: stitch the per-process trace
  shards a run exported into ``telemetry_dir`` into ONE
  perfetto-loadable timeline (cross-process flow events matched,
  per-rank clock skew corrected) and run the round critical-path
  analyzer — ``trace_merged.json`` + ``round_report.json``
  (``core/tracing.py``, docs/observability.md).
- ``check``    — beyond the reference: replay a finished run's
  artifacts (``round_wal.jsonl`` + ``telemetry.jsonl`` +
  ``trace.json``) through the post-hoc ``InvariantChecker``
  (``core/invariants.py``) — exactly-once folds, model-version
  monotonicity across restarts, quorum/cohort accounting, no reissued
  dispatch seqs, no lost-but-unreported folds. Exit 0 = clean, 1 =
  violations (printed as one JSON line).
- ``lint``     — beyond the reference: the JAX-/federation-aware
  static-analysis suite (``fedml_tpu/analysis``,
  docs/static_analysis.md): host-sync/retrace/donation hazards on the
  round hot paths, determinism and exception hygiene, cross-thread
  lock discipline, and MSG_TYPE/telemetry/knob registry consistency —
  ratcheted against the checked-in ``lint_baseline.json`` (CI fails
  on any NEW finding and on stale suppressions). Pure AST: no JAX
  import, runs in seconds on a bare checkout.
- ``audit``    — beyond the reference: the compiled-artifact
  counterpart of ``lint`` (``fedml_tpu/analysis/compiled.py`` +
  ``audit.py``): AOT-lowers every registered hot-path executable
  (round fn, aggregation term/fold jits, planet group jit, serving
  forward) across the pow2 shape census — nothing executes — and
  verifies donation aliasing, host-transfer freedom, census size and
  baked-constant budgets against the ratcheted
  ``audit_baseline.json``, emitting the ``audit_report.json``
  FLOPs/bytes roofline.

State lives under ``~/.fedml_tpu/`` (override: FEDML_TPU_HOME).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import zipfile

from . import __version__


def _home() -> str:
    root = os.environ.get(
        "FEDML_TPU_HOME", os.path.join(os.path.expanduser("~"), ".fedml_tpu")
    )
    os.makedirs(root, exist_ok=True)
    return root


def _account_path() -> str:
    return os.path.join(_home(), "account.json")


def _pid_path() -> str:
    return os.path.join(_home(), "edge_agent.pid")


def cmd_version(_args) -> int:
    print(f"fedml_tpu version {__version__}")
    return 0


def cmd_login(args) -> int:
    account = {
        "account_id": args.account_id,
        "server": args.server,
        "role": args.role,
        "broker_host": args.broker_host,
        "broker_port": args.broker_port,
    }
    with open(_account_path(), "w") as f:
        json.dump(account, f)
    print(f"login: bound account {args.account_id} (role={args.role})")
    if args.no_daemon:
        return 0
    import subprocess

    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "fedml_tpu.edge_agent",
            "--account-id",
            str(args.account_id),
            "--broker-host",
            args.broker_host,
            "--broker-port",
            str(args.broker_port),
        ],
        stdout=open(os.path.join(_home(), "edge_agent.log"), "ab"),
        stderr=subprocess.STDOUT,
        start_new_session=True,
    )
    with open(_pid_path(), "w") as f:
        f.write(str(proc.pid))
    print(f"edge agent daemon started (pid {proc.pid})")
    return 0


def cmd_logout(_args) -> int:
    if os.path.exists(_pid_path()):
        try:
            with open(_pid_path()) as f:
                pid = int(f.read().strip())
            os.kill(pid, signal.SIGTERM)
            print(f"edge agent daemon (pid {pid}) stopped")
        except (OSError, ValueError) as e:
            # stale/corrupt pid file or an already-gone daemon: logout
            # proceeds either way, but say what happened
            print(f"logout: daemon already gone ({e})", file=sys.stderr)
        os.remove(_pid_path())
    if os.path.exists(_account_path()):
        os.remove(_account_path())
    print("logout: account binding cleared")
    return 0


def cmd_build(args) -> int:
    """Zip the user's source dir + entry point + manifest
    (cli/cli.py:141-250's build, without platform templates)."""
    src = os.path.abspath(args.source_folder)
    if not os.path.isdir(src):
        print(f"build: source folder {src!r} not found", file=sys.stderr)
        return 2
    entry = args.entry_point
    if not os.path.exists(os.path.join(src, entry)):
        print(f"build: entry {entry!r} not in {src!r}", file=sys.stderr)
        return 2
    os.makedirs(args.dest_folder, exist_ok=True)
    out = os.path.join(args.dest_folder, f"fedml_{args.type}_package.zip")
    manifest = {
        "type": args.type,
        "entry": entry,
        "config": args.config_folder,
        "version": __version__,
    }
    with zipfile.ZipFile(out, "w", zipfile.ZIP_DEFLATED) as z:
        for base, _, files in os.walk(src):
            for name in files:
                path = os.path.join(base, name)
                z.write(path, os.path.relpath(path, src))
        if args.config_folder:
            cfg = os.path.abspath(args.config_folder)
            for base, _, files in os.walk(cfg):
                for name in files:
                    path = os.path.join(base, name)
                    z.write(
                        path,
                        os.path.join("config", os.path.relpath(path, cfg)),
                    )
        z.writestr("MANIFEST.json", json.dumps(manifest))
    print(f"build: {args.type} package -> {out}")
    return 0


def cmd_serve(args) -> int:
    """Serve the federated global model over LOCAL or GRPC.

    Builds the model from the YAML config (``--cf``), restores the
    newest restorable checkpoint from ``--checkpoint-dir`` (corrupt
    latest falls back to the previous version — CheckpointWatcher
    semantics), starts the fleet (``--fleet-size`` micro-batching
    engines behind one load-aware frontend; size 1 = the classic single
    endpoint), and keeps hot-swapping weights as the trainer publishes
    new rounds. ``--mesh DxF`` serves every endpoint pjit'd over a
    named (data, fsdp) mesh with publishes restored device-direct onto
    it. ``--dry-run`` builds everything, prints one status JSON line,
    and exits — the smoke seam for tests and deploy scripts."""
    import importlib

    jax = importlib.import_module("jax")
    from .arguments import Arguments
    from . import models
    from .core.checkpoint import CheckpointWatcher
    from .serving import FleetFrontend, ServingFleet
    from .serving.frontends import build_serving_com

    ns = argparse.Namespace(
        yaml_config_file=args.cf or "",
        rank=0,
        role="server",
        run_id=args.run_id,
    )
    a = Arguments(ns)
    if args.fleet_size is not None:
        a.serve_fleet_size = max(1, int(args.fleet_size))
    if args.mesh:
        try:
            d, f = (int(t) for t in str(args.mesh).lower().split("x"))
        except ValueError:
            print(f"serve: --mesh {args.mesh!r} is not DATAxFSDP (e.g. 2x2)",
                  file=sys.stderr)
            return 2
        a.serve_mesh = {"data": d, "fsdp": f}
    mesh = None
    if getattr(a, "serve_mesh", None):
        from .parallel.layout import build_fed_mesh

        # serving draws no in-jit randomness, so the threefry
        # partitionability warning would be noise here
        mesh = build_fed_mesh(
            mesh_shape=a.serve_mesh, warn_nonpartitionable=False
        )
    model = models.create(a, int(args.output_dim))
    params = model.init(jax.random.PRNGKey(int(a.random_seed)))
    fleet = ServingFleet.build(model, params, a, mesh=mesh)

    watcher = None
    if args.checkpoint_dir:
        # restore_target: after the first (host-side) publish teaches
        # the fleet the state tree, mesh restores land device-direct
        watcher = CheckpointWatcher(
            args.checkpoint_dir,
            poll_interval_s=a.serve_watch_interval_s,
            restore_target=fleet.restore_target,
        )
        update = watcher.poll()
        if update is not None:
            step, state = update
            fleet.publish_state(state, step)
            print(f"serve: loaded checkpoint step {step}", file=sys.stderr)

    fleet.start()
    engine = fleet.engines[0]
    status = {
        "model": model.name,
        "version": engine.endpoint.version,
        "backend": args.backend,
        "queue_size": engine.queue_size,
        "max_batch": engine.max_batch,
        "bucket_policy": engine.bucket_policy,
        "deadline_ms": a.serve_deadline_ms,
        "checkpoint_dir": args.checkpoint_dir,
        "fleet_size": len(fleet.engines),
        "mesh": getattr(a, "serve_mesh", None),
        "route_policy": fleet.route_policy,
    }
    if args.dry_run:
        print(json.dumps(status))
        fleet.stop()
        if watcher is not None:
            watcher.close()
        return 0

    com = build_serving_com(a, rank=0, size=int(args.world_size), backend=args.backend)
    frontend = FleetFrontend(fleet, com, a, rank=0)
    if watcher is not None:
        watcher.watch(lambda step, state: fleet.publish_state(state, step))
    print(f"serve: ready ({json.dumps(status)})", file=sys.stderr)
    try:
        frontend.serve_forever()
    except KeyboardInterrupt:  # lint: except-ok — Ctrl-C is the normal
        pass  # way to stop `serve`; the finally below shuts down cleanly
    finally:
        frontend.stop()
        fleet.stop()
        if watcher is not None:
            watcher.close()
        from .core.telemetry import Telemetry

        Telemetry.get_instance().export_run_artifacts(
            getattr(a, "telemetry_dir", None)
        )
    return 0


def cmd_edge(args) -> int:
    """Launch one edge aggregator rank of the hierarchical server plane
    (``fedml_tpu/cross_silo/hierarchical`` — docs/hierarchical.md).

    Reads the federation config (``--cf``), forces ``edge_plane:
    ranks``, and runs an ``EdgeServerManager``: rank N of the root
    fabric, server of its own client fabric, streaming-folding its
    partition's uploads and shipping one merged limb-set per round.
    ``--dry-run`` builds the model + partition, prints one status JSON
    line, and exits (the ``serve --dry-run`` smoke seam)."""
    from .arguments import Arguments
    from .edge_agent import run_edge

    ns = argparse.Namespace(
        yaml_config_file=args.cf or "",
        rank=int(args.rank),
        role="edge_server",
        run_id=args.run_id,
    )
    a = Arguments(ns)
    a.edge_plane = "ranks"
    if args.backend:
        a.backend = args.backend
    a._validate()
    return run_edge(a, dry_run=args.dry_run)


def cmd_device(args) -> int:
    """Run the cross-device Beehive federation (docs/cross_device.md).

    Reads the federation config (``--cf``), builds the device registry,
    and drives ``comm_round`` connectionless check-in rounds end to end
    on the in-process fabric: check-in, int8 round offer, pairwise-
    masked uploads, fold-target close, dropout recovery. ``--dry-run``
    builds the registry and world wiring, prints one status JSON line,
    and exits (the ``serve --dry-run`` smoke seam)."""
    from .arguments import Arguments
    from .cross_device.driver import run_beehive_world
    from .cross_device.protocol import flat_dim
    from .scale.registry import ClientRegistry

    ns = argparse.Namespace(
        yaml_config_file=args.cf or "",
        rank=0,
        role="server",
        run_id=args.run_id,
    )
    a = Arguments(ns)
    a._validate()
    size = int(getattr(a, "client_registry_size", 0) or 0) or 10_000
    registry = ClientRegistry(
        size,
        seed=int(getattr(a, "random_seed", 0) or 0),
        duty_hours=int(getattr(a, "crossdevice_duty_hours", 14)),
    )
    feature_dim = int(args.feature_dim)
    class_num = int(args.output_dim)
    cohort = (
        int(getattr(a, "crossdevice_cohort", 0) or 0)
        or int(getattr(a, "cohort_size", 0) or 0)
        or int(getattr(a, "client_num_per_round", 4))
    )
    status = {
        "plane": "crossdevice",
        "registry_size": registry.size,
        "registry_bytes": registry.nbytes(),
        "cohort": cohort,
        "rounds": int(a.comm_round),
        "fold_target_frac": float(a.crossdevice_fold_target_frac),
        "secure_agg": bool(a.crossdevice_secure_agg),
        "quant_scale": float(a.crossdevice_quant_scale),
        "update_dim": flat_dim(feature_dim, class_num),
    }
    if args.dry_run:
        print(json.dumps(status))
        return 0
    out = run_beehive_world(
        a,
        feature_dim=feature_dim,
        class_num=class_num,
        registry=registry,
    )
    status["round_records"] = out["round_records"]
    status["trace_count"] = out["trace_count"]
    print(json.dumps(status))
    return 0


def cmd_trace(args) -> int:
    """Stitch a run's trace shards + analyze round critical paths.

    Prints one JSON summary line (shards, matched flows, rounds
    analyzed, artifact paths); per-round detail goes to
    ``round_report.json``. ``--summary`` additionally pretty-prints the
    per-round segment table to stderr for quick terminal reading."""
    from .core.tracing import trace_run

    try:
        out = trace_run(args.telemetry_dir, out_dir=args.out)
    except FileNotFoundError as e:
        print(f"trace: {e}", file=sys.stderr)
        return 2
    if args.summary:
        with open(out["round_report"]) as fh:
            report = json.load(fh)
        for r in report["rounds"]:
            segs = ", ".join(
                f"{k}={v * 1e3:.1f}ms" for k, v in r["segments_s"].items()
            )
            print(
                f"round {r['round']}: wall={r['wall_s'] * 1e3:.1f}ms "
                f"straggler=rank{r['straggler_rank']} [{segs}]",
                file=sys.stderr,
            )
    print(json.dumps(out))
    return 0


def cmd_check(args) -> int:
    """Run the post-hoc invariant checker over a run's artifacts.

    Prints one JSON line ``{ok, checked, skipped, violations}``;
    exit code 1 when any invariant is violated (CI-gateable). The WAL
    is read from ``--checkpoint-dir`` when the run kept its
    checkpoints elsewhere than its telemetry."""
    from .core.invariants import InvariantChecker

    if not os.path.isdir(args.telemetry_dir):
        print(f"check: {args.telemetry_dir!r} not found", file=sys.stderr)
        return 2
    report = InvariantChecker(
        telemetry_dir=args.telemetry_dir,
        checkpoint_dir=args.checkpoint_dir,
    ).check()
    out = report.to_dict()
    print(json.dumps(out))
    if not report.ok:
        for v in report.violations:
            print(
                f"check: VIOLATED {v['invariant']}: {v['detail']}",
                file=sys.stderr,
            )
        return 1
    return 0


def cmd_lint(args) -> int:
    """Run the static-analysis suite (docs/static_analysis.md). Kept
    import-light on purpose: the AST pass needs neither JAX nor the
    training stack, so the CI gate runs it on a bare checkout."""
    from .analysis.engine import run_cli

    return run_cli(args)


def cmd_audit(args) -> int:
    """Run the compiled-artifact audit (docs/static_analysis.md):
    AOT-lower every registered hot-path executable (nothing executes)
    and verify donation aliasing, host-transfer freedom, the pow2
    shape census and baked-constant budgets against the ratcheted
    audit_baseline.json, emitting the audit_report.json static-cost
    roofline. Needs JAX (unlike `lint`); lowers for CPU by default."""
    from .analysis.audit import run_cli

    return run_cli(args)


def cmd_perf(args) -> int:
    """Performance-attribution plane (docs/observability.md): join a
    run's measured ``exec_device_seconds`` onto the audit roofline
    (per-executable achieved FLOP/s + MFU + bound verdict), summarize
    the per-round idle-time ledger, or — with ``--ratchet`` — gate the
    BENCH trajectory against its best prior record per phase and
    device kind. Pure stdlib, like `lint`: runs on a bare checkout."""
    from .analysis.perf import run_cli

    return run_cli(args)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="fedml-tpu")
    sub = p.add_subparsers(dest="command", required=True)

    sub.add_parser("version").set_defaults(fn=cmd_version)

    login = sub.add_parser("login")
    login.add_argument("account_id")
    login.add_argument("--server", default="local")
    login.add_argument("--role", default="client", choices=["client", "edge_server"])
    login.add_argument("--broker-host", default="127.0.0.1")
    login.add_argument("--broker-port", type=int, default=18830)
    login.add_argument("--no-daemon", action="store_true")
    login.set_defaults(fn=cmd_login)

    sub.add_parser("logout").set_defaults(fn=cmd_logout)

    serve = sub.add_parser("serve")
    serve.add_argument("--cf", "--yaml_config_file", dest="cf", default="")
    serve.add_argument("--checkpoint-dir", default=None)
    serve.add_argument(
        "--backend", default="LOCAL", type=str.upper, choices=["LOCAL", "GRPC"]
    )
    serve.add_argument("--world-size", type=int, default=2)
    serve.add_argument("--output-dim", type=int, default=10)
    serve.add_argument(
        "--fleet-size", type=int, default=None,
        help="endpoints behind the fleet frontend (default: "
        "serve_fleet_size knob)",
    )
    serve.add_argument(
        "--mesh", default=None, metavar="DATAxFSDP",
        help="serve on a named (data, fsdp) mesh, e.g. 2x2 "
        "(default: serve_mesh knob; omit to serve single-device)",
    )
    serve.add_argument("--run-id", dest="run_id", default="0")
    serve.add_argument("--dry-run", action="store_true")
    serve.set_defaults(fn=cmd_serve)

    edge = sub.add_parser("edge")
    edge.add_argument("--cf", "--yaml_config_file", dest="cf", default="")
    edge.add_argument(
        "--rank", type=int, required=True,
        help="this edge's rank on the root fabric (1..edge_num)",
    )
    edge.add_argument(
        "--backend", default=None,
        type=lambda s: s.upper(), choices=[None, "LOCAL", "GRPC"],
    )
    edge.add_argument("--run-id", dest="run_id", default="0")
    edge.add_argument("--dry-run", action="store_true")
    edge.set_defaults(fn=cmd_edge)

    device = sub.add_parser("device")
    device.add_argument("--cf", "--yaml_config_file", dest="cf", default="")
    device.add_argument("--feature-dim", type=int, default=8)
    device.add_argument("--output-dim", type=int, default=4)
    device.add_argument("--run-id", dest="run_id", default="0")
    device.add_argument("--dry-run", action="store_true")
    device.set_defaults(fn=cmd_device)

    trace = sub.add_parser("trace")
    trace.add_argument(
        "--telemetry-dir", required=True,
        help="directory holding the run's trace*.json shards",
    )
    trace.add_argument(
        "--out", default=None,
        help="where to write trace_merged.json / round_report.json "
             "(default: the telemetry dir itself)",
    )
    trace.add_argument(
        "--summary", action="store_true",
        help="also print a per-round segment table to stderr",
    )
    trace.set_defaults(fn=cmd_trace)

    check = sub.add_parser("check")
    check.add_argument(
        "--telemetry-dir", required=True,
        help="directory holding the run's telemetry.jsonl / trace.json",
    )
    check.add_argument(
        "--checkpoint-dir", default=None,
        help="directory holding round_wal.jsonl (default: the telemetry dir)",
    )
    check.set_defaults(fn=cmd_check)

    lint = sub.add_parser("lint")
    from .analysis.engine import add_lint_arguments

    add_lint_arguments(lint)
    lint.set_defaults(fn=cmd_lint)

    audit = sub.add_parser("audit")
    from .analysis.audit import add_audit_arguments

    add_audit_arguments(audit)
    audit.set_defaults(fn=cmd_audit)

    perf = sub.add_parser("perf")
    from .analysis.perf import add_perf_arguments

    add_perf_arguments(perf)
    perf.set_defaults(fn=cmd_perf)

    build = sub.add_parser("build")
    build.add_argument("-t", "--type", required=True, choices=["client", "server"])
    build.add_argument("-sf", "--source-folder", required=True)
    build.add_argument("-ep", "--entry-point", required=True)
    build.add_argument("-cf", "--config-folder", default=None)
    build.add_argument("-df", "--dest-folder", default="./dist")
    build.set_defaults(fn=cmd_build)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
