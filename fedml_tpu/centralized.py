"""Centralized (non-federated) baseline trainer.

Parity with ``centralized/centralized_trainer.py`` (163 LoC): plain
training on the coalesced federated dataset, used as the numeric
baseline the CI equivalence oracles compare against
(ci/CI-script-fedavg.sh:44-63). Here it is the same jitted scan-based
local trainer the clients use, pointed at the global split — so
"federated full-batch == centralized" is a one-line assertion.
"""

from __future__ import annotations

import time
from typing import Dict, List

import jax

from .core.local_trainer import (
    compute_dtype_from_args,
    make_eval_fn,
    make_local_train_fn,
)
from .core.optimizers import create_client_optimizer


class CentralizedTrainer:
    def __init__(self, args, device, dataset, model) -> None:
        self.args = args
        self.dataset = dataset
        self.model = model
        self.history: List[Dict[str, float]] = []
        self.rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        self.rng, init_rng = jax.random.split(self.rng)
        self.params = model.init(init_rng)
        self._train_fn = jax.jit(
            make_local_train_fn(
                model.apply,
                model.loss_fn,
                create_client_optimizer(args),
                epochs=1,
                shuffle=bool(getattr(args, "shuffle", True)),
                compute_dtype=compute_dtype_from_args(args),
            )
        )
        self._eval = jax.jit(
            make_eval_fn(
                model.apply, model.loss_fn,
                compute_dtype=compute_dtype_from_args(args),
            )
        )

    def train(self) -> Dict[str, float]:
        epochs = int(getattr(self.args, "epochs", 1))
        final: Dict[str, float] = {}
        for epoch in range(epochs):
            t0 = time.perf_counter()
            self.rng, ep_rng = jax.random.split(self.rng)
            self.params, _ = self._train_fn(
                self.params, self.dataset.train_data_global, ep_rng
            )
            tr = self.model.metrics_from_sums(
                self._eval(self.params, self.dataset.train_data_global)
            )
            te = self.model.metrics_from_sums(
                self._eval(self.params, self.dataset.test_data_global)
            )
            final = {
                "epoch": epoch,
                "train_acc": tr["acc"],
                "train_loss": tr["loss"],
                "test_acc": te["acc"],
                "test_loss": te["loss"],
                "epoch_time_s": time.perf_counter() - t0,
            }
            self.history.append(final)
        return final
