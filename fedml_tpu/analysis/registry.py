"""Rule ``registry`` — consistency between the codebase's three
registries and their sources of truth.

The drift this catches is exactly what the last five PRs' review
passes kept finding by hand:

1. **MSG_TYPE coverage** — every ``MSG_TYPE_*`` constant in
   ``constants.py`` must be *dispatchable*: registered via
   ``register_message_receive_handler`` somewhere, or consumed at the
   comm layer (a ``==`` / ``in`` comparison — the reliable channel's
   ACK path). An orphaned message type is a protocol message nothing
   can receive.

2. **Telemetry naming + documentation** — every series name emitted
   through ``.inc`` / ``.set_gauge`` / ``.observe`` must (a) follow
   the convention — counters end ``_total``; histograms carry a unit
   suffix (``_seconds``/``_s``/``_ms``/``_bytes``/``_frac`` or
   ``_total``); gauges must NOT end ``_total`` (Prometheus reserves
   it for counters) — and (b) appear in the docs counters tables
   (``docs/*.md``): an undocumented counter is invisible to the
   invariant checker's operators and to dashboards.

3. **Knob coverage** — every ``args.<knob>`` read (attribute access
   or ``getattr(args, "<knob>")``) must have an entry in
   ``arguments.py``'s ``_DEFAULTS`` schema (which doubles as the
   validation table) or be a recognised runtime attribute (rank,
   role, process identity — set by ``init()``/launchers, not
   configuration). A knob read without a schema entry is exactly the
   "no typed schema, no validation" reference bug the Arguments layer
   exists to fix.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .engine import Finding, ModuleSource

RULE = "registry"

# runtime attributes assigned by init()/launchers/tests rather than
# declared configuration — reads of these are not knob reads
RUNTIME_ARGS = {
    "rank", "local_rank", "role", "run_id", "process_id",
    "yaml_config_file", "worker_num", "client_rank", "client_id",
    "device", "verbose", "distributed_coordinator", "proc_rank_in_silo",
    "rank_in_node", "node_rank", "n_proc_in_silo", "silo_rank", "comm",
}

# unit vocabulary for histogram names; "_rounds" is a federation-native
# unit (staleness, probation length) just like seconds or bytes, and
# "_ratio" is the dimensionless quotient that may exceed 1 (anomaly
# scores) where "_frac" promises [0, 1]
_HISTOGRAM_SUFFIXES = (
    "_seconds", "_s", "_ms", "_bytes", "_frac", "_ratio", "_rounds",
    "_total",
)

_EMIT_METHODS = {"inc": "counter", "set_gauge": "gauge", "observe": "histogram"}

# unit-suffix near-misses: abbreviations and synonyms of the canonical
# vocabulary that read fine in review but split dashboards into two
# series families ("wire_utilization_fraction" next to "_frac")
_UNIT_NEAR_MISSES = (
    "_sec", "_secs", "_second", "_millis", "_msec", "_fraction",
    "_percent", "_pct", "_byte", "_count",
)

# Arguments methods — `args.get(...)` et al. are API calls, not knob
# attribute reads (the .get STRING key is collected separately)
_ARGS_METHODS = {
    "get", "to_dict", "load_yaml_config", "set_attr_from_config",
}


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def collect_msg_types(constants_mod: ModuleSource) -> List[Tuple[str, int]]:
    out = []
    for node in ast.walk(constants_mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id.startswith("MSG_TYPE_"):
                out.append((t.id, node.lineno))
    return out


def _msg_type_consumers(corpus: Iterable[ModuleSource]) -> Set[str]:
    """MSG_TYPE_* names that are registered to a handler or consumed
    in a comparison/membership test somewhere in the corpus."""
    consumed: Set[str] = set()

    def names_in(node: ast.AST) -> Set[str]:
        found = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and sub.attr.startswith(
                "MSG_TYPE_"
            ):
                found.add(sub.attr)
            elif isinstance(sub, ast.Name) and sub.id.startswith("MSG_TYPE_"):
                found.add(sub.id)
        return found

    for mod in corpus:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                fn = node.func
                callee = (
                    fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None
                )
                if callee == "register_message_receive_handler" and node.args:
                    consumed |= names_in(node.args[0])
            elif isinstance(node, ast.Compare):
                consumed |= names_in(node)
            elif isinstance(node, ast.Dict):
                # handler tables keyed by msg type
                for k in node.keys:
                    if k is not None:
                        consumed |= names_in(k)
    return consumed


def collect_telemetry_emissions(
    corpus: Iterable[ModuleSource],
) -> List[Tuple[str, str, str, int]]:
    """(kind, name, path, line) for every literal-named emission."""
    out = []
    for mod in corpus:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute):
                continue
            kind = _EMIT_METHODS.get(fn.attr)
            if kind is None or not node.args:
                continue
            name = _const_str(node.args[0])
            if name is None:
                continue  # variable-named series are the caller's job
            out.append((kind, name, mod.path, node.lineno))
    return out


def collect_defaults_keys(arguments_mod: ModuleSource) -> Set[str]:
    """Keys of the module-level ``_DEFAULTS`` dict literal — the knob
    schema the validation layer is built over."""
    keys: Set[str] = set()
    for node in arguments_mod.tree.body:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        names = {t.id for t in targets if isinstance(t, ast.Name)}
        if "_DEFAULTS" not in names:
            continue
        value = node.value
        if isinstance(value, ast.Dict):
            for k in value.keys:
                s = _const_str(k) if k is not None else None
                if s:
                    keys.add(s)
    return keys


# modules whose local `args` is an argparse CLI namespace, not the
# federation Arguments schema — their attribute reads are flag reads
_ARGPARSE_MODULES = ("fedml_tpu/cli.py", "fedml_tpu/edge_agent.py")
_ARGPARSE_PREFIXES = ("fedml_tpu/analysis/",)


def _is_argparse_module(path: str) -> bool:
    return path in _ARGPARSE_MODULES or path.startswith(_ARGPARSE_PREFIXES)


def collect_knob_reads(
    corpus: Iterable[ModuleSource],
) -> List[Tuple[str, str, int]]:
    """(knob, path, line) for every ``args.<k>`` / ``self.args.<k>``
    attribute read and every ``getattr(<args-ish>, "<k>"[, default])``.
    Argparse-namespace modules (the CLIs and this analysis package)
    are exempt — their ``args`` is not the federation schema."""
    out = []
    for mod in corpus:
        if _is_argparse_module(mod.path):
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                v = node.value
                is_args = (
                    (isinstance(v, ast.Name) and v.id == "args")
                    or (isinstance(v, ast.Attribute) and v.attr == "args")
                )
                if (
                    is_args
                    and not node.attr.startswith("_")
                    and node.attr not in _ARGS_METHODS
                ):
                    out.append((node.attr, mod.path, node.lineno))
            elif isinstance(node, ast.Call):
                fn = node.func
                is_getattr = isinstance(fn, ast.Name) and fn.id == "getattr"
                is_args_get = (
                    isinstance(fn, ast.Attribute)
                    and fn.attr == "get"
                    and (
                        (isinstance(fn.value, ast.Name)
                         and fn.value.id == "args")
                        or (isinstance(fn.value, ast.Attribute)
                            and fn.value.attr == "args")
                    )
                )
                if is_args_get and node.args:
                    key = _const_str(node.args[0])
                    if key and not key.startswith("_"):
                        out.append((key, mod.path, node.lineno))
                    continue
                if not is_getattr or len(node.args) < 2:
                    continue
                tgt, key = node.args[0], _const_str(node.args[1])
                if key is None or key.startswith("_"):
                    continue
                is_args = (
                    (isinstance(tgt, ast.Name) and tgt.id == "args")
                    or (isinstance(tgt, ast.Attribute) and tgt.attr == "args")
                )
                if is_args:
                    out.append((key, mod.path, node.lineno))
    return out


def _assigned_args_attrs(corpus: Iterable[ModuleSource]) -> Set[str]:
    """Attributes the codebase *assigns* onto an args object
    (``args.X = ...`` / ``setattr(args, "X", ...)``) — runtime state,
    not configuration, so reads of them are covered."""
    out: Set[str] = set()
    for mod in corpus:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Store
            ):
                v = node.value
                if (isinstance(v, ast.Name) and v.id == "args") or (
                    isinstance(v, ast.Attribute) and v.attr == "args"
                ):
                    out.add(node.attr)
            elif isinstance(node, ast.Call):
                fn = node.func
                if (
                    isinstance(fn, ast.Name)
                    and fn.id == "setattr"
                    and len(node.args) >= 3
                ):
                    tgt, key = node.args[0], _const_str(node.args[1])
                    if key and (
                        (isinstance(tgt, ast.Name) and tgt.id == "args")
                        or (isinstance(tgt, ast.Attribute)
                            and tgt.attr == "args")
                    ):
                        out.add(key)
    return out


def check_registry(
    corpus: List[ModuleSource],
    docs_text: str,
    constants_path: str = "fedml_tpu/constants.py",
    arguments_path: str = "fedml_tpu/arguments.py",
    runtime_args: Optional[Set[str]] = None,
) -> List[Finding]:
    findings: List[Finding] = []
    by_path = {m.path: m for m in corpus}
    runtime = RUNTIME_ARGS if runtime_args is None else runtime_args

    # 1) MSG_TYPE coverage
    constants_mod = by_path.get(constants_path)
    if constants_mod is not None:
        consumed = _msg_type_consumers(corpus)
        for name, line in collect_msg_types(constants_mod):
            if name not in consumed:
                findings.append(Finding(
                    path=constants_path, line=line, rule=RULE,
                    message=(
                        f"{name} has no handler registration and no "
                        "comm-layer dispatch — an orphaned protocol "
                        "message nothing can receive"
                    ),
                ))

    # 2) telemetry naming + documentation
    documented = set(re.findall(r"[a-z][a-z0-9_]{2,}", docs_text))
    seen_names: Set[Tuple[str, str]] = set()
    for kind, name, path, line in collect_telemetry_emissions(corpus):
        if kind == "counter" and not name.endswith("_total"):
            findings.append(Finding(
                path=path, line=line, rule=RULE,
                message=(
                    f"counter '{name}' does not end in _total (the "
                    "Prometheus counter convention every dashboard "
                    "and the invariant checker key on)"
                ),
            ))
        elif kind == "gauge" and name.endswith("_total"):
            findings.append(Finding(
                path=path, line=line, rule=RULE,
                message=(
                    f"gauge '{name}' ends in _total — Prometheus "
                    "reserves _total for counters; rename the gauge"
                ),
            ))
        elif kind in ("gauge", "histogram") and name.endswith(
            _UNIT_NEAR_MISSES
        ):
            findings.append(Finding(
                path=path, line=line, rule=RULE,
                message=(
                    f"{kind} '{name}' ends in a unit-suffix near-miss "
                    "— use the canonical vocabulary "
                    "(_seconds/_s/_ms/_bytes/_frac/_ratio/_rounds) so "
                    "one quantity stays one series family"
                ),
            ))
        elif kind == "histogram" and not name.endswith(_HISTOGRAM_SUFFIXES):
            findings.append(Finding(
                path=path, line=line, rule=RULE,
                message=(
                    f"histogram '{name}' has no unit suffix "
                    "(_seconds/_s/_ms/_bytes/_frac/_ratio/_rounds) — "
                    "unitless series are unreadable on dashboards"
                ),
            ))
        if (kind, name) not in seen_names:
            seen_names.add((kind, name))
            if name not in documented:
                findings.append(Finding(
                    path=path, line=line, rule=RULE,
                    message=(
                        f"telemetry series '{name}' is not documented "
                        "in any docs/ counters table "
                        "(docs/observability.md is the catalog)"
                    ),
                ))

    # 3) knob coverage
    arguments_mod = by_path.get(arguments_path)
    if arguments_mod is not None:
        defaults = collect_defaults_keys(arguments_mod)
        assigned = _assigned_args_attrs(corpus)
        reported: Set[Tuple[str, str, int]] = set()
        for knob, path, line in collect_knob_reads(corpus):
            if path == arguments_path:
                continue  # the schema/validation layer reads itself
            if knob in defaults or knob in runtime or knob in assigned:
                continue
            site = (knob, path, line)
            if site in reported:
                continue
            reported.add(site)
            findings.append(Finding(
                path=path, line=line, rule=RULE,
                message=(
                    f"args.{knob} is read but has no entry in "
                    "arguments.py _DEFAULTS — undeclared knobs skip "
                    "type coercion and validation"
                ),
            ))
    return findings
