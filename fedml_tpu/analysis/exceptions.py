"""Rule ``except`` — exception hygiene, repo-wide.

Two shapes, both of which PR 10's review pass fixed instances of by
hand (``reliable.py``'s three bare excepts became debug-logged,
``comm_internal_errors_total``-counted sites):

- **bare ``except:``** — catches ``SystemExit`` / ``KeyboardInterrupt``
  / ``ProcessKilled`` (the chaos plane's in-process kill -9, which
  MUST propagate), turning deliberate crashes into silent hangs;
- **swallow-without-evidence** — a handler whose entire body is
  ``pass`` / ``continue`` / ``break``: the failure leaves no log line
  and no counter, so a chaos run cannot distinguish "nothing broke"
  from "everything broke quietly". The fix pattern is a
  ``logging.debug(..., exc_info=True)`` plus a
  ``*_internal_errors_total`` counter tag, or a comment-suppression
  naming why silence is correct.
"""

from __future__ import annotations

import ast
from typing import List

from .engine import Finding, ModuleSource

RULE = "except"


def _is_noop(stmt: ast.stmt) -> bool:
    # `continue`/`break` in a handler is exception-as-control-flow
    # (queue.Empty, shutdown races) — observable behaviour, not a
    # swallow; only a pure `pass` body hides the failure entirely
    if isinstance(stmt, ast.Pass):
        return True
    # a bare docstring/Ellipsis expression
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
        return True
    return False


def check_exceptions(mod: ModuleSource) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            findings.append(Finding(
                path=mod.path, line=node.lineno, rule=RULE,
                message=(
                    "bare `except:` catches SystemExit/KeyboardInterrupt/"
                    "ProcessKilled — name the exception types"
                ),
            ))
        if node.body and all(_is_noop(s) for s in node.body):
            findings.append(Finding(
                path=mod.path, line=node.lineno, rule=RULE,
                message=(
                    "exception swallowed without a log or counter — add "
                    "logging.debug(..., exc_info=True) and/or a "
                    "*_internal_errors_total tag, or mark the line "
                    "`# lint: except-ok` naming why silence is correct"
                ),
            ))
    return findings
