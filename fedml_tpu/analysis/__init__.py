"""``fedml_tpu.analysis`` — the JAX-/federation-aware static-analysis
suite behind ``fedml-tpu lint`` (docs/static_analysis.md).

Pure stdlib: importing this package must never import JAX, NumPy or
YAML — the CI gate runs the whole AST pass in seconds on a bare
checkout. Rule ids (one checker each):

- ``host-sync``    hidden device->host fetches on round/serving hot paths
- ``retrace``      jit-in-loop, jit-over-mutable-self, traced-arg branching
- ``donation``     donated buffers reused; round-shaped jits not donating
- ``determinism``  global RNG / wall clock in seeded paths
- ``except``       bare excepts and swallow-without-log/counter
- ``thread-lock``  cross-thread attribute access without the owning lock
- ``registry``     MSG_TYPE/telemetry/knob registries vs their docs+schema
"""

from .engine import (  # noqa: F401
    BASELINE_NAME,
    Finding,
    ModuleSource,
    RULES,
    diff_baseline,
    find_repo_root,
    findings_to_counts,
    load_baseline,
    load_corpus,
    main,
    run_lint,
    save_baseline,
)
