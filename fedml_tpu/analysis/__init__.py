"""``fedml_tpu.analysis`` — the JAX-/federation-aware static-analysis
suite behind ``fedml-tpu lint`` and ``fedml-tpu audit``
(docs/static_analysis.md).

Pure stdlib: importing this package must never import JAX, NumPy or
YAML — the CI gate runs the whole AST pass in seconds on a bare
checkout (the audit engine imports JAX lazily, only when a lowering
actually runs). Source rule ids (one checker each):

- ``host-sync``    hidden device->host fetches on round/serving hot paths
- ``retrace``      jit-in-loop, jit-over-mutable-self, traced-arg branching
- ``donation``     donated buffers reused; round-shaped jits not donating
- ``determinism``  global RNG / wall clock in seeded paths (+ tests/,
                   relaxed profile)
- ``except``       bare excepts and swallow-without-log/counter
- ``thread-lock``  cross-thread attribute access without the owning lock
- ``registry``     MSG_TYPE/telemetry/knob registries vs their docs+schema

Compiled-artifact rule ids (``audit.py``, over AOT-lowered HLO —
nothing executes):

- ``aot-donation``      claimed donations must alias in the artifact;
                        round-shaped executables must alias at all
- ``aot-host-transfer`` no infeed/outfeed/callbacks in hot executables
- ``aot-census``        lowered shape keys within the pow2 budget
- ``aot-constant``      no large non-splat baked-in constants
"""

from .engine import (  # noqa: F401
    BASELINE_NAME,
    Finding,
    ModuleSource,
    RULES,
    diff_baseline,
    find_repo_root,
    findings_to_counts,
    load_baseline,
    load_corpus,
    main,
    run_lint,
    save_baseline,
)
