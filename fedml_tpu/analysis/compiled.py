"""Compiled-artifact audit plane — the registry and AOT-lowering layer
under ``fedml-tpu audit`` (docs/static_analysis.md).

``fedml-tpu lint`` checks what the *source* says; nothing checked what
XLA *actually lowers* — donation contracts lived in docstrings, the
"no host transfers in hot executables" rule was enforced only at the
Python-source level, and the compile census (one executable per pow2
shape bucket) was asserted per-module by tests that execute training.
This module closes that gap without executing anything:

- hot-path modules REGISTER their executables via the
  :func:`auditable` decorator — either directly on a module-level jit
  (with an ``abstract_inputs`` builder producing
  ``jax.ShapeDtypeStruct`` argument trees), or on a *provider*
  function that builds the executable the same way the runtime does
  (``build_round_fn`` / ``build_group_fn`` / ``build_forward``) and
  returns fully-formed :class:`LoweringCase`\\s across the pow2 shape
  census;
- the auditor (``fedml_tpu/analysis/audit.py``) AOT-lowers every case
  (``jit(...).lower(*abstract_args)`` — tracing only, **nothing is
  ever executed**, no data exists) and verifies compile-time
  invariants against the lowered StableHLO module: input–output
  aliasing for every docstring-claimed donation, no host-transfer ops
  in hot executables, shape-key counts within the pow2 budget, no
  large baked-in constants, and XLA's static cost analysis
  (FLOPs / bytes accessed) for the ``audit_report.json`` roofline.

Import discipline: importing THIS module must not import JAX — the
CLI surface (``fedml_tpu.cli``) builds its parser from the audit
module on a bare checkout. JAX is imported lazily the moment a case
is built or lowered; the registered host modules (which all import
JAX at top level anyway) are imported on demand by
:func:`load_registry`.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "AUDITED_MODULES",
    "AuditContext",
    "AuditableSpec",
    "LoweredArtifact",
    "LoweringCase",
    "auditable",
    "load_registry",
    "lower_case",
    "pow2_budget",
]

# the modules that register auditable executables; load_registry()
# imports each so their @auditable declarations run. Growing the hot
# path? Register the executable AND add its module here.
AUDITED_MODULES = (
    "fedml_tpu.core.aggregation",
    "fedml_tpu.simulation.fedavg_api",
    "fedml_tpu.scale.engine",
    "fedml_tpu.serving.endpoint",
    "fedml_tpu.serving.mesh_endpoint",
)


def pow2_budget(sizes: Sequence[int]) -> int:
    """How many pow2 shape keys the span [min(sizes), max(sizes)]
    legitimately needs — the census rule's budget (8..512 -> 7)."""
    lo, hi = min(sizes), max(sizes)
    return int(math.log2(max(hi, 1) // max(lo, 1))) + 1


@dataclass
class LoweringCase:
    """One (executable, abstract inputs) pair — a single shape key of
    a registered executable's census. ``fn`` must be a jit-wrapped
    callable (it is ``.lower()``-ed, never called)."""

    key: str  # census key, e.g. "b8" / "b8xnb4"
    fn: Any
    args: Tuple[Any, ...] = ()
    kwargs: Dict[str, Any] = field(default_factory=dict)


@dataclass
class AuditableSpec:
    """One registered executable: how to build its census cases and
    which compile-time contracts its docstrings claim."""

    name: str
    path: str  # repo-relative module path (baseline key namespace)
    provider: Callable[["AuditContext"], List[LoweringCase]]
    # argnums the docstrings claim are donated — the lowered module
    # must carry input-output aliasing for every leaf of these args
    donate: Tuple[int, ...] = ()
    # round-shaped executables (carried state in, carried state out)
    # with ZERO aliasing are findings even without a donation claim —
    # the ground truth behind the lint suite's donation TODOs
    round_shaped: bool = False
    # hot executables must contain no host-transfer ops at all
    hot: bool = True
    # census rule: max lowered shape keys (int, or callable(ctx) ->
    # int); None skips the census check for this spec
    census_budget: Any = None
    # aot-constant rule: largest tolerated non-splat baked-in constant
    constant_budget_bytes: int = 64 * 1024


_REGISTRY: Dict[str, AuditableSpec] = {}


def _module_to_path(module: str) -> str:
    return module.replace(".", "/") + ".py"


def auditable(
    name: str,
    abstract_inputs: Optional[Callable[["AuditContext"], List[Tuple]]] = None,
    *,
    donate: Tuple[int, ...] = (),
    round_shaped: bool = False,
    hot: bool = True,
    census_budget: Any = None,
    constant_budget_bytes: int = 64 * 1024,
):
    """Register an executable with the compiled-artifact auditor.

    Two application forms:

    - on a module-level jit, with ``abstract_inputs`` — a function
      ``ctx -> [(case_key, args, kwargs), ...]`` of
      ``jax.ShapeDtypeStruct`` trees; the decorated jit itself is
      lowered for each tuple;
    - on a *provider* function ``ctx -> [LoweringCase, ...]`` (no
      ``abstract_inputs``) — for executables the runtime builds per
      instance (the round fn, the planet group fn, the serving
      forward): the provider constructs them through the same
      module-level builders the runtime uses.

    Returns the decorated object unchanged — zero runtime cost.
    """

    def register(obj):
        if abstract_inputs is not None:
            def provider(ctx, _fn=obj):
                return [
                    LoweringCase(key=k, fn=_fn, args=tuple(a), kwargs=dict(kw))
                    for k, a, kw in abstract_inputs(ctx)
                ]
        else:
            provider = obj
        module = getattr(obj, "__module__", None) or "fedml_tpu"
        _REGISTRY[name] = AuditableSpec(
            name=name,
            path=_module_to_path(module),
            provider=provider,
            donate=tuple(donate),
            round_shaped=round_shaped,
            hot=hot,
            census_budget=census_budget,
            constant_budget_bytes=int(constant_budget_bytes),
        )
        return obj

    return register


def load_registry() -> Dict[str, AuditableSpec]:
    """Import every audited module (running their ``@auditable``
    registrations) and return the registry. JAX loads here — never at
    ``fedml_tpu.analysis`` import time."""
    import importlib

    for mod in AUDITED_MODULES:
        importlib.import_module(mod)
    return dict(_REGISTRY)


# ---------------------------------------------------------------------
# audit context: the shared abstract world every provider builds from
# ---------------------------------------------------------------------


@dataclass
class AuditContext:
    """The abstract (data-free) world the census is lowered against: a
    small real model from the zoo plus ``ShapeDtypeStruct`` factories.
    Small on purpose — the audit's subject is compile-time structure
    (aliasing, host ops, shape keys, cost ratios), not model scale; a
    CPU-only box lowers the full census in seconds."""

    cohort_buckets: Tuple[int, ...] = (8, 32)
    nb_census: Tuple[int, ...] = (2, 4)
    batch_size: int = 4
    feature_dim: int = 8
    class_num: int = 4
    serve_buckets: Tuple[int, ...] = (4, 16)
    edge_num: int = 2
    epochs: int = 1
    learning_rate: float = 0.03

    _model: Any = field(default=None, repr=False)
    _params: Any = field(default=None, repr=False)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "cohort_buckets": list(self.cohort_buckets),
            "nb_census": list(self.nb_census),
            "batch_size": self.batch_size,
            "feature_dim": self.feature_dim,
            "class_num": self.class_num,
            "serve_buckets": list(self.serve_buckets),
            "edge_num": self.edge_num,
            "epochs": self.epochs,
        }

    # -- model ---------------------------------------------------------
    def model(self):
        """A real zoo model (logistic regression over
        ``feature_dim`` -> ``class_num``) — the smallest member of the
        family every audited executable is generic over."""
        if self._model is None:
            from ..models.linear import LogisticRegression
            from ..models.spec import FedModel

            self._model = FedModel(
                name="lr",
                module=LogisticRegression(self.class_num),
                example_shape=(self.feature_dim,),
            )
        return self._model

    def abstract_params(self):
        """The model's parameter pytree as ``ShapeDtypeStruct`` leaves
        — obtained via ``jax.eval_shape`` so nothing initializes."""
        import jax

        if self._params is None:
            self._params = jax.eval_shape(
                self.model().init, jax.random.PRNGKey(0)
            )
        return self._params

    def local_train_fn(self):
        """The stock local-training fn over the audit model — built by
        the same factory the runtime uses."""
        import optax

        from ..core.local_trainer import make_local_train_fn

        model = self.model()
        return make_local_train_fn(
            model.apply,
            model.loss_fn,
            optax.sgd(self.learning_rate),
            epochs=self.epochs,
        )

    # -- ShapeDtypeStruct factories -----------------------------------
    def sds(self, shape, dtype="float32"):
        import jax
        import jax.numpy as jnp

        return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))

    def abstract_key(self):
        """A raw uint32[2] PRNG key shape — what the round loops thread
        through ``jax.random.split`` chains."""
        return self.sds((2,), "uint32")

    def abstract_batches(self, *lead: int):
        """A packed ``Batches`` of ShapeDtypeStructs with the given
        leading axes (e.g. federation size, or group client bucket)."""
        from ..core.types import Batches

        nb, bs, f = max(self.nb_census), self.batch_size, self.feature_dim
        return Batches(
            x=self.sds(tuple(lead) + (nb, bs, f)),
            y=self.sds(tuple(lead) + (nb, bs), "int32"),
            mask=self.sds(tuple(lead) + (nb, bs), "float32"),
        )

    def abstract_group_batches(self, clients: int, nb: int):
        """Group-shaped ``Batches`` for the planet engine's
        per-(bucket, nb) jit."""
        from ..core.types import Batches

        bs, f = self.batch_size, self.feature_dim
        return Batches(
            x=self.sds((clients, nb, bs, f)),
            y=self.sds((clients, nb, bs), "int32"),
            mask=self.sds((clients, nb, bs), "float32"),
        )

    def abstract_params_f32(self):
        """The param tree re-typed to float32 — the fold/term currency
        (terms and expansion limbs are always f32)."""
        import jax

        return jax.tree.map(
            lambda a: self.sds(a.shape, "float32"), self.abstract_params()
        )


# ---------------------------------------------------------------------
# lowering + artifact parsing
# ---------------------------------------------------------------------

# host-transfer vocabulary in lowered modules: python callbacks
# (jax.debug.*, io_callback/pure_callback), infeed/outfeed, and the
# TPU host-offload custom calls all match here
_HOST_TRANSFER_TARGET = re.compile(
    r"callback|host|infeed|outfeed", re.IGNORECASE
)
_CUSTOM_CALL = re.compile(r"custom_call\s*@([\w.]+)")
_INFEED_OP = re.compile(r"\b(?:stablehlo|mhlo)\.(infeed|outfeed)\b")
# input-output aliasing in lowered modules takes two forms: a
# single-device lowering resolves donation eagerly into per-arg
# `tf.aliasing_output = N` attributes, while a multi-device SPMD
# lowering (the mesh round engine) marks each donated leaf
# `jax.buffer_donor = true` and lets XLA bind the aliases once the
# output layouts are fixed. Both prove the donation contract is
# present in the artifact; they never co-occur on one argument.
_ALIASING = re.compile(r"tf\.aliasing_output|jax\.buffer_donor = true")
_CONST_LINE = re.compile(
    r"(?:stablehlo|mhlo)\.constant\s+dense<(.)"
)
_TENSOR_TYPE = re.compile(r"tensor<([0-9x]*)((?:[a-z][a-z0-9]*))>")

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4, "i16": 2, "ui16": 2,
    "i8": 1, "ui8": 1, "i1": 1, "i4": 1, "ui4": 1,
}


def _tensor_bytes(dims: str, dtype: str) -> int:
    n = 1
    for d in dims.split("x"):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


@dataclass
class LoweredArtifact:
    """Everything the four checkers need from one lowered case."""

    spec_name: str
    case_key: str
    aliased_inputs: int  # inputs carrying tf.aliasing_output
    claimed_donated_leaves: int  # leaves of the docstring-claimed args
    host_transfers: List[str]  # offending op/custom-call targets
    constants_bytes: List[int]  # NON-SPLAT baked-in constants, bytes
    flops: Optional[float]
    bytes_accessed: Optional[float]

    @property
    def max_constant_bytes(self) -> int:
        return max(self.constants_bytes, default=0)


def _parse_host_transfers(text: str) -> List[str]:
    found = set()
    for m in _CUSTOM_CALL.finditer(text):
        if _HOST_TRANSFER_TARGET.search(m.group(1)):
            found.add(m.group(1))
    for m in _INFEED_OP.finditer(text):
        found.add(m.group(1))
    return sorted(found)


def _parse_constants(text: str) -> List[int]:
    """Byte sizes of NON-SPLAT baked-in constants. A splat
    (``dense<0.0>``) is a compile-time fill — cheap and value-stable;
    a bracketed/hex blob is a closure-captured concrete array: it
    bloats the executable, occupies HBM per shape key, and a changing
    value forces a recompile."""
    out = []
    for line in text.splitlines():
        m = _CONST_LINE.search(line)
        if m is None or m.group(1) not in ("[", '"'):
            continue
        tm = None
        for tm in _TENSOR_TYPE.finditer(line):
            pass  # the LAST tensor<> on the line is the result type
        if tm is not None:
            out.append(_tensor_bytes(tm.group(1), tm.group(2)))
    return out


def lower_case(spec: AuditableSpec, case: LoweringCase) -> LoweredArtifact:
    """AOT-lower one case (trace only — nothing executes) and parse
    the contracts out of the StableHLO module text."""
    import jax

    if not hasattr(case.fn, "lower"):
        raise TypeError(
            f"auditable '{spec.name}' case '{case.key}': fn has no "
            ".lower() — register the jit-wrapped executable, not the "
            "bare python function"
        )
    lowered = case.fn.lower(*case.args, **case.kwargs)
    text = lowered.as_text()
    claimed = 0
    for i in spec.donate:
        if i < len(case.args):
            claimed += len(jax.tree.leaves(case.args[i]))
    try:
        cost = lowered.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
    except Exception:  # pragma: no cover - backend-dependent support
        cost = {}
    return LoweredArtifact(
        spec_name=spec.name,
        case_key=case.key,
        aliased_inputs=len(_ALIASING.findall(text)),
        claimed_donated_leaves=claimed,
        host_transfers=_parse_host_transfers(text),
        constants_bytes=_parse_constants(text),
        flops=float(cost["flops"]) if "flops" in cost else None,
        bytes_accessed=(
            float(cost["bytes accessed"]) if "bytes accessed" in cost else None
        ),
    )
