"""Rule ``thread-lock`` — cross-thread attribute access without the
owning lock.

The ~15 threaded modules (reliable channel, heartbeat detector,
serving engine, chaos timers, telemetry watchdog, checkpoint watcher)
all follow the same discipline: state a worker thread writes is either
(a) guarded by ``with self._lock`` at *every* access, (b) an
intrinsically thread-safe object (``queue.Queue``, ``threading.Event``,
a one-shot handle), or (c) funneled onto the single dispatch thread by
a loopback message. This checker enforces (a) mechanically:

  an attribute assigned inside a ``threading.Thread``/``Timer``
  **target method** (or a Thread subclass's ``run``) and *also*
  accessed in another method, where any of those accesses is outside
  every ``with self.<lock>`` block, is a finding at the unguarded
  site.

Heuristics that keep it honest rather than noisy:

- lock-ish context managers: any ``with self.<attr>`` where the attr
  name contains ``lock`` / ``cond`` / ``mutex``;
- attributes whose *names* mark them thread-safe-by-type (``*_lock``,
  ``*_cond``, ``*_event``, ``*_queue``, ``*_q``, ``*_thread``,
  ``*_timer``, ``*_stop``) are exempt, as is everything only ever
  touched inside one method (thread-private state);
- ``__init__`` is construction-time (the thread does not exist yet)
  and never counts as an access site.

Suppress a deliberately unguarded site (e.g. a monotonic counter read
where staleness is acceptable) with ``# lint: thread-lock-ok``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .engine import Finding, ModuleSource

RULE = "thread-lock"

_LOCKISH = ("lock", "cond", "mutex")
_SAFE_NAME_TOKENS = (
    "lock", "cond", "mutex", "event", "queue", "thread", "timer", "stop",
)


def _is_safe_attr_name(name: str) -> bool:
    low = name.lower()
    return any(tok in low for tok in _SAFE_NAME_TOKENS)


def _is_lockish_ctx(expr: ast.AST) -> bool:
    """`with self.<lock>` / `with self.<x>.lock` — anything on self
    whose final attribute name smells like a lock."""
    if isinstance(expr, ast.Call):  # e.g. self._lock.acquire_timeout()
        expr = expr.func
    if isinstance(expr, ast.Attribute):
        return any(tok in expr.attr.lower() for tok in _LOCKISH)
    return False


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _MethodScan(ast.NodeVisitor):
    """Per-method scan: every `self.X` access site with its guard
    state (inside/outside a lock-ish `with`). ``skip`` holds nested
    FunctionDef nodes scanned separately (closures handed to a
    Thread/Timer run on the *other* thread, not this method's)."""

    def __init__(self, skip=()) -> None:
        self.guard_depth = 0
        self.skip = set(id(n) for n in skip)
        # attr -> list of (line, is_store, guarded)
        self.sites: Dict[str, List[Tuple[int, bool, bool]]] = {}

    def visit_FunctionDef(self, node):  # noqa: N802
        if id(node) in self.skip:
            return
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # noqa: N815

    def visit_With(self, node):  # noqa: N802
        lockish = any(_is_lockish_ctx(item.context_expr) for item in node.items)
        if lockish:
            self.guard_depth += 1
        self.generic_visit(node)
        if lockish:
            self.guard_depth -= 1

    def visit_Attribute(self, node):  # noqa: N802
        attr = _self_attr(node)
        if attr is not None:
            is_store = isinstance(node.ctx, (ast.Store, ast.Del))
            self.sites.setdefault(attr, []).append(
                (node.lineno, is_store, self.guard_depth > 0)
            )
        self.generic_visit(node)

    # nested defs run in whatever thread calls them; keep scanning
    # (a closure handed to a Timer from this method shares the state)


def _target_exprs(node: ast.Call) -> List[ast.AST]:
    """The callable expressions a Thread/Timer creation runs."""
    fn = node.func
    callee = (
        fn.id if isinstance(fn, ast.Name)
        else fn.attr if isinstance(fn, ast.Attribute) else None
    )
    if callee not in ("Thread", "Timer"):
        return []
    out = []
    for kw in node.keywords:
        if kw.arg in ("target", "function"):
            out.append(kw.value)
    if callee == "Timer" and len(node.args) >= 2:
        out.append(node.args[1])
    return out


def _thread_target_names(cls: ast.ClassDef) -> Set[str]:
    """Method names run on another thread: `target=self.<m>` /
    `Timer(_, self.<m>)` creations anywhere in the class, plus `run`
    for Thread subclasses."""
    targets: Set[str] = set()
    for base in cls.bases:
        name = (
            base.id if isinstance(base, ast.Name)
            else base.attr if isinstance(base, ast.Attribute) else None
        )
        if name == "Thread":
            targets.add("run")
    for node in ast.walk(cls):
        if isinstance(node, ast.Call):
            for expr in _target_exprs(node):
                attr = _self_attr(expr)
                if attr:
                    targets.add(attr)
    return targets


def _closure_targets(
    method: ast.FunctionDef,
) -> List[ast.FunctionDef]:
    """Nested functions this method hands to a Thread/Timer — they run
    on the other thread and are scanned as targets of their own."""
    local_defs = {
        n.name: n for n in ast.walk(method)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n is not method
    }
    out = []
    for node in ast.walk(method):
        if isinstance(node, ast.Call):
            for expr in _target_exprs(node):
                if isinstance(expr, ast.Name) and expr.id in local_defs:
                    fn = local_defs[expr.id]
                    if fn not in out:
                        out.append(fn)
    return out


def check_thread_shared_state(mod: ModuleSource) -> List[Finding]:
    findings: List[Finding] = []
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        targets = _thread_target_names(cls)
        methods = {
            m.name: m for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        if not targets and not any(
            _closure_targets(m) for m in methods.values()
        ):
            continue
        scans: Dict[str, _MethodScan] = {}
        for name, m in methods.items():
            closures = _closure_targets(m)
            scan = _MethodScan(skip=closures)
            for stmt in m.body:
                scan.visit(stmt)
            scans[name] = scan
            # closures handed to a Thread/Timer are targets of their
            # own — their accesses happen on the spawned thread
            for fn in closures:
                cname = f"{name}.<{fn.name}>"
                cscan = _MethodScan()
                for stmt in fn.body:
                    cscan.visit(stmt)
                scans[cname] = cscan
                targets = targets | {cname}

        # attrs written from a thread target
        written_in_target: Set[str] = set()
        for t in targets & set(scans):
            for attr, sites in scans[t].sites.items():
                if any(is_store for (_, is_store, _) in sites):
                    written_in_target.add(attr)

        for attr in sorted(written_in_target):
            if _is_safe_attr_name(attr):
                continue
            accessed_in = {
                mname for mname, scan in scans.items()
                if attr in scan.sites and mname != "__init__"
            }
            in_target = accessed_in & targets
            outside_target = accessed_in - targets
            if not in_target or not outside_target:
                continue  # thread-private (or init-only): not shared
            for mname in sorted(accessed_in):
                for line, _is_store, guarded in scans[mname].sites[attr]:
                    if guarded:
                        continue
                    findings.append(Finding(
                        path=mod.path, line=line, rule=RULE,
                        message=(
                            f"self.{attr} is written from thread target "
                            f"'{sorted(in_target)[0]}' and accessed in "
                            f"'{mname}' without holding a lock — guard "
                            "every access with the owning lock or mark "
                            "the site `# lint: thread-lock-ok`"
                        ),
                    ))
    return findings
