"""``fedml-tpu audit`` — compiled-artifact verification over the
:mod:`fedml_tpu.analysis.compiled` registry (docs/static_analysis.md).

Four checkers over each registered executable's AOT-lowered StableHLO
(lowering traces; **nothing executes**, no data exists, a CPU-only box
finishes the whole census in bounded time):

- ``aot-donation``     — input–output aliasing must cover every buffer
  the docstrings claim donated; a round-shaped executable with ZERO
  aliasing is a finding (the compiled ground truth behind the lint
  suite's source-level donation TODOs).
- ``aot-host-transfer``— no infeed/outfeed/host custom-calls/python
  callbacks in hot executables: the compiled-HLO counterpart of the
  lint suite's source-level host-sync rule.
- ``aot-census``       — lowered shape keys per executable must fit
  the pow2 bucket budget (a census overflow is a retrace storm
  compiled into the artifact set).
- ``aot-constant``     — no large non-splat baked-in constants
  (closure-captured arrays force per-value recompiles and waste HBM).

Static cost (XLA cost analysis: FLOPs / bytes accessed per
executable) is emitted into ``audit_report.json`` — the denominator
the TPU MFU trajectory (ROADMAP item 5) is measured against.

Findings ride the SAME count-keyed baseline/ratchet machinery as the
lint suite (``engine.diff_baseline``), against a checked-in
``audit_baseline.json``: CI (``fedml-tpu audit --ci``) fails on any
NEW finding and on any STALE entry.

Import discipline: importing this module must not import JAX — the
CLI builds its parser from here on a bare checkout. JAX loads inside
:func:`run_audit`.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .compiled import (
    AuditContext,
    AuditableSpec,
    LoweringCase,
    load_registry,
    lower_case,
)
from .engine import (
    Finding,
    find_repo_root,
    run_ratchet_cli,
)

AUDIT_BASELINE_NAME = "audit_baseline.json"
AUDIT_REPORT_NAME = "audit_report.json"

RULE_DONATION = "aot-donation"
RULE_HOST = "aot-host-transfer"
RULE_CENSUS = "aot-census"
RULE_CONSTANT = "aot-constant"

AUDIT_RULES = (RULE_DONATION, RULE_HOST, RULE_CENSUS, RULE_CONSTANT)

_BASELINE_COMMENT = (
    "Ratchet-only suppression ledger for `fedml-tpu audit` "
    "(docs/static_analysis.md — compiled-artifact audit). Entries are "
    "compile-time contract violations accepted as known TODOs (e.g. a "
    "round-shaped executable that cannot donate yet); they may only "
    "be REMOVED (by fixing the executable). CI fails on new findings "
    "AND on stale entries. Regenerate with `fedml-tpu audit "
    "--update-baseline` after a burn-down."
)


def audit_spec(
    spec: AuditableSpec, ctx: AuditContext
) -> Tuple[List[Finding], List[Dict[str, Any]]]:
    """Lower one spec's census and run the four checkers. Returns
    (findings, per-case report entries)."""
    findings: List[Finding] = []
    entries: List[Dict[str, Any]] = []
    try:
        cases = spec.provider(ctx)
    except Exception as e:
        raise RuntimeError(
            f"auditable '{spec.name}' ({spec.path}): provider failed to "
            f"build its census: {e}"
        ) from e
    budget = spec.census_budget
    if callable(budget):
        budget = budget(ctx)
    if budget is not None and len(cases) > int(budget):
        findings.append(Finding(
            path=spec.path, line=0, rule=RULE_CENSUS,
            message=(
                f"executable '{spec.name}': {len(cases)} lowered shape "
                f"keys exceed the pow2 census budget of {int(budget)} — "
                "a census overflow is a retrace storm compiled into "
                "the artifact set"
            ),
        ))
    for case in cases:
        try:
            art = lower_case(spec, case)
        except Exception as e:
            raise RuntimeError(
                f"auditable '{spec.name}' case '{case.key}' "
                f"({spec.path}): AOT lowering failed: {e}"
            ) from e
        if spec.donate and art.aliased_inputs < art.claimed_donated_leaves:
            findings.append(Finding(
                path=spec.path, line=0, rule=RULE_DONATION,
                message=(
                    f"executable '{spec.name}': docstring claims "
                    f"donate_argnums={tuple(spec.donate)} but the "
                    f"lowered module aliases only {art.aliased_inputs} "
                    f"of {art.claimed_donated_leaves} donated input "
                    "buffers — an unmatched donation copies instead of "
                    "updating in place"
                ),
            ))
        elif (
            spec.round_shaped
            and not spec.donate
            and art.aliased_inputs == 0
        ):
            findings.append(Finding(
                path=spec.path, line=0, rule=RULE_DONATION,
                message=(
                    f"executable '{spec.name}' is round-shaped but its "
                    "compiled artifact has zero input-output aliasing "
                    "— the carried state is copied every call; donate "
                    "it (SNIPPETS [1], ROADMAP item 5) or baseline "
                    "this as a known TODO"
                ),
            ))
        if spec.hot and art.host_transfers:
            findings.append(Finding(
                path=spec.path, line=0, rule=RULE_HOST,
                message=(
                    f"executable '{spec.name}': hot executable lowers "
                    "host-transfer ops "
                    f"({', '.join(art.host_transfers)}) — every call "
                    "stalls the device on the host"
                ),
            ))
        if art.max_constant_bytes > spec.constant_budget_bytes:
            findings.append(Finding(
                path=spec.path, line=0, rule=RULE_CONSTANT,
                message=(
                    f"executable '{spec.name}': baked-in constant of "
                    f"{art.max_constant_bytes} bytes exceeds the "
                    f"{spec.constant_budget_bytes}-byte budget — "
                    "closure-captured arrays force per-value recompiles "
                    "and waste HBM; pass them as arguments"
                ),
            ))
        entry: Dict[str, Any] = {
            "executable": spec.name,
            "case": case.key,
            "path": spec.path,
            "round_shaped": spec.round_shaped,
            "hot": spec.hot,
            "claimed_donated_leaves": art.claimed_donated_leaves,
            "aliased_inputs": art.aliased_inputs,
            "host_transfers": art.host_transfers,
            "max_constant_bytes": art.max_constant_bytes,
            "flops": art.flops,
            "bytes_accessed": art.bytes_accessed,
        }
        if art.flops and art.bytes_accessed:
            # arithmetic intensity (FLOPs/byte): where this executable
            # sits on the roofline — the compile-time denominator the
            # BENCH MFU captures divide measured wall time into
            entry["arithmetic_intensity"] = art.flops / art.bytes_accessed
        entries.append(entry)
    return findings, entries


def run_audit(
    ctx: Optional[AuditContext] = None,
    only: Optional[Sequence[str]] = None,
    registry: Optional[Dict[str, AuditableSpec]] = None,
) -> Tuple[List[Finding], Dict[str, Any]]:
    """Lower and check every registered executable. ``registry`` is
    injectable for tests; ``only`` filters by executable name. The
    registry always comes from the imported package — there is no
    root-relative corpus here (unlike lint), so no root parameter."""
    import jax

    ctx = ctx or AuditContext()
    specs = registry if registry is not None else load_registry()
    names = sorted(specs)
    if only:
        missing = sorted(set(only) - set(names))
        if missing:
            raise KeyError(
                f"unknown auditable(s) {missing}; registered: {names}"
            )
        names = [n for n in names if n in set(only)]
    findings: List[Finding] = []
    executables: List[Dict[str, Any]] = []
    for name in names:
        f, entries = audit_spec(specs[name], ctx)
        findings.extend(f)
        executables.extend(entries)
    report = {
        "version": 1,
        "tool": "fedml-tpu audit",
        "platform": jax.default_backend(),
        "jax_version": jax.__version__,
        "census": ctx.to_dict(),
        "executables": executables,
        # the MFU-denominator view (ROADMAP item 5): per round-shaped
        # executable and census case, the static FLOPs a BENCH capture
        # divides its measured wall time into
        "roofline": [
            {
                "executable": e["executable"],
                "case": e["case"],
                "flops": e["flops"],
                "bytes_accessed": e["bytes_accessed"],
                "arithmetic_intensity": e.get("arithmetic_intensity"),
            }
            for e in executables
            if e["round_shaped"] and e["flops"] is not None
        ],
    }
    return sorted(findings), report


# -- CLI surface (shared by fedml_tpu.cli and the bare entry point) ----


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="fedml-tpu-audit")
    add_audit_arguments(p)
    return run_cli(p.parse_args(argv))


def add_audit_arguments(p) -> None:
    p.add_argument(
        "--root", default=None,
        help="repo root (default: auto-detected from the package "
             "location / cwd)",
    )
    p.add_argument(
        "--baseline", default=None,
        help=f"baseline path (default: <root>/{AUDIT_BASELINE_NAME})",
    )
    p.add_argument(
        "--report", default=None,
        help=f"where to write the static-cost report (default: "
             f"<root>/{AUDIT_REPORT_NAME})",
    )
    p.add_argument(
        "--only", action="append", default=None, metavar="NAME",
        help="audit only this registered executable (repeatable). The "
             "ratchet still applies, filtered to the selected "
             "executables' baseline entries — other entries are "
             "neither new nor stale in a subset run",
    )
    p.add_argument(
        "--json", dest="as_json", action="store_true",
        help="machine-readable output (one JSON object)",
    )
    p.add_argument(
        "--ci", action="store_true",
        help="CI gate mode: the baseline file MUST exist (a deleted "
             "baseline must fail the gate, not silently pass a raw "
             "run) and --update-baseline is rejected",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline from the current findings "
             "(burn-down workflow; never valid under --ci)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="report raw findings without ratcheting (exit 1 if any)",
    )


def run_cli(args) -> int:
    import sys

    # hermetic by default: audit is a lowering-only pass, so a box with
    # an attached accelerator must not spend device init on it (and CI
    # wants CPU-lowered artifacts regardless of the runner). An
    # explicit JAX_PLATFORMS always wins; a jax already imported
    # in-process is left alone.
    if "jax" not in sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        root = find_repo_root(args.root)
    except FileNotFoundError as e:
        print(f"audit: {e}", file=sys.stderr)
        return 2
    if args.ci and args.update_baseline:
        print(
            "audit: --ci and --update-baseline are mutually exclusive "
            "(the CI gate ratchets; it never rewrites)", file=sys.stderr,
        )
        return 2
    if args.only and args.update_baseline:
        print(
            "audit: --update-baseline needs a FULL run — an --only "
            "subset would overwrite the ledger with only the subset's "
            "findings", file=sys.stderr,
        )
        return 2
    try:
        findings, report = run_audit(only=args.only)
    except (RuntimeError, KeyError) as e:
        print(f"audit: {e}", file=sys.stderr)
        return 2
    baseline_path = args.baseline or os.path.join(root, AUDIT_BASELINE_NAME)

    if not args.only:
        report_path = args.report or os.path.join(root, AUDIT_REPORT_NAME)
        with open(report_path, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=1)
            fh.write("\n")
    else:
        report_path = None

    def only_filter(baseline):
        # a subset run can only judge the executables it lowered —
        # other specs' baseline entries are neither new nor stale
        # here. Every audit message embeds "executable '<name>'", so
        # filtering by that tag keeps exactly the selected specs'
        # accepted TODOs in force (mirrors lint's path-subset
        # semantics)
        tags = tuple(f"executable '{n}'" for n in args.only)
        return {
            k: v for k, v in baseline.items()
            if any(t in k for t in tags)
        }

    return run_ratchet_cli(
        "audit", args, findings, baseline_path,
        baseline_filter=only_filter if args.only else None,
        save_comment=_BASELINE_COMMENT,
        json_extra={
            "root": root,
            "report": report_path,
            "executables": len(report["executables"]),
        },
        summary_prefix=f"{len(report['executables'])} lowered case(s), ",
        summary_suffix=(f"; report -> {report_path}" if report_path else ""),
    )


if __name__ == "__main__":
    import sys

    sys.exit(main())
