"""``fedml-tpu perf`` — the performance-attribution plane.

Closes the loop between what the compiled-artifact audit *proved*
(``audit_report.json``: static FLOPs / bytes / arithmetic intensity
per registered executable, ``fedml-tpu audit``) and what a run
*measured* (``exec_device_seconds{executable,bucket}`` histograms from
``core/devtime.py``, ``round.ledger`` instants from the cross-silo
server). Three outputs:

* **roofline join** — per measured executable series: achieved
  FLOP/s = audit FLOPs x dispatch count / measured seconds,
  ``mfu_vs_bf16_peak`` against the per-device-kind peak table in
  ``constants.py`` (THE shared denominator — bench and the watch loop
  use the same one) and a compute- vs memory-bound verdict from
  arithmetic intensity vs the device's ridge point. The audit lowers
  small abstract shapes, so the joined MFU *attributes* time across
  executables consistently; absolute MFU claims come from bench's
  run-shaped captures.
* **idle-time ledger** — per round, the measured segments plus the
  ``round_idle_seconds{gap=...}`` gaps; segments + intra-round idle
  reconcile to ``round_wall_seconds`` (the CLI reports the
  reconciliation fraction; tests gate it at 5%). The PiPar overlap
  opportunity (ROADMAP item 1), measured for free every round.
* **bench ratchet** — ``--ratchet BENCH_*.json`` groups records by
  (phase, device_kind, smoke) via their mandatory meta blocks and
  fails loudly when the newest record regresses beyond ``--tolerance``
  against the best prior record of the SAME group — CPU smoke never
  ratchets against TPU captures.

Pure stdlib (the ``analysis`` package contract): no jax, no numpy —
the CI gate runs the ratchet on a bare checkout.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import constants
from .engine import find_repo_root

AUDIT_REPORT_NAME = "audit_report.json"
PERF_REPORT_NAME = "perf_report.json"

# ratchet tolerance: relative regression allowed before the gate trips.
# 10% rides out benchmark jitter on shared/CI hosts (the checked-in
# trajectory's worst benign wobble is ~6%) while catching the 2x-class
# regressions the gate exists for.
DEFAULT_TOLERANCE = 0.10

# roofline-join coverage gate: fraction of measured device seconds that
# joined to an audit row (the acceptance bar for instrumented runs)
DEFAULT_MIN_COVERAGE = 0.9


# -- series-key parsing ------------------------------------------------

_SERIES_RE = re.compile(r"^(?P<name>[^{]+)(?:\{(?P<tags>.*)\})?$")


def parse_series_key(key: str) -> Tuple[str, Dict[str, str]]:
    """``"name{k=v,k2=v2}"`` (Telemetry._fmt) -> (name, tags)."""
    m = _SERIES_RE.match(key)
    if not m:
        return key, {}
    tags: Dict[str, str] = {}
    raw = m.group("tags")
    if raw:
        for part in raw.split(","):
            k, _, v = part.partition("=")
            tags[k.strip()] = v.strip()
    return m.group("name"), tags


# -- telemetry.jsonl / trace.json loaders ------------------------------


def load_snapshots(telemetry_dir: str) -> List[Dict[str, Any]]:
    """Last ``telemetry_snapshot`` line per (run_id, rank) from
    ``telemetry.jsonl`` — the registry state at export time (cumulative
    since process start, so the last snapshot per process wins)."""
    path = os.path.join(telemetry_dir, "telemetry.jsonl")
    if not os.path.isfile(path):
        return []
    last: Dict[Tuple[str, int], Dict[str, Any]] = {}
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("kind") != "telemetry_snapshot":
                continue
            key = (str(rec.get("run_id")), int(rec.get("rank", 0) or 0))
            last[key] = rec
    return [last[k] for k in sorted(last)]


def exec_seconds_from_snapshots(
    snapshots: Sequence[Dict[str, Any]],
) -> Dict[Tuple[str, str], Dict[str, float]]:
    """Merge ``exec_device_seconds`` histograms across processes:
    (executable, bucket) -> {count, sum, min, max}. Bucket ``""`` means
    the series carried no bucket tag (the untagged agg folds)."""
    merged: Dict[Tuple[str, str], Dict[str, float]] = {}
    for snap in snapshots:
        for key, h in (snap.get("histograms") or {}).items():
            name, tags = parse_series_key(key)
            if name != "exec_device_seconds":
                continue
            k = (tags.get("executable", ""), tags.get("bucket", ""))
            cur = merged.get(k)
            if cur is None:
                merged[k] = {
                    "count": float(h.get("count", 0.0)),
                    "sum": float(h.get("sum", 0.0)),
                    "min": float(h.get("min", 0.0)),
                    "max": float(h.get("max", 0.0)),
                }
            else:
                cur["count"] += float(h.get("count", 0.0))
                cur["sum"] += float(h.get("sum", 0.0))
                cur["min"] = min(cur["min"], float(h.get("min", 0.0)))
                cur["max"] = max(cur["max"], float(h.get("max", 0.0)))
    return merged


def load_ledgers(telemetry_dir: str) -> List[Dict[str, Any]]:
    """``round.ledger`` instant args from every trace shard in the
    run directory (``trace.json`` / ``trace_rank*.json``), ordered by
    (shard, round)."""
    ledgers: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(telemetry_dir, "trace*.json"))):
        if os.path.basename(path).startswith("trace_merged"):
            continue  # the stitcher's output duplicates the shards
        try:
            with open(path, encoding="utf-8") as fh:
                payload = json.load(fh)
        except (json.JSONDecodeError, OSError):
            continue
        for ev in payload.get("traceEvents", []):
            if ev.get("name") == "round.ledger" and ev.get("ph") == "i":
                args = dict(ev.get("args") or {})
                if "wall_s" in args:
                    ledgers.append(args)
    return ledgers


# -- idle-gap attribution (shared with the live server) ---------------


def attribute_idle(
    *,
    now: float,
    bcast_t0: float,
    last_arrival: float,
    aggregate_s: float,
    prev_close: Optional[float] = None,
) -> Dict[str, float]:
    """The idle-gap arithmetic, in one place: the cross-silo server
    calls this live per round and the oracle tests call it with
    synthetic timelines. ``arrival_to_aggregate`` is intra-round (last
    upload in hand -> aggregate start) and reconciles with the
    measured segments to the round wall; ``close_to_broadcast`` is the
    server's idle BETWEEN rounds (previous ledger close -> this
    broadcast) and is excluded from intra-round reconciliation."""
    agg_start = now - max(aggregate_s, 0.0)
    idle = {"arrival_to_aggregate": max(agg_start - last_arrival, 0.0)}
    if prev_close is not None:
        idle["close_to_broadcast"] = max(bcast_t0 - prev_close, 0.0)
    return idle


def summarize_ledger(ledgers: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-round reconciliation + run totals from ``round.ledger``
    instants. ``recon_frac`` = (segments + intra-round idle) / wall —
    1.0 means the ledger accounts for every second of the round."""
    rounds: List[Dict[str, Any]] = []
    total_wall = 0.0
    idle_totals: Dict[str, float] = {}
    wire_fracs: List[float] = []
    for led in ledgers:
        wall = float(led.get("wall_s", 0.0))
        segs = {k: float(v) for k, v in (led.get("segments") or {}).items()}
        idle = {k: float(v) for k, v in (led.get("idle") or {}).items()}
        intra_idle = idle.get("arrival_to_aggregate", 0.0)
        accounted = sum(segs.values()) + intra_idle
        rounds.append(
            {
                "round": led.get("round"),
                "wall_s": wall,
                "segments": segs,
                "idle": idle,
                "accounted_s": round(accounted, 6),
                "recon_frac": round(accounted / wall, 4) if wall > 0 else None,
                "wire_utilization_frac": led.get("wire_utilization_frac"),
            }
        )
        total_wall += wall
        for k, v in idle.items():
            idle_totals[k] = idle_totals.get(k, 0.0) + v
        wf = led.get("wire_utilization_frac")
        if wf is not None:
            wire_fracs.append(float(wf))
    return {
        "rounds": rounds,
        "total_wall_s": round(total_wall, 6),
        "idle_totals_s": {k: round(v, 6) for k, v in sorted(idle_totals.items())},
        "mean_wire_utilization_frac": (
            round(sum(wire_fracs) / len(wire_fracs), 4) if wire_fracs else None
        ),
    }


# -- roofline join -----------------------------------------------------


def load_audit_report(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def _pick_audit_row(
    rows: List[Dict[str, Any]], bucket: str
) -> Tuple[Optional[Dict[str, Any]], bool]:
    """Case match for one measured series: exact ``case == bucket``
    wins; otherwise fall back to the hot row with the largest FLOPs
    (flagged ``case_matched=False`` so the table is honest about it)."""
    for row in rows:
        if bucket and row.get("case") == bucket:
            return row, True
    with_flops = [r for r in rows if r.get("flops")]
    if not with_flops:
        return (rows[0], False) if rows else (None, False)
    hot = [r for r in with_flops if r.get("hot")]
    pool = hot or with_flops
    return max(pool, key=lambda r: float(r.get("flops") or 0.0)), False


def join_roofline(
    audit: Dict[str, Any],
    measured: Dict[Tuple[str, str], Dict[str, float]],
    device_kind: str,
    n_chips: int = 1,
) -> Dict[str, Any]:
    """Join measured device seconds onto audit FLOPs. Coverage is
    seconds-weighted: the fraction of measured device time that joined
    to an audit row (the acceptance gate), plus the plain series-count
    rate and the registered-executable coverage for context."""
    by_name: Dict[str, List[Dict[str, Any]]] = {}
    for row in audit.get("executables", []):
        by_name.setdefault(row["executable"], []).append(row)
    peak = constants.peak_bf16_flops(device_kind) * max(int(n_chips), 1)
    bw = constants.hbm_bandwidth_bytes(device_kind) * max(int(n_chips), 1)
    ridge = (peak / bw) if (peak > 0 and bw > 0) else None

    rows: List[Dict[str, Any]] = []
    joined_s = total_s = 0.0
    joined_series = 0
    for (exe, bucket), h in sorted(measured.items()):
        total_s += h["sum"]
        entry: Dict[str, Any] = {
            "executable": exe,
            "bucket": bucket or None,
            "calls": int(h["count"]),
            "device_seconds": round(h["sum"], 6),
            "mean_seconds": round(h["sum"] / h["count"], 6)
            if h["count"]
            else None,
            "joined": False,
        }
        cand = by_name.get(exe, [])
        row, matched = _pick_audit_row(cand, bucket)
        if row is not None and row.get("flops") and h["sum"] > 0:
            flops = float(row["flops"])
            achieved = flops * h["count"] / h["sum"]
            ai = row.get("arithmetic_intensity")
            if ai is None and row.get("bytes_accessed"):
                ai = flops / float(row["bytes_accessed"])
            entry.update(
                joined=True,
                case=row.get("case"),
                case_matched=matched,
                flops_per_call=flops,
                achieved_flops_per_sec=round(achieved, 1),
                arithmetic_intensity=round(float(ai), 4)
                if ai is not None
                else None,
            )
            if peak > 0:
                entry["mfu_vs_bf16_peak"] = round(achieved / peak, 6)
            if ridge is not None and ai is not None:
                entry["bound"] = (
                    "compute" if float(ai) >= ridge else "memory"
                )
            joined_s += h["sum"]
            joined_series += 1
        rows.append(entry)

    registered = sorted(by_name)
    measured_names = {exe for (exe, _b) in measured}
    return {
        "device_kind": constants.normalize_device_kind(device_kind),
        "n_chips": int(n_chips),
        "peak_bf16_flops": peak or None,
        "hbm_bytes_per_sec": bw or None,
        "ridge_flops_per_byte": round(ridge, 2) if ridge else None,
        "rows": rows,
        "coverage": round(joined_s / total_s, 4) if total_s > 0 else None,
        "series_join_rate": (
            round(joined_series / len(measured), 4) if measured else None
        ),
        "registered_executables": len(registered),
        "registered_measured": sorted(measured_names & set(registered)),
        "registered_unmeasured": sorted(set(registered) - measured_names),
    }


# -- bench-trajectory ratchet ------------------------------------------

_ROUND_RE = re.compile(r"r(\d+)")

# units whose metric improves downward (everything else: up is better)
_LOWER_BETTER_HINTS = ("second", "latency", "_ms", " ms")


def _lower_is_better(unit: str, metric: str) -> bool:
    text = f"{unit} {metric}".lower()
    if "per_sec" in text or "/s" in text:
        return False
    return any(h in text for h in _LOWER_BETTER_HINTS)


def _record_order_key(path: str) -> Tuple[int, str]:
    """Chronology of the checked-in trajectory: the rNN round number in
    the filename, then the name (driver record before same-round
    sidecar captures sorts fine — groups rarely span both)."""
    base = os.path.basename(path)
    m = _ROUND_RE.search(base)
    return (int(m.group(1)) if m else 0, base)


def _walk_metas(node: Any, out: List[Dict[str, Any]]) -> None:
    if isinstance(node, dict):
        meta = node.get("meta")
        if (
            isinstance(meta, dict)
            and "device_kind" in meta
            and "phase" in meta
        ):
            out.append(meta)
        for v in node.values():
            _walk_metas(v, out)
    elif isinstance(node, list):
        for v in node:
            _walk_metas(v, out)


def _record_is_skippable(rec: Any) -> Optional[str]:
    """Crashed / error records carry no benchmark result to ratchet —
    skipped with a note instead of failing the gate."""
    if not isinstance(rec, dict):
        return "not a JSON object"
    if "error" in rec:
        return f"error record: {rec['error']!r}"
    if "parsed" in rec and rec.get("parsed") is None:
        rc = rec.get("rc")
        return f"crashed driver record (rc={rc}, parsed=null)"
    return None


def extract_bench_metas(path: str) -> Tuple[List[Dict[str, Any]], Optional[str]]:
    """All meta blocks in one BENCH record file -> (metas, skip_note).
    A readable record with NO meta block is a contract violation (the
    ratchet cannot group it) — the caller fails loudly."""
    with open(path, encoding="utf-8") as fh:
        rec = json.load(fh)
    skip = _record_is_skippable(rec)
    if skip is not None:
        return [], skip
    metas: List[Dict[str, Any]] = []
    _walk_metas(rec, metas)
    return metas, None


def run_ratchet(
    paths: Sequence[str], tolerance: float = DEFAULT_TOLERANCE
) -> Dict[str, Any]:
    """Compare the newest record per (phase, device_kind, smoke) group
    against the best prior record of the same group. Returns a report
    dict; ``report["ok"]`` is the gate. Exit-2-class contract
    violations (no meta on a live record, unreadable file) are in
    ``report["violations"]``."""
    entries: List[Dict[str, Any]] = []
    skipped: List[str] = []
    violations: List[str] = []
    for path in sorted(paths, key=_record_order_key):
        try:
            metas, skip = extract_bench_metas(path)
        except (OSError, json.JSONDecodeError) as e:
            violations.append(f"{path}: unreadable ({e})")
            continue
        if skip is not None:
            skipped.append(f"{path}: {skip}")
            continue
        if not metas:
            violations.append(
                f"{path}: no meta block on any phase record — run "
                "scripts/backfill_bench_meta.py (new records get one "
                "from bench.py automatically)"
            )
            continue
        for meta in metas:
            value = meta.get("value")
            if not isinstance(value, (int, float)) or isinstance(value, bool):
                continue  # info-only meta (e.g. a phase with no headline)
            entries.append(
                {
                    "file": os.path.basename(path),
                    "order": _record_order_key(path),
                    "phase": str(meta.get("phase")),
                    "device_kind": constants.normalize_device_kind(
                        str(meta.get("device_kind"))
                    ),
                    "smoke": bool(meta.get("smoke", False)),
                    "value": float(value),
                    "unit": str(meta.get("unit", "")),
                    "metric": str(meta.get("metric", "")),
                    "mfu": meta.get("mfu"),
                }
            )

    groups: Dict[Tuple[str, str, bool], List[Dict[str, Any]]] = {}
    for e in entries:
        groups.setdefault((e["phase"], e["device_kind"], e["smoke"]), []).append(e)

    results: List[Dict[str, Any]] = []
    regressions = 0
    for key in sorted(groups):
        phase, kind, smoke = key
        seq = groups[key]  # already in trajectory order (sorted paths)
        current = seq[-1]
        prior = seq[:-1]
        res: Dict[str, Any] = {
            "phase": phase,
            "device_kind": kind,
            "smoke": smoke,
            "current": current["value"],
            "unit": current["unit"],
            "file": current["file"],
            "n_records": len(seq),
        }
        if not prior:
            res["verdict"] = "seeded"
        else:
            lower = _lower_is_better(current["unit"], current["metric"])
            best = (
                min(prior, key=lambda e: e["value"])
                if lower
                else max(prior, key=lambda e: e["value"])
            )
            res["best_prior"] = best["value"]
            res["best_prior_file"] = best["file"]
            if lower:
                regressed = current["value"] > best["value"] * (1.0 + tolerance)
                res["delta_frac"] = round(
                    current["value"] / best["value"] - 1.0, 4
                ) if best["value"] else None
            else:
                regressed = current["value"] < best["value"] * (1.0 - tolerance)
                res["delta_frac"] = round(
                    current["value"] / best["value"] - 1.0, 4
                ) if best["value"] else None
            res["verdict"] = "REGRESSION" if regressed else "ok"
            regressions += int(regressed)
        results.append(res)

    return {
        "tool": "fedml-tpu perf --ratchet",
        "tolerance": tolerance,
        "groups": results,
        "regressions": regressions,
        "skipped": skipped,
        "violations": violations,
        "ok": regressions == 0 and not violations,
    }


# -- CLI ---------------------------------------------------------------


def add_perf_arguments(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--telemetry-dir", default=None,
        help="run directory holding telemetry.jsonl / trace*.json "
             "(report mode: roofline join + idle ledger)",
    )
    p.add_argument(
        "--audit-report", default=None,
        help=f"audit_report.json to join against (default: "
             f"<root>/{AUDIT_REPORT_NAME})",
    )
    p.add_argument(
        "--device-kind", default=None,
        help="MFU denominator device kind (default: the audit "
             "report's platform — 'cpu' reports seconds without MFU)",
    )
    p.add_argument("--n-chips", type=int, default=1)
    p.add_argument(
        "--min-coverage", type=float, default=DEFAULT_MIN_COVERAGE,
        help="fail (exit 1) when less than this fraction of measured "
             "device seconds joined to an audit row",
    )
    p.add_argument(
        "--ratchet", nargs="+", default=None, metavar="BENCH_JSON",
        help="ratchet mode: compare the newest BENCH record per "
             "(phase, device_kind, smoke) group against the best "
             "prior record; exit 1 on regression, 2 on contract "
             "violations (missing meta)",
    )
    p.add_argument(
        "--tolerance", type=float, default=DEFAULT_TOLERANCE,
        help="relative regression allowed before the ratchet trips",
    )
    p.add_argument(
        "--out", default=None,
        help=f"write the JSON report here (report mode default: "
             f"<telemetry-dir>/{PERF_REPORT_NAME}; ratchet: stdout only)",
    )
    p.add_argument("--root", default=None, help="repo root override")
    p.add_argument(
        "--quiet", action="store_true",
        help="suppress the human-readable table (JSON line only)",
    )


def _print_roofline_table(report: Dict[str, Any]) -> None:
    print(
        f"perf: device_kind={report['device_kind']} "
        f"n_chips={report['n_chips']} "
        f"coverage={report['coverage']}",
        file=sys.stderr,
    )
    hdr = (
        f"{'executable':<36} {'bucket':>10} {'calls':>7} "
        f"{'dev_s':>10} {'FLOP/s':>12} {'MFU':>9} {'bound':>8}"
    )
    print(hdr, file=sys.stderr)
    for row in report["rows"]:
        mfu = row.get("mfu_vs_bf16_peak")
        print(
            f"{row['executable']:<36} {str(row.get('bucket') or '-'):>10} "
            f"{row['calls']:>7} {row['device_seconds']:>10.4f} "
            f"{row.get('achieved_flops_per_sec') or '-':>12} "
            f"{(f'{mfu:.2%}' if mfu is not None else '-'):>9} "
            f"{row.get('bound') or '-':>8}",
            file=sys.stderr,
        )


def _print_ledger_table(ledger: Dict[str, Any]) -> None:
    print(
        f"idle ledger: {len(ledger['rounds'])} round(s), "
        f"wall {ledger['total_wall_s']:.3f}s, idle "
        f"{json.dumps(ledger['idle_totals_s'])}, mean wire util "
        f"{ledger['mean_wire_utilization_frac']}",
        file=sys.stderr,
    )
    for r in ledger["rounds"]:
        print(
            f"  round {r['round']}: wall {r['wall_s']:.4f}s "
            f"accounted {r['accounted_s']:.4f}s "
            f"(recon {r['recon_frac']}) idle {json.dumps(r['idle'])}",
            file=sys.stderr,
        )


def run_cli(args) -> int:
    if args.ratchet:
        report = run_ratchet(args.ratchet, tolerance=args.tolerance)
        print(json.dumps(report))
        if not args.quiet:
            for g in report["groups"]:
                prior = (
                    f" best_prior={g.get('best_prior')} "
                    f"({g.get('best_prior_file')})"
                    if "best_prior" in g
                    else ""
                )
                print(
                    f"ratchet: {g['verdict']:>10}  {g['phase']}"
                    f"[{g['device_kind']}, smoke={g['smoke']}] "
                    f"current={g['current']} {g['unit']}{prior}",
                    file=sys.stderr,
                )
            for s in report["skipped"]:
                print(f"ratchet: skipped {s}", file=sys.stderr)
        for v in report["violations"]:
            print(f"ratchet: VIOLATION {v}", file=sys.stderr)
        if report["violations"]:
            return 2
        return 0 if report["ok"] else 1

    if not args.telemetry_dir:
        print(
            "perf: pass --telemetry-dir (report mode) or --ratchet "
            "BENCH_*.json (gate mode)",
            file=sys.stderr,
        )
        return 2
    if not os.path.isdir(args.telemetry_dir):
        print(f"perf: {args.telemetry_dir!r} not found", file=sys.stderr)
        return 2
    root = find_repo_root(args.root)
    audit_path = args.audit_report or os.path.join(root, AUDIT_REPORT_NAME)
    if not os.path.isfile(audit_path):
        print(
            f"perf: no audit report at {audit_path!r} — run "
            "`fedml-tpu audit` first (it writes the FLOPs denominator)",
            file=sys.stderr,
        )
        return 2
    audit = load_audit_report(audit_path)
    snapshots = load_snapshots(args.telemetry_dir)
    measured = exec_seconds_from_snapshots(snapshots)
    device_kind = args.device_kind or str(audit.get("platform", "cpu"))
    roofline = join_roofline(
        audit, measured, device_kind, n_chips=args.n_chips
    )
    ledger = summarize_ledger(load_ledgers(args.telemetry_dir))
    report = {
        "tool": "fedml-tpu perf",
        "version": 1,
        "telemetry_dir": args.telemetry_dir,
        "audit_report": audit_path,
        "roofline": roofline,
        "ledger": ledger,
    }
    out_path = args.out or os.path.join(args.telemetry_dir, PERF_REPORT_NAME)
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=1)
    if not args.quiet:
        _print_roofline_table(roofline)
        _print_ledger_table(ledger)
    print(
        json.dumps(
            {
                "ok": True,
                "series": len(roofline["rows"]),
                "coverage": roofline["coverage"],
                "rounds": len(ledger["rounds"]),
                "report": out_path,
            }
        )
    )
    cov = roofline["coverage"]
    if measured and cov is not None and cov < args.min_coverage:
        print(
            f"perf: coverage {cov} < --min-coverage {args.min_coverage} "
            "— measured executables missing from the audit registry?",
            file=sys.stderr,
        )
        return 1
    return 0
