"""Rule ``determinism`` — unseeded randomness / wall clocks in paths
that promise seeded reproducibility.

The round path (sampling, aggregation, defenses), the chaos plane
("an identical (schedule, seed) pair reproduces the identical fault
trace") and the data/poison synthesis all document bit-level or
draw-level determinism. A single ``np.random.rand()`` or
``random.random()`` against the *global* RNG breaks that silently —
and ``np.random.seed()`` / ``random.seed()`` is worse: it clobbers
every other component's stream (the exact bug PR 2 fixed in client
sampling). ``time.time()`` in these modules is flagged too: wall
clocks leak into decisions that replays cannot reproduce (telemetry
/ tracing modules are deliberately off this list — timestamps are
their job).

Allowed and never flagged: ``np.random.RandomState(seed)`` /
``np.random.default_rng(seed)`` / ``random.Random(seed)`` instances,
``np.random.SeedSequence``/``Generator`` type references, and any
derived-key JAX randomness.
"""

from __future__ import annotations

import ast
from typing import List

from .engine import Finding, ModuleSource

RULE = "determinism"

# modules (files or directory prefixes ending in /) that document
# seeded reproducibility
SEEDED_PATHS = (
    "fedml_tpu/core/aggregation.py",
    "fedml_tpu/core/defense.py",
    "fedml_tpu/core/round_pipeline.py",
    "fedml_tpu/core/chaos.py",
    "fedml_tpu/core/secure_agg.py",
    "fedml_tpu/core/partition.py",
    "fedml_tpu/core/scheduler.py",
    "fedml_tpu/scale/",
    "fedml_tpu/data/",
    "fedml_tpu/simulation/",
    "fedml_tpu/cross_silo/",
    "fedml_tpu/cross_device/",
)

# np.random.<attr> that are constructors/types for locally-seeded
# streams, not draws from the global RNG
_SEEDED_FACTORIES = {
    "RandomState", "default_rng", "Generator", "SeedSequence",
    "PCG64", "Philox",
}


def _in_seeded_path(path: str) -> bool:
    return any(
        path == p or (p.endswith("/") and path.startswith(p))
        for p in SEEDED_PATHS
    )


def check_determinism(mod: ModuleSource, force: bool = False) -> List[Finding]:
    """``force=True`` applies the rule regardless of the module-set
    gate — the relaxed ``tests/`` profile (engine.py) uses it: a test
    drawing from the global RNG is exactly how order-dependent flakes
    are born, even though tests/ is not a shipped seeded path."""
    if not force and not _in_seeded_path(mod.path):
        return []
    findings: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Attribute):
            continue
        # time.time()
        if (
            node.attr == "time"
            and isinstance(node.value, ast.Name)
            and node.value.id in ("time", "_time")
        ):
            findings.append(Finding(
                path=mod.path, line=node.lineno, rule=RULE,
                message=(
                    "time.time() in a seeded/deterministic path — wall "
                    "clocks are unreplayable; use a monotonic clock for "
                    "durations or thread a timestamp in"
                ),
            ))
            continue
        # np.random.<draw> on the GLOBAL stream
        v = node.value
        if (
            isinstance(v, ast.Attribute)
            and v.attr == "random"
            and isinstance(v.value, ast.Name)
            and v.value.id in ("np", "numpy", "onp")
        ):
            if node.attr in _SEEDED_FACTORIES:
                continue
            what = (
                "np.random.seed() reseeds the GLOBAL NumPy RNG and "
                "clobbers every other component's stream"
                if node.attr == "seed"
                else f"np.random.{node.attr} draws from the global NumPy "
                     "RNG in a seeded path"
            )
            findings.append(Finding(
                path=mod.path, line=node.lineno, rule=RULE,
                message=f"{what}; derive a local RandomState/key instead",
            ))
            continue
        # random.<draw> on the stdlib global stream
        if (
            isinstance(v, ast.Name)
            and v.id == "random"
            and node.attr not in ("Random", "SystemRandom")
        ):
            what = (
                "random.seed() reseeds the GLOBAL stdlib RNG"
                if node.attr == "seed"
                else f"random.{node.attr} draws from the global stdlib "
                     "RNG in a seeded path"
            )
            findings.append(Finding(
                path=mod.path, line=node.lineno, rule=RULE,
                message=f"{what}; derive a local random.Random(seed) instead",
            ))
    return findings
