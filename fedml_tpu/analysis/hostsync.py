"""Rule ``host-sync`` — hidden device->host synchronisation on a round
or serving hot path.

BENCH_r03 measured a 573x gap between device-resident and host-hop
aggregation; PR 2's DeferredMetrics exists exactly because one stray
``float(device_value)`` per round serialises the pipeline. This
checker flags, **in the hot-path modules only**, the conversions that
force a device fetch:

- ``float(x)`` / ``int(x)`` / ``bool(x)`` on a non-trivial expression
  (a name, attribute, subscript or call result — the shapes a jit
  output arrives in);
- ``.item()`` anywhere;
- ``np.asarray`` / ``np.array`` / ``jax.device_get`` /
  ``block_until_ready`` — explicit materialisation.

Deliberate syncs (a DeferredMetrics flush, the pipeline's
back-pressure ``block_until_ready``) are *named* with
``# lint: host-sync-ok`` on the line — the allowlist is visible in the
diff, never ambient.

Host-side arithmetic is not flagged: arguments that mention ``args``
/ ``getattr`` (knob coercion), ``.shape`` / ``len()`` (metadata), or
plain constants never touch the device.
"""

from __future__ import annotations

import ast
from typing import List

from .engine import Finding, ModuleSource

RULE = "host-sync"

# the per-round / per-upload / per-request hot paths; everything else
# may sync freely (setup, teardown, tests, CLIs)
HOT_PATH_MODULES = {
    "fedml_tpu/core/round_pipeline.py",
    "fedml_tpu/core/aggregation.py",
    "fedml_tpu/core/defense.py",
    "fedml_tpu/scale/engine.py",
    "fedml_tpu/scale/tree.py",
    "fedml_tpu/serving/engine.py",
    "fedml_tpu/serving/endpoint.py",
    "fedml_tpu/serving/batcher.py",
    "fedml_tpu/cross_silo/horizontal/fedml_aggregator.py",
    "fedml_tpu/simulation/fedavg_api.py",
}

_CONVERTERS = {"float", "int", "bool"}
_MATERIALIZERS = {"asarray", "array", "device_get", "block_until_ready"}
# host-only sources a conversion may safely wrap. BUILTIN names apply
# to bare-Name calls only: `sum(host_list)` is host-side, but
# `x.sum()` / `jnp.sum(x)` reduce ON DEVICE — treating those as safe
# would wave through the exact per-round `float(jnp.sum(losses))`
# fetch this rule exists for. Attribute calls are safe only for clocks.
_SAFE_BUILTIN_CALLS = {
    "getattr", "len", "round", "min", "max", "abs", "sum", "str",
    "float", "int", "bool",
}
_SAFE_CLOCK_ATTRS = {"perf_counter", "monotonic", "time", "time_ns"}
_SAFE_ATTR_MENTIONS = {"shape", "size", "ndim", "dtype", "args"}


def _mentions_safe_host_source(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == "args":
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in _SAFE_ATTR_MENTIONS:
            return True
        if isinstance(sub, ast.Call):
            fn = sub.func
            if isinstance(fn, ast.Name) and fn.id in _SAFE_BUILTIN_CALLS:
                return True
            if isinstance(fn, ast.Attribute) and fn.attr in _SAFE_CLOCK_ATTRS:
                return True
    return False


def _is_trivial(node: ast.AST) -> bool:
    """Constants and pure-constant arithmetic never touch the device."""
    return all(
        isinstance(
            sub,
            (ast.Constant, ast.UnaryOp, ast.BinOp, ast.operator, ast.unaryop,
             ast.Tuple, ast.List, ast.Load),
        )
        for sub in ast.walk(node)
    )


_CONSTRUCTION_FUNCS = {"__init__", "__post_init__"}


def _nodes_outside_construction(tree: ast.AST):
    """Walk the tree skipping ``__init__``/``__post_init__`` bodies —
    construction happens once, before any hot loop exists."""
    stack = [tree]
    while stack:
        node = stack.pop()
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name in _CONSTRUCTION_FUNCS
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def check_host_sync(mod: ModuleSource) -> List[Finding]:
    if mod.path not in HOT_PATH_MODULES:
        return []
    findings: List[Finding] = []

    for node in _nodes_outside_construction(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if (
            isinstance(fn, ast.Name)
            and fn.id in _CONVERTERS
            and len(node.args) == 1
            and not node.keywords
        ):
            arg = node.args[0]
            if _is_trivial(arg) or _mentions_safe_host_source(arg):
                continue
            findings.append(Finding(
                path=mod.path, line=node.lineno, rule=RULE,
                message=(
                    f"{fn.id}() forces a device fetch on a hot path; "
                    "defer it (DeferredMetrics) or mark the line "
                    "`# lint: host-sync-ok`"
                ),
            ))
        elif isinstance(fn, ast.Attribute) and fn.attr == "item":
            findings.append(Finding(
                path=mod.path, line=node.lineno, rule=RULE,
                message=(
                    ".item() forces a device fetch on a hot path; "
                    "defer it or mark the line `# lint: host-sync-ok`"
                ),
            ))
        elif isinstance(fn, ast.Attribute) and fn.attr in _MATERIALIZERS:
            owner = fn.value
            owner_name = owner.id if isinstance(owner, ast.Name) else None
            if fn.attr in ("asarray", "array") and owner_name not in (
                "np", "numpy", "onp",
            ):
                continue  # jnp.asarray stays on device
            findings.append(Finding(
                path=mod.path, line=node.lineno, rule=RULE,
                message=(
                    f"{owner_name + '.' if owner_name else ''}{fn.attr}() "
                    "materialises device values on a hot path; mark "
                    "`# lint: host-sync-ok` if it is a deliberate sync "
                    "point"
                ),
            ))
    return sorted(findings)
