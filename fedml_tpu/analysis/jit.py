"""Rules ``retrace`` and ``donation`` — jit lifecycle hazards.

``retrace`` (FedJAX's core lesson, PAPERS.md 2108.02117: JAX-FL
performance lives or dies on a trace-stable round loop):

- ``jax.jit(...)`` constructed inside a ``for``/``while`` loop — a
  fresh jit wrapper per iteration compiles every time and caches
  nothing;
- a jitted **lambda / nested function closing over ``self``** — the
  closure captures mutable attributes by reference, so attribute
  churn silently bakes stale values into the trace (or retraces);
- Python ``if``/``while`` **branching on a traced parameter** inside
  a ``@jax.jit`` function with no ``static_argnums``/``static_argnames``
  — value-dependent control flow either fails to trace or retraces
  per value.

``donation`` (SNIPPETS [1]; ROADMAP item 5's donation audit):

- an argument donated via ``donate_argnums`` whose buffer is **read
  again after the call** — donation invalidates it; XLA may have
  aliased the output into it;
- a **round-shaped jit** (name mentions train/round/step/update/fold/
  epoch) in a hot-path module built **without** ``donate_argnums`` —
  every call copies the params instead of updating in place.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .engine import Finding, ModuleSource

RULE_RETRACE = "retrace"
RULE_DONATION = "donation"

_ROUND_SHAPED = ("train", "round", "step", "update", "fold", "epoch")

# donation is a per-call perf contract; only the round/serving hot
# paths are held to it (same set as the host-sync rule, plus the
# trainer seams that own the per-round executables)
DONATION_HOT_MODULES = {
    "fedml_tpu/core/round_pipeline.py",
    "fedml_tpu/core/aggregation.py",
    "fedml_tpu/core/frame.py",
    "fedml_tpu/core/local_trainer.py",
    "fedml_tpu/scale/engine.py",
    "fedml_tpu/distributed.py",
    "fedml_tpu/simulation/fedavg_api.py",
    "fedml_tpu/simulation/decentralized.py",
    "fedml_tpu/cross_silo/horizontal/fedml_aggregator.py",
}


def _is_jit_func(fn: ast.AST) -> bool:
    """``jit`` / ``jax.jit`` (any dotted tail ending in .jit)."""
    if isinstance(fn, ast.Name):
        return fn.id == "jit"
    if isinstance(fn, ast.Attribute):
        return fn.attr == "jit"
    return False


def _jit_call(node: ast.AST) -> Optional[ast.Call]:
    """The Call node if ``node`` constructs a jitted function:
    ``jax.jit(...)`` or ``functools.partial(jax.jit, ...)``."""
    if not isinstance(node, ast.Call):
        return None
    if _is_jit_func(node.func):
        return node
    if (
        isinstance(node.func, (ast.Name, ast.Attribute))
        and (
            (isinstance(node.func, ast.Name) and node.func.id == "partial")
            or (isinstance(node.func, ast.Attribute)
                and node.func.attr == "partial")
        )
        and node.args
        and _is_jit_func(node.args[0])
    ):
        return node
    return None


def _jit_keywords(call: ast.Call) -> Set[str]:
    return {kw.arg for kw in call.keywords if kw.arg}


def _references_self(node: ast.AST) -> bool:
    return any(
        isinstance(sub, ast.Name) and sub.id == "self"
        for sub in ast.walk(node)
    )


def _decorated_jit(fn: ast.AST) -> Optional[ast.Call]:
    """For a FunctionDef decorated with jit, the decorator Call (or a
    synthesized empty one for a bare ``@jax.jit``)."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    for dec in fn.decorator_list:
        if _is_jit_func(dec):
            return ast.Call(func=dec, args=[], keywords=[])
        call = _jit_call(dec)
        if call is not None:
            return call
    return None


def check_retrace(mod: ModuleSource) -> List[Finding]:
    findings: List[Finding] = []

    # (a) jit constructed inside a loop
    class LoopVisitor(ast.NodeVisitor):
        def __init__(self) -> None:
            self.loop_depth = 0

        def visit_For(self, node):  # noqa: N802
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        visit_While = visit_For  # noqa: N815

        def visit_Call(self, node):  # noqa: N802
            call = _jit_call(node)
            if call is not None and self.loop_depth > 0:
                findings.append(Finding(
                    path=mod.path, line=node.lineno, rule=RULE_RETRACE,
                    message=(
                        "jax.jit constructed inside a loop — a fresh "
                        "wrapper per iteration compiles every time; "
                        "hoist the jit out of the loop"
                    ),
                ))
            self.generic_visit(node)

    LoopVisitor().visit(mod.tree)

    # collect nested function defs per scope so a jit of a local
    # function that closes over self can be resolved by name
    local_funcs: Dict[Tuple[int, str], ast.FunctionDef] = {}
    for scope in ast.walk(mod.tree):
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for stmt in ast.walk(scope):
                if (
                    isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and stmt is not scope
                ):
                    local_funcs[(id(scope), stmt.name)] = stmt

    # (b) jitted lambda / local function closing over self
    for scope in ast.walk(mod.tree):
        if not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(scope):
            call = _jit_call(node) if isinstance(node, ast.Call) else None
            if call is None:
                continue
            # the jitted object: first arg of jax.jit(...), second of
            # partial(jax.jit, fn)
            target = None
            if _is_jit_func(call.func):
                target = call.args[0] if call.args else None
            elif call.args and _is_jit_func(call.args[0]):
                target = call.args[1] if len(call.args) > 1 else None
            if target is None:
                continue
            closes_over_self = False
            if isinstance(target, ast.Lambda) and _references_self(target.body):
                closes_over_self = True
            elif isinstance(target, ast.Name):
                local = local_funcs.get((id(scope), target.id))
                if local is not None and _references_self(local):
                    closes_over_self = True
            if closes_over_self:
                findings.append(Finding(
                    path=mod.path, line=node.lineno, rule=RULE_RETRACE,
                    message=(
                        "jitted function closes over `self` — mutable "
                        "attributes are baked into the trace (stale "
                        "values) or force retraces; pass them as "
                        "arguments instead"
                    ),
                ))

    # (c) value-dependent Python branching on a traced parameter
    for fn in ast.walk(mod.tree):
        dec = _decorated_jit(fn)
        if dec is None:
            continue
        if _jit_keywords(dec) & {"static_argnums", "static_argnames"}:
            continue  # some params are static; branching may be fine
        params = {
            a.arg for a in fn.args.args + fn.args.kwonlyargs
            if a.arg not in ("self", "cls")
        }
        if not params:
            continue
        for stmt in ast.walk(fn):
            if not isinstance(stmt, (ast.If, ast.While)):
                continue
            test_names = {
                sub.id for sub in ast.walk(stmt.test)
                if isinstance(sub, ast.Name)
            }
            traced = sorted(test_names & params)
            if traced:
                findings.append(Finding(
                    path=mod.path, line=stmt.lineno, rule=RULE_RETRACE,
                    message=(
                        f"Python branch on traced argument "
                        f"'{traced[0]}' inside a @jax.jit function "
                        "with no static_argnums — use lax.cond/select "
                        "or mark the arg static"
                    ),
                ))
    return findings


def _donated_indices(call: ast.Call) -> List[int]:
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return [v.value]
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for elt in v.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                    out.append(elt.value)
            return out
    return []


def _target_names(node: ast.AST) -> Set[str]:
    """Unparsed names/attribute chains bound by an assignment target."""
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Name, ast.Attribute)):
            out.add(ast.unparse(sub))
    return out


def check_donation(mod: ModuleSource) -> List[Finding]:
    findings: List[Finding] = []

    # map of jitted-callable name -> donated positional indices,
    # gathered from `<name> = jax.jit(..., donate_argnums=...)` and
    # `self.<name> = jax.jit(...)` assignments anywhere in the module
    donating: Dict[str, List[int]] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        call = _jit_call(node.value)
        if call is None:
            continue
        tgt = node.targets[0]
        name = None
        if isinstance(tgt, ast.Name):
            name = tgt.id
        elif isinstance(tgt, ast.Attribute):
            name = tgt.attr
        if name is None:
            continue
        donated = _donated_indices(call)
        if donated:
            donating[name] = donated
        if (
            mod.path in DONATION_HOT_MODULES
            and any(tok in name.lower() for tok in _ROUND_SHAPED)
            and not donated
        ):
            findings.append(Finding(
                path=mod.path, line=node.lineno, rule=RULE_DONATION,
                message=(
                    f"round-shaped jit '{name}' has no donate_argnums "
                    "— each call copies its inputs instead of updating "
                    "in place (SNIPPETS [1]); donate the carried state "
                    "or mark the line `# lint: donation-ok`"
                ),
            ))

    if not donating:
        return findings

    # use-after-donation, per function scope, flow-approximate:
    # a donated positional arg that is a plain name/attribute read
    # again on a LATER line of the same function (and not rebound by
    # the call's own assignment) is a read of an invalidated buffer
    for scope in ast.walk(mod.tree):
        if not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        donated_exprs: List[Tuple[str, int]] = []  # (expr text, call line)
        for stmt in ast.walk(scope):
            calls = []
            if isinstance(stmt, ast.Assign):
                for sub in ast.walk(stmt.value):
                    if isinstance(sub, ast.Call):
                        calls.append((sub, stmt))
            elif isinstance(stmt, ast.Expr):
                for sub in ast.walk(stmt.value):
                    if isinstance(sub, ast.Call):
                        calls.append((sub, stmt))
            for call, owner in calls:
                fn = call.func
                callee = (
                    fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute)
                    else None
                )
                idxs = donating.get(callee)
                if not idxs:
                    continue
                rebinds: Set[str] = set()
                if isinstance(owner, ast.Assign):
                    for t in owner.targets:
                        rebinds |= _target_names(t)
                for i in idxs:
                    if i >= len(call.args):
                        continue
                    arg = call.args[i]
                    if not isinstance(arg, (ast.Name, ast.Attribute)):
                        continue
                    expr = ast.unparse(arg)
                    if expr in rebinds:
                        continue  # x = f(x): the donated name is rebound
                    # anchor past the WHOLE call statement — a
                    # multi-line call's own arguments are not
                    # "reads after the call"
                    stmt_end = max(
                        getattr(call, "end_lineno", call.lineno) or call.lineno,
                        getattr(owner, "end_lineno", call.lineno)
                        or call.lineno,
                    )
                    donated_exprs.append((expr, stmt_end))
        if not donated_exprs:
            continue
        # store lines per expression — a rebind between the donating
        # call and a read makes the read a read of the NEW value
        store_lines: Dict[str, List[int]] = {}
        for sub in ast.walk(scope):
            if isinstance(sub, (ast.Name, ast.Attribute)) and isinstance(
                getattr(sub, "ctx", None), ast.Store
            ):
                store_lines.setdefault(ast.unparse(sub), []).append(sub.lineno)
        for expr, call_line in donated_exprs:
            for sub in ast.walk(scope):
                if (
                    isinstance(sub, (ast.Name, ast.Attribute))
                    and isinstance(getattr(sub, "ctx", None), ast.Load)
                    and sub.lineno > call_line
                ):
                    if ast.unparse(sub) != expr:
                        continue
                    if any(
                        call_line < s <= sub.lineno
                        for s in store_lines.get(expr, ())
                    ):
                        continue  # rebound before this read
                    findings.append(Finding(
                        path=mod.path, line=sub.lineno, rule=RULE_DONATION,
                        message=(
                            f"'{expr}' is read after being donated to a "
                            "jit call — donation invalidates the "
                            "buffer; reorder the read or drop the "
                            "donation"
                        ),
                    ))
                    break  # one finding per donated expr is enough
    return findings
