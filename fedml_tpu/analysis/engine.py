"""`fedml-tpu lint` — the AST engine under the JAX-/federation-aware
static-analysis suite (docs/static_analysis.md).

Why a purpose-built linter instead of flake8 plugins: the defect
classes that keep recurring in review (hidden host syncs in round hot
paths, retrace hazards, missed donation, non-derived RNG in seeded
paths, swallowed exceptions, unlocked cross-thread state, and drift
between MSG_TYPE/telemetry/knob registries and their docs) are all
*semantic to this codebase* — they need to know which modules are hot
paths, what the telemetry naming convention is, and where the knob
schema lives. Generic linters cannot say any of that.

Design:

- pure stdlib (``ast`` + ``re`` + ``json``). Importing this package
  must never import JAX — the CI gate runs the whole pass in seconds
  on a bare checkout (``pyproject.toml`` ``lint`` extra).
- checkers are functions. *Module* checkers take one
  :class:`ModuleSource` and return findings; *project* checkers take
  the whole corpus (plus the docs text) — registry-consistency checks
  are cross-file by nature.
- suppression is per-line and per-rule: ``# lint: <rule>-ok`` on the
  offending line (or the line above, for wrapped statements) —
  mirroring the DeferredMetrics discipline where a deliberate host
  sync is *named*, never silent.
- the baseline (:func:`load_baseline` / :func:`diff_baseline`) is a
  **ratchet**: pre-existing findings are keyed by
  ``path:rule:message`` with a count; CI fails on any NEW finding
  *and* on any stale entry (a fixed finding must shrink the baseline
  in the same change — suppressions can only burn down).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

BASELINE_NAME = "lint_baseline.json"

# one id per checker; docs/static_analysis.md is the rule catalog
RULES = (
    "host-sync",
    "retrace",
    "donation",
    "determinism",
    "except",
    "thread-lock",
    "registry",
)

_SUPPRESS_RE = re.compile(r"#\s*lint:\s*([a-z-]+(?:\s*,\s*[a-z-]+)*)-ok\b")
_SUPPRESS_SPLIT_RE = re.compile(r"-ok\b[\s,]*")


@dataclass(frozen=True, order=True)
class Finding:
    """One defect at one site. ``message`` is line-number-free on
    purpose: the baseline keys on ``path:rule:message`` (+ count), so
    unrelated edits that shift lines never churn the ratchet."""

    path: str  # repo-relative, posix separators
    line: int
    rule: str
    message: str

    def key(self) -> str:
        return f"{self.path}:{self.rule}:{self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> Dict[str, object]:
        return {
            "path": self.path,
            "line": self.line,
            "rule": self.rule,
            "message": self.message,
        }


@dataclass
class ModuleSource:
    """One parsed module: source text, AST, and the per-line rule
    suppressions the engine honours for every checker."""

    path: str  # repo-relative
    text: str
    tree: ast.AST
    lines: List[str] = field(default_factory=list)
    # line number (1-based) -> set of suppressed rule ids
    suppressions: Dict[int, set] = field(default_factory=dict)
    # lines that are ONLY a suppression comment — these also cover the
    # following line (for wrapped statements); inline ones cover only
    # their own line
    standalone_suppressions: set = field(default_factory=set)

    @classmethod
    def parse(cls, path: str, text: str) -> "ModuleSource":
        tree = ast.parse(text, filename=path)
        lines = text.splitlines()
        suppressions: Dict[int, set] = {}
        standalone = set()
        for i, line in enumerate(lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            chunk = line[m.start(1):]
            rules = {
                tok.strip() for tok in _SUPPRESS_SPLIT_RE.split(chunk)
                if tok.strip()
            }
            suppressions[i] = rules
            if line.lstrip().startswith("#"):
                standalone.add(i)
        return cls(
            path=path, text=text, tree=tree, lines=lines,
            suppressions=suppressions, standalone_suppressions=standalone,
        )

    def is_suppressed(self, rule: str, line: int) -> bool:
        """The finding's own line always; the line above only when it
        is a standalone suppression comment (an inline suppression
        covers its own statement, not its neighbour)."""
        if rule in self.suppressions.get(line, set()):
            return True
        prev = line - 1
        return prev in self.standalone_suppressions and rule in (
            self.suppressions.get(prev, set())
        )


# -- corpus ------------------------------------------------------------

_SKIP_DIRS = {"__pycache__"}

# directories linted under the RELAXED profile: tests are not shipped
# hot paths, but a bare `except:` still eats ProcessKilled mid-chaos
# and a global-RNG draw is exactly how order-dependent flakes are born
# — so the exception + determinism rules apply there (nothing else),
# baselined and ratcheted like the main corpus
RELAXED_DIRS = ("tests",)
RELAXED_PREFIXES = tuple(d + "/" for d in RELAXED_DIRS)


def is_relaxed_path(path: str) -> bool:
    return path.startswith(RELAXED_PREFIXES)


def find_repo_root(start: Optional[str] = None) -> str:
    """The directory holding ``fedml_tpu/`` and ``pyproject.toml`` —
    walked up from ``start`` (default: this file's grandparent, which
    is correct for an in-tree checkout; ``--root`` overrides)."""
    here = os.path.dirname(os.path.abspath(__file__))
    candidates = []
    if start:
        candidates.append(os.path.abspath(start))
    candidates.append(os.path.abspath(os.path.join(here, "..", "..")))
    candidates.append(os.getcwd())
    for cand in candidates:
        d = cand
        for _ in range(6):
            if os.path.isdir(os.path.join(d, "fedml_tpu")) and os.path.isfile(
                os.path.join(d, "pyproject.toml")
            ):
                return d
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
    raise FileNotFoundError(
        "could not locate the repo root (a directory containing both "
        "fedml_tpu/ and pyproject.toml); pass --root explicitly"
    )


def load_corpus(
    root: str, rel_paths: Optional[Sequence[str]] = None
) -> List[ModuleSource]:
    """Parse every ``fedml_tpu/**/*.py`` under ``root`` (or an explicit
    subset). Unparseable files raise — a syntax error is not a lint
    finding, it is a broken tree nothing downstream could run."""
    if rel_paths:
        files = sorted(os.path.normpath(p).replace(os.sep, "/") for p in rel_paths)
    else:
        files = []
        for top in ("fedml_tpu",) + RELAXED_DIRS:
            pkg = os.path.join(root, top)
            if not os.path.isdir(pkg):
                continue
            for base, dirs, names in os.walk(pkg):
                dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
                for name in sorted(names):
                    if name.endswith(".py"):
                        rel = os.path.relpath(os.path.join(base, name), root)
                        files.append(rel.replace(os.sep, "/"))
    corpus = []
    for rel in files:
        with open(os.path.join(root, rel), "r", encoding="utf-8") as fh:
            text = fh.read()
        corpus.append(ModuleSource.parse(rel, text))
    return corpus


def load_docs_text(root: str) -> str:
    """Concatenated ``docs/*.md`` — the registry checker's
    documentation source of truth."""
    chunks = []
    docs = os.path.join(root, "docs")
    if os.path.isdir(docs):
        for name in sorted(os.listdir(docs)):
            if name.endswith(".md"):
                with open(os.path.join(docs, name), "r", encoding="utf-8") as fh:
                    chunks.append(fh.read())
    return "\n".join(chunks)


# -- checker registry --------------------------------------------------

ModuleChecker = Callable[[ModuleSource], List[Finding]]


def _module_checkers() -> List[ModuleChecker]:
    from . import determinism, exceptions, hostsync, jit, threads

    return [
        hostsync.check_host_sync,
        jit.check_retrace,
        jit.check_donation,
        determinism.check_determinism,
        exceptions.check_exceptions,
        threads.check_thread_shared_state,
    ]


def _relaxed_checkers() -> List[ModuleChecker]:
    """The tests/ profile: exception hygiene + determinism only. Hot-
    path rules (host-sync/retrace/donation/thread-lock) are shipped-
    code contracts — they do not apply to test harness code."""
    from . import determinism, exceptions

    return [
        lambda mod: determinism.check_determinism(mod, force=True),
        exceptions.check_exceptions,
    ]


def run_lint(
    root: str,
    rel_paths: Optional[Sequence[str]] = None,
    corpus: Optional[List[ModuleSource]] = None,
    docs_text: Optional[str] = None,
) -> List[Finding]:
    """Run every checker over the corpus, apply suppressions, return
    sorted findings. ``corpus``/``docs_text`` are injectable for tests."""
    from .registry import check_registry

    if corpus is None:
        corpus = load_corpus(root, rel_paths)
    if docs_text is None:
        docs_text = load_docs_text(root)
    by_path = {m.path: m for m in corpus}
    findings: List[Finding] = []
    for mod in corpus:
        checkers = (
            _relaxed_checkers() if is_relaxed_path(mod.path)
            else _module_checkers()
        )
        for checker in checkers:
            findings.extend(checker(mod))
    # the project checker only makes sense over the full package —
    # a path-subset run would report every registry entry as missing.
    # The relaxed corpus (tests/) is excluded: its args are fixtures,
    # its series names are assertions, not emissions
    if not rel_paths:
        findings.extend(check_registry(
            [m for m in corpus if not is_relaxed_path(m.path)], docs_text
        ))
    kept = []
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None and mod.is_suppressed(f.rule, f.line):
            continue
        kept.append(f)
    return sorted(kept)


# -- baseline ratchet --------------------------------------------------

def findings_to_counts(findings: Iterable[Finding]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    return counts


def load_baseline(path: str) -> Dict[str, int]:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or "entries" not in data:
        raise ValueError(
            f"{path}: not a lint baseline (expected an object with an "
            "'entries' map)"
        )
    entries = data["entries"]
    return {str(k): int(v) for k, v in entries.items()}


def save_baseline(
    path: str, findings: Iterable[Finding], comment: Optional[str] = None
) -> None:
    """Write a ratchet ledger. ``comment`` lets the compiled-artifact
    auditor (analysis/audit.py) reuse this exact machinery for its own
    ``audit_baseline.json``."""
    counts = findings_to_counts(findings)
    payload = {
        "comment": comment or (
            "Ratchet-only suppression ledger for `fedml-tpu lint` "
            "(docs/static_analysis.md). Entries may only be REMOVED "
            "(by fixing the finding); CI fails on new findings AND on "
            "stale entries. Regenerate with `fedml-tpu lint "
            "--update-baseline` after a burn-down."
        ),
        "version": 1,
        "entries": {k: counts[k] for k in sorted(counts)},
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=False)
        fh.write("\n")


def diff_baseline(
    findings: Sequence[Finding], baseline: Dict[str, int]
) -> Tuple[List[Finding], List[str]]:
    """(new findings, stale baseline keys). New = beyond the
    baselined count for that key; stale = the baseline grants more
    suppressions than findings exist (the fix must also shrink the
    baseline — that is the ratchet)."""
    counts = findings_to_counts(findings)
    new: List[Finding] = []
    budget = dict(baseline)
    for f in sorted(findings):
        if budget.get(f.key(), 0) > 0:
            budget[f.key()] -= 1
        else:
            new.append(f)
    stale = sorted(
        k for k, v in baseline.items() if counts.get(k, 0) < v
    )
    return new, stale


# -- CLI surface (shared by fedml_tpu.cli and the bare entry point) ----

def run_ratchet_cli(
    prog: str,
    args,
    findings: Sequence[Finding],
    baseline_path: str,
    baseline_filter: Optional[Callable[[Dict[str, int]], Dict[str, int]]] = None,
    save_comment: Optional[str] = None,
    json_extra: Optional[Dict[str, object]] = None,
    summary_prefix: str = "",
    summary_suffix: str = "",
) -> int:
    """THE ratchet gate ladder, shared by `lint` and `audit`: rewrite
    on --update-baseline, raw on --no-baseline, diff against the
    (optionally subset-filtered) baseline when it exists, refuse --ci
    without one — then render text or JSON and return the exit code.
    Keeping one copy means a gate-semantics fix can never silently
    diverge between the two tools."""
    import sys

    if args.ci and args.no_baseline:
        print(
            f"{prog}: --ci and --no-baseline are mutually exclusive "
            "(the CI gate IS the ratchet — a raw run silently drops "
            "the stale-entry check)", file=sys.stderr,
        )
        return 2
    if args.update_baseline:
        save_baseline(baseline_path, findings, comment=save_comment)
        print(
            f"{prog}: baseline rewritten with {len(findings)} finding(s) "
            f"-> {baseline_path}"
        )
        return 0

    if args.no_baseline:
        new, stale = list(findings), []
        baselined = 0
    elif os.path.isfile(baseline_path):
        baseline = load_baseline(baseline_path)
        if baseline_filter is not None:
            baseline = baseline_filter(baseline)
        new, stale = diff_baseline(findings, baseline)
        baselined = len(findings) - len(new)
    elif args.ci:
        print(
            f"{prog}: --ci requires the checked-in baseline "
            f"({baseline_path}); refusing to run raw", file=sys.stderr,
        )
        return 2
    else:
        new, stale = list(findings), []
        baselined = 0

    ok = not new and not stale
    if args.as_json:
        payload: Dict[str, object] = {"ok": ok}
        payload.update(json_extra or {})
        payload.update({
            "total": len(findings),
            "baselined": baselined,
            "new": [f.to_dict() for f in new],
            "stale": stale,
            "findings": [f.to_dict() for f in findings],
        })
        print(json.dumps(payload))
    else:
        for f in new:
            print(f.render())
        for key in stale:
            print(
                f"stale baseline entry (finding fixed — remove it from "
                f"the baseline): {key}"
            )
        print(
            f"{prog}: {summary_prefix}{len(findings)} finding(s) — "
            f"{len(new)} new, {baselined} baselined, {len(stale)} stale "
            f"baseline entr{'y' if len(stale) == 1 else 'ies'}"
            f"{summary_suffix}"
        )
    return 0 if ok else 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="fedml-tpu-lint")
    add_lint_arguments(p)
    return run_cli(p.parse_args(argv))


def add_lint_arguments(p) -> None:
    p.add_argument(
        "paths", nargs="*",
        help="repo-relative .py files to lint (default: all of "
             "fedml_tpu/; a subset run skips the project-wide "
             "registry checker)",
    )
    p.add_argument(
        "--root", default=None,
        help="repo root (default: auto-detected from the package "
             "location / cwd)",
    )
    p.add_argument(
        "--baseline", default=None,
        help=f"baseline path (default: <root>/{BASELINE_NAME})",
    )
    p.add_argument(
        "--json", dest="as_json", action="store_true",
        help="machine-readable output (one JSON object)",
    )
    p.add_argument(
        "--ci", action="store_true",
        help="CI gate mode: the baseline file MUST exist (a deleted "
             "baseline must fail the gate, not silently pass a raw "
             "run) and --update-baseline is rejected",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline from the current findings "
             "(burn-down workflow; never valid under --ci)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="report raw findings without ratcheting (exit 1 if any)",
    )


def run_cli(args) -> int:
    import sys

    try:
        root = find_repo_root(args.root)
    except FileNotFoundError as e:
        print(f"lint: {e}", file=sys.stderr)
        return 2
    if args.ci and args.update_baseline:
        print(
            "lint: --ci and --update-baseline are mutually exclusive "
            "(the CI gate ratchets; it never rewrites)", file=sys.stderr,
        )
        return 2
    if args.paths and args.update_baseline:
        print(
            "lint: --update-baseline needs a FULL run — a subset run "
            "skips the registry checker and would overwrite the "
            "ledger with only the subset's findings", file=sys.stderr,
        )
        return 2
    findings = run_lint(root, rel_paths=args.paths or None)
    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)

    def subset_filter(baseline: Dict[str, int]) -> Dict[str, int]:
        # a subset run can only judge the files it linted — other
        # files' baseline entries are neither new nor stale here.
        # Registry entries are dropped too: the project-wide registry
        # checker does not run on subsets, so its baselined findings
        # would all read as falsely stale
        linted = {
            os.path.normpath(p).replace(os.sep, "/") for p in args.paths
        }
        return {
            k: v for k, v in baseline.items()
            if k.split(":", 1)[0] in linted
            and k.split(":", 2)[1] != "registry"
        }

    return run_ratchet_cli(
        "lint", args, findings, baseline_path,
        baseline_filter=subset_filter if args.paths else None,
        json_extra={"root": root},
    )


if __name__ == "__main__":
    import sys

    sys.exit(main())
