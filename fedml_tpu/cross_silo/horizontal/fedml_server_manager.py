"""Cross-silo server manager: presence handshake + round loop.

Parity with ``python/fedml/cross_silo/horizontal/fedml_server_manager.py:11-235``:

- clients announce ONLINE (``MSG_TYPE_C2S_CLIENT_STATUS``); the server
  waits for ALL before ``send_init_msg`` (:95-119) — the handshake the
  simulation scenario doesn't need;
- round loop: on every client model received -> aggregate -> silo/client
  selection -> sync (:121-207);
- client-id indirection: messages go to ranks 1..N, training assignments
  are silo indices (``data_silo_selection``).

The terminal round sends ``MSG_TYPE_S2C_FINISH`` so clients exit their
receive loops cleanly (the reference relies on ``finish()`` +
sys.exit, fedml_server_manager.py:209-213).
"""

from __future__ import annotations

import logging
from typing import Dict

from ... import constants
from ...core.managers import ServerManager
from ...core.message import Message


def _resolve_client_real_ids(args, size: int):
    """Client-id indirection (fedml_server_manager.py:33): edge devices
    carry real ids from ``args.client_id_list`` (JSON string or list);
    without one, ids default to the transport ranks 1..size-1."""
    raw = getattr(args, "client_id_list", None)
    if raw:
        if isinstance(raw, str):
            import json

            raw = json.loads(raw)
        ids = [int(i) for i in raw]
        if size and len(ids) != size - 1:
            raise ValueError(
                f"client_id_list has {len(ids)} entries but comm world has "
                f"{size - 1} clients"
            )
        return ids
    return list(range(1, size))


class FedMLServerManager(ServerManager):
    def __init__(
        self,
        args,
        aggregator,
        comm=None,
        rank=0,
        size=0,
        backend=constants.COMM_BACKEND_LOCAL,
    ) -> None:
        super().__init__(args, comm, rank, size, backend)
        self.aggregator = aggregator
        self.round_num = int(args.comm_round)
        self.round_idx = 0
        self.client_online_status: Dict[int, bool] = {}
        # Identity vs address: ``client_real_ids`` are edge-device
        # IDENTITIES (selection, reporting); transport ADDRESSES are
        # ranks 1..size-1. Position p in the list <-> rank p+1 (the
        # reference's rank<->real-id convention, fedml_server_manager.py:33).
        self.client_real_ids = _resolve_client_real_ids(args, size)
        self._rank_of_real_id = {
            rid: pos + 1 for pos, rid in enumerate(self.client_real_ids)
        }
        self.is_initialized = False
        from ...core.tracking import MetricsReporter, ProfilerEvent

        # reference instrumentation points (fedml_server_manager.py:
        # 71-74, :123-150: server.wait / aggregate spans + round info)
        self.profiler = ProfilerEvent(args)
        self.metrics_reporter = MetricsReporter(args, keep_history=False)
        self._wait_open = False

    # -- handlers ------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            constants.MSG_TYPE_C2S_CLIENT_STATUS,
            self.handle_message_client_status_update,
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self.handle_message_receive_model_from_client,
        )

    def handle_message_client_status_update(self, msg: Message) -> None:
        """(fedml_server_manager.py:95-119)"""
        status = msg.get(constants.MSG_ARG_KEY_CLIENT_STATUS)
        if status == constants.CLIENT_STATUS_ONLINE:
            self.client_online_status[int(msg.get_sender_id())] = True
        all_online = all(
            self.client_online_status.get(rank, False)
            for rank in range(1, len(self.client_real_ids) + 1)
        )
        if all_online and not self.is_initialized:
            self.is_initialized = True
            self.send_init_msg()

    def send_init_msg(self) -> None:
        """(fedml_server_manager.py:47-69)"""
        self._broadcast_model(constants.MSG_TYPE_S2C_INIT_CONFIG)

    def _broadcast_model(self, msg_type: str) -> None:
        """Selection + model broadcast shared by init and per-round sync
        (fedml_server_manager.py:47-69 and :167-207): pick which edge
        ranks participate (``client_selection``), map them onto data-silo
        indices (``data_silo_selection``), send the global model."""
        selected_real_ids = self.aggregator.client_selection(
            self.round_idx, self.client_real_ids, len(self.client_real_ids)
        )
        silo_indexes = self.aggregator.data_silo_selection(
            self.round_idx,
            int(self.args.client_num_in_total),
            len(selected_real_ids),
        )
        global_params = self.aggregator.get_global_model_params()
        for real_id, silo_idx in zip(selected_real_ids, silo_indexes):
            rank = self._rank_of_real_id[real_id]
            msg = Message(msg_type, self.rank, rank)
            msg.add_params(constants.MSG_ARG_KEY_MODEL_PARAMS, global_params)
            msg.add_params(constants.MSG_ARG_KEY_CLIENT_INDEX, silo_idx)
            msg.add_params(constants.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
            self.send_message(msg)

    def handle_message_receive_model_from_client(self, msg: Message) -> None:
        """(fedml_server_manager.py:121-207)"""
        sender_rank = int(msg.get_sender_id())
        model_params = msg.get(constants.MSG_ARG_KEY_MODEL_PARAMS)
        local_sample_num = msg.get(constants.MSG_ARG_KEY_NUM_SAMPLES)
        self.aggregator.add_local_trained_result(
            sender_rank - 1, model_params, local_sample_num
        )
        if not self._wait_open:
            self.profiler.log_event_started("server.wait")
            self._wait_open = True
        if not self.aggregator.check_whether_all_receive():
            return
        self.profiler.log_event_ended("server.wait")
        self._wait_open = False
        with self.profiler.span("aggregate"):
            self.aggregator.aggregate()
        self.aggregator.test_on_server_for_all_clients(self.round_idx)
        self.metrics_reporter.report(
            {"kind": "round_info", "round": self.round_idx, "clients": len(self.client_real_ids)}
        )
        self.round_idx += 1
        if self.round_idx >= self.round_num:
            self.send_finish()
            self.finish()
            return
        self._broadcast_model(constants.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)

    def send_finish(self) -> None:
        for rank in range(1, len(self.client_real_ids) + 1):
            self.send_message(
                Message(constants.MSG_TYPE_S2C_FINISH, self.rank, rank)
            )
        logging.info("server: training finished after %d rounds", self.round_idx)
