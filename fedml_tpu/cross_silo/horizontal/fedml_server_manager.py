"""Cross-silo server manager: presence handshake + round loop +
deadline cohort (straggler handling).

Parity with ``python/fedml/cross_silo/horizontal/fedml_server_manager.py:11-235``:

- clients announce ONLINE (``MSG_TYPE_C2S_CLIENT_STATUS``); the server
  waits for ALL before ``send_init_msg`` (:95-119) — the handshake the
  simulation scenario doesn't need;
- round loop: on every client model received -> aggregate -> silo/client
  selection -> sync (:121-207);
- client-id indirection: messages go to ranks 1..N, training assignments
  are silo indices (``data_silo_selection``).

The terminal round sends ``MSG_TYPE_S2C_FINISH`` so clients exit their
receive loops cleanly (the reference relies on ``finish()`` +
sys.exit, fedml_server_manager.py:209-213).

**Beyond the reference — deadline cohort**: the reference's server
waits for EVERY selected client, so one straggler stalls the whole
federation. With ``args.aggregation_deadline_s`` set, the server arms a
timer per round; when it fires it aggregates whoever reported by then
(weights renormalize over the subset) and moves on. Late uploads carry
their round tag and are discarded with a log line. The timer thread
never touches state directly — it posts a message to the server's own
inbox, so all mutation stays on the single dispatch thread.

**Beyond the reference — elastic membership**: with
``args.elastic_membership`` the federation starts as soon as
``client_num_per_round`` clients are ONLINE, accepts late joins (a new
rank's ONLINE registers it; it trains from the next round), and
handles OFFLINE leaves mid-round (the leaver's slot is dropped from
the round's expected set so the federation never stalls on it). The
reference blocks round 0 until every configured client appears and has
no membership changes after that (fedml_server_manager.py:95-119).

**Beyond the reference — failure detection**: a client killed WITHOUT
sending OFFLINE (kill -9) stalls any non-deadline world forever. With
``args.heartbeat_timeout_s`` the server runs a ``FailureDetector``
(core/comm/heartbeat.py): any traffic from a rank counts as liveness
(clients additionally beat every ``heartbeat_interval_s``), and a rank
silent past the timeout is declared dead via a self-addressed
``MSG_TYPE_S2S_CLIENT_DEAD`` message — all membership mutation stays
on the dispatch thread — which folds into the same drop-expected path
as an OFFLINE leave, so the round completes over the survivors.

**Beyond the reference — streaming aggregate-on-arrival**: with
``agg_mode: stream`` (default) every upload is folded into the
aggregator's O(model) running accumulator the moment it lands
(``core/aggregation.py``; quantized uplinks decode+accumulate in one
fused jitted step), so the post-barrier "aggregate" is a finalize and
server memory stops scaling with the cohort. On top of the fold,
``round_quorum_frac`` + ``round_grace_s`` give a **quorum close**:
once the quorum has folded, a grace timer arms (loopback message
pattern, like the deadline); when it fires the round closes over the
partial cohort with weights renormalized, and ranks the
``FailureDetector`` declares dead leave the quorum denominator — a
kill -9'd client shrinks the round instead of stalling the grace.
Late uploads are discarded by round tag and counted
(``agg_late_uploads_total``).

**Beyond the reference — async staleness-weighted aggregation**
(``agg_mode: async``, FedBuff-style): no round barrier exists at all.
Each downlink carries a dispatch seq (in ``ROUND_INDEX``) and the
publish ``MODEL_VERSION`` it shipped; clients upload update DELTAS
which fold immediately with weight ``n * staleness_decay^staleness``
(hard cap ``staleness_max``), and every ``async_publish_every`` folds
the server publishes ``global += weighted-mean delta`` — through the
checkpoint dir when one is set, so the PR-4 serving plane hot-swaps
each publish. The WAL records the folded ``(rank, seq)`` set per
publish; a restarted server seeds its dedup ledger from it, so a
retransmitted pre-crash upload can neither double-fold nor be
silently half-applied.

**Beyond the reference — Byzantine defense on every path**
(docs/robustness.md threat model): ``norm_diff_clipping`` / ``weak_dp``
ride the streaming fold itself (clip fused into the per-term jit,
noise at finalize — the aggregator's job), and this manager wires the
quarantine half: an upload the anomaly screen rejects drops its
rank's slot through the SAME drop-expected path a failure-detector
death uses (the quorum denominator shrinks — a quarantined rank never
stalls ``round_grace_s``), quarantined ranks are excluded from
subsequent broadcasts/dispatches until their probation expires (ticked
per round close in sync modes, per publish in async, where released
ranks are re-dispatched immediately), and an async federation whose
every online rank is quarantined finishes loudly instead of waiting
for a fold that can never arrive.

**Beyond the reference — crash recovery**: with ``checkpoint_dir`` the
server keeps a ``RoundWAL`` (round idx + checkpoint step + sampled
cohort + folded set per completed round) next to its orbax
checkpoints. A restarted
server restores the newest checkpoint, cross-checks the WAL (loudly
reporting rounds lost to ``checkpoint_freq > 1``), and releases
reconnecting clients with ``MSG_TYPE_S2C_RESYNC`` — current round +
params — instead of a stale round-0 init. Client heartbeats double as
the reconnect probe: a beat or ONLINE from a rank the server doesn't
know (it just restarted) re-registers that rank, and a rank that
reappears mid-round is resynced into its still-pending assignment.
"""

from __future__ import annotations

import logging
from typing import Dict

from ... import constants
from ...core.chaos import chaos_barrier
from ...core.managers import ServerManager
from ...core.message import Message

# Async dispatch-seq epoch: each server incarnation issues seqs from
# its own epoch band, so a seq handed out after the last durable
# publish (and therefore unknown to the restored high-water mark) can
# never be reissued by the next incarnation — the (rank, seq) fold
# ledger stays collision-free without persisting every dispatch.
_SEQ_EPOCH = 1 << 32


def _resolve_client_real_ids(args, size: int):
    """Client-id indirection (fedml_server_manager.py:33): edge devices
    carry real ids from ``args.client_id_list`` (JSON string or list);
    without one, ids default to the transport ranks 1..size-1."""
    raw = getattr(args, "client_id_list", None)
    if raw:
        if isinstance(raw, str):
            import json

            raw = json.loads(raw)
        ids = [int(i) for i in raw]
        if size and len(ids) != size - 1:
            raise ValueError(
                f"client_id_list has {len(ids)} entries but comm world has "
                f"{size - 1} clients"
            )
        return ids
    return list(range(1, size))


class FedMLServerManager(ServerManager):
    def __init__(
        self,
        args,
        aggregator,
        comm=None,
        rank=0,
        size=0,
        backend=constants.COMM_BACKEND_LOCAL,
    ) -> None:
        super().__init__(args, comm, rank, size, backend)
        self.aggregator = aggregator
        self.round_num = int(args.comm_round)
        self.round_idx = 0
        self.client_online_status: Dict[int, bool] = {}
        # Identity vs address: ``client_real_ids`` are edge-device
        # IDENTITIES (selection, reporting); transport ADDRESSES are
        # ranks 1..size-1. Position p in the list <-> rank p+1 (the
        # reference's rank<->real-id convention, fedml_server_manager.py:33).
        self.client_real_ids = _resolve_client_real_ids(args, size)
        self._rank_of_real_id = {
            rid: pos + 1 for pos, rid in enumerate(self.client_real_ids)
        }
        self.is_initialized = False
        from ...core.tracking import MetricsReporter, ProfilerEvent

        # reference instrumentation points (fedml_server_manager.py:
        # 71-74, :123-150: server.wait / aggregate spans + round info)
        self.profiler = ProfilerEvent(args)
        self.metrics_reporter = MetricsReporter(args, keep_history=False)
        # flight recorder + stall surface (core/telemetry.py): spans on
        # the shared timeline; round progress heartbeats for the
        # watchdog (self.telemetry comes from _ManagerBase)
        self.telemetry.attach_profiler(self.profiler)
        self.telemetry.maybe_start_watchdog(args)
        # pull-based exposition (core/telemetry.py MetricsServer): live
        # /metrics scrape endpoint for the run, off unless metrics_port
        self.telemetry.maybe_start_metrics_server(args)
        # on-demand per-round device profiling (core/tracing.py)
        from ...core.tracing import RoundProfiler

        self._round_profiler = RoundProfiler(args)
        # live critical-path attribution (docs/observability.md): per
        # round the server observes broadcast/wait/aggregate segments,
        # straggler slack (who held the round and by how much), and SLO
        # violations against round_deadline_s — the offline analyzer
        # (cli trace) computes the precise cross-process version
        self.round_deadline_s = float(
            getattr(args, "round_deadline_s", 0) or 0
        )
        self._bcast_t0 = None  # perf_counter at round broadcast start
        self._bcast_done_t = None
        # perf_counter at the previous round's ledger close: the
        # close->broadcast gap is the server's inter-round idle
        # (round_idle_seconds{gap=close_to_broadcast})
        self._last_round_close_t = None
        self._upload_arrivals: Dict[int, float] = {}
        self._upload_train_s: Dict[int, float] = {}
        self._round_span_open = False
        self._wait_open = False
        self.deadline_s = float(getattr(args, "aggregation_deadline_s", 0) or 0)
        self._deadline_timer = None
        self.stragglers_dropped = 0
        # streaming-aggregation round close (beyond the reference):
        # quorum + grace; timers post loopback messages, never mutate
        self.agg_mode = str(getattr(args, "agg_mode", "stream"))
        self.quorum_frac = float(getattr(args, "round_quorum_frac", 0.0) or 0.0)
        self.round_grace_s = float(getattr(args, "round_grace_s", 0.0) or 0.0)
        self._quorum_timer = None
        self._quorum_armed_round = None
        self.quorum_closes = 0
        # async (FedBuff-style) state — see the class docstring
        self.staleness_decay = float(getattr(args, "staleness_decay", 0.5))
        self.staleness_max = int(getattr(args, "staleness_max", 10))
        self.async_publish_every = int(getattr(args, "async_publish_every", 4))
        self.version = 0  # publish counter (the model version clients see)
        self._dispatch_seq = 0  # monotone per-dispatch id, never reused
        # folded pairs whose WAL record could not be written (disk
        # error): carried into the next successful record so the
        # ledger never under-covers the checkpointed params
        self._unwaled_folds = []
        # rank -> (seq, base_version, silo_idx) of its in-flight dispatch
        self._outstanding: Dict[int, tuple] = {}
        self._folded_ids = set()  # (rank, seq) ever folded (WAL-seeded)
        self._folded_since_publish = []
        self.async_folds = 0  # folds across incarnations (target counter)
        # (rank, seq, staleness, sample_num, weight) — the bench checks
        # these against the staleness_weight unit oracle
        self.async_weight_log = []
        # zero-upload deadline handling: rebroadcast (the downlink may
        # have been lost) at most this many times per round, then shut
        # down instead of extending forever
        _max_ext = getattr(args, "aggregation_deadline_max_extensions", None)
        self.deadline_max_extensions = 3 if _max_ext is None else int(_max_ext)
        self._empty_deadline_fires = 0
        self._last_broadcast_type = None
        self.elastic = bool(getattr(args, "elastic_membership", False))
        if self.elastic and getattr(args, "client_id_list", None):
            raise ValueError(
                "elastic_membership assigns real ids dynamically (rank = "
                "id); it cannot be combined with a fixed client_id_list"
            )
        self.joins = 0
        self.leaves = 0
        # failure detector (core/comm/heartbeat.py): declared-dead
        # ranks are excluded from broadcasts until they reconnect
        self.deaths = 0
        self._dead_ranks = set()
        # rank -> silo index of the CURRENT round's broadcast; the
        # reconnect path resyncs a reappearing rank into its pending slot
        self._round_assignment: Dict[int, int] = {}
        self._failure_detector = None
        timeout_s = float(getattr(args, "heartbeat_timeout_s", 0.0) or 0.0)
        if timeout_s > 0:
            from ...core.comm.heartbeat import FailureDetector

            self._failure_detector = FailureDetector(
                timeout_s, self._post_client_dead
            ).start()
        from ...core.compression import make_codec

        # compressed-uplink decode (core/compression.py): clients ship
        # encoded deltas; reconstruct against the pre-round global tree
        self._codec = make_codec(args)
        # checkpoint/resume (core/checkpoint.py — beyond the reference,
        # which loses the whole federation when the server dies): save
        # {params, round} after aggregation; on construction, restore
        # the latest state so a restarted server resumes mid-federation.
        # Clients are stateless between rounds (they receive the model
        # with every broadcast), so server-side state is sufficient.
        self._ckpt = None
        self._wal = None
        self._resumed = False
        ckpt_dir = getattr(args, "checkpoint_dir", None)
        if ckpt_dir:
            from ...core.checkpoint import RoundCheckpointer, RoundWAL

            self._ckpt = RoundCheckpointer(ckpt_dir)
            self._wal = RoundWAL(ckpt_dir)
            # None = this scenario's historical cadence (every round)
            self._ckpt_freq = max(
                1, int(getattr(args, "checkpoint_freq", None) or 1)
            )
            state = self._ckpt.restore()
            if state is not None:
                import jax

                self.round_idx = int(state["round_idx"])
                self.aggregator.set_global_model_params(
                    jax.device_put(state["params"], jax.devices()[0])
                )
                # the aggregation counter seeds the L3 server
                # aggregator's per-round rng stream — without it a
                # resumed custom aggregator would silently replay
                # round 0's randomness
                self.aggregator._agg_round = int(
                    state.get("agg_round", self.round_idx)
                )
                self._resumed = True
                logging.info(
                    "cross-silo server resumed at round %d from %s",
                    self.round_idx, ckpt_dir,
                )
                # PR 10's pinned pre-existing race: init used to wait
                # for ALL ranks to re-announce, but a client killed
                # BEFORE the server crash never will — its heartbeats
                # died with it. Arm the failure detector over every
                # expected rank NOW: survivors' beats/ONLINEs refresh
                # the watch; a rank silent past heartbeat_timeout_s is
                # declared dead pre-init and leaves the awaited set
                # (_ready_to_init). Without a detector the resumed
                # server keeps the reference behavior (wait for all).
                if self._failure_detector is not None:
                    for r in range(1, len(self.client_real_ids) + 1):
                        self._failure_detector.watch(r)
                if self.agg_mode == "async":
                    # version/seq/fold counters ride the checkpoint;
                    # the WAL's publish records are the exactly-once
                    # fold ledger a restart must not forget (the
                    # sync-mode retrain cross-check below does not
                    # apply — async never retrains; lost publishes are
                    # reported by _seed_async_ledger_from_wal instead)
                    self.version = int(state.get("version", self.round_idx))
                    self._dispatch_seq = int(state.get("dispatch_seq", 0))
                    self.async_folds = int(state.get("async_folds", 0))
                    self._seed_async_ledger_from_wal()
                else:
                    # WAL cross-check: with checkpoint_freq > 1 the
                    # last COMPLETED round can be ahead of the newest
                    # restorable params — those rounds retrain after
                    # the restart; say so loudly instead of silently
                    # repeating work
                    last = self._wal.last()
                    if (
                        last is not None
                        and int(last["round_idx"]) + 1 > self.round_idx
                    ):
                        logging.warning(
                            "round WAL shows round %d completed but newest "
                            "checkpoint resumes at round %d — %d round(s) "
                            "will retrain (checkpoint_freq=%d)",
                            int(last["round_idx"]), self.round_idx,
                            int(last["round_idx"]) + 1 - self.round_idx,
                            self._ckpt_freq,
                        )

    # -- handlers ------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            constants.MSG_TYPE_C2S_CLIENT_STATUS,
            self.handle_message_client_status_update,
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self.handle_message_receive_model_from_client,
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_S2S_AGG_DEADLINE,
            self.handle_message_deadline,
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_S2S_QUORUM_GRACE,
            self.handle_message_quorum_grace,
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_C2S_HEARTBEAT,
            self.handle_message_heartbeat,
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_S2S_CLIENT_DEAD,
            self.handle_message_client_dead,
        )

    def receive_message(self, msg_type: int, msg_params: Message) -> None:
        # ANY inbound traffic proves the sender alive — uploads and
        # status changes carry liveness as well as heartbeats do
        if self._failure_detector is not None:
            sender = int(msg_params.get_sender_id())
            if sender != self.rank:
                self._failure_detector.note_alive(sender)
        super().receive_message(msg_type, msg_params)

    def _active_ranks(self):
        return [r for r, on in sorted(self.client_online_status.items()) if on]

    def handle_message_client_status_update(self, msg: Message) -> None:
        """(fedml_server_manager.py:95-119) + elastic join/leave."""
        status = msg.get(constants.MSG_ARG_KEY_CLIENT_STATUS)
        sender = int(msg.get_sender_id())
        if status == constants.CLIENT_STATUS_ONLINE:
            known = 1 <= sender <= len(self.client_real_ids)
            if not known:
                if not self.elastic:
                    logging.warning(
                        "ONLINE from unknown rank %d ignored (set "
                        "elastic_membership to accept joins)", sender,
                    )
                    return
                max_clients = int(getattr(self.args, "max_clients", 4096))
                if sender < 1 or sender > max_clients:
                    # one misconfigured hello must not bloat server
                    # state with ghost ranks
                    logging.error(
                        "ONLINE from rank %d rejected (max_clients=%d)",
                        sender, max_clients,
                    )
                    return
                # register ranks up to the newcomer (real id = rank)
                for r in range(len(self.client_real_ids) + 1, sender + 1):
                    self.client_real_ids.append(r)
                    self._rank_of_real_id[r] = r
            was_online = self.client_online_status.get(sender, False)
            self.client_online_status[sender] = True
            self._dead_ranks.discard(sender)
            if self._failure_detector is not None:
                self._failure_detector.watch(sender)
            if self.is_initialized:
                if self.elastic and not was_online:
                    self.joins += 1
                    logging.info(
                        "elastic join: rank %d online at round %d "
                        "(participates from the next broadcast)",
                        sender, self.round_idx,
                    )
                # resync regardless of was_online: a kill -9'd client's
                # replacement re-announces ONLINE while the server may
                # not yet have noticed the death — if its slot in the
                # current round is still pending, ship it the round
                # (re-training a slot whose upload later turns out to
                # have landed is idempotent by design)
                self._maybe_resync(sender)
                return
            self._maybe_init()
        elif status == constants.CLIENT_STATUS_OFFLINE:
            if not self.elastic:
                logging.warning("OFFLINE from rank %d ignored (non-elastic)", sender)
                return
            if not self.client_online_status.get(sender, False):
                return  # duplicated/stale OFFLINE: already gone, count once
            self.client_online_status[sender] = False
            if self._failure_detector is not None:
                self._failure_detector.unwatch(sender)
            self.leaves += 1
            # counted so the invariant checker can account a partial
            # round close to a voluntary leave from artifacts alone
            self.telemetry.inc("cross_silo_client_leaves_total")
            logging.info(
                "elastic leave: rank %d offline at round %d", sender, self.round_idx
            )
            if self.agg_mode == "async":
                self._async_client_gone(sender)
                return
            if self.is_initialized and self.aggregator.drop_expected(sender - 1):
                # the round was only waiting on the leaver
                if self.aggregator.check_whether_all_receive():
                    self._finish_round()
                else:
                    # the leaver also shrank the quorum denominator
                    self._maybe_arm_quorum()

    def _ready_to_init(self) -> bool:
        """The presence handshake's readiness predicate. Non-elastic:
        every expected rank must be online — EXCEPT ranks the failure
        detector has declared dead (a client killed before a server
        crash never re-announces; a resumed server must not await a
        corpse — the PR 10 pinned race). An all-dead world is
        vacuously ready: init falls through to the loud
        no-online-clients finish instead of blocking forever."""
        if self.elastic:
            return len(self._active_ranks()) >= int(
                self.args.client_num_per_round
            )
        return all(
            self.client_online_status.get(rank, False)
            for rank in range(1, len(self.client_real_ids) + 1)
            if rank not in self._dead_ranks
        )

    def _maybe_init(self) -> None:
        if not self.is_initialized and self._ready_to_init():
            self.is_initialized = True
            self.send_init_msg()

    # -- liveness / failure detection (beyond the reference) ----------
    def handle_message_heartbeat(self, msg: Message) -> None:
        """A beat from an unknown-or-offline rank is an implicit ONLINE:
        after a server restart the clients' ONLINE messages are long
        gone, and their periodic beats are what re-announces presence
        (liveness itself was already noted in ``receive_message``)."""
        sender = int(msg.get_sender_id())
        if not self.client_online_status.get(sender, False):
            synth = Message(
                constants.MSG_TYPE_C2S_CLIENT_STATUS, sender, self.rank
            )
            synth.add_params(
                constants.MSG_ARG_KEY_CLIENT_STATUS,
                constants.CLIENT_STATUS_ONLINE,
            )
            logging.info(
                "heartbeat from rank %d not currently online: treating "
                "as (re)connect", sender,
            )
            self.handle_message_client_status_update(synth)

    def _post_loopback(self, msg: Message, what: str, stale=None) -> bool:
        """Post a self-addressed control message with bounded retry —
        shared by every timer/detector thread that must reach the
        dispatch thread (a silently lost control signal re-creates the
        stall these features exist to prevent). ``stale()`` aborts the
        retry when the signal is no longer needed. True = delivered
        (or stale); False = the caller must arrange a re-fire."""
        import time as _time

        for attempt in range(3):
            try:
                self.send_message(msg)
                return True
            except Exception:  # noqa: BLE001 — transport may be flaky/tearing down
                if stale is not None and stale():
                    return True
                logging.warning(
                    "%s send failed (attempt %d/3)",
                    what, attempt + 1, exc_info=True,
                )
                _time.sleep(1.0)
        return False

    def _post_client_dead(self, rank: int) -> None:
        """FailureDetector ``on_dead`` callback (detector thread): post
        to our own inbox so membership mutation stays on the dispatch
        thread — the deadline-timer pattern, including its retry: the
        declaration is one-shot (the detector unwatches before firing).
        If the send ultimately fails, re-watch the rank so the detector
        re-fires after another timeout instead of never."""
        msg = Message(constants.MSG_TYPE_S2S_CLIENT_DEAD, self.rank, self.rank)
        msg.add_params(constants.MSG_ARG_KEY_RANK, int(rank))
        if not self._post_loopback(msg, f"death notice for rank {rank}"):
            logging.error(
                "failure detector: could not post death of rank %d; "
                "re-arming the watch so it is re-declared", rank,
            )
            if self._failure_detector is not None:
                self._failure_detector.watch(rank)

    def handle_message_client_dead(self, msg: Message) -> None:
        rank = int(msg.get(constants.MSG_ARG_KEY_RANK, -1))
        if (
            self._failure_detector is not None
            and self._failure_detector.seen_recently(rank)
        ):
            # raced: a message from this rank was queued behind the
            # death notice — it is alive after all
            self._failure_detector.watch(rank)
            return
        if not self.client_online_status.get(rank, False):
            if self.is_initialized or rank in self._dead_ranks:
                return  # already offline/dead; stale declaration
            # pre-init death on a RESUMED server (__init__ armed the
            # detector over every expected rank): this rank was killed
            # before the crash and will never re-announce — stop
            # awaiting it, and re-check whether the survivors complete
            # the handshake (the PR 10 pinned async-restart race)
            self._dead_ranks.add(rank)
            self.deaths += 1
            self.telemetry.inc("cross_silo_clients_declared_dead_total")
            logging.warning(
                "rank %d declared DEAD before init (no reconnect since "
                "the server restart); init proceeds without it", rank,
            )
            self._maybe_init()
            return
        self.client_online_status[rank] = False
        self._dead_ranks.add(rank)
        self.deaths += 1
        self.telemetry.inc("cross_silo_clients_declared_dead_total")
        logging.warning(
            "rank %d declared DEAD at round %d (no traffic for %.1fs); "
            "dropping from the current round and future broadcasts "
            "until it reconnects",
            rank, self.round_idx,
            self._failure_detector.timeout_s if self._failure_detector else 0.0,
        )
        if self.agg_mode == "async":
            self._async_client_gone(rank)
            return
        # same unstall path as an elastic OFFLINE leave — works with or
        # without elastic membership (a crash is not a voluntary leave)
        if self.is_initialized and self.aggregator.drop_expected(rank - 1):
            if self.aggregator.check_whether_all_receive():
                self._finish_round()
            else:
                # quorum accounting consults the failure detector: a
                # dead rank leaves the denominator, so a quorum that
                # was one corpse short arms its grace timer now
                self._maybe_arm_quorum()
        elif not self.is_initialized:
            # an announced-then-killed rank must not stall the
            # handshake either: the survivors may now complete it
            self._maybe_init()

    def _async_client_gone(self, rank: int) -> None:
        """A dead/left rank in async mode: retire its in-flight
        dispatch (a reconnect gets fresh work via RESYNC), and if
        NOBODY is left to fold from, shut down loudly — async's only
        finish path is an upload, so an empty federation would
        otherwise hang forever (the sync path's empty-broadcast
        shutdown has no async equivalent)."""
        self._outstanding.pop(rank, None)
        if self.is_initialized and not self._active_ranks():
            logging.error(
                "async: no online clients remain (%d/%d folds done); "
                "finishing", self.async_folds, self._async_target_folds(),
            )
            # accepted-but-unpublished folds must reach the model and
            # the WAL ledger before the shutdown (the fold-target
            # finish path flushes the same way)
            self._async_publish()
            self.send_finish()
            self.finish()
            return
        # the death may have left only QUARANTINED ranks online — no
        # fold (and therefore no publish, no probation tick) can ever
        # arrive, so the stall check must run here too
        self._async_check_quarantine_stall()

    def _async_check_quarantine_stall(self) -> None:
        """Async liveness under quarantine: folds are the only progress
        signal, and probation ticks ride publishes (which ride folds).
        If every online rank is quarantined and nothing is outstanding,
        no fold can ever arrive — finish loudly instead of hanging (the
        sync path has no analog: its rounds close via drop_expected)."""
        online = set(self._active_ranks())
        quarantined = self.aggregator.quarantined_ranks()
        if (
            self.is_initialized
            and online
            and not (online - quarantined)
            and not self._outstanding
        ):
            logging.error(
                "async: every online client is quarantined (%s) with no "
                "work outstanding (%d/%d folds done); finishing",
                sorted(quarantined), self.async_folds,
                self._async_target_folds(),
            )
            # flush accepted-but-unpublished folds, then record the
            # terminal eval like the fold-target done path does. The
            # publish's probation tick may hand a just-released rank
            # one dispatch the FINISH right behind it abandons — a
            # wasted local round, never wrong state.
            self._async_publish()
            self.aggregator.test_on_server_for_all_clients(self.version)
            self.send_finish()
            self.finish()

    def _maybe_resync(self, rank: int) -> None:
        """Ship the CURRENT round + params + pending assignment to a
        rank that (re)appeared mid-round — a restarted client resumes
        the round instead of stalling it until detector/deadline."""
        if self.agg_mode == "async":
            if self.aggregator.screen.is_quarantined(rank - 1):
                # no fresh work for a quarantined rank; if it is now
                # the ONLY rank left, the federation must finish loudly
                # rather than wait for a fold that cannot come
                self._async_check_quarantine_stall()
                return
            # async reconnect: hand the rank fresh work at the current
            # version (a fresh seq supersedes any pre-crash dispatch,
            # so its in-flight upload — if any — discards cleanly)
            logging.info("RESYNC (async): dispatching rank %d fresh work", rank)
            self.telemetry.inc("cross_silo_resyncs_total")
            self._async_dispatch(rank, constants.MSG_TYPE_S2C_RESYNC)
            return
        silo_idx = self._round_assignment.get(rank)
        if silo_idx is None:
            return  # not part of the current round; next broadcast picks it up
        if self.aggregator.flag_client_model_uploaded_dict.get(rank - 1, False):
            return  # its upload already landed; nothing to redo
        logging.info(
            "RESYNC: rank %d rejoins round %d (silo %d)",
            rank, self.round_idx, silo_idx,
        )
        self.telemetry.inc("cross_silo_resyncs_total")
        msg = Message(constants.MSG_TYPE_S2C_RESYNC, self.rank, rank)
        msg.add_params(
            constants.MSG_ARG_KEY_MODEL_PARAMS,
            self.aggregator.get_global_model_params(),
        )
        msg.add_params(constants.MSG_ARG_KEY_CLIENT_INDEX, silo_idx)
        msg.add_params(constants.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
        self.send_message(msg)

    def send_init_msg(self) -> None:
        """(fedml_server_manager.py:47-69)"""
        if self.agg_mode == "async":
            if self.async_folds >= self._async_target_folds():
                # resumed past the fold target: release clients cleanly
                logging.info(
                    "async resume: %d folds already done (target %d); "
                    "finishing", self.async_folds, self._async_target_folds(),
                )
                self.aggregator.test_on_server_for_all_clients(self.version)
                self.send_finish()
                self.finish()
                return
            self._async_begin(
                constants.MSG_TYPE_S2C_RESYNC
                if self._resumed
                else constants.MSG_TYPE_S2C_INIT_CONFIG
            )
            return
        if self.round_idx >= self.round_num:
            # resumed from a checkpoint taken at/after the final round:
            # nothing left to train, release the freshly-connected
            # clients instead of broadcasting a round past the end. The
            # pre-crash process may have died between its final save
            # and its final eval, so produce the terminal eval here.
            logging.info(
                "resumed at round %d >= comm_round %d; finishing",
                self.round_idx, self.round_num,
            )
            self.aggregator.test_on_server_for_all_clients(self.round_num - 1)
            self.send_finish()
            self.finish()
            return
        if self._resumed:
            # crash recovery: reconnecting clients get the CURRENT
            # round + params as a RESYNC — same payload as an init, but
            # the type says "mid-federation", not "round 0"
            logging.info(
                "resumed server releasing clients with RESYNC at round %d",
                self.round_idx,
            )
            self._broadcast_model(constants.MSG_TYPE_S2C_RESYNC)
            return
        self._broadcast_model(constants.MSG_TYPE_S2C_INIT_CONFIG)

    def _broadcast_model(self, msg_type: str) -> None:
        """Selection + model broadcast shared by init and per-round sync
        (fedml_server_manager.py:47-69 and :167-207): pick which edge
        ranks participate (``client_selection``), map them onto data-silo
        indices (``data_silo_selection``), send the global model."""
        # quarantined ranks sit out entire cohorts until their
        # probation expires (docs/robustness.md quarantine lifecycle) —
        # excluded here exactly like detector-declared-dead ranks
        quarantined = self.aggregator.quarantined_ranks()
        self.telemetry.set_gauge("defense_quarantined_now", len(quarantined))
        if self.elastic:
            # membership is whoever is online right now; selection caps
            # at client_num_per_round of them
            candidate_ids = [
                self.client_real_ids[r - 1]
                for r in self._active_ranks()
                if r not in quarantined
            ]
            n_select = min(
                int(self.args.client_num_per_round), len(candidate_ids)
            )
        else:
            # fixed membership still excludes detector-declared-dead
            # ranks: broadcasting to a corpse re-stalls every round
            # (a reconnect clears the rank from the dead set)
            candidate_ids = [
                rid
                for rid in self.client_real_ids
                if self._rank_of_real_id[rid] not in self._dead_ranks
                and self._rank_of_real_id[rid] not in quarantined
            ]
            n_select = len(candidate_ids)
        # named chaos barrier: a scheduled kill_server here models a
        # death between round close and the next broadcast
        chaos_barrier("server.broadcast", round=self.round_idx, rank=self.rank)
        selected_real_ids = self.aggregator.client_selection(
            self.round_idx, candidate_ids, n_select
        )
        silo_indexes = self.aggregator.data_silo_selection(
            self.round_idx,
            int(self.args.client_num_in_total),
            len(selected_real_ids),
        )
        if not selected_real_ids:
            # an empty federation cannot progress; shut down loudly
            # instead of blocking forever on an inbox nobody feeds
            logging.error(
                "round %d: no online clients to broadcast to; finishing",
                self.round_idx,
            )
            self.send_finish()
            self.finish()
            return
        self._last_broadcast_type = msg_type
        global_params = self.aggregator.get_global_model_params()
        import time as _time

        self._round_profiler.tick(self.round_idx)
        if not self._round_span_open:
            # one flight-recorder span per round, broadcast -> aggregate
            # end (a zero-upload rebroadcast extends the same round)
            self.telemetry.recorder.begin(
                "cross_silo.round", cat="round", round=self.round_idx
            )
            self._round_span_open = True
        self._bcast_t0 = _time.perf_counter()
        self._upload_arrivals = {}
        self._upload_train_s = {}
        expected = []
        self._round_assignment = {}
        for real_id, silo_idx in zip(selected_real_ids, silo_indexes):
            rank = self._rank_of_real_id[real_id]
            expected.append(rank - 1)
            self._round_assignment[rank] = silo_idx
            msg = Message(msg_type, self.rank, rank)
            msg.add_params(constants.MSG_ARG_KEY_MODEL_PARAMS, global_params)
            msg.add_params(constants.MSG_ARG_KEY_CLIENT_INDEX, silo_idx)
            msg.add_params(constants.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
            self.send_message(msg)
        self._bcast_done_t = _time.perf_counter()
        self.aggregator.begin_round(expected)
        self._arm_deadline()

    # -- deadline cohort (beyond the reference) -----------------------
    def _arm_deadline(self) -> None:
        if self.deadline_s <= 0:
            return
        import threading

        round_idx = self.round_idx

        def fire() -> None:
            # post to our own inbox; never mutate from the timer thread.
            # A lost deadline message re-creates the straggler hang this
            # feature exists to prevent, so transient send failures are
            # retried (shared _post_loopback policy) and logged loudly.
            msg = Message(constants.MSG_TYPE_S2S_AGG_DEADLINE, self.rank, self.rank)
            msg.add_params(constants.MSG_ARG_KEY_ROUND_INDEX, round_idx)
            if not self._post_loopback(
                msg, "deadline message",
                stale=lambda: round_idx != self.round_idx,
            ):
                logging.error(
                    "deadline for round %d could not be delivered; the round "
                    "will only advance when all clients report", round_idx,
                )

        self._deadline_timer = threading.Timer(self.deadline_s, fire)
        self._deadline_timer.daemon = True
        self._deadline_timer.start()

    def _cancel_deadline(self) -> None:
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()
            self._deadline_timer = None

    def handle_message_deadline(self, msg: Message) -> None:
        fired_round = int(msg.get(constants.MSG_ARG_KEY_ROUND_INDEX, -1))
        if fired_round != self.round_idx:
            return  # the round completed in time; stale timer
        n = self.aggregator.num_received()
        if n == 0:
            # There is nothing to aggregate, so extending alone can
            # livelock (e.g. a correlated fault ate every uplink, or
            # the downlink itself was lost and nobody is training).
            # Rebroadcast the round — _broadcast_model re-runs
            # selection, resends the model and re-arms the deadline —
            # a bounded number of times, then shut down loudly.
            self._empty_deadline_fires += 1
            if self._empty_deadline_fires > self.deadline_max_extensions:
                logging.error(
                    "round %d: %d deadline(s) of %.1fs elapsed with ZERO "
                    "uploads; giving up (aggregation_deadline_max_extensions=%d)",
                    self.round_idx, self._empty_deadline_fires - 1,
                    self.deadline_s, self.deadline_max_extensions,
                )
                self.send_finish()
                self.finish()
                return
            logging.warning(
                "round %d deadline (%.1fs) with ZERO uploads; rebroadcasting "
                "(extension %d/%d)",
                self.round_idx, self.deadline_s,
                self._empty_deadline_fires, self.deadline_max_extensions,
            )
            self._broadcast_model(self._last_broadcast_type)
            return
        self._empty_deadline_fires = 0
        expected = self.aggregator.client_num  # per-round cohort size
        missing = max(expected - n, 0)
        self.stragglers_dropped += missing
        logging.warning(
            "round %d deadline: aggregating %d/%d clients (%d straggler(s) dropped)",
            self.round_idx, n, expected, missing,
        )
        self._finish_round()

    def _extract_upload_payload(self, msg: Message, sender_rank: int):
        """Validate an upload's payload against the server codec and
        return ``(model_params, encoded)`` (exactly one set), or None
        after shutting the federation down on a fatal config mismatch.
        Neither is decoded here — the streaming fold decodes inside its
        fused jitted step; the buffered path decodes at aggregate."""
        model_params = msg.get(constants.MSG_ARG_KEY_MODEL_PARAMS)
        if model_params is not None:
            if self._codec is not None:
                logging.warning(
                    "server has compression=%s but rank %d uploaded full "
                    "model_params; aggregating it, but the uplink is NOT "
                    "compressed — check the client config",
                    self.args.compression,
                    sender_rank,
                )
            return model_params, None
        encoded = msg.get(constants.MSG_ARG_KEY_MODEL_DELTA)
        if encoded is None:
            mismatch = "carries neither model_params nor model_delta"
        elif self._codec is None:
            mismatch = "is compressed but server has compression=none"
        else:
            mismatch = self._codec_mismatch(encoded)
        if mismatch:
            self._fatal_payload_mismatch(sender_rank, mismatch)
            return None
        return None, encoded

    def _codec_mismatch(self, encoded) -> "str | None":
        """Does this wire payload fit the server codec? (shared by the
        sync and async upload paths)."""
        from ...core.compression import payload_matches_codec

        if not payload_matches_codec(self._codec, encoded):
            return (
                f"payload does not match server codec "
                f"'{self._codec.name}' (int8 vs topk skew)"
            )
        return None

    def _fatal_payload_mismatch(self, sender_rank: int, mismatch: str) -> None:
        """Config mismatch is fatal but must not strand clients: shut
        the federation down cleanly (same pattern as the
        no-online-clients path in _broadcast_model)."""
        logging.error(
            "rank %d upload %s; configure args.compression (and agg_mode) "
            "identically on server and clients — finishing run",
            sender_rank,
            mismatch,
        )
        self.send_finish()
        self.finish()

    def handle_message_receive_model_from_client(self, msg: Message) -> None:
        """(fedml_server_manager.py:121-207)"""
        sender_rank = int(msg.get_sender_id())
        if self.agg_mode == "async":
            self._handle_async_upload(msg, sender_rank)
            return
        upload_round = int(msg.get(constants.MSG_ARG_KEY_ROUND_INDEX, self.round_idx))
        if upload_round != self.round_idx:
            logging.warning(
                "discarding straggler upload from rank %d for round %d "
                "(now on round %d)", sender_rank, upload_round, self.round_idx,
            )
            self.telemetry.inc("agg_late_uploads_total")
            return
        import time as _time

        # straggler analytics: when each upload landed and how much of
        # that was the client's own training (self-reported). FIRST
        # arrival wins — a network-duplicated copy of a fast client's
        # upload landing late must not rename the straggler (the same
        # rule the offline analyzer applies to duplicate flows)
        if sender_rank not in self._upload_arrivals:
            self._upload_arrivals[sender_rank] = _time.perf_counter()
            reported_train_s = msg.get(constants.MSG_ARG_KEY_TRAIN_SECONDS)
            if reported_train_s is not None:
                self._upload_train_s[sender_rank] = float(reported_train_s)
        payload = self._extract_upload_payload(msg, sender_rank)
        if payload is None:
            return
        model_params, encoded = payload
        local_sample_num = msg.get(constants.MSG_ARG_KEY_NUM_SAMPLES)
        # streaming (agg_mode=stream): folded into the running
        # accumulator RIGHT NOW — the straggler-wait window does the
        # aggregation work, and quantized payloads decode inside the
        # fold's fused jit. Buffered/fallback: stored until close.
        status = self.aggregator.receive_upload(
            sender_rank - 1,
            local_sample_num,
            model_params=model_params,
            encoded=encoded,
        )
        if status == "quarantined":
            # the anomaly screen rejected this upload BEFORE folding.
            # The rank must not stall the round either: drop its
            # pending slot exactly like a failure-detector death, so
            # the quorum denominator shrinks and the grace timer can
            # arm/close over the survivors.
            logging.warning(
                "round %d: upload from quarantined rank %d rejected; "
                "dropping its slot from the round",
                self.round_idx, sender_rank,
            )
            if self.is_initialized and self.aggregator.drop_expected(
                sender_rank - 1
            ):
                if self.aggregator.check_whether_all_receive():
                    self._finish_round()
                    return
                self._maybe_arm_quorum()
            return
        # post-restart in-flight uploads: the PREVIOUS incarnation
        # broadcast this round, so a just-restarted server can receive
        # (and fold) round-tagged uploads before it ever re-broadcasts.
        # Record the sender into the round's cohort — the WAL's
        # folded ⊆ cohort invariant is about membership, not about
        # which incarnation did the broadcasting. Recorded only once
        # the upload is ACCEPTED (past the payload and quarantine
        # rejections): a rejected sender must stay resync-eligible,
        # and a silo of -1 here is safe because the accept sets the
        # rank's uploaded flag, which short-circuits _maybe_resync
        self._round_assignment.setdefault(sender_rank, -1)
        if not self._wait_open:
            self.profiler.log_event_started("server.wait")
            self._wait_open = True
        if self.aggregator.check_whether_all_receive():
            self._finish_round()
            return
        self._maybe_arm_quorum()

    # -- quorum round close (streaming tentpole) ----------------------
    def _maybe_arm_quorum(self) -> None:
        """Arm the grace timer the first time the current round's
        folded count reaches quorum. The denominator is the LIVE
        cohort: ``drop_expected`` (elastic leaves, failure-detector
        deaths) shrinks it, so this is re-checked from those paths too
        — a declared-dead rank can tip an already-arrived quorum into
        arming instead of waiting on a corpse."""
        if (
            self.quorum_frac <= 0
            or not self.is_initialized
            or self._quorum_armed_round == self.round_idx
            or not self.aggregator.quorum_met(self.quorum_frac)
        ):
            return
        import threading

        self._quorum_armed_round = self.round_idx
        round_idx = self.round_idx
        n = self.aggregator.num_received()
        logging.info(
            "round %d: quorum reached (%d/%d folded >= target %d); "
            "grace %.2fs for the rest",
            round_idx, n, self.aggregator.client_num,
            self.aggregator.quorum_target(self.quorum_frac),
            self.round_grace_s,
        )

        def fire() -> None:
            msg = Message(
                constants.MSG_TYPE_S2S_QUORUM_GRACE, self.rank, self.rank
            )
            msg.add_params(constants.MSG_ARG_KEY_ROUND_INDEX, round_idx)
            self._post_loopback(
                msg, "quorum grace message",
                stale=lambda: round_idx != self.round_idx,
            )

        self._quorum_timer = threading.Timer(self.round_grace_s, fire)
        self._quorum_timer.daemon = True
        self._quorum_timer.start()

    def _cancel_quorum(self) -> None:
        if self._quorum_timer is not None:
            self._quorum_timer.cancel()
            self._quorum_timer = None
        self._quorum_armed_round = None

    def handle_message_quorum_grace(self, msg: Message) -> None:
        fired_round = int(msg.get(constants.MSG_ARG_KEY_ROUND_INDEX, -1))
        if fired_round != self.round_idx:
            return  # the round completed in time; stale timer
        n = self.aggregator.num_received()
        expected = self.aggregator.client_num
        missing = max(expected - n, 0)
        if n == 0:
            return  # cannot happen (armed only after a fold) — guard anyway
        if missing:
            ages = {}
            for idx in self.aggregator.missing_indexes():
                rank = idx + 1
                age = (
                    self._failure_detector.last_seen_age_s(rank)
                    if self._failure_detector is not None
                    else None
                )
                ages[rank] = None if age is None else round(age, 2)
            self.stragglers_dropped += missing
            self.quorum_closes += 1
            self.telemetry.inc("agg_quorum_closes_total")
            logging.warning(
                "round %d quorum close: aggregating %d/%d clients after "
                "%.2fs grace (%d straggler(s) dropped; last seen ages %s)",
                self.round_idx, n, expected, self.round_grace_s, missing, ages,
            )
        self._finish_round()

    # -- async (FedBuff-style) aggregation (agg_mode=async) -----------
    def _async_target_folds(self) -> int:
        """Run length in folds: the async analog of comm_round — the
        federation finishes once comm_round x client_num_per_round
        updates have been accepted (discarded-stale ones don't count)."""
        return int(self.args.comm_round) * int(self.args.client_num_per_round)

    def _seed_async_ledger_from_wal(self) -> None:
        """Rebuild the exactly-once fold ledger after a restart: every
        WAL publish record's ``folded`` (rank, seq) pairs are already
        inside (or superseded with) the restored params, so a
        retransmitted pre-crash upload must never fold again. The WAL
        is written BEFORE the checkpoint (write-ahead), so the ledger
        can only over-cover — an upload may be dropped after a badly
        timed crash (its sender gets fresh work), but never folded
        twice. Publishes that made the WAL but not the checkpoint
        (every publish checkpoints, so that window is one publish) are
        reported LOUDLY: their folds' contributions are gone from the
        params and are not replayable."""
        ckpt_version = self.version  # what the restored params contain
        publishes = 0
        lost_folds = []
        for rec in self._wal.records():
            if rec.get("kind") != "publish":
                continue
            publishes += 1
            rec_version = int(rec.get("version", 0))
            for pair in rec.get("folded") or []:
                if isinstance(pair, (list, tuple)) and len(pair) == 2:
                    self._folded_ids.add((int(pair[0]), int(pair[1])))
                    if rec_version > ckpt_version:
                        lost_folds.append((int(pair[0]), int(pair[1])))
            self._dispatch_seq = max(
                self._dispatch_seq, int(rec.get("max_seq", 0))
            )
            self.async_folds = max(
                self.async_folds, int(rec.get("folds_total", 0))
            )
            self.version = max(self.version, rec_version)
        self.round_idx = self.version
        # new incarnation = new seq epoch: dispatches issued between
        # the last durable publish and the crash carried seqs above the
        # restored high-water mark; stepping to the next epoch band
        # guarantees none of them is ever reissued
        self._dispatch_seq = (self._dispatch_seq // _SEQ_EPOCH + 1) * _SEQ_EPOCH
        if lost_folds:
            # reported-lost counter: the InvariantChecker's
            # "no lost-but-unreported folds" invariant balances
            # accepted folds against ledgered + reported-lost
            self.telemetry.inc("agg_folds_lost_total", len(lost_folds))
            logging.warning(
                "async resume: %d fold(s) %s from publish(es) > version %d "
                "were write-ahead logged but their checkpoint never landed "
                "— those contributions are LOST (not replayable; their "
                "senders get fresh work). They stay in the dedup ledger so "
                "retransmits cannot half-apply them.",
                len(lost_folds), sorted(lost_folds), ckpt_version,
            )
        if publishes:
            logging.info(
                "async resume: %d publish record(s) seed a %d-entry fold "
                "ledger; version %d, %d/%d folds done, dispatch seq > %d",
                publishes, len(self._folded_ids), self.version,
                self.async_folds, self._async_target_folds(),
                self._dispatch_seq,
            )

    def _async_begin(self, msg_type: str) -> None:
        """Initial (or post-restart) dispatch: every online rank gets
        the current model + a fresh seq. No barrier ever forms — each
        upload triggers that rank's next dispatch."""
        ranks = self._active_ranks()
        if not ranks:
            logging.error("async: no online clients to dispatch; finishing")
            self.send_finish()
            self.finish()
            return
        silos = self.aggregator.data_silo_selection(
            0, int(self.args.client_num_in_total), len(ranks)
        )
        for r, s in zip(ranks, silos):
            self._round_assignment.setdefault(r, s)
        logging.info(
            "async federation: dispatching %d clients (target %d folds, "
            "publish every %d, staleness decay %.3g cap %d)",
            len(ranks), self._async_target_folds(), self.async_publish_every,
            self.staleness_decay, self.staleness_max,
        )
        for r in ranks:
            self._async_dispatch(r, msg_type)

    def _async_dispatch(
        self,
        rank: int,
        msg_type: str = constants.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
    ) -> None:
        if not self.client_online_status.get(rank, False):
            return  # nothing to hand a rank that is not there
        self._dispatch_seq += 1
        seq = self._dispatch_seq
        silo = self._round_assignment.get(
            rank, (rank - 1) % max(int(self.args.client_num_in_total), 1)
        )
        self._round_assignment[rank] = silo
        # one outstanding dispatch per rank; overwriting supersedes any
        # in-flight predecessor (its upload will fail the seq check)
        self._outstanding[rank] = (seq, self.version, silo)
        msg = Message(msg_type, self.rank, rank)
        msg.add_params(
            constants.MSG_ARG_KEY_MODEL_PARAMS,
            self.aggregator.get_global_model_params(),
        )
        msg.add_params(constants.MSG_ARG_KEY_CLIENT_INDEX, silo)
        msg.add_params(constants.MSG_ARG_KEY_ROUND_INDEX, seq)
        msg.add_params(constants.MSG_ARG_KEY_MODEL_VERSION, self.version)
        self.send_message(msg)

    def _handle_async_upload(self, msg: Message, sender_rank: int) -> None:
        seq = int(msg.get(constants.MSG_ARG_KEY_ROUND_INDEX, -1))
        if (sender_rank, seq) in self._folded_ids:
            # retransmit of an upload that already folded (possibly
            # before a server restart — the WAL ledger remembers)
            self.telemetry.inc("agg_async_superseded_total", reason="dup")
            return
        out = self._outstanding.get(sender_rank)
        if out is None or out[0] != seq:
            # not this rank's in-flight dispatch: a duplicate raced its
            # redispatch, or a pre-crash upload whose work was reissued
            self.telemetry.inc("agg_async_superseded_total", reason="superseded")
            logging.info(
                "async: discarding superseded upload from rank %d (seq %d)",
                sender_rank, seq,
            )
            return
        _seq, base_version, _silo = out
        payload = msg.get(constants.MSG_ARG_KEY_MODEL_DELTA)
        if payload is None:
            self._fatal_payload_mismatch(
                sender_rank,
                "carries no model_delta (async clients ship update "
                "deltas; set agg_mode=async on every process)",
            )
            return
        raw, enc = (payload, None) if self._codec is None else (None, payload)
        if enc is not None:
            mismatch = self._codec_mismatch(enc)
            if mismatch:
                self._fatal_payload_mismatch(sender_rank, mismatch)
                return
        del self._outstanding[sender_rank]
        staleness = max(self.version - int(base_version), 0)
        n = float(msg.get(constants.MSG_ARG_KEY_NUM_SAMPLES))
        if staleness > self.staleness_max:
            self.telemetry.inc("agg_stale_discarded_total")
            logging.warning(
                "async: rank %d update is %d publishes stale "
                "(> staleness_max=%d); discarded",
                sender_rank, staleness, self.staleness_max,
            )
        else:
            scale = float(self.staleness_decay) ** staleness
            status = self.aggregator.fold_delta(
                n, delta=raw, encoded=enc, weight_scale=scale,
                index=sender_rank - 1, staleness=staleness,
            )
            if status == "quarantined":
                # rejected before folding; no fresh work until the
                # probation (ticked per publish) releases the rank —
                # _async_publish redispatches released ranks
                logging.warning(
                    "async: upload from quarantined rank %d rejected "
                    "(seq %d); rank sits out until probation expires",
                    sender_rank, seq,
                )
                self._async_check_quarantine_stall()
                return
            self._folded_ids.add((sender_rank, seq))
            self._folded_since_publish.append((sender_rank, seq))
            self.async_folds += 1
            self.async_weight_log.append(
                {
                    "rank": sender_rank,
                    "seq": seq,
                    "staleness": staleness,
                    "sample_num": n,
                    "weight": n * scale,
                }
            )
            self.telemetry.observe(
                "agg_staleness_rounds", staleness, buckets=(0, 1, 2, 4, 8, 16)
            )
            if len(self._folded_since_publish) >= self.async_publish_every:
                self._async_publish()
        if self.async_folds >= self._async_target_folds():
            self._async_publish()  # flush the partial buffer
            logging.info(
                "async federation done: %d folds, %d publishes",
                self.async_folds, self.version,
            )
            self.aggregator.test_on_server_for_all_clients(self.version)
            self.send_finish()
            self.finish()
            return
        self._async_dispatch(sender_rank)

    def _async_publish(self) -> None:
        """Fold buffer -> global model -> durable publish. WAL first
        (write-ahead: the fold ledger must cover everything the params
        might contain), then the checkpoint — which is also the serving
        plane's hot-swap feed (``CheckpointWatcher`` polls the same
        dir, so every publish can go live without a restart)."""
        folded = self._folded_since_publish
        if not folded:
            return
        chaos_barrier("server.publish", round=self.version, rank=self.rank)
        with self.profiler.span("async_publish", version=self.version + 1):
            self.aggregator.publish_async()
        self.version += 1
        self.round_idx = self.version
        self._folded_since_publish = []
        # EVERY publish checkpoints (checkpoint_freq does not apply in
        # async): the publish cadence IS the durability cadence — folds
        # applied to an uncheckpointed publish are unreplayable, so a
        # sparser checkpoint would turn every crash into silent update
        # loss. Tune async_publish_every to trade checkpoint I/O for
        # freshness instead.
        ckpt_due = self._ckpt is not None
        if self._wal is not None:
            try:
                written = self._unwaled_folds + folded
                self._wal.append(
                    self.version,
                    self.version if ckpt_due else None,
                    sorted(self._outstanding),
                    # include any folds orphaned by an earlier failed
                    # append: the ledger must cover everything the
                    # about-to-be-checkpointed params contain
                    folded=written,
                    kind="publish",
                    extra={
                        "version": self.version,
                        "max_seq": self._dispatch_seq,
                        "folds_total": self.async_folds,
                    },
                )
                self._unwaled_folds = []
                # durable-ledger counter: folds that reached the WAL —
                # the InvariantChecker's "WAL ledger == fold counters"
                # evidence (incremented only on a successful append, so
                # it can never over-count the log)
                self.telemetry.inc("agg_folds_published_total", len(written))
            except OSError:
                # write-ahead invariant: the ledger must cover every
                # fold a checkpoint might contain. If the WAL cannot be
                # written, SKIP this publish's checkpoint too — a
                # checkpoint whose folds are missing from the ledger
                # would let a retransmit double-fold after a restart.
                # The params stay live in memory; the next successful
                # publish carries them.
                logging.exception(
                    "async WAL append failed for publish %d; skipping its "
                    "checkpoint (durability degraded until the WAL "
                    "recovers)", self.version,
                )
                # counted as InvariantChecker evidence: a failed append
                # whose bytes nonetheless landed (fsync refused) leaves
                # a durable record the counters never acknowledged, and
                # its folds re-appear carried in the next successful
                # record — both gaps are bounded by this counter
                self.telemetry.inc("wal_append_failures_total")
                self._unwaled_folds.extend(folded)
                ckpt_due = False
        if ckpt_due:
            self._save_checkpoint()
        # async probation ticks per publish; a released rank gets fresh
        # work immediately (nothing else would re-engage it — async has
        # no per-round broadcast to pick it back up)
        for idx in self.aggregator.tick_defense():
            rank = idx + 1
            if self.client_online_status.get(rank, False):
                self._async_dispatch(rank)
        self.telemetry.set_gauge(
            "defense_quarantined_now",
            len(self.aggregator.quarantined_ranks()),
        )
        self.telemetry.inc("agg_publish_total")
        self.telemetry.heartbeat("cross_silo.round", self.version)
        self.telemetry.inc("cross_silo_rounds_total")
        self.metrics_reporter.report(
            {
                "kind": "async_publish",
                "version": self.version,
                "folds": len(folded),
                "folds_total": self.async_folds,
            }
        )
        logging.info(
            "async publish %d: %d fold(s) applied (%d/%d total)",
            self.version, len(folded), self.async_folds,
            self._async_target_folds(),
        )

    def _finish_round(self) -> None:
        """Aggregate whatever was received, eval, advance (shared by
        the all-received, deadline and quorum-grace paths)."""
        chaos_barrier("server.round_close", round=self.round_idx, rank=self.rank)
        self._cancel_deadline()
        self._cancel_quorum()
        self._empty_deadline_fires = 0
        if self._wait_open:
            self.profiler.log_event_ended("server.wait")
            self._wait_open = False
        import time as _time

        n_aggregated = self.aggregator.num_received()
        # which ranks actually folded into this aggregate (the WAL's
        # exactly-once record) — captured BEFORE aggregate() resets it
        folded_ranks = [i + 1 for i in self.aggregator.folded_indexes()]
        t_agg0 = _time.perf_counter()
        if n_aggregated:
            # the round tag lets the critical-path analyzer pick THIS
            # round's aggregate span off the stitched timeline
            with self.profiler.span("aggregate", round=self.round_idx):
                self.aggregator.aggregate()
        else:
            # every expected client left before uploading (elastic):
            # the global model is unchanged this round; keep going
            logging.warning(
                "round %d: no contributions (all expected clients left); "
                "global model unchanged", self.round_idx,
            )
        # one quarantine-probation period per round close; released
        # ranks re-enter candidate selection at the next broadcast
        released = self.aggregator.tick_defense()
        if released:
            logging.info(
                "round %d: quarantine probation expired for rank(s) %s",
                self.round_idx, [i + 1 for i in released],
            )
        self._record_round_segments(
            self.round_idx, _time.perf_counter() - t_agg0
        )
        eval_round = self.round_idx
        cohort = self.aggregator.client_num  # before begin_round re-arms
        # the completed round's broadcast set, captured BEFORE the next
        # broadcast overwrites the assignment (WAL record)
        cohort_ranks = sorted(self._round_assignment)
        self.round_idx += 1
        ckpt_due = (
            self._ckpt is not None
            and n_aggregated
            and (
                self.round_idx % self._ckpt_freq == 0
                or self.round_idx >= self.round_num
            )
        )
        if self.round_idx >= self.round_num:
            if ckpt_due:
                self._save_checkpoint()
            self._wal_append(eval_round, ckpt_due, cohort_ranks, folded_ranks)
            if n_aggregated:
                self.aggregator.test_on_server_for_all_clients(eval_round)
            self._report_round(eval_round, cohort, n_aggregated)
            self.send_finish()
            self.finish()
            return
        # comm/compute overlap (SURVEY.md §7 "the round loop must
        # overlap comm and compute explicitly"; the reference evals
        # before syncing, stalling every client for the server's eval):
        # broadcast the next round FIRST so clients train while the
        # server evaluates the round that just closed. The checkpoint
        # save rides the same overlap window — it reads only state the
        # broadcast does not mutate.
        self._broadcast_model(constants.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)
        if ckpt_due:
            self._save_checkpoint()
        self._wal_append(eval_round, ckpt_due, cohort_ranks, folded_ranks)
        if n_aggregated:
            with self.profiler.span("server_eval_overlapped"):
                self.aggregator.test_on_server_for_all_clients(eval_round)
        self._report_round(eval_round, cohort, n_aggregated)

    def _record_round_segments(self, round_idx: int, aggregate_s: float) -> None:
        """Live per-round critical-path attribution into the telemetry
        registry (``round_segment_seconds{segment=...}``), straggler
        analytics (slack histogram + rank gauge) and the SLO check
        against ``round_deadline_s``. Server-observable times plus the
        clients' self-reported ``train_seconds``; the stitched-trace
        analyzer (``cli trace``) computes the exact cross-process
        version offline."""
        import time as _time

        tel = self.telemetry
        if self._round_span_open:
            tel.recorder.end("cross_silo.round", cat="round", round=round_idx)
            self._round_span_open = False
        if self._bcast_t0 is None:
            return
        now = _time.perf_counter()
        wall = now - self._bcast_t0
        bcast_done = self._bcast_done_t or self._bcast_t0
        segs = {
            "broadcast_send": bcast_done - self._bcast_t0,
            "aggregate": aggregate_s,
        }
        arrivals = self._upload_arrivals
        if arrivals:
            last = max(arrivals.values())
            straggler = max(arrivals, key=arrivals.get)
            wait = max(last - bcast_done, 0.0)
            compute = self._upload_train_s.get(straggler)
            if compute is not None:
                segs["client_compute"] = min(compute, wait)
                segs["wire"] = max(wait - compute, 0.0)
            else:
                segs["wire"] = wait
            tel.set_gauge("round_straggler_rank", straggler)
            # slack: how long each client's finished upload sat waiting
            # on the straggler — the overlap budget items 3/4 of the
            # roadmap (aggregate-on-arrival, PiPar) would reclaim
            for rank, ts in arrivals.items():
                tel.observe(
                    "round_straggler_slack_s",
                    max(last - ts, 0.0),
                    buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0),
                )
        for name, dur in segs.items():
            tel.observe("round_segment_seconds", max(dur, 0.0), segment=name)
        tel.observe("round_wall_seconds", wall)
        # -- idle-time ledger (the PiPar opportunity, measured live) --
        # arrival_to_aggregate: the last upload is in hand but the
        # aggregate hasn't started — segs + this gap reconstruct the
        # round wall exactly (the perf plane asserts within 5%).
        # close_to_broadcast: server idle BETWEEN rounds (previous
        # ledger close -> this broadcast); inter-round by construction,
        # so it is excluded from the intra-round reconciliation. The
        # arithmetic lives in analysis/perf.py (attribute_idle) so the
        # oracle tests exercise the exact code the live server runs.
        from ...analysis.perf import attribute_idle

        idle = attribute_idle(
            now=now,
            bcast_t0=self._bcast_t0,
            last_arrival=max(arrivals.values()) if arrivals else bcast_done,
            aggregate_s=aggregate_s,
            prev_close=self._last_round_close_t,
        )
        for gap, dur in idle.items():
            tel.observe(
                "round_idle_seconds", dur, gap=gap,
                buckets=(0.001, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0),
            )
        # fraction of the round wall the wire was actually moving bytes
        # (broadcast down + straggler-path upload); the rest is the
        # overlap budget items 1/3 of the roadmap would reclaim
        wire_busy = segs["broadcast_send"] + segs.get("wire", 0.0)
        wire_frac = min(wire_busy / wall, 1.0) if wall > 0 else 0.0
        tel.set_gauge("wire_utilization_frac", wire_frac)
        tel.recorder.instant(
            "round.ledger", cat="perf", round=round_idx,
            wall_s=round(wall, 6),
            segments={k: round(max(v, 0.0), 6) for k, v in segs.items()},
            idle={k: round(v, 6) for k, v in idle.items()},
            wire_utilization_frac=round(wire_frac, 6),
        )
        self._last_round_close_t = now
        if self.round_deadline_s > 0 and wall > self.round_deadline_s:
            tel.inc("slo_violations_total")
            logging.warning(
                "round %d violated round_deadline_s: %.3fs > %.3fs "
                "(straggler rank %s)",
                round_idx, wall, self.round_deadline_s,
                max(arrivals, key=arrivals.get) if arrivals else "n/a",
            )

    def _save_checkpoint(self) -> None:
        """step = the NEXT round to run (sync) or the publish version
        (async); a restarted server picks up exactly where the
        broadcast/dispatch would have gone."""
        state = {
            "params": self.aggregator.get_global_model_params(),
            "round_idx": self.round_idx,
            "agg_round": self.aggregator._agg_round,
        }
        if self.agg_mode == "async":
            state.update(
                version=self.version,
                dispatch_seq=self._dispatch_seq,
                async_folds=self.async_folds,
            )
        self._ckpt.save(self.round_idx, state)

    def _wal_append(
        self, eval_round: int, ckpt_saved: bool, cohort_ranks, folded_ranks=None
    ) -> None:
        """One WAL record per COMPLETED round (crash recovery): which
        round finished, which checkpoint step (if any) carries it, who
        the round was broadcast to, and whose uploads actually folded
        into the aggregate (a strict subset under a quorum/deadline
        close — the exactly-once ledger)."""
        if self._wal is None:
            return
        try:
            self._wal.append(
                eval_round,
                self.round_idx if ckpt_saved else None,
                cohort_ranks,
                folded=folded_ranks,
            )
            # durable-ledger counters (InvariantChecker evidence): one
            # round record and its fold count, bumped ONLY after the
            # append returned — a crash at the write boundary leaves at
            # most the final record unaccounted, which the checker
            # bounds by the injected-crash count
            self.telemetry.inc("wal_rounds_logged_total")
            self.telemetry.inc(
                "wal_folds_logged_total", len(folded_ranks or [])
            )
        except OSError:
            # the WAL is an aid to recovery, never a reason to kill a
            # healthy federation (disk-full on the log must not)
            logging.exception("round WAL append failed for round %d", eval_round)
            # InvariantChecker evidence: a refused fsync can leave a
            # durable record the ledger counters never acknowledged —
            # this bounds that counter/ledger gap from artifacts alone
            self.telemetry.inc("wal_append_failures_total")

    def _report_round(self, round_idx: int, cohort: int, n_aggregated: int) -> None:
        self.metrics_reporter.report(
            {
                "kind": "round_info",
                "round": round_idx,
                "clients": cohort,
                "clients_aggregated": n_aggregated,
            }
        )
        self.telemetry.heartbeat("cross_silo.round", round_idx)
        self.telemetry.inc("cross_silo_rounds_total")
        self.telemetry.inc("cross_silo_clients_aggregated_total", n_aggregated)
        if self.stragglers_dropped:
            self.telemetry.set_gauge(
                "cross_silo_stragglers_dropped", self.stragglers_dropped
            )

    def send_finish(self) -> None:
        # clean-finish marker: tells the post-hoc InvariantChecker the
        # final incarnation flushed its state (counter-vs-ledger
        # equality is only provable on a cleanly finished run)
        self.telemetry.inc("cross_silo_finish_total")
        for rank in range(1, len(self.client_real_ids) + 1):
            self.send_message(
                Message(constants.MSG_TYPE_S2C_FINISH, self.rank, rank)
            )
        logging.info("server: training finished after %d rounds", self.round_idx)
        if self._failure_detector is not None:
            self._failure_detector.stop()
        self._round_profiler.close()
        self.telemetry.stop_watchdog()
        self.telemetry.stop_metrics_server()
        self.telemetry.export_run_artifacts(
            getattr(self.args, "telemetry_dir", None)
        )
