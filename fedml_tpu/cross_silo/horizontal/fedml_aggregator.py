"""Server-side aggregation state for cross-silo rounds.

Parity with ``python/fedml/cross_silo/horizontal/fedml_aggregator.py:15-153``:
collect per-client results, check-all-received, weighted aggregate, the
``data_silo_selection`` / ``client_selection`` split that lets N real
edge devices map onto M data silos, and deterministic per-round
sampling. Aggregation itself is the on-device pytree reduction from
``core.aggregation`` (the reference loops over python dicts on host).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.aggregation import (
    normalize_weights,
    stack_pytrees,
    weighted_average,
)
from ...core.frame import bind_operator
from ...core.local_trainer import compute_dtype_from_args, make_eval_fn

Params = Any


class FedMLAggregator:
    def __init__(self, args, model, test_data=None, server_aggregator=None) -> None:
        self.args = args
        self.model = model
        self.test_data = test_data
        self.server_aggregator = bind_operator(server_aggregator, model, args)
        self._agg_round = 0
        self.client_num = int(args.client_num_per_round)
        self._expected = None  # set per round via begin_round (elastic)
        self.model_dict: Dict[int, Params] = {}
        self.sample_num_dict: Dict[int, float] = {}
        self.flag_client_model_uploaded_dict: Dict[int, bool] = {}
        # same init-rng convention as the simulators (FedAvgAPI.__init__)
        # so cross-silo and simulation runs are bit-comparable
        _, init_rng = jax.random.split(
            jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        )
        self.global_params: Params = model.init(init_rng)
        self._eval = jax.jit(
            make_eval_fn(
                model.apply, model.loss_fn,
                compute_dtype=compute_dtype_from_args(args),
            )
        )

    def get_global_model_params(self) -> Params:
        return self.global_params

    def set_global_model_params(self, params: Params) -> None:
        self.global_params = params

    def add_local_trained_result(
        self, index: int, model_params: Params, sample_num: float
    ) -> None:
        """(fedml_aggregator.py:58-63)

        Incoming trees may live on a client-private device subset (a
        hierarchical silo's DP mesh, where params are replicated) —
        reconcile onto the server's device only when the device sets
        actually differ, so the in-process zero-copy path stays
        zero-copy. Note: FedAvg-family servers aggregate full param
        trees by design; a model-parallel (sharded-params) silo would
        need a sharded server aggregation path instead of this."""
        from ...core.aggregation import reconcile_to_device

        model_params = reconcile_to_device(model_params)
        self.model_dict[index] = model_params
        self.sample_num_dict[index] = float(sample_num)
        self.flag_client_model_uploaded_dict[index] = True

    def check_whether_all_receive(self) -> bool:
        """(fedml_aggregator.py:65-71)"""
        expected = (
            self._expected
            if self._expected is not None
            else range(self.client_num)
        )
        for idx in expected:
            if not self.flag_client_model_uploaded_dict.get(idx, False):
                return False
        for idx in expected:
            self.flag_client_model_uploaded_dict[idx] = False
        return True

    def num_received(self) -> int:
        return len(self.model_dict)

    def drop_expected(self, index: int) -> bool:
        """Remove a leaver's PENDING slot from the current round's
        expected set (elastic membership). A leaver that already
        uploaded keeps its slot — its contribution counts and the round
        completes through the normal path. Returns True only when a
        pending slot was dropped."""
        if self._expected is None or index not in self._expected:
            return False
        if self.flag_client_model_uploaded_dict.get(index, False):
            return False  # contribution already in; keep it
        self._expected.discard(index)
        self.client_num = len(self._expected)
        return True

    def begin_round(self, expected_indexes) -> None:
        """Declare which client indexes this round was broadcast to.
        With elastic membership the active set is not contiguous
        (clients join/leave mid-run), so completion is checked against
        THIS set instead of range(client_num)."""
        self._expected = set(int(i) for i in expected_indexes)
        self.client_num = len(self._expected)

    def aggregate(self) -> Params:
        """Weighted average of the received models
        (fedml_aggregator.py:73-101). Aggregates whatever has been
        received — under a deadline cohort (straggler handling) that
        may be a subset; weights renormalize over the subset."""
        idxs = sorted(self.model_dict.keys())
        if not idxs:
            raise RuntimeError("aggregate() with no received models")
        trees = [self.model_dict[i] for i in idxs]
        ns = jnp.asarray([self.sample_num_dict[i] for i in idxs])
        stacked = stack_pytrees(trees)
        weights = normalize_weights(ns)
        if self.server_aggregator is not None:
            # L3 operator seam (core/frame.py): custom pure reduction
            rng = jax.random.fold_in(
                jax.random.PRNGKey(int(getattr(self.args, "random_seed", 0))),
                self._agg_round,
            )
            self.global_params = self.server_aggregator.aggregate(
                self.global_params, stacked, weights, rng
            )
        else:
            self.global_params = weighted_average(stacked, weights)
        self._agg_round += 1
        self.model_dict.clear()
        self.sample_num_dict.clear()
        self.flag_client_model_uploaded_dict.clear()
        return self.global_params

    # -- selection (fedml_aggregator.py:103-153) ----------------------
    def data_silo_selection(
        self, round_idx: int, data_silo_num_in_total: int, client_num_in_total: int
    ) -> List[int]:
        """Pick which data silos train this round: one silo index per
        participating client."""
        if data_silo_num_in_total == client_num_in_total:
            return list(range(data_silo_num_in_total))
        # local RandomState: identical MT19937 draws to the reference's
        # np.random.seed(round_idx), no global RNG side effect
        return (
            np.random.RandomState(round_idx)
            .choice(range(data_silo_num_in_total), client_num_in_total, replace=False)
            .tolist()
        )

    def client_selection(
        self, round_idx: int, client_id_list_in_total: List, client_num_per_round: int
    ) -> List:
        """Pick which REAL clients participate (client-id indirection,
        fedml_server_manager.py:33)."""
        if client_num_per_round >= len(client_id_list_in_total):
            return list(client_id_list_in_total)
        return (
            np.random.RandomState(round_idx)
            .choice(client_id_list_in_total, client_num_per_round, replace=False)
            .tolist()
        )

    def test_on_server_for_all_clients(self, round_idx: int) -> Optional[Dict]:
        if self.test_data is None:
            return None
        sums = self._eval(self.global_params, self.test_data)
        stats = self.model.metrics_from_sums(sums)
        logging.info("server eval round %d: %s", round_idx, stats)
        return stats
