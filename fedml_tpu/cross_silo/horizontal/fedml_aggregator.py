"""Server-side aggregation state for cross-silo rounds.

Parity with ``python/fedml/cross_silo/horizontal/fedml_aggregator.py:15-153``:
collect per-client results, check-all-received, weighted aggregate, the
``data_silo_selection`` / ``client_selection`` split that lets N real
edge devices map onto M data silos, and deterministic per-round
sampling.

**Beyond the reference — streaming aggregate-on-arrival** (ROADMAP
items 3/5): with ``agg_mode: stream`` (the default) each upload is
folded into O(model) running accumulators the moment it lands
(``core.aggregation.StreamingAccumulator``): server memory stops
scaling with the cohort and the post-barrier aggregate shrinks to a
finalize. The fold is bit-order-independent, so streaming results are
bit-identical to ``agg_mode: buffered`` (which routes its sorted
buffer through the same fold). Aggregations that need the whole cohort
at once — ``defense_type: median`` or a custom ``ServerAggregator`` —
fall back to the buffered path LOUDLY: one warning plus the
``agg_stream_fallback_total`` counter, never a silent wrong answer.
``agg_mode: async`` (FedBuff-style, see the server manager) folds with
staleness-discounted weights through the same accumulator and never
clears a cohort barrier at all.

**Byzantine robustness on the streaming path** (docs/robustness.md
threat model): ``norm_diff_clipping`` and ``weak_dp`` are per-upload
defenses and RIDE the fold — each upload's delta is clipped against
the broadcast global inside the fused term jit before accumulation
(``defense_clipped_total``), and weak-DP noise is drawn once at
finalize from a run-seed + round derived key
(``core.aggregation.derive_defense_rng``). The buffered close folds
through the same clipped executables, so stream == buffered stays
bitwise for these configs and ``agg_stream_fallback_total`` stays 0.
On top, an optional on-arrival anomaly screen
(``core/defense.py`` ``AnomalyScreen``, ``defense_anomaly_threshold``)
scores every upload (norm excess + cosine to the running aggregate),
keeps a per-rank reputation, and QUARANTINES ranks past the threshold:
their uploads are rejected before folding
(``defense_quarantined_total{rank}``) and the server manager excludes
them from cohorts until probation (``defense_quarantine_rounds``)
expires.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ... import constants
from ...core.aggregation import (
    RobustAggregator,
    StreamingAccumulator,
    derive_defense_rng,
    needs_full_cohort,
    normalize_weights,
    stack_pytrees,
)
from ...core.frame import bind_operator
from ...core.local_trainer import compute_dtype_from_args, make_eval_fn

Params = Any


class FedMLAggregator:
    def __init__(self, args, model, test_data=None, server_aggregator=None) -> None:
        self.args = args
        self.model = model
        self.test_data = test_data
        self.server_aggregator = bind_operator(server_aggregator, model, args)
        self._agg_round = 0
        self.client_num = int(args.client_num_per_round)
        self._expected = None  # set per round via begin_round (elastic)
        self.sample_num_dict: Dict[int, float] = {}
        self.flag_client_model_uploaded_dict: Dict[int, bool] = {}
        # same init-rng convention as the simulators (FedAvgAPI.__init__)
        # so cross-silo and simulation runs are bit-comparable
        _, init_rng = jax.random.split(
            jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        )
        self.global_params: Params = model.init(init_rng)
        self._eval = jax.jit(
            make_eval_fn(
                model.apply, model.loss_fn,
                compute_dtype=compute_dtype_from_args(args),
            )
        )
        # -- aggregation mode (streaming tentpole) ---------------------
        from ...core.compression import make_codec
        from ...core.telemetry import Telemetry

        self._tel = Telemetry.get_instance(args)
        self._codec = make_codec(args)
        self.agg_mode = str(getattr(args, "agg_mode", "stream"))
        # -- Byzantine defenses (docs/robustness.md threat model) ------
        # RobustAggregator construction validates defense_type /
        # norm_bound / stddev loudly; needs_full_cohort below rejects
        # unknown strings too, so a typo can never aggregate undefended
        from ...core.defense import AnomalyScreen

        self._robust = (
            RobustAggregator(args)
            if (getattr(args, "defense_type", None) or None) is not None
            else None
        )
        # clipping/weak_dp stream per-upload; median/custom stay buffered
        self._clip_streaming = self._robust is not None and (
            self._robust.defense_type
            in (
                constants.DEFENSE_NORM_DIFF_CLIPPING,
                constants.DEFENSE_WEAK_DP,
            )
        )
        self.screen = AnomalyScreen(args)
        self.defense_clipped = 0  # uploads whose clip bound actually bit
        self.defense_rejected = 0  # uploads rejected by quarantine
        # buffered/fallback modes have no accumulator until close, so
        # the screen's cosine reference is this screening-only weighted
        # sum of accepted deltas (cosine is scale-invariant — the
        # unnormalized sum carries the same direction a mean would)
        self._screen_ref: Optional[Params] = None
        self._fallback_reason = needs_full_cohort(args, self.server_aggregator)
        if self.agg_mode == "async" and self._fallback_reason:
            raise ValueError(
                "agg_mode=async requires the incremental fold but "
                f"{self._fallback_reason}; use agg_mode=buffered with a "
                "synchronous round loop instead"
            )
        self.streaming = (
            self.agg_mode in ("stream", "async") and self._fallback_reason is None
        )
        if self.agg_mode == "stream" and self._fallback_reason is not None:
            # loud one-time fallback (satellite contract): the operator
            # asked for streaming and is getting the buffered path
            logging.warning(
                "agg_mode=stream falling back to the BUFFERED aggregation "
                "path: %s (counted in agg_stream_fallback_total)",
                self._fallback_reason,
            )
            self._tel.inc("agg_stream_fallback_total")
        self._acc: Optional[StreamingAccumulator] = None
        # two-tier edge tier (fedml_tpu/scale/tree.py): with
        # edge_num >= 2 each rank's upload folds into its edge's
        # accumulator and aggregate() finalizes through the root merge
        # — bit-identical to the flat fold (the tree's contract), so an
        # edge tier slides under a live federation without changing a
        # result bit. Sync streaming only: async folds deltas against a
        # moving global and keeps the flat accumulator.
        # with edge_plane=ranks the edges are REAL processes
        # (cross_silo/hierarchical): each process runs a flat streaming
        # accumulator and the ROOT does the tree merge — building the
        # in-process tree here too would nest the tiers
        edge_num = int(getattr(args, "edge_num", 0) or 0)
        self._tree = None
        if (
            edge_num >= 2
            and self.streaming
            and self.agg_mode == "stream"
            and str(getattr(args, "edge_plane", "inproc")) != "ranks"
        ):
            from ...scale.tree import EdgeAggregationTree

            self._tree = EdgeAggregationTree(self.global_params, edge_num)
        # encoded/raw payloads awaiting a buffered aggregate; streaming
        # never populates it (that is the whole point)
        self._pending: Dict[int, Tuple[str, Params, float]] = {}
        self._folded: Set[int] = set()
        self.peak_buffered = 0  # max simultaneous buffered uploads (O(model) proof)
        self.folds_total = 0  # lifetime incremental folds (exactly-once evidence)

    def get_global_model_params(self) -> Params:
        return self.global_params

    def set_global_model_params(self, params: Params) -> None:
        self.global_params = params

    def _accumulator(self, index: int = 0) -> StreamingAccumulator:
        """The accumulator upload ``index`` folds into: the rank's edge
        accumulator when the edge tier is active, else the single flat
        one (async always flat — see ``__init__``)."""
        if self._tree is not None:
            return self._tree.acc_for(index)
        if self._acc is None:
            self._acc = StreamingAccumulator(self.global_params)
        return self._acc

    def _running_mean(self) -> Optional[Params]:
        """Streaming running aggregate for the anomaly screen, across
        whichever fold topology is active."""
        if self._tree is not None:
            return self._tree.running_mean()
        return self._acc.running_mean() if self._acc is not None else None

    def receive_upload(
        self,
        index: int,
        sample_num: float,
        model_params: Optional[Params] = None,
        encoded: Optional[Params] = None,
        weight_scale: float = 1.0,
    ) -> str:
        """One client upload landed: fold it NOW (streaming/async) or
        buffer it (buffered / full-cohort fallback). Returns a status —
        ``"folded"`` / ``"buffered"`` / ``"duplicate"`` /
        ``"quarantined"`` — so the manager can route a rejected rank
        through the drop-expected path (a quarantined rank must not
        stall the quorum grace).

        Exactly one of ``model_params`` (full tree) / ``encoded``
        (compressed delta against the current global tree) is given.
        ``weight_scale`` discounts the sample weight — 1.0 in sync
        modes, the staleness decay factor in async mode.

        Incoming trees may live on a client-private device subset (a
        hierarchical silo's DP mesh, where params are replicated) —
        reconcile onto the server's device only when the device sets
        actually differ, so the in-process zero-copy path stays
        zero-copy. Note: FedAvg-family servers aggregate full param
        trees by design; a model-parallel (sharded-params) silo would
        need a sharded server aggregation path instead of this."""
        from ...core.aggregation import reconcile_to_device

        if index in self._folded:
            # at-least-once delivery without the reliable channel's
            # dedup: the buffered dict was naturally idempotent
            # (overwrite); the incremental fold must enforce at-most-
            # once per (rank, round) itself or a duplicate folds twice
            self._tel.inc("agg_dup_uploads_ignored_total")
            logging.info(
                "duplicate upload from index %d ignored (already folded "
                "this round)", index,
            )
            return "duplicate"
        payload = model_params if model_params is not None else encoded
        payload = reconcile_to_device(payload)
        w = float(sample_num) * float(weight_scale)  # lint: host-sync-ok — wire/knob scalars, never device values
        if self.screen.enabled and self._screen_upload(
            index, payload, raw=model_params is not None, delta_mode=False,
            w=w,
        ):
            return "quarantined"
        if self.streaming:
            if self._clip_streaming:
                # defense in the fold: clip against the broadcast
                # global inside the fused term step (stream == buffered
                # stays bitwise — the close folds the same executables)
                bound = self._robust.norm_bound
                if model_params is not None:
                    _, clipped = self._accumulator(index).fold_clipped(
                        payload, self.global_params, bound, w
                    )
                else:
                    _, clipped = self._accumulator(index).fold_encoded_clipped(
                        self._codec, payload, self.global_params, bound, w
                    )
                self._note_clipped(clipped)
            elif model_params is not None:
                self._accumulator(index).fold(payload, w)
            else:
                self._accumulator(index).fold_encoded(
                    self._codec, payload, self.global_params, w
                )
            self.folds_total += 1
            self._tel.inc("agg_folds_total", mode=self.agg_mode)
        else:
            self._pending[index] = (
                "raw" if model_params is not None else "enc", payload, w,
            )
            self.peak_buffered = max(self.peak_buffered, len(self._pending))
            self._tel.set_gauge("agg_peak_buffered", self.peak_buffered)
        self._folded.add(index)
        self.sample_num_dict[index] = float(sample_num)  # lint: host-sync-ok — wire scalar
        self.flag_client_model_uploaded_dict[index] = True
        return "folded" if self.streaming else "buffered"

    # -- defense plumbing (clip counters + anomaly screen) ------------
    def _note_clipped(self, clipped: bool) -> None:
        if clipped:
            self.defense_clipped += 1
            self._tel.inc("defense_clipped_total")

    def _screen_upload(
        self,
        index: int,
        payload: Params,
        raw: bool,
        delta_mode: bool,
        staleness: int = 0,
        w: float = 1.0,
    ) -> bool:
        """Score one upload for the anomaly screen; True -> REJECT (the
        rank is quarantined — already, or this upload just tripped it).
        ``delta_mode`` says the payload is an update delta (async)
        rather than a full model (sync); ``staleness`` makes the screen
        staleness-aware (catch-up norms are expected, not anomalous).

        Cost note: with a codec configured this decodes the payload a
        SECOND time (the accepted fold decodes again inside its fused
        executable). Deliberate: scoring must happen BEFORE folding (a
        rejected upload never touches the accumulator), and routing the
        fold through a pre-decoded delta would put stream and buffered
        on different executables, forfeiting their bit-identity. The
        extra O(model) pass only exists when screening is enabled."""
        from ...core.defense import decoded_delta, delta_from

        if self.screen.is_quarantined(index):
            self.defense_rejected += 1
            self._tel.inc("defense_quarantined_rejected_total")
            logging.warning(
                "defense: rejecting upload from quarantined index %d", index
            )
            return True
        if delta_mode:
            d = (
                payload
                if raw
                else decoded_delta(self._codec, payload, self.global_params)
            )
            # async running aggregate IS a (weighted) mean delta
            running = (
                self._acc.running_mean() if self._acc is not None else None
            )
        else:
            d = (
                delta_from(payload, self.global_params)
                if raw
                else decoded_delta(self._codec, payload, self.global_params)
            )
            rm = self._running_mean() if self.streaming else None
            # sync running aggregate is a mean MODEL; compare deltas.
            # Buffered/fallback: the screening-only running delta sum
            # (no accumulator exists until close)
            running = (
                delta_from(rm, self.global_params)
                if rm is not None
                else (None if self.streaming else self._screen_ref)
            )
        score, norm, _cos = self.screen.score_upload(
            d, running, staleness=staleness
        )
        self._tel.observe(
            "defense_anomaly_score_ratio", score,
            buckets=(0.05, 0.1, 0.2, 0.4, 0.8, 1.6),
        )
        if self.screen.observe(index, score, norm):
            self.defense_rejected += 1
            self._tel.inc("defense_quarantined_total", rank=index + 1)
            self._tel.inc("defense_quarantined_rejected_total")
            return True
        if not delta_mode and not self.streaming:
            # accepted: extend the buffered-mode cosine reference
            term = jax.tree.map(lambda x: w * x, d)
            self._screen_ref = (
                term
                if self._screen_ref is None
                else jax.tree.map(jnp.add, self._screen_ref, term)
            )
        return False

    def quarantined_ranks(self):
        """Transport ranks currently quarantined (the manager excludes
        them from broadcasts and the quorum denominator)."""
        return {i + 1 for i in self.screen.quarantined_indexes()}

    def tick_defense(self):
        """One probation period elapsed (round close / async publish).
        Returns the released aggregator indexes."""
        if not self.screen.enabled:
            return []
        return self.screen.tick()

    def _apply_weak_dp(self, params: Params) -> Params:
        """Weak-DP noise at finalize — run-seed + round derived key
        (``derive_defense_rng``), never a fixed key. A custom
        ``ServerAggregator`` owns its whole reduction including any
        defense, so it is exempt."""
        if (
            self._robust is None
            or self._robust.defense_type != constants.DEFENSE_WEAK_DP
            or self.server_aggregator is not None
        ):
            return params
        rng = derive_defense_rng(
            getattr(self.args, "random_seed", 0), self._agg_round
        )
        self._tel.inc("defense_noise_rounds_total")
        return self._robust.add_noise(params, rng)

    def add_local_trained_result(
        self, index: int, model_params: Params, sample_num: float
    ) -> str:
        """(fedml_aggregator.py:58-63) — legacy entry point; routes
        through ``receive_upload`` and propagates its status: a
        screening-enabled caller must route ``"quarantined"`` through
        drop-expected (see the server manager) or the round waits on a
        slot that will never fill."""
        return self.receive_upload(
            index, sample_num, model_params=model_params
        )

    # -- async (FedBuff-style) fold/publish ---------------------------
    def fold_delta(
        self,
        sample_num: float,
        delta: Optional[Params] = None,
        encoded: Optional[Params] = None,
        weight_scale: float = 1.0,
        index: Optional[int] = None,
        staleness: int = 0,
    ) -> str:
        """Fold a staleness-discounted update DELTA (async mode). The
        server applies deltas to whatever the global model is NOW —
        it never stores the stale base params the client trained from,
        which is what keeps async memory O(model) at any staleness.

        With clipping defenses the delta is clipped to ``norm_bound``
        inside the fused term step BEFORE the staleness weight applies
        (the discount rides ``weight_scale``, never the clip geometry);
        with ``index`` given and the anomaly screen enabled the upload
        is scored first and may come back ``"quarantined"`` — rejected,
        not folded."""
        from ...core.aggregation import reconcile_to_device

        payload = delta if delta is not None else encoded
        payload = reconcile_to_device(payload)
        w = float(sample_num) * float(weight_scale)  # lint: host-sync-ok — wire/knob scalars, never device values
        if (
            index is not None
            and self.screen.enabled
            and self._screen_upload(
                index, payload, raw=delta is not None, delta_mode=True,
                staleness=staleness, w=w,
            )
        ):
            return "quarantined"
        if self._clip_streaming:
            bound = self._robust.norm_bound
            if delta is not None:
                _, clipped = self._accumulator().fold_delta_clipped(
                    payload, bound, w
                )
            else:
                _, clipped = self._accumulator().fold_encoded_delta_clipped(
                    self._codec, payload, self.global_params, bound, w
                )
            self._note_clipped(clipped)
        elif delta is not None:
            self._accumulator().fold(payload, w)
        else:
            self._accumulator().fold_encoded_delta(
                self._codec, payload, self.global_params, w
            )
        self.folds_total += 1
        self._tel.inc("agg_folds_total", mode=self.agg_mode)
        return "folded"

    def pending_folds(self) -> int:
        return 0 if self._acc is None else self._acc.count

    def publish_async(self) -> Params:
        """Close the async buffer: global += weighted-mean folded delta
        (the finalize divides by the folded staleness-discounted
        weights). A no-op when nothing folded since the last publish.
        Weak-DP noise (if configured) lands on each published global,
        keyed by run seed + publish index."""
        if self.pending_folds() == 0:
            return self.global_params
        mean_delta = self._acc.finalize()
        self.global_params = jax.tree.map(
            lambda g, d: g + d.astype(g.dtype), self.global_params, mean_delta
        )
        self.global_params = self._apply_weak_dp(self.global_params)
        self._agg_round += 1
        self._reset_window()
        return self.global_params

    def check_whether_all_receive(self) -> bool:
        """(fedml_aggregator.py:65-71)"""
        expected = (
            self._expected
            if self._expected is not None
            else range(self.client_num)
        )
        for idx in expected:
            if not self.flag_client_model_uploaded_dict.get(idx, False):
                return False
        for idx in expected:
            self.flag_client_model_uploaded_dict[idx] = False
        return True

    def num_received(self) -> int:
        return len(self._folded)

    def folded_indexes(self) -> List[int]:
        """Aggregator indexes (rank-1) folded/buffered into the round
        so far — the WAL's per-round folded-set record."""
        return sorted(self._folded)

    def missing_indexes(self) -> List[int]:
        """Expected indexes that have not folded yet (the quorum
        close's straggler report)."""
        if self._expected is None:
            return []
        return sorted(set(self._expected) - self._folded)

    def drop_expected(self, index: int) -> bool:
        """Remove a leaver's PENDING slot from the current round's
        expected set (elastic membership). A leaver that already
        uploaded keeps its slot — its contribution counts and the round
        completes through the normal path. Returns True only when a
        pending slot was dropped."""
        if self._expected is None or index not in self._expected:
            return False
        if self.flag_client_model_uploaded_dict.get(index, False):
            return False  # contribution already in; keep it
        self._expected.discard(index)
        self.client_num = len(self._expected)
        return True

    def quorum_target(self, frac: float) -> int:
        """How many folds satisfy a quorum of ``frac`` over the CURRENT
        round cohort. The denominator is ``client_num``, which
        ``drop_expected`` shrinks when the failure detector declares a
        rank dead mid-round — a corpse stops counting against the
        quorum instead of stalling the grace timer."""
        import math

        return max(1, math.ceil(float(frac) * self.client_num))  # lint: host-sync-ok — knob scalar

    def quorum_met(self, frac: float) -> bool:
        return len(self._folded) >= self.quorum_target(frac)

    def begin_round(self, expected_indexes) -> None:
        """Declare which client indexes this round was broadcast to.
        With elastic membership the active set is not contiguous
        (clients join/leave mid-run), so completion is checked against
        THIS set instead of range(client_num)."""
        self._expected = set(int(i) for i in expected_indexes)  # lint: host-sync-ok — host rank ints
        self.client_num = len(self._expected)

    def _reconstructed_pending(self) -> List[Tuple[int, Params, float]]:
        """Decode buffered payloads to full trees, sorted by index —
        the full-cohort fallback's input."""
        from ...core.compression import reconstruct_from_encoded

        out = []
        for i in sorted(self._pending):
            kind, payload, w = self._pending[i]
            if kind == "enc":
                payload = reconstruct_from_encoded(
                    self._codec, payload, self.global_params
                )
            out.append((i, payload, w))
        return out

    def aggregate(self) -> Params:
        """Close the aggregation window (fedml_aggregator.py:73-101
        semantics). Aggregates whatever has been folded/buffered —
        under a quorum/deadline cohort (straggler handling) that may be
        a subset; weights renormalize over the subset, which the
        streaming finalize does for free (it divides by the folded
        total weight).

        Streaming: the round's work already happened upload-by-upload;
        this is an O(model) finalize (plus weak-DP noise when
        configured — clipping already happened per term). Buffered: the
        sorted buffer runs through the SAME fold — including the SAME
        clipped executables for clipping defenses — so the two modes
        are bit-identical. Full-cohort fallback (median/custom
        aggregator): the legacy stacked reduction."""
        if not self._folded:
            raise RuntimeError("aggregate() with no received models")
        if self.streaming:
            acc = self._tree if self._tree is not None else self._acc
            self.global_params = self._apply_weak_dp(acc.finalize())
        elif self._fallback_reason is not None:
            idxs_trees = self._reconstructed_pending()
            trees = [t for _, t, _ in idxs_trees]
            ns = jnp.asarray([w for _, _, w in idxs_trees])
            stacked = stack_pytrees(trees)
            weights = normalize_weights(ns)
            rng = derive_defense_rng(
                getattr(self.args, "random_seed", 0), self._agg_round
            )
            if self.server_aggregator is not None:
                # L3 operator seam (core/frame.py): custom pure reduction
                self.global_params = self.server_aggregator.aggregate(
                    self.global_params, stacked, weights, rng
                )
            else:
                self.global_params = self._robust.aggregate(
                    stacked, weights, self.global_params, rng=rng
                )
        else:
            # buffered baseline: identical math to streaming, applied
            # in sorted index order at close (order is immaterial — the
            # fold is order-independent — but sorted keeps it obvious)
            acc = StreamingAccumulator(self.global_params)
            bound = self._robust.norm_bound if self._clip_streaming else None
            for i in sorted(self._pending):
                kind, payload, w = self._pending[i]
                if bound is not None:
                    if kind == "enc":
                        _, clipped = acc.fold_encoded_clipped(
                            self._codec, payload, self.global_params, bound, w
                        )
                    else:
                        _, clipped = acc.fold_clipped(
                            payload, self.global_params, bound, w
                        )
                    self._note_clipped(clipped)
                elif kind == "enc":
                    acc.fold_encoded(self._codec, payload, self.global_params, w)
                else:
                    acc.fold(payload, w)
                self.folds_total += 1
                self._tel.inc("agg_folds_total", mode=self.agg_mode)
            self.global_params = self._apply_weak_dp(acc.finalize())
        self._agg_round += 1
        self._reset_window()
        return self.global_params

    # -- hierarchical server plane (cross_silo/hierarchical) ----------
    def export_fold_state(self) -> dict:
        """The edge→root merge payload: this window's streaming fold
        state (exact 3-limb expansion + weights + count) as a
        wire-portable dict, WITHOUT finalizing — the root merges the
        limbs through the same add-only exact jit, so the federation's
        finalize stays bitwise identical to a flat fold of the same
        uploads. Streaming mode only (the edge plane rejects buffered/
        async at construction)."""
        if not self.streaming or self._tree is not None:
            raise RuntimeError(
                "export_fold_state() needs the flat streaming accumulator "
                "(agg_mode=stream, no in-process edge tree)"
            )
        if self._acc is None or self._acc.count == 0:
            # an edge whose whole partition died/left ships an empty
            # report; the root skips the merge and drops the cohort
            return {"limbs": [], "total_w": 0.0, "count": 0}
        return self._acc.export_state()

    def reset_window(self) -> None:
        """Public window reset for callers that close a round WITHOUT
        finalizing here — the edge tier finalizes at the ROOT, so the
        edge resets its own window after shipping ``export_fold_state``
        upstream."""
        self._agg_round += 1
        self._reset_window()

    def _reset_window(self) -> None:
        """Clear per-round upload state (shared by ``aggregate`` and
        the async publish path)."""
        if self._acc is not None:
            self._acc.reset()
        if self._tree is not None:
            self._tree.reset()
        self._screen_ref = None
        self._pending.clear()
        self._folded.clear()
        self.sample_num_dict.clear()
        self.flag_client_model_uploaded_dict.clear()

    # -- selection (fedml_aggregator.py:103-153) ----------------------
    def data_silo_selection(
        self, round_idx: int, data_silo_num_in_total: int, client_num_in_total: int
    ) -> List[int]:
        """Pick which data silos train this round: one silo index per
        participating client."""
        if data_silo_num_in_total == client_num_in_total:
            return list(range(data_silo_num_in_total))
        # local RandomState: identical MT19937 draws to the reference's
        # np.random.seed(round_idx), no global RNG side effect
        return (
            np.random.RandomState(round_idx)
            .choice(range(data_silo_num_in_total), client_num_in_total, replace=False)
            .tolist()
        )

    def client_selection(
        self, round_idx: int, client_id_list_in_total: List, client_num_per_round: int
    ) -> List:
        """Pick which REAL clients participate (client-id indirection,
        fedml_server_manager.py:33)."""
        if client_num_per_round >= len(client_id_list_in_total):
            return list(client_id_list_in_total)
        return (
            np.random.RandomState(round_idx)
            .choice(client_id_list_in_total, client_num_per_round, replace=False)
            .tolist()
        )

    def test_on_server_for_all_clients(self, round_idx: int) -> Optional[Dict]:
        if self.test_data is None:
            return None
        sums = self._eval(self.global_params, self.test_data)
        stats = self.model.metrics_from_sums(sums)
        logging.info("server eval round %d: %s", round_idx, stats)
        return stats
