"""Cross-silo client manager + trainer wrapper.

Parity with ``python/fedml/cross_silo/horizontal/fedml_client_manager.py:14-171``
and ``fedml_trainer.py:4-60``: on CONNECTION_IS_READY announce ONLINE;
on init/sync set global params, train the assigned silo, send the
result. Training is the jitted functional local trainer — params stay
on device between receive and send when the transport is in-process.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp

from ... import constants
from ...core.frame import bind_operator
from ...core.local_trainer import compute_dtype_from_args, make_local_train_fn
from ...core.managers import ClientManager
from ...core.message import Message
from ...core.optimizers import create_client_optimizer
from ...core.types import Batches


class FedMLTrainer:
    """(fedml_trainer.py:4-60): holds the local data dict and the
    jitted update; ``update_dataset(index)`` switches silo."""

    def __init__(self, args, dataset, model, client_trainer=None) -> None:
        self.args = args
        self.dataset = dataset
        self.model = model
        self.client_index: Optional[int] = None
        from ...core.optimizers import resolve_round_lr_schedule

        # round-indexed LR (decay across the federation; VERDICT r3 #5)
        self._round_lr = resolve_round_lr_schedule(args)
        if client_trainer is not None:
            if self._round_lr is not None:
                raise ValueError(
                    "lr_schedule with a custom client_trainer: the "
                    "trainer owns its optimizer — implement the "
                    "schedule inside it or use lr_schedule=constant"
                )
            # L3 operator seam (core/frame.py): same custom pure train
            # fn the simulators consume, here jitted per-silo.
            fn = bind_operator(client_trainer, model, args).make_train_fn(args)
        else:
            fn = make_local_train_fn(
                model.apply,
                model.loss_fn,
                create_client_optimizer(
                    args,
                    lr=float(args.learning_rate)
                    if self._round_lr is not None
                    else None,
                ),
                epochs=int(args.epochs),
                prox_mu=float(getattr(args, "fedprox_mu", 0.0) or 0.0),
                shuffle=bool(getattr(args, "shuffle", True)),
                compute_dtype=compute_dtype_from_args(args),
            )
        self._fn = jax.jit(fn)

    def update_dataset(self, client_index: int) -> None:
        self.client_index = int(client_index)

    def train(self, params, round_idx: int):
        i = self.client_index
        packed = self.dataset.packed_train
        client = Batches(x=packed.x[i], y=packed.y[i], mask=packed.mask[i])
        # fold_in takes 32-bit data. Sync round indexes never come
        # close (identical draws to the simulators), but async-mode
        # dispatch seqs live in per-incarnation epoch bands above 2^32
        # — reduce into range, deterministically
        rng = jax.random.fold_in(
            jax.random.PRNGKey(int(getattr(self.args, "random_seed", 0))),
            (round_idx * 100003 + i) % (2**31),
        )
        if self._round_lr is not None:
            mult = jnp.float32(
                float(self._round_lr(round_idx))
                / float(self.args.learning_rate)
            )
            new_params, metrics = self._fn(params, client, rng, mult)
        else:
            new_params, metrics = self._fn(params, client, rng)
        n = float(self.dataset.packed_num_samples[i])
        return new_params, n


class FedMLClientManager(ClientManager):
    def __init__(
        self,
        args,
        trainer: FedMLTrainer,
        comm=None,
        rank=0,
        size=0,
        backend=constants.COMM_BACKEND_LOCAL,
    ) -> None:
        super().__init__(args, comm, rank, size, backend)
        self.trainer = trainer
        self.server_rank = 0
        from ...core.compression import EncoderState, make_codec

        codec = make_codec(args)
        self._encoder = EncoderState(codec) if codec is not None else None
        # async mode (agg_mode=async): uploads ship update DELTAS (the
        # FedBuff currency — the server folds them into whatever the
        # global model is by then), encoded when a codec is configured
        self._async = str(getattr(args, "agg_mode", "stream")) == "async"
        from ...core.tracking import ProfilerEvent

        # spans mirror the reference's instrumentation points
        # (client_master_manager.py:117-121: train / comm_c2s)
        self.profiler = ProfilerEvent(args)
        # shared flight-recorder timeline + per-round progress marks
        # for the stall watchdog (self.telemetry from _ManagerBase)
        self.telemetry.attach_profiler(self.profiler)
        # liveness beats (core/comm/heartbeat.py): started once the
        # connection is up; they feed the server's failure detector and
        # double as the reconnect probe after a server restart
        self._heartbeat = None
        self._heartbeat_interval_s = float(
            getattr(args, "heartbeat_interval_s", 0.0) or 0.0
        )

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            constants.MSG_TYPE_CONNECTION_IS_READY, self.handle_connection_ready
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_S2C_INIT_CONFIG, self.handle_message_init
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
            self.handle_message_receive_model_from_server,
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_S2C_RESYNC, self.handle_message_resync
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_S2C_FINISH, self.handle_message_finish
        )

    # -- handlers (fedml_client_manager.py:49-130) --------------------
    def handle_connection_ready(self, msg: Message) -> None:
        self.send_client_status(self.server_rank)
        if self._heartbeat_interval_s > 0 and self._heartbeat is None:
            from ...core.comm.heartbeat import HeartbeatEmitter

            self._heartbeat = HeartbeatEmitter(
                self._send_heartbeat, self._heartbeat_interval_s
            ).start()

    def _send_heartbeat(self) -> None:
        # a fresh Message per beat: the LOCAL fabric passes objects by
        # reference, so a reused envelope would alias in-flight beats
        self.send_message(
            Message(constants.MSG_TYPE_C2S_HEARTBEAT, self.rank, self.server_rank)
        )

    def send_client_status(self, receiver_id: int) -> None:
        msg = Message(constants.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, receiver_id)
        msg.add_params(
            constants.MSG_ARG_KEY_CLIENT_STATUS, constants.CLIENT_STATUS_ONLINE
        )
        self.send_message(msg)

    def leave(self) -> None:
        """Graceful exit from an elastic federation: announce OFFLINE
        (the server drops this client from the current round's expected
        set and future selections) and stop the receive loop."""
        msg = Message(
            constants.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, self.server_rank
        )
        msg.add_params(
            constants.MSG_ARG_KEY_CLIENT_STATUS, constants.CLIENT_STATUS_OFFLINE
        )
        self.send_message(msg)
        self.finish()

    def handle_message_init(self, msg: Message) -> None:
        self._train_and_send(msg)

    def handle_message_receive_model_from_server(self, msg: Message) -> None:
        self._train_and_send(msg)

    def handle_message_resync(self, msg: Message) -> None:
        """Crash-recovery downlink: the server (restarted, or seeing
        this client reconnect) ships the CURRENT round + params instead
        of a stale init — train it like any sync."""
        logging.info(
            "client rank %d: RESYNC to round %s",
            self.rank, msg.get(constants.MSG_ARG_KEY_ROUND_INDEX),
        )
        self.telemetry.inc("cross_silo_client_resyncs_total")
        self._train_and_send(msg)

    def handle_message_finish(self, msg: Message) -> None:
        logging.info("client rank %d: finish", self.rank)
        self.finish()

    def finish(self) -> None:
        if self._heartbeat is not None:
            self._heartbeat.stop()
            self._heartbeat = None
        # client-side telemetry (spans, comm counters) must survive the
        # process: rank-suffixed artifacts next to the server's
        self.telemetry.export_run_artifacts(
            getattr(self.args, "telemetry_dir", None)
        )
        super().finish()

    def _train_and_send(self, msg: Message) -> None:
        import time as _time

        from ...core.chaos import ProcessKilled, chaos_barrier

        try:
            # named chaos barrier: a scheduled kill_client here is the
            # kill -9 analog the chaos worlds choreograph by hand —
            # the beat thread dies with the "process" (a corpse that
            # kept beating would defeat the failure detector)
            chaos_barrier(
                "client.train",
                round=int(msg.get(constants.MSG_ARG_KEY_ROUND_INDEX, 0)),
                rank=self.rank,
            )
        except ProcessKilled:
            if self._heartbeat is not None:
                self._heartbeat.stop()
                self._heartbeat = None
            raise
        params = msg.get(constants.MSG_ARG_KEY_MODEL_PARAMS)
        client_index = msg.get(constants.MSG_ARG_KEY_CLIENT_INDEX)
        round_idx = int(msg.get(constants.MSG_ARG_KEY_ROUND_INDEX, 0))
        self.trainer.update_dataset(client_index)
        t_train = _time.perf_counter()
        # round/rank tags land on the flight-recorder span — the
        # critical-path analyzer (core/tracing.py) attributes the
        # straggler's compute segment from them
        with self.profiler.span("train", round=round_idx, rank=self.rank):
            new_params, n = self.trainer.train(params, round_idx)
        train_s = _time.perf_counter() - t_train
        self.telemetry.heartbeat(f"client{self.rank}.train", round_idx)
        out = Message(
            constants.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank, self.server_rank
        )
        # causal link: the upload names the broadcast that caused it
        # (trace id + parent flow), so the stitched trace carries one
        # broadcast -> train -> upload -> aggregate chain per client
        from ...core.tracing import continue_context

        continue_context(msg, out)
        # server-side live attribution: how long local training ran
        # (the precise cross-process version comes from the stitched
        # trace; this rides the upload so the server can emit
        # round_segment_seconds without waiting for a trace merge)
        out.add_params(constants.MSG_ARG_KEY_TRAIN_SECONDS, float(train_s))
        # async staleness bookkeeping: echo the publish version this
        # model came from so the server can discount the update by how
        # many publishes it missed (the server cross-checks against its
        # own dispatch record; the echo keeps the wire self-describing)
        base_version = msg.get(constants.MSG_ARG_KEY_MODEL_VERSION)
        if base_version is not None:
            out.add_params(constants.MSG_ARG_KEY_MODEL_VERSION, base_version)
        if self._encoder is not None or self._async:
            # compressed uplink (core/compression.py): ship the encoded
            # update delta; the server reconstructs against the same
            # global tree it broadcast this round. A hierarchical silo
            # trains on its own device subset (params replicated over
            # the silo's DP mesh) while the broadcast tree sits on the
            # server's device — align before subtracting.
            from ...core.aggregation import is_device_tree

            if is_device_tree(new_params):
                delta = jax.tree.map(
                    lambda a, b: a - jax.device_put(b, a.sharding),
                    new_params,
                    params,
                )
            else:
                delta = jax.tree.map(lambda a, b: a - b, new_params, params)
            out.add_params(
                constants.MSG_ARG_KEY_MODEL_DELTA,
                self._encoder.encode(delta)
                if self._encoder is not None
                else delta,  # async without a codec: raw delta
            )
        else:
            out.add_params(constants.MSG_ARG_KEY_MODEL_PARAMS, new_params)
        out.add_params(constants.MSG_ARG_KEY_NUM_SAMPLES, n)
        # round tag: lets a deadline-cohort server discard stale uploads
        out.add_params(constants.MSG_ARG_KEY_ROUND_INDEX, round_idx)
        with self.profiler.span("comm_c2s"):
            self.send_message(out)
