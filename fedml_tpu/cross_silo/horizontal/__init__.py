"""Horizontal cross-silo FL (reference: ``python/fedml/cross_silo/horizontal/``)."""

from .fedml_aggregator import FedMLAggregator  # noqa: F401
from .fedml_client_manager import FedMLClientManager  # noqa: F401
from .fedml_server_manager import FedMLServerManager  # noqa: F401
