"""In-silo data-parallel trainer.

TPU analog of ``cross_silo/hierarchical/trainer_dist_adapter.py:40-141``:
where the reference wraps the model in ``DistributedDataParallel``
(allreduce per backward) and barriers before each round (:121-127), here
the silo owns a ``Mesh`` with a ``data`` axis and the jitted local train
step consumes a batch whose example axis is sharded over it. GSPMD then
partitions the per-example forward/backward across the silo's chips and
inserts the gradient all-reduce over ICI — DDP semantics as a compiler
transform, zero communication code.

Numerics contract: the sharded step computes the same math as the
horizontal (unsharded) trainer — only the reduction order differs — so
hierarchical == horizontal holds to float tolerance (asserted in
``tests/test_hierarchical_cross_silo.py``).
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.frame import bind_operator
from ...core.local_trainer import compute_dtype_from_args, make_local_train_fn
from ...core.optimizers import create_client_optimizer
from ...core.types import Batches


def default_silo_devices(args) -> Sequence[jax.Device]:
    """Device slice for this silo. Single-silo-per-host deployments use
    every local device; the test harness packs several silos into one
    process by setting ``args.silo_device_count`` (silo i of FL rank
    i+1 takes devices [i*cnt, (i+1)*cnt))."""
    devices = jax.devices()
    cnt = int(getattr(args, "silo_device_count", 0) or 0)
    if cnt <= 0:
        return devices
    silo = int(getattr(args, "rank", 1)) - 1  # FL ranks are 1-based
    lo = silo * cnt
    if lo + cnt > len(devices):
        raise ValueError(
            f"silo {silo}: devices [{lo},{lo + cnt}) out of range ({len(devices)})"
        )
    return devices[lo : lo + cnt]


class TrainerDistAdapter:
    """Same surface as the horizontal ``FedMLTrainer`` (update_dataset /
    train) so the master manager is scenario-agnostic."""

    def __init__(
        self,
        args,
        dataset,
        model,
        process_group,
        silo_devices: Optional[Sequence[jax.Device]] = None,
        client_trainer=None,
    ) -> None:
        self.args = args
        self.dataset = dataset
        self.model = model
        self.pg = process_group
        self.client_index: Optional[int] = None

        devices = list(
            silo_devices if silo_devices is not None else default_silo_devices(args)
        )
        self.mesh = Mesh(np.array(devices), ("data",))
        n_dp = len(devices)
        bs = dataset.packed_train.batch_size
        if bs % n_dp != 0:
            # GSPMD needs the sharded axis to tile; replicate instead of
            # failing so odd configs still run (just without in-silo DP).
            logging.warning(
                "silo batch_size %d not divisible by %d devices; replicating",
                bs,
                n_dp,
            )
            self._batch_spec = P()
        else:
            self._batch_spec = P(None, "data")  # [nb, bs, ...]: shard examples
        self._replicated = NamedSharding(self.mesh, P())
        self._batch_sharding = NamedSharding(self.mesh, self._batch_spec)

        from ...core.optimizers import resolve_round_lr_schedule

        # round-indexed LR (decay across the federation; VERDICT r3 #5)
        self._round_lr = resolve_round_lr_schedule(args)
        if client_trainer is not None:
            if self._round_lr is not None:
                raise ValueError(
                    "lr_schedule with a custom client_trainer: the "
                    "trainer owns its optimizer — implement the "
                    "schedule inside it or use lr_schedule=constant"
                )
            # L3 operator seam (core/frame.py): the custom pure train fn
            # is simply jitted with the silo's DP shardings — in-silo
            # data parallelism composes with custom operators for free.
            local_fn = bind_operator(client_trainer, model, args).make_train_fn(args)
        else:
            local_fn = make_local_train_fn(
                model.apply,
                model.loss_fn,
                create_client_optimizer(
                    args,
                    lr=float(args.learning_rate)
                    if self._round_lr is not None
                    else None,
                ),
                epochs=int(args.epochs),
                prox_mu=float(getattr(args, "fedprox_mu", 0.0) or 0.0),
                shuffle=bool(getattr(args, "shuffle", True)),
                compute_dtype=compute_dtype_from_args(args),
            )
        batch_in = Batches(
            x=self._batch_sharding,
            y=self._batch_sharding,
            mask=self._batch_sharding,
        )
        self._fn = jax.jit(
            local_fn,
            # params/opt-state replicated, batch data-sharded: exactly
            # the DDP layout, declared instead of hand-implemented.
            # (the trailing replicated None is the lr multiplier)
            in_shardings=(None, batch_in, None)
            if self._round_lr is None
            else (None, batch_in, None, None),
            out_shardings=None,
        )

    def update_dataset(self, client_index: int) -> None:
        self.client_index = int(client_index)

    def _put(self, a, sharding):
        """Host array -> global device array on the silo mesh — the
        shared single/multi-controller placement seam
        (``parallel.mesh._put``): the assembly step the reference gets
        from DDP scattering per-rank loaders."""
        from ...parallel.mesh import _put

        return _put(a, sharding, self.pg.multi_controller)

    def _silo_batch(self) -> Batches:
        i = self.client_index
        packed = self.dataset.packed_train
        client = Batches(x=packed.x[i], y=packed.y[i], mask=packed.mask[i])
        put = lambda a: self._put(a, self._batch_sharding)
        return Batches(x=put(client.x), y=put(client.y), mask=put(client.mask))

    def train(self, params, round_idx: int):
        i = self.client_index
        params = jax.tree.map(lambda a: self._put(a, self._replicated), params)
        rng = jax.random.fold_in(
            jax.random.PRNGKey(int(getattr(self.args, "random_seed", 0))),
            round_idx * 100003 + i,
        )
        if self.pg.multi_controller:
            # uncommitted host value: identical on every process, so the
            # jit treats it as consistently replicated
            rng = np.asarray(rng)
        if self._round_lr is not None:
            mult = np.float32(
                float(self._round_lr(round_idx))
                / float(self.args.learning_rate)
            )
            new_params, _metrics = self._fn(
                params, self._silo_batch(), rng, mult
            )
        else:
            new_params, _metrics = self._fn(params, self._silo_batch(), rng)
        if self.pg.multi_controller:
            # fully-replicated global arrays -> host copies, so the FL
            # message layer (and the server's single-device aggregation)
            # never sees cross-process buffers
            new_params = jax.tree.map(np.asarray, new_params)
        n = float(self.dataset.packed_num_samples[i])
        return new_params, n

    def participate(self, params, round_idx: int) -> None:
        """Slave-side entry: under multi-controller SPMD every process
        must run the same computation for its collectives to complete
        (the ``dist.barrier``+DDP-step analog, trainer_dist_adapter.py:
        121-127). Under a single controller the master's step already
        drives all silo chips, so this is a no-op."""
        if self.pg.multi_controller:
            self.train(params, round_idx)
