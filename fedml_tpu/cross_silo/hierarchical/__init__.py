"""Hierarchical cross-silo ("Octopus", SURVEY.md §2.10 hierarchical).

Each FL client is itself a distributed training group: the reference
nests a PyTorch-DDP process group inside every silo
(``cross_silo/hierarchical/trainer_dist_adapter.py:40-141`` wraps the
model in DDP, ``process_group_manager.py:6-43`` builds NCCL/GLOO
groups, ``client_master_manager.py:48-269`` speaks the FL protocol
outward and broadcasts inward, ``client_slave_manager.py:5-54`` blocks
on the broadcast).

TPU-native redesign — the silo's data parallelism is a **mesh axis, not
a process group**:

- in-silo DP = the silo's local batch sharded over a ``data`` axis of a
  per-silo ``jax.sharding.Mesh``; XLA inserts the gradient all-reduce
  over ICI (the DDP allreduce analog) during jit, no NCCL calls;
- the master process drives the jitted sharded train step and speaks
  the horizontal FL protocol to the server (same 3-message loop);
- slave processes exist for **multi-controller** runs (one process per
  host of a multi-host silo): they block on the silo-private control
  fabric for ``[round_idx, params, client_index]`` and enter the same
  jitted computation so the collectives complete. Under a
  single-controller runtime (one process drives all silo chips —
  ``jax.process_count() == 1``) the master's step already uses every
  chip and slaves skip the redundant compute.
"""

from __future__ import annotations

from .client_master_manager import ClientMasterManager
from .client_slave_manager import ClientSlaveManager
from .edge_server_manager import EdgeServerManager
from .federation import (
    HierEdge,
    HierRoot,
    hier_partition,
    prepare_client_args,
    run_local_hier_world,
)
from .launcher import launch_silo_processes
from .plane import (
    edge_clients,
    edge_fabric_run_id,
    edge_port_base,
    plan_edge_partition,
)
from .process_group_manager import (
    ProcessGroupManager,
    build_silo_fabric,
    ensure_distributed_initialized,
    silo_fabric_name,
)
from .root_server_manager import RootServerManager
from .trainer_dist_adapter import TrainerDistAdapter

__all__ = [
    "ClientMasterManager",
    "ClientSlaveManager",
    "EdgeServerManager",
    "HierEdge",
    "HierRoot",
    "ProcessGroupManager",
    "RootServerManager",
    "TrainerDistAdapter",
    "HierarchicalClient",
    "build_silo_fabric",
    "edge_clients",
    "edge_fabric_run_id",
    "edge_port_base",
    "ensure_distributed_initialized",
    "hier_partition",
    "launch_silo_processes",
    "plan_edge_partition",
    "prepare_client_args",
    "run_local_hier_world",
    "silo_fabric_name",
]


class HierarchicalClient:
    """Facade: one process of one silo. Role (master/slave) follows
    ``proc_rank_in_silo`` exactly as the reference forks on
    ``process_id`` (``fedml_hierarchical_api.py``)."""

    def __init__(
        self, args, device, dataset, model, silo_devices=None, client_trainer=None
    ) -> None:
        self.args = args
        pg = ProcessGroupManager(args)
        trainer = TrainerDistAdapter(
            args, dataset, model, pg, silo_devices=silo_devices,
            client_trainer=client_trainer,
        )
        if pg.is_master():
            from .. import _world_size
            from ... import constants

            rank = int(getattr(args, "rank", 1))
            if rank < 1:
                raise ValueError("silo FL rank must be >= 1 (0 is the server)")
            self.manager = ClientMasterManager(
                args,
                trainer,
                pg,
                rank=rank,
                size=_world_size(args),
                backend=getattr(args, "backend", constants.COMM_BACKEND_LOCAL),
            )
        else:
            self.manager = ClientSlaveManager(args, trainer, pg)

    def run(self) -> None:
        self.manager.run()
