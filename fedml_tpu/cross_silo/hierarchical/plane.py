"""Hierarchical server plane: topology planning + fabric addressing.

ROADMAP item "scale-out server plane": PR 9's ``scale/tree.py`` folds
through edge accumulators bit-identically but **in one process** — the
server is still a single-process ingestion bottleneck at heavy traffic
(the Smart-NIC diagnosis, PAPERS.md 2307.06561; FedML Parrot's
hierarchical training, 2303.01778). This plane promotes the edges to
REAL ranks over the existing comm seam:

- the **root** is rank 0 of the *root fabric*; the E edges are ranks
  1..E of that fabric (they look like clients to the root's comm
  stack — ReliableChannel, FaultInjector, instrumentation all stack
  exactly as for a flat world);
- each **edge** is additionally rank 0 (the "server") of its own
  *edge fabric*, where its assigned clients connect as their GLOBAL
  ranks — clients run the stock ``FedMLClientManager`` completely
  unchanged, which is what routes their heartbeats client→edge;
- fabric identity per hop: LOCAL fabrics are named
  ``run_{run_id}`` (root) / ``run_{run_id}_edge{E}`` (edge E); gRPC
  fabrics take disjoint port blocks ``grpc_port_base + E *
  hier_port_stride``.

The client→edge **partition** is planned once per run with
``EdgeAggregationTree.assign_by_load`` (the PR 9 boustrophedon deal
over per-client sample counts), so every process — launcher, root,
edges — derives the identical assignment from the same inputs.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

__all__ = [
    "edge_clients",
    "edge_fabric_run_id",
    "edge_port_base",
    "plan_edge_partition",
]


def plan_edge_partition(
    n_clients: int,
    edge_num: int,
    sizes: Optional[Sequence[float]] = None,
) -> Dict[int, int]:
    """Global client rank (1..N) -> edge rank (1..E), load-balanced.

    ``sizes`` are per-client workloads (sample counts) indexed by
    client rank - 1; without them every client weighs 1 and the deal
    degrades to the stable boustrophedon round-robin. Deterministic:
    every process in the world derives the same partition."""
    from ...scale.tree import EdgeAggregationTree

    n, e = int(n_clients), int(edge_num)
    if e < 1:
        raise ValueError(f"edge_num={e}: the edge plane needs >= 1 edge")
    if n < 1:
        raise ValueError(f"n_clients={n}: nothing to partition")
    load = list(sizes) if sizes is not None else [1] * n
    if len(load) != n:
        raise ValueError(
            f"sizes has {len(load)} entries for {n} clients"
        )
    by_index = EdgeAggregationTree.assign_by_load(load, e)
    return {idx + 1: edge + 1 for idx, edge in by_index.items()}


def edge_clients(partition: Dict[int, int]) -> Dict[int, List[int]]:
    """Invert a partition: edge rank -> sorted client ranks."""
    out: Dict[int, List[int]] = {}
    for rank, edge in partition.items():
        out.setdefault(int(edge), []).append(int(rank))
    return {e: sorted(rs) for e, rs in out.items()}


def edge_fabric_run_id(run_id, edge_rank: int) -> str:
    """The LOCAL fabric name / gRPC world id of edge ``edge_rank``'s
    client-facing hop."""
    return f"{run_id}_edge{int(edge_rank)}"


def edge_port_base(args, edge_rank: int) -> int:
    """gRPC port block for edge ``edge_rank``'s client fabric: each
    fabric binds ``port_base + rank``, so blocks are strided by
    ``hier_port_stride`` (which must exceed the largest global client
    rank — validated here, loudly, instead of colliding at bind)."""
    base = int(getattr(args, "grpc_port_base", 8890))
    stride = int(getattr(args, "hier_port_stride", 64) or 64)
    n_clients = int(getattr(args, "client_num_per_round", 0) or 0)
    if n_clients and stride <= n_clients:
        raise ValueError(
            f"hier_port_stride={stride} must exceed the client count "
            f"{n_clients}: edge fabrics bind port_base + global rank"
        )
    return base + int(edge_rank) * stride
