"""Silo master: FL protocol outward, silo broadcast inward.

Parity with ``cross_silo/hierarchical/client_master_manager.py:48-269``:
the rank-0 process of a silo speaks the horizontal 3-message FedAvg
protocol to the server, and before every local round broadcasts
``[round_idx, params, client_index]`` to the silo's slave processes
(``sync_process_group`` :239-249 uses ``dist.broadcast_object_list``;
here the triple is a message on the silo-private control fabric — see
``process_group_manager.build_silo_fabric``: in-process queues for
thread silos, gRPC for one-OS-process-per-host silos). On FINISH the
master relays a silo-finish so slaves exit their loops.
"""

from __future__ import annotations

import logging

import jax
import numpy as np

from ... import constants
from ...core.message import Message
from ..horizontal.fedml_client_manager import FedMLClientManager


class ClientMasterManager(FedMLClientManager):
    def __init__(self, args, trainer, process_group, **kw) -> None:
        super().__init__(args, trainer, **kw)
        self.pg = process_group
        # control fabric: master is silo-rank 0, slaves 1..n-1
        self._silo_com = self.pg.build_fabric()

    def sync_process_group(self, round_idx, params, client_index) -> None:
        """(client_master_manager.py:239-249)"""
        if self.pg.n_proc_in_silo <= 1:
            return
        # networked fabrics serialize; ship host arrays, not jax buffers
        from ...core.aggregation import is_device_tree

        host_params = jax.tree.map(np.asarray, params) if is_device_tree(params) else params
        for slave in self.pg.slave_ranks():
            msg = Message(constants.MSG_TYPE_SILO_SYNC_PROCESS_GROUP, 0, slave)
            msg.add_params(constants.MSG_ARG_KEY_ROUND_INDEX, round_idx)
            msg.add_params(constants.MSG_ARG_KEY_MODEL_PARAMS, host_params)
            msg.add_params(constants.MSG_ARG_KEY_CLIENT_INDEX, client_index)
            self._silo_com.send_message(msg)

    def _train_and_send(self, msg: Message) -> None:
        params = msg.get(constants.MSG_ARG_KEY_MODEL_PARAMS)
        client_index = msg.get(constants.MSG_ARG_KEY_CLIENT_INDEX)
        round_idx = int(msg.get(constants.MSG_ARG_KEY_ROUND_INDEX, 0))
        self.sync_process_group(round_idx, params, client_index)
        super()._train_and_send(msg)

    def handle_message_finish(self, msg: Message) -> None:
        for slave in self.pg.slave_ranks():
            self._silo_com.send_message(
                Message(constants.MSG_TYPE_SILO_FINISH, 0, slave)
            )
        logging.info("silo master rank %d: finish", self.rank)
        # release fabric resources (gRPC server/channels); for LOCAL,
        # drop the process-global fabric so a later run reusing this
        # run_id starts with fresh inboxes (no stale _STOP sentinels)
        self._silo_com.stop_receive_message()
        if hasattr(self._silo_com, "destroy_fabric"):
            self._silo_com.destroy_fabric()
        super().handle_message_finish(msg)
        self.pg.cleanup()
