"""Silo process launcher.

Parity with ``cross_silo/hierarchical/dist_trainer_launcher.py:23-48``:
the reference spawns per-node ``torchrun --rdzv_backend=c10d`` via pdsh
over ssh. Here a silo's processes are plain OS processes that rendezvous
through ``jax.distributed`` (coordinator = process 0), so the launcher
is ordinary ``subprocess`` + env plumbing: one child per silo process,
each told its ``proc_rank_in_silo`` / coordinator / fabric ports.

Single-host only (this environment has no ssh fan-out); multi-host
deployments run the same entry script per host with the same arguments,
exactly like torchrun's per-node invocation.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Dict, List, Optional, Sequence


def launch_silo_processes(
    entry_script: str,
    n_proc_in_silo: int,
    coordinator_port: int,
    silo_grpc_port_base: int,
    extra_argv: Sequence[str] = (),
    env_overrides: Optional[Dict[str, str]] = None,
    local_devices_per_proc: Optional[int] = None,
) -> List[subprocess.Popen]:
    """Spawn ``n_proc_in_silo`` OS processes running ``entry_script``.

    Each child receives ``--proc_rank_in_silo r --n_proc_in_silo N
    --distributed_coordinator 127.0.0.1:<port> --silo_grpc_port_base
    <base>`` plus ``extra_argv``. Caller waits on the returned Popens
    (process 0 is the master and the jax.distributed coordinator).

    ``local_devices_per_proc``: when set, forces that many virtual CPU
    devices per child (test harness; real TPU hosts discover their local
    chips natively).
    """
    procs: List[subprocess.Popen] = []
    for r in range(n_proc_in_silo):
        env = dict(os.environ)
        if local_devices_per_proc:
            env["JAX_PLATFORMS"] = "cpu"
            env["PALLAS_AXON_POOL_IPS"] = ""
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={local_devices_per_proc}"
            )
        if env_overrides:
            env.update(env_overrides)
        cmd = [
            sys.executable,
            entry_script,
            "--proc_rank_in_silo",
            str(r),
            "--n_proc_in_silo",
            str(n_proc_in_silo),
            "--distributed_coordinator",
            f"127.0.0.1:{coordinator_port}",
            "--silo_grpc_port_base",
            str(silo_grpc_port_base),
            *extra_argv,
        ]
        procs.append(subprocess.Popen(cmd, env=env))
    return procs
