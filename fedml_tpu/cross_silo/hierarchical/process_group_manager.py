"""Silo process-group bookkeeping + silo control-fabric dispatch.

TPU analog of ``cross_silo/hierarchical/process_group_manager.py:6-43``:
the reference calls ``dist.init_process_group`` (NCCL/GLOO) plus a
second ``new_group()`` for control messaging. Here the compute group is
the JAX runtime itself — for multi-host silos,
``jax.distributed.initialize`` (the runtime's own process group) is
invoked once per process; collectives then ride ICI/DCN under jit with
no backend objects to manage. The control group is a silo-private
message fabric selected by ``args.silo_backend``:

- ``LOCAL`` (default): in-process queues — valid only when every silo
  actor is a thread of ONE process (the test/sim configuration);
- ``GRPC``: rank-addressed gRPC on ``args.silo_grpc_port_base + rank``
  — the real multi-controller path, one OS process per host, the
  counterpart of the reference's torchrun rendezvous + second gloo
  group (``dist_trainer_launcher.py:23-48``).
"""

from __future__ import annotations

import logging
import threading

_dist_lock = threading.Lock()
_dist_initialized = False


def ensure_distributed_initialized(args) -> bool:
    """Join the JAX runtime's process group (idempotent).

    MUST run before anything touches the backend (first ``jax.devices()``
    / array creation), which is why ``fedml_tpu.init()`` calls this as
    its first JAX-touching act for multi-controller cross-silo runs —
    the analog of the reference initializing torch.distributed from
    torchrun env before building trainers (``fedml/__init__.py:85-130``).
    Returns True when this run is multi-controller."""
    global _dist_initialized
    coordinator = getattr(args, "distributed_coordinator", None)
    n_proc = int(getattr(args, "n_proc_in_silo", 1) or 1)
    if not coordinator:
        return False
    if n_proc <= 1:
        # fail loudly: a coordinator with a 1-process group is always a
        # misconfiguration (the other host would hang as an orphan slave)
        raise ValueError(
            "distributed_coordinator is set but n_proc_in_silo is "
            f"{n_proc}; a multi-controller silo needs n_proc_in_silo >= 2"
        )
    with _dist_lock:
        if not _dist_initialized:
            import jax

            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=n_proc,
                process_id=int(getattr(args, "proc_rank_in_silo", 0) or 0),
            )
            _dist_initialized = True
            logging.info(
                "jax.distributed: joined %s as process %s/%d",
                coordinator,
                getattr(args, "proc_rank_in_silo", 0),
                n_proc,
            )
    return True


def silo_fabric_name(args) -> str:
    """Silo-private control-fabric name (one fabric per FL client)."""
    run_id = getattr(args, "run_id", "0")
    silo = int(getattr(args, "rank", 1))  # FL rank of this silo's client
    return f"hier_{run_id}_silo{silo}"


def build_silo_fabric(args, rank: int, size: int):
    """Control-fabric dispatch for the master->slave round broadcast
    (the reference's second ``new_group()`` for control messaging,
    process_group_manager.py:30-34). Ranks are silo-process ranks
    0..size-1 (0 = master)."""
    backend = str(getattr(args, "silo_backend", "LOCAL") or "LOCAL").upper()
    if backend == "LOCAL":
        from ...core.comm.local import LocalCommunicationManager

        return LocalCommunicationManager(silo_fabric_name(args), rank, size)
    if backend == "GRPC":
        from ...core.managers import build_grpc_manager

        # per-silo port block: silo k (FL rank k, 1-based) owns
        # [base + (k-1)*size, base + k*size) so co-hosted silos don't
        # collide — the port-space analog of silo_fabric_name
        base = int(getattr(args, "silo_grpc_port_base", 9890))
        silo = max(int(getattr(args, "rank", 1)), 1)
        return build_grpc_manager(
            rank,
            size,
            ipconfig_path=getattr(args, "silo_grpc_ipconfig_path", None),
            port_base=base + (silo - 1) * size,
        )
    raise ValueError(f"unsupported silo_backend {backend!r}")


class ProcessGroupManager:
    """Identity + lifecycle of one process inside a silo.

    ``n_proc_in_silo`` / ``proc_rank_in_silo`` mirror the reference's
    torchrun-derived env (``fedml/__init__.py:85-130``). When
    ``args.distributed_coordinator`` is set this is a multi-controller
    run: each silo process is a JAX host process and joins the
    runtime's process group (``jax.distributed.initialize`` — normally
    already done by ``fedml_tpu.init()``; the call here is the
    idempotent safety net for directly-constructed managers).
    """

    def __init__(self, args) -> None:
        self.args = args
        self.n_proc_in_silo = int(getattr(args, "n_proc_in_silo", 1) or 1)
        self.proc_rank_in_silo = int(getattr(args, "proc_rank_in_silo", 0) or 0)
        self.fabric_name = silo_fabric_name(args)
        self.multi_controller = ensure_distributed_initialized(args)

    def is_master(self) -> bool:
        return self.proc_rank_in_silo == 0

    def slave_ranks(self):
        return range(1, self.n_proc_in_silo)

    def build_fabric(self):
        """This process's endpoint on the silo control fabric."""
        return build_silo_fabric(self.args, self.proc_rank_in_silo, self.n_proc_in_silo)

    def cleanup(self) -> None:
        global _dist_initialized
        if self.multi_controller:
            with _dist_lock:
                if _dist_initialized:
                    import jax

                    jax.distributed.shutdown()
                    _dist_initialized = False
