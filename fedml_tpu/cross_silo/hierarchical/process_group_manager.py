"""Silo process-group bookkeeping.

TPU analog of ``cross_silo/hierarchical/process_group_manager.py:6-43``:
the reference calls ``dist.init_process_group`` (NCCL/GLOO) plus a
second ``new_group()`` for control messaging. Here the compute group is
the JAX runtime itself — for multi-host silos,
``jax.distributed.initialize`` (the runtime's own process group) is
invoked once; collectives then ride ICI/DCN under jit with no backend
objects to manage. The control group is a silo-private message fabric
(in-process queues or any configured transport) carrying the
master->slave round broadcast.
"""

from __future__ import annotations

import logging


def silo_fabric_name(args) -> str:
    """Silo-private control-fabric name (one fabric per FL client)."""
    run_id = getattr(args, "run_id", "0")
    silo = int(getattr(args, "rank", 1))  # FL rank of this silo's client
    return f"hier_{run_id}_silo{silo}"


class ProcessGroupManager:
    """Identity + lifecycle of one process inside a silo.

    ``n_proc_in_silo`` / ``proc_rank_in_silo`` mirror the reference's
    torchrun-derived env (``fedml/__init__.py:85-130``). When
    ``args.distributed_coordinator`` is set this is a multi-controller
    run: each silo process is a JAX host process and we join the
    runtime's process group (``jax.distributed.initialize``).
    """

    def __init__(self, args) -> None:
        self.args = args
        self.n_proc_in_silo = int(getattr(args, "n_proc_in_silo", 1) or 1)
        self.proc_rank_in_silo = int(getattr(args, "proc_rank_in_silo", 0) or 0)
        self.fabric_name = silo_fabric_name(args)
        coordinator = getattr(args, "distributed_coordinator", None)
        self.multi_controller = bool(coordinator)
        if self.multi_controller:
            import jax

            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=self.n_proc_in_silo,
                process_id=self.proc_rank_in_silo,
            )
            logging.info(
                "silo process group: joined %s as %d/%d",
                coordinator,
                self.proc_rank_in_silo,
                self.n_proc_in_silo,
            )

    def is_master(self) -> bool:
        return self.proc_rank_in_silo == 0

    def slave_ranks(self):
        return range(1, self.n_proc_in_silo)

    def cleanup(self) -> None:
        if self.multi_controller:
            import jax

            jax.distributed.shutdown()
