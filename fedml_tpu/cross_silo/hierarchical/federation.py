"""Hierarchical server plane facades + local world launcher.

The three-tier federation (clients → edge aggregators → root) as
user-facing objects mirroring the flat ``cross_silo.Server`` /
``Client`` facades:

- :class:`HierRoot` — rank 0 of the root fabric (the global model,
  selection, merge-and-finalize, quarantine/death decisions);
- :class:`HierEdge` — one edge aggregator process (rank E of the root
  fabric, server of its own client fabric);
- clients are the UNCHANGED flat ``cross_silo.Client`` — point them at
  their edge's fabric with :func:`prepare_client_args` and they never
  know an edge tier exists.

Enabled by ``edge_plane: ranks`` + ``edge_num: E`` (arguments.py). The
client→edge partition is planned identically in every process from the
same inputs (:func:`hier_partition`); pass an explicit ``partition``
to any facade to override.

``run_local_hier_world`` wires a whole LOCAL world as threads in one
process — the test/bench harness, mirroring the thread worlds the flat
scenario tests use.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ... import constants
from ..horizontal.fedml_aggregator import FedMLAggregator
from .edge_server_manager import EdgeServerManager
from .plane import (
    edge_clients,
    edge_fabric_run_id,
    edge_port_base,
    plan_edge_partition,
)
from .root_server_manager import RootServerManager

__all__ = [
    "HierEdge",
    "HierRoot",
    "hier_partition",
    "prepare_client_args",
    "run_local_hier_world",
]


def _partition_sizes(args, dataset):
    """Per-client load for ``assign_by_load``: the silo sample counts,
    when every client maps 1:1 onto a silo (the cross-silo common
    case); otherwise uniform. Must be a deterministic function of
    (args, dataset) — every process derives the same partition."""
    n = int(args.client_num_per_round)
    if (
        dataset is not None
        and getattr(dataset, "packed_num_samples", None) is not None
        and int(args.client_num_in_total) == n
        and len(dataset.packed_num_samples) >= n
    ):
        return [float(s) for s in dataset.packed_num_samples[:n]]
    return None


def hier_partition(args, dataset=None) -> Dict[int, int]:
    """Global client rank (1..N) -> edge rank (1..E) for this run."""
    return plan_edge_partition(
        int(args.client_num_per_round),
        int(args.edge_num),
        sizes=_partition_sizes(args, dataset),
    )


def prepare_client_args(args, partition: Dict[int, int]):
    """Point a CLIENT's args at its edge's fabric (in place): the stock
    flat ``Client`` then connects to the edge as if it were the server.
    Returns the args for chaining."""
    rank = int(getattr(args, "rank", 0))
    edge = partition.get(rank)
    if edge is None:
        raise ValueError(
            f"client rank {rank} is not in the edge partition "
            f"(clients 1..{len(partition)})"
        )
    if str(getattr(args, "backend", "LOCAL")).upper() == (
        constants.COMM_BACKEND_GRPC
    ):
        args.grpc_port_base = edge_port_base(args, edge)
    args.run_id = edge_fabric_run_id(getattr(args, "run_id", "0"), edge)
    return args


class HierRoot:
    def __init__(
        self,
        args,
        device,
        dataset,
        model,
        server_aggregator=None,
        partition: Optional[Dict[int, int]] = None,
    ) -> None:
        self.args = args
        self.partition = partition or hier_partition(args, dataset)
        aggregator = FedMLAggregator(
            args,
            model,
            test_data=dataset.test_data_global if dataset else None,
            server_aggregator=server_aggregator,
        )
        self.aggregator = aggregator
        self.manager = RootServerManager(
            args,
            aggregator,
            self.partition,
            backend=getattr(args, "backend", constants.COMM_BACKEND_LOCAL),
        )

    def run(self) -> None:
        self.manager.run()
        com = self.manager.com_manager
        if hasattr(com, "destroy_fabric"):
            com.destroy_fabric()


class HierEdge:
    def __init__(
        self,
        args,
        device,
        dataset,
        model,
        partition: Optional[Dict[int, int]] = None,
    ) -> None:
        self.args = args
        edge_rank = int(getattr(args, "rank", 1))
        if edge_rank < 1:
            raise ValueError("edge rank must be >= 1 (0 is the root)")
        self.partition = partition or hier_partition(args, dataset)
        my_clients = edge_clients(self.partition).get(edge_rank, [])
        # the edge's aggregator is the stock streaming FedMLAggregator
        # (fold + defenses + screen); it never builds the in-process
        # tree (edge_plane=ranks suppresses it) and never evaluates
        aggregator = FedMLAggregator(args, model, test_data=None)
        self.aggregator = aggregator
        self.manager = EdgeServerManager(
            args,
            aggregator,
            edge_rank,
            my_clients,
            backend=getattr(args, "backend", constants.COMM_BACKEND_LOCAL),
        )

    def run(self) -> None:
        self.manager.run()
        com = self.manager.com_manager
        if hasattr(com, "destroy_fabric"):
            com.destroy_fabric()


def run_local_hier_world(
    mk: Callable,
    n_clients: int,
    edge_num: int,
    join_timeout_s: float = 180.0,
    client_wrapper: Optional[Callable] = None,
    edge_wrapper: Optional[Callable] = None,
    on_world: Optional[Callable] = None,
):
    """Run a full LOCAL three-tier world as threads in one process.

    ``mk(role, rank)`` -> ``(args, dataset, model)`` with ``args.rank``
    already set — role is ``"root"`` (rank 0), ``"edge"`` (1..E) or
    ``"client"`` (1..N). Client args are re-pointed at their edge's
    fabric here. ``client_wrapper(rank, client)`` / ``edge_wrapper(
    rank, edge)`` may decorate the thread targets (kill/restart
    choreography); ``on_world(world)`` runs after construction, before
    any thread starts. Returns the dict world: root/edges/clients/
    partition/threads (joined)."""
    from .. import Client

    a0, ds0, m0 = mk("root", 0)
    root = HierRoot(a0, None, ds0, m0)
    partition = root.partition
    edges = {}
    for e in sorted(edge_clients(partition)):
        ae, dse, me = mk("edge", e)
        edges[e] = HierEdge(ae, None, dse, me, partition=partition)
    clients = {}
    for r in range(1, int(n_clients) + 1):
        ac, dsc, mc = mk("client", r)
        prepare_client_args(ac, partition)
        clients[r] = Client(ac, None, dsc, mc)
    world = {
        "root": root,
        "edges": edges,
        "clients": clients,
        "partition": partition,
        "threads": [],
    }
    if on_world is not None:
        on_world(world)
    threads = []
    for e, edge in edges.items():
        target = edge.run if edge_wrapper is None else edge_wrapper(e, edge)
        threads.append(
            threading.Thread(target=target, daemon=True, name=f"hier-edge{e}")
        )
    for r, c in clients.items():
        target = c.run if client_wrapper is None else client_wrapper(r, c)
        threads.append(
            threading.Thread(target=target, daemon=True, name=f"hier-c{r}")
        )
    for t in threads:
        t.start()
    world["threads"] = threads
    root.run()  # blocks until the final round
    for t in threads:
        t.join(timeout=join_timeout_s)
    hung = [t.name for t in threads if t.is_alive()]
    if hung:
        raise RuntimeError(f"hier world: threads hung: {hung}")
    return world
