"""Root of the hierarchical server plane: edges are its "clients".

Rank 0 of the root fabric. Per round it runs the SAME selection as the
flat server (``FedMLAggregator.client_selection`` /
``data_silo_selection`` over the global client ids — which is what
keeps hierarchical training bit-comparable to the flat world), then
ships each live edge its slice of the assignment plus the current
quarantine decision. Each edge folds its clients' uploads on arrival
and ships back ONE merged limb-set; the root merges the limb-sets
through ``StreamingAccumulator.merge`` (the add-only exact jit — tree
finalize bitwise identical to flat) and finalizes at close.

Decision plane (root decides, edges enforce):

- **quarantine** — edges report anomaly-screen trips as evidence; the
  root holds the authoritative quarantine set with
  ``defense_quarantine_rounds`` probation ticked per round close, and
  every round broadcast carries the current list;
- **death/leave** — client deaths are detected AT THE EDGE (heartbeats
  route client→edge only) and reported up; the root excludes reported-
  dead clients from future assignments until an ONLINE event clears
  them. A dead EDGE is detected HERE (edges beat root-ward): its whole
  partition leaves the current round — quorum denominators are summed
  over LIVE edges, so a dead edge can never stall the grace window —
  and a federation with no live edges left finishes loudly;
- **recovery** — a reconnecting edge is RESYNCed with the current
  round + params + its client assignment; the root WAL's per-round
  records carry an ``edge_folds`` sub-ledger (which edge contributed
  which folded ranks) and merges are deduped per (edge, round), so a
  restarted edge re-running an in-flight round can never double-merge.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Dict, List, Optional, Set

from ... import constants
from ...core.aggregation import StreamingAccumulator
from ...core.chaos import chaos_barrier
from ...core.managers import ServerManager
from ...core.message import Message

__all__ = ["RootServerManager"]


class RootServerManager(ServerManager):
    def __init__(
        self,
        args,
        aggregator,
        partition: Dict[int, int],
        comm=None,
        backend=constants.COMM_BACKEND_LOCAL,
    ) -> None:
        from .plane import edge_clients

        self.partition = {int(r): int(e) for r, e in partition.items()}
        self.edge_client_map = edge_clients(self.partition)
        self.edge_num = max(self.edge_client_map) if self.edge_client_map else 0
        super().__init__(args, comm, 0, self.edge_num + 1, backend)
        self.aggregator = aggregator
        self.round_num = int(args.comm_round)
        self.round_idx = 0
        self.is_initialized = False
        from ...core.tracking import MetricsReporter, ProfilerEvent

        self.profiler = ProfilerEvent(args)
        self.metrics_reporter = MetricsReporter(args, keep_history=False)
        self.telemetry.attach_profiler(self.profiler)
        self.telemetry.maybe_start_watchdog(args)
        # -- membership state ------------------------------------------
        self.edge_online: Dict[int, bool] = {}
        self._dead_edges: Set[int] = set()
        self.edge_deaths = 0
        self._dead_clients: Set[int] = set()
        self._left_clients: Set[int] = set()
        # client rank -> remaining probation closes (root's decision)
        self._quarantine: Dict[int, int] = {}
        self.quarantine_rounds = int(
            getattr(args, "defense_quarantine_rounds", 2) or 2
        )
        # -- per-round state -------------------------------------------
        self._round_assignment: Dict[int, int] = {}
        self._expected_edges: Set[int] = set()
        self._reports: Dict[int, Dict] = {}
        self._root_acc: Optional[StreamingAccumulator] = None
        self._last_broadcast_type = None
        self._round_t0 = None
        self.round_walls: List[float] = []  # steady-round walls (bench)
        self.stragglers_dropped = 0
        self.quorum_closes = 0
        # quorum over CLIENTS, denominators summed over live edges
        self.quorum_frac = float(getattr(args, "round_quorum_frac", 0.0) or 0.0)
        self.round_grace_s = float(getattr(args, "round_grace_s", 0.0) or 0.0)
        self._quorum_timer = None
        self._quorum_armed_round = None
        # -- edge liveness (edges beat root-ward) ----------------------
        self._failure_detector = None
        timeout_s = float(getattr(args, "heartbeat_timeout_s", 0.0) or 0.0)
        if timeout_s > 0:
            from ...core.comm.heartbeat import FailureDetector

            self._failure_detector = FailureDetector(
                timeout_s, self._post_edge_dead
            ).start()
        # -- crash recovery (root checkpoint + WAL with edge_folds) ----
        self._ckpt = None
        self._wal = None
        self._resumed = False
        ckpt_dir = getattr(args, "checkpoint_dir", None)
        if ckpt_dir:
            from ...core.checkpoint import RoundCheckpointer, RoundWAL

            self._ckpt = RoundCheckpointer(ckpt_dir)
            self._wal = RoundWAL(ckpt_dir)
            self._ckpt_freq = max(
                1, int(getattr(args, "checkpoint_freq", None) or 1)
            )
            state = self._ckpt.restore()
            if state is not None:
                import jax

                self.round_idx = int(state["round_idx"])
                self.aggregator.set_global_model_params(
                    jax.device_put(state["params"], jax.devices()[0])
                )
                self.aggregator._agg_round = int(
                    state.get("agg_round", self.round_idx)
                )
                self._resumed = True
                logging.info(
                    "hier root resumed at round %d from %s",
                    self.round_idx, ckpt_dir,
                )
                if self._failure_detector is not None:
                    for e in self.edge_client_map:
                        self._failure_detector.watch(e)

    # -- handlers ------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            constants.MSG_TYPE_C2S_CLIENT_STATUS,
            self.handle_message_edge_status,
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_E2R_EDGE_REPORT, self.handle_message_edge_report
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_E2R_CLIENT_EVENT,
            self.handle_message_client_event,
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_C2S_HEARTBEAT, self.handle_message_heartbeat
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_S2S_CLIENT_DEAD, self.handle_message_edge_dead
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_S2S_QUORUM_GRACE,
            self.handle_message_quorum_grace,
        )

    def receive_message(self, msg_type: int, msg_params: Message) -> None:
        if self._failure_detector is not None:
            sender = int(msg_params.get_sender_id())
            if sender != self.rank:
                self._failure_detector.note_alive(sender)
        super().receive_message(msg_type, msg_params)

    # -- presence / liveness of edges ----------------------------------
    def handle_message_edge_status(self, msg: Message) -> None:
        status = msg.get(constants.MSG_ARG_KEY_CLIENT_STATUS)
        sender = int(msg.get_sender_id())
        if status != constants.CLIENT_STATUS_ONLINE:
            return
        if sender not in self.edge_client_map:
            logging.warning("ONLINE from unknown edge rank %d ignored", sender)
            return
        self.edge_online[sender] = True
        self._dead_edges.discard(sender)
        if self._failure_detector is not None:
            self._failure_detector.watch(sender)
        if self.is_initialized:
            self._maybe_resync_edge(sender)
            return
        if all(
            self.edge_online.get(e, False)
            for e in self.edge_client_map
            if e not in self._dead_edges
        ):
            self.is_initialized = True
            self.send_init_msg()

    def handle_message_heartbeat(self, msg: Message) -> None:
        sender = int(msg.get_sender_id())
        if not self.edge_online.get(sender, False):
            synth = Message(constants.MSG_TYPE_C2S_CLIENT_STATUS, sender, 0)
            synth.add_params(
                constants.MSG_ARG_KEY_CLIENT_STATUS,
                constants.CLIENT_STATUS_ONLINE,
            )
            logging.info(
                "root: heartbeat from offline edge %d — treating as "
                "(re)connect", sender,
            )
            self.handle_message_edge_status(synth)

    def _post_edge_dead(self, rank: int) -> None:
        msg = Message(constants.MSG_TYPE_S2S_CLIENT_DEAD, 0, 0)
        msg.add_params(constants.MSG_ARG_KEY_RANK, int(rank))
        try:
            self.send_message(msg)
        except Exception:  # noqa: BLE001 — transport tearing down
            logging.warning(
                "root: death notice for edge %d could not be posted",
                rank, exc_info=True,
            )
            if self._failure_detector is not None:
                self._failure_detector.watch(rank)

    def handle_message_edge_dead(self, msg: Message) -> None:
        """A whole EDGE went silent (the satellite fix: the root must
        not stall its grace window on a dead aggregator tier). Its
        entire client partition leaves the current round — the quorum
        denominator shrinks by the edge's live cohort — and with no
        live edge left the federation finishes loudly. The partition
        itself stays assigned: clients are wired to their edge's
        fabric, so they rejoin when the edge restarts and is RESYNCed."""
        rank = int(msg.get(constants.MSG_ARG_KEY_RANK, -1))
        if (
            self._failure_detector is not None
            and self._failure_detector.seen_recently(rank)
        ):
            self._failure_detector.watch(rank)
            return
        if not self.edge_online.get(rank, False):
            return
        self.edge_online[rank] = False
        self._dead_edges.add(rank)
        self.edge_deaths += 1
        self.telemetry.inc("hier_edges_declared_dead_total")
        logging.warning(
            "root: edge %d declared DEAD at round %d (%d client slots "
            "leave the round); dropping it until it reconnects",
            rank, self.round_idx, len(self.edge_client_map.get(rank, [])),
        )
        if not self.is_initialized:
            return
        self._expected_edges.discard(rank)
        live = [
            e
            for e in self.edge_client_map
            if self.edge_online.get(e, False)
        ]
        if not live:
            logging.error(
                "root: no live edge aggregators remain; finishing loudly "
                "instead of stalling the grace window"
            )
            self.send_finish()
            self.finish()
            return
        if self._expected_edges <= set(self._reports):
            # the dead edge was the only report the round still waited
            # on (a zero-report round closes too: the global model is
            # unchanged and the survivors get the next broadcast)
            self._finish_round()
        else:
            self._maybe_arm_quorum()

    def _maybe_resync_edge(self, edge: int) -> None:
        """Ship a reconnecting edge the CURRENT round (params +
        assignment + quarantine) so a restarted edge resumes instead of
        stalling its partition until the next broadcast."""
        if edge in self._reports:
            return  # already contributed; the next broadcast picks it up
        self._expected_edges.add(edge)
        logging.info("root: RESYNC edge %d into round %d", edge, self.round_idx)
        self.telemetry.inc("cross_silo_resyncs_total")
        self._send_round_to_edge(
            edge, constants.MSG_TYPE_S2C_RESYNC, self._round_assignment
        )

    # -- client events forwarded by edges ------------------------------
    def handle_message_client_event(self, msg: Message) -> None:
        kind = msg.get(constants.MSG_ARG_KEY_EVENT_KIND)
        rank = int(msg.get(constants.MSG_ARG_KEY_RANK, -1))
        edge = int(msg.get_sender_id())
        self.telemetry.inc("hier_client_events_total", kind=str(kind))
        if kind == constants.HIER_EVENT_DEAD:
            self._dead_clients.add(rank)
            self.telemetry.inc("cross_silo_clients_declared_dead_total")
        elif kind == constants.HIER_EVENT_LEAVE:
            self._left_clients.add(rank)
            self._dead_clients.add(rank)
            self.telemetry.inc("cross_silo_client_leaves_total")
        elif kind == constants.HIER_EVENT_ONLINE:
            self._dead_clients.discard(rank)
            self._left_clients.discard(rank)
        elif kind == constants.HIER_EVENT_QUARANTINE:
            # the ROOT decision: federation-wide exclusion for the
            # probation window, enforced by every edge from the next
            # broadcast's quarantine list
            if rank not in self._quarantine:
                self.telemetry.inc("defense_quarantined_total", rank=rank)
            self._quarantine[rank] = self.quarantine_rounds
            logging.warning(
                "root: quarantining rank %d for %d round close(s) on edge "
                "%d screen evidence", rank, self.quarantine_rounds, edge,
            )
        else:
            logging.warning("root: unknown client event %r ignored", kind)
        # a mid-round death/quarantine shrinks the quorum denominator
        self._maybe_arm_quorum()

    # -- round lifecycle ----------------------------------------------
    def send_init_msg(self) -> None:
        if self.round_idx >= self.round_num:
            logging.info(
                "resumed at round %d >= comm_round %d; finishing",
                self.round_idx, self.round_num,
            )
            self.aggregator.test_on_server_for_all_clients(self.round_num - 1)
            self.send_finish()
            self.finish()
            return
        self._broadcast_round(
            constants.MSG_TYPE_S2C_RESYNC
            if self._resumed
            else constants.MSG_TYPE_S2C_INIT_CONFIG
        )

    def _live_edges(self) -> List[int]:
        return sorted(
            e
            for e in self.edge_client_map
            if self.edge_online.get(e, False) and e not in self._dead_edges
        )

    def _broadcast_round(self, msg_type) -> None:
        chaos_barrier("server.broadcast", round=self.round_idx, rank=self.rank)
        quarantined = sorted(self._quarantine)
        self.telemetry.set_gauge("defense_quarantined_now", len(quarantined))
        # SAME selection as the flat server over the same candidate
        # order — the bit-identity anchor: every client trains the same
        # (silo, round) it would have trained in the flat world
        candidates = [
            r
            for r in sorted(self.partition)
            if r not in self._dead_clients and r not in quarantined
        ]
        live_edges = self._live_edges()
        if not candidates or not live_edges:
            logging.error(
                "round %d: no live clients/edges to broadcast to; finishing",
                self.round_idx,
            )
            self.send_finish()
            self.finish()
            return
        selected = self.aggregator.client_selection(
            self.round_idx, candidates, len(candidates)
        )
        silos = self.aggregator.data_silo_selection(
            self.round_idx,
            int(self.args.client_num_in_total),
            len(selected),
        )
        self._round_assignment = dict(zip(selected, silos))
        self._reports = {}
        self._expected_edges = set(live_edges)
        self._root_acc = StreamingAccumulator(
            self.aggregator.get_global_model_params()
        )
        self._last_broadcast_type = msg_type
        self._round_t0 = time.perf_counter()
        self.telemetry.recorder.begin(
            "cross_silo.round", cat="round", round=self.round_idx
        )
        for e in live_edges:
            self._send_round_to_edge(e, msg_type, self._round_assignment)

    def _send_round_to_edge(self, edge, msg_type, assignment) -> None:
        mine = {
            str(r): int(s)
            for r, s in assignment.items()
            if self.partition.get(r) == edge
        }
        msg = Message(msg_type, self.rank, edge)
        msg.add_params(
            constants.MSG_ARG_KEY_MODEL_PARAMS,
            self.aggregator.get_global_model_params(),
        )
        msg.add_params(constants.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
        msg.add_params(constants.MSG_ARG_KEY_HIER_ASSIGNMENT, mine)
        msg.add_params(
            constants.MSG_ARG_KEY_QUARANTINED, sorted(self._quarantine)
        )
        self.send_message(msg)

    def handle_message_edge_report(self, msg: Message) -> None:
        sender = int(msg.get_sender_id())
        report_round = int(msg.get(constants.MSG_ARG_KEY_ROUND_INDEX, -1))
        if report_round != self.round_idx or not self.is_initialized:
            self.telemetry.inc("hier_edge_merge_dups_total", reason="stale")
            logging.warning(
                "root: discarding stale edge %d report for round %d (now %d)",
                sender, report_round, self.round_idx,
            )
            return
        if sender in self._reports:
            # a restarted edge re-ran the round, or the wire duplicated
            # past the channel dedup (fresh incarnation = fresh channel
            # id): merges are exactly-once per (edge, round) HERE
            self.telemetry.inc("hier_edge_merge_dups_total", reason="dup")
            logging.warning(
                "root: duplicate report from edge %d for round %d dropped",
                sender, report_round,
            )
            return
        state = msg.get(constants.MSG_ARG_KEY_EDGE_STATE) or {}
        folded = [int(r) for r in msg.get(constants.MSG_ARG_KEY_FOLDED) or []]
        cohort = [int(r) for r in msg.get(constants.MSG_ARG_KEY_COHORT) or []]
        with self.profiler.span(
            "root_fold", round=self.round_idx, edge=sender
        ):
            if int(state.get("count", 0)):
                shell = StreamingAccumulator(
                    self.aggregator.get_global_model_params()
                ).load_state(state)
                self._root_acc.merge(shell)
        self._reports[sender] = {"folded": folded, "cohort": cohort}
        self._expected_edges.add(sender)  # a resynced straggler counts
        self.telemetry.inc("hier_edge_merges_total", edge=sender)
        if self._expected_edges <= set(self._reports):
            self._finish_round()
        else:
            self._maybe_arm_quorum()

    # -- quorum over clients, denominators summed over edges ----------
    def _quorum_progress(self):
        """(folded_so_far, denominator): folds counted from received
        reports; the denominator adds each still-missing LIVE edge's
        live assigned cohort — a dead edge's clients leave it, which is
        what keeps a grace window from waiting on a corpse tier."""
        folded = sum(len(r["folded"]) for r in self._reports.values())
        den = folded
        for e in self._expected_edges:
            if e in self._reports:
                continue
            den += sum(
                1
                for r in self._round_assignment
                if self.partition.get(r) == e and r not in self._dead_clients
            )
        return folded, den

    def _maybe_arm_quorum(self) -> None:
        if (
            self.quorum_frac <= 0
            or not self.is_initialized
            or self._quorum_armed_round == self.round_idx
            or not self._reports
        ):
            return
        folded, den = self._quorum_progress()
        target = max(1, math.ceil(self.quorum_frac * max(den, 1)))
        if folded < target:
            return
        self._quorum_armed_round = self.round_idx
        round_idx = self.round_idx
        logging.info(
            "root: round %d quorum reached (%d/%d folds over %d edges); "
            "grace %.2fs for the remaining edge reports",
            round_idx, folded, den, len(self._expected_edges),
            self.round_grace_s,
        )

        def fire() -> None:
            out = Message(constants.MSG_TYPE_S2S_QUORUM_GRACE, 0, 0)
            out.add_params(constants.MSG_ARG_KEY_ROUND_INDEX, round_idx)
            try:
                self.send_message(out)
            except Exception:  # noqa: BLE001 — transport tearing down
                logging.warning(
                    "root: quorum grace post failed", exc_info=True
                )

        self._quorum_timer = threading.Timer(self.round_grace_s, fire)
        self._quorum_timer.daemon = True
        self._quorum_timer.start()

    def handle_message_quorum_grace(self, msg: Message) -> None:
        fired_round = int(msg.get(constants.MSG_ARG_KEY_ROUND_INDEX, -1))
        if fired_round != self.round_idx or not self._reports:
            return
        missing = sorted(self._expected_edges - set(self._reports))
        if missing:
            dropped = sum(
                1
                for r in self._round_assignment
                if self.partition.get(r) in missing
            )
            self.stragglers_dropped += dropped
            self.quorum_closes += 1
            self.telemetry.inc("agg_quorum_closes_total")
            logging.warning(
                "root: round %d quorum close — aggregating %d edge "
                "report(s) after %.2fs grace (edge(s) %s dropped, %d "
                "client slot(s))",
                self.round_idx, len(self._reports), self.round_grace_s,
                missing, dropped,
            )
        self._finish_round()

    # -- round close ---------------------------------------------------
    def _cancel_quorum(self) -> None:
        if self._quorum_timer is not None:
            self._quorum_timer.cancel()
            self._quorum_timer = None
        self._quorum_armed_round = None

    def _finish_round(self) -> None:
        chaos_barrier(
            "server.round_close", round=self.round_idx, rank=self.rank
        )
        self._cancel_quorum()
        folded_all: List[int] = []
        edge_folds = {}
        for e, rep in sorted(self._reports.items()):
            folded_all.extend(rep["folded"])
            edge_folds[str(e)] = sorted(rep["folded"])
        n_aggregated = len(folded_all)
        eval_round = self.round_idx
        cohort_ranks = sorted(self._round_assignment)
        t_agg0 = time.perf_counter()
        if n_aggregated:
            with self.profiler.span("aggregate", round=self.round_idx):
                params = self._root_acc.finalize()
                params = self.aggregator._apply_weak_dp(params)
                self.aggregator.set_global_model_params(params)
            # reset_window advances _agg_round exactly like the flat
            # aggregate() — weak-DP keys and custom-aggregator rng
            # streams stay bit-comparable across topologies
            self.aggregator.reset_window()
        else:
            logging.warning(
                "root: round %d closed with no contributions; global "
                "model unchanged", self.round_idx,
            )
        # probation ticks per round close; released ranks re-enter the
        # next broadcast's candidate list (and its quarantine list
        # shrinks — the edges enforce whatever the root now says)
        released = [
            r for r, left in self._quarantine.items() if left - 1 <= 0
        ]
        self._quarantine = {
            r: left - 1
            for r, left in self._quarantine.items()
            if left - 1 > 0
        }
        if released:
            logging.info(
                "root: quarantine probation expired for rank(s) %s",
                sorted(released),
            )
        if self._round_t0 is not None:
            wall = time.perf_counter() - self._round_t0
            self.round_walls.append(wall)
            self.telemetry.observe("round_wall_seconds", wall)
            self.telemetry.observe(
                "round_segment_seconds",
                max(time.perf_counter() - t_agg0, 0.0),
                segment="aggregate",
            )
        self.telemetry.recorder.end(
            "cross_silo.round", cat="round", round=eval_round
        )
        self.round_idx += 1
        ckpt_due = (
            self._ckpt is not None
            and n_aggregated
            and (
                self.round_idx % self._ckpt_freq == 0
                or self.round_idx >= self.round_num
            )
        )
        if self.round_idx >= self.round_num:
            if ckpt_due:
                self._save_checkpoint()
            self._wal_append(eval_round, ckpt_due, cohort_ranks, folded_all, edge_folds)
            if n_aggregated:
                self.aggregator.test_on_server_for_all_clients(eval_round)
            self._report_round(eval_round, len(cohort_ranks), n_aggregated)
            self.send_finish()
            self.finish()
            return
        # overlap like the flat server: next broadcast FIRST, then the
        # durable writes and the eval ride the training window
        self._broadcast_round(constants.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)
        if ckpt_due:
            self._save_checkpoint()
        self._wal_append(eval_round, ckpt_due, cohort_ranks, folded_all, edge_folds)
        if n_aggregated:
            with self.profiler.span("server_eval_overlapped"):
                self.aggregator.test_on_server_for_all_clients(eval_round)
        self._report_round(eval_round, len(cohort_ranks), n_aggregated)

    def _save_checkpoint(self) -> None:
        self._ckpt.save(
            self.round_idx,
            {
                "params": self.aggregator.get_global_model_params(),
                "round_idx": self.round_idx,
                "agg_round": self.aggregator._agg_round,
            },
        )

    def _wal_append(
        self, eval_round, ckpt_saved, cohort_ranks, folded_ranks, edge_folds
    ) -> None:
        """One record per completed round, like the flat server's, PLUS
        the per-edge fold sub-ledger: ``edge_folds`` maps each merged
        edge to the client ranks its limb-set folded — the multi-tier
        invariants (edge sets partition the root's folded set; one
        merge per (edge, round)) check it from artifacts alone."""
        if self._wal is None:
            return
        try:
            self._wal.append(
                eval_round,
                self.round_idx if ckpt_saved else None,
                cohort_ranks,
                folded=folded_ranks,
                extra={"edge_folds": edge_folds},
            )
            self.telemetry.inc("wal_rounds_logged_total")
            self.telemetry.inc(
                "wal_folds_logged_total", len(folded_ranks or [])
            )
        except OSError:
            logging.exception(
                "root: WAL append failed for round %d", eval_round
            )
            self.telemetry.inc("wal_append_failures_total")

    def _report_round(self, round_idx, cohort, n_aggregated) -> None:
        self.metrics_reporter.report(
            {
                "kind": "round_info",
                "round": round_idx,
                "clients": cohort,
                "clients_aggregated": n_aggregated,
                "edges": len(self._live_edges()),
            }
        )
        self.telemetry.heartbeat("cross_silo.round", round_idx)
        self.telemetry.inc("cross_silo_rounds_total")
        self.telemetry.inc("cross_silo_clients_aggregated_total", n_aggregated)
        if self.stragglers_dropped:
            self.telemetry.set_gauge(
                "cross_silo_stragglers_dropped", self.stragglers_dropped
            )

    def send_finish(self) -> None:
        self.telemetry.inc("cross_silo_finish_total")
        for e in self.edge_client_map:
            self.send_message(Message(constants.MSG_TYPE_S2C_FINISH, 0, e))
        logging.info(
            "root: federation finished after %d rounds over %d edges",
            self.round_idx, len(self.edge_client_map),
        )
        if self._failure_detector is not None:
            self._failure_detector.stop()
        self.telemetry.stop_watchdog()
        self.telemetry.export_run_artifacts(
            getattr(self.args, "telemetry_dir", None)
        )
