"""Edge aggregator rank: a real process between clients and root.

One ``EdgeServerManager`` is TWO comm endpoints in one process:

- **downlink** — rank 0 ("the server") of its own edge fabric, where
  its assigned clients run the stock ``FedMLClientManager`` completely
  unchanged: they announce ONLINE here, beat here (heartbeats route
  client→edge only — the root never sees client liveness directly),
  and upload here;
- **uplink** — client-side rank E of the root fabric
  (``core.managers.build_comm_stack``: instrumentation, fault
  injection and the ReliableChannel stack EXACTLY as on the downlink,
  channel outermost), where it announces ONLINE, beats, ships one
  merged limb-set per round close, and forwards client death/leave/
  anomaly evidence as CLIENT_EVENTs.

Per round: the root's broadcast carries this edge's client→silo
assignment plus the root's quarantine decision; the edge re-broadcasts
to its live clients, folds each upload ON ARRIVAL through the PR 7
``StreamingAccumulator`` (via the stock ``FedMLAggregator`` in
streaming mode — clipping defenses fused into the term jit, the PR 8
anomaly screen scoring before the fold), and at close ships the
accumulator's exact 3-limb expansion upstream
(``FedMLAggregator.export_fold_state``). The root merges limb-sets
through the same add-only exact jit, so the federation's finalize is
**bitwise identical** to the flat single-server world — the tree
contract of ``scale/tree.py``, now across processes.

Failure model (docs/hierarchical.md): a dead client is detected HERE
(edge-local ``FailureDetector``), dropped from the edge's expected set
(the report ships without it) and reported upstream — the root
decides membership, the edges enforce. A dead EDGE is the root's
detector's job. An edge restart resumes from its WAL sub-ledger
(``{checkpoint_dir}/edge_{rank}/round_wal.jsonl``): the ledger names
the rounds this edge already folded+shipped; a re-run of an in-flight
round is idempotent because the root dedups merges per (edge, round).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional, Set

from ... import constants
from ...core.chaos import chaos_barrier
from ...core.managers import ServerManager, _build_com_manager, build_comm_stack
from ...core.message import Message
from ...core.tracing import continue_context
from .plane import edge_fabric_run_id, edge_port_base

__all__ = ["EdgeServerManager"]


class EdgeServerManager(ServerManager):
    def __init__(
        self,
        args,
        aggregator,
        edge_rank: int,
        client_ranks,
        comm=None,
        uplink=None,
        backend=constants.COMM_BACKEND_LOCAL,
    ) -> None:
        import copy

        self.edge_rank = int(edge_rank)
        self.client_ranks = sorted(int(r) for r in client_ranks)
        # downlink fabric: this edge is rank 0 of run_{run_id}_edge{E};
        # clients join as their GLOBAL ranks, so "size" only needs to
        # exceed the largest of them (LOCAL inboxes are a dict; gRPC
        # binds port_base + rank inside this edge's port block)
        down_size = (max(self.client_ranks) if self.client_ranks else 0) + 1
        down_args = copy.copy(args)
        down_args.run_id = edge_fabric_run_id(
            getattr(args, "run_id", "0"), self.edge_rank
        )
        if str(backend).upper() == constants.COMM_BACKEND_GRPC:
            down_args.grpc_port_base = edge_port_base(args, self.edge_rank)
        raw_down = comm if comm is not None else _build_com_manager(
            down_args, 0, down_size, backend
        )
        super().__init__(args, raw_down, 0, down_size, backend)
        # uplink: a full comm stack (reliable outermost) toward the root
        edge_num = int(getattr(args, "edge_num", 1) or 1)
        self.uplink = uplink if uplink is not None else build_comm_stack(
            args, rank=self.edge_rank, size=edge_num + 1, backend=backend
        )
        self.uplink.add_observer(_UplinkObserver(self))
        self.aggregator = aggregator
        from ...core.tracking import ProfilerEvent

        self.profiler = ProfilerEvent(args)
        self.telemetry.attach_profiler(self.profiler)
        # -- per-round state (assigned by the root's broadcast) --------
        self.round_idx = -1
        self._round_open = False
        self._round_msg: Optional[Message] = None
        self._pending_round: Optional[Message] = None
        self._assignment: Dict[int, int] = {}  # client rank -> silo idx
        self._quarantined: Set[int] = set()  # root's decision, enforced here
        self.client_online: Dict[int, bool] = {}
        self._dead_clients: Set[int] = set()
        self.reports_shipped = 0
        self.uploads_folded = 0
        self._finished = False
        # -- client liveness (heartbeats route client->edge ONLY) ------
        self._failure_detector = None
        timeout_s = float(getattr(args, "heartbeat_timeout_s", 0.0) or 0.0)
        if timeout_s > 0:
            from ...core.comm.heartbeat import FailureDetector

            self._failure_detector = FailureDetector(
                timeout_s, self._post_client_dead
            ).start()
        # edge->root beats feed the ROOT's failure detector
        self._heartbeat = None
        self._heartbeat_interval_s = float(
            getattr(args, "heartbeat_interval_s", 0.0) or 0.0
        )
        # -- WAL sub-ledger (crash recovery evidence) ------------------
        # one RoundWAL per edge under the federation's checkpoint dir:
        # {round_idx, cohort, folded, kind="edge_fold"} appended
        # WRITE-AHEAD of the upstream ship, so the root's per-round
        # merge records and the edge sub-ledgers cross-check
        # (core/invariants.py multi-tier invariants)
        self._wal = None
        self.completed_through = -1
        ckpt_dir = getattr(args, "checkpoint_dir", None)
        if ckpt_dir:
            import os

            from ...core.checkpoint import RoundWAL

            self._wal = RoundWAL(
                os.path.join(ckpt_dir, f"edge_{self.edge_rank}")
            )
            last = self._wal.last()
            if last is not None:
                self.completed_through = int(last["round_idx"])
                logging.info(
                    "edge %d resumed: WAL sub-ledger shows rounds through "
                    "%d folded+shipped (an in-flight round re-runs; the "
                    "root dedups per (edge, round))",
                    self.edge_rank, self.completed_through,
                )

    # -- lifecycle -----------------------------------------------------
    def run(self) -> None:
        self.register_message_receive_handlers()
        self._uplink_thread = threading.Thread(
            target=self.uplink.handle_receive_message,
            daemon=True,
            name=f"edge{self.edge_rank}-uplink",
        )
        self._uplink_thread.start()
        self._announce_online()
        self.com_manager.handle_receive_message()
        logging.info("edge %d manager loop exited", self.edge_rank)

    def _announce_online(self) -> None:
        msg = Message(constants.MSG_TYPE_C2S_CLIENT_STATUS, self.edge_rank, 0)
        msg.add_params(
            constants.MSG_ARG_KEY_CLIENT_STATUS, constants.CLIENT_STATUS_ONLINE
        )
        self.uplink.send_message(msg)
        if self._heartbeat_interval_s > 0 and self._heartbeat is None:
            from ...core.comm.heartbeat import HeartbeatEmitter

            self._heartbeat = HeartbeatEmitter(
                self._send_uplink_heartbeat, self._heartbeat_interval_s
            ).start()

    def _send_uplink_heartbeat(self) -> None:
        self.uplink.send_message(
            Message(constants.MSG_TYPE_C2S_HEARTBEAT, self.edge_rank, 0)
        )

    def finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        if self._heartbeat is not None:
            self._heartbeat.stop()
            self._heartbeat = None
        if self._failure_detector is not None:
            self._failure_detector.stop()
        self.telemetry.export_run_artifacts(
            getattr(self.args, "telemetry_dir", None)
        )
        self.uplink.stop_receive_message()
        super().finish()

    # -- handler registry ---------------------------------------------
    def register_message_receive_handlers(self) -> None:
        # root -> edge (arrive via the uplink observer)
        for t in (
            constants.MSG_TYPE_S2C_INIT_CONFIG,
            constants.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
            constants.MSG_TYPE_S2C_RESYNC,
        ):
            self.register_message_receive_handler(t, self.handle_message_round)
        self.register_message_receive_handler(
            constants.MSG_TYPE_S2C_FINISH, self.handle_message_finish
        )
        # client -> edge (downlink)
        self.register_message_receive_handler(
            constants.MSG_TYPE_C2S_CLIENT_STATUS,
            self.handle_message_client_status,
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self.handle_message_upload,
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_C2S_HEARTBEAT, self.handle_message_heartbeat
        )
        self.register_message_receive_handler(
            constants.MSG_TYPE_S2S_CLIENT_DEAD, self.handle_message_client_dead
        )

    def receive_message(self, msg_type: int, msg_params: Message) -> None:
        # any downlink traffic proves its client alive (uplink messages
        # come from the root — rank 0 — and are not detector-watched)
        if self._failure_detector is not None:
            sender = int(msg_params.get_sender_id())
            if sender in self.client_ranks:
                self._failure_detector.note_alive(sender)
        super().receive_message(msg_type, msg_params)

    # -- root -> edge: round lifecycle --------------------------------
    def handle_message_round(self, msg: Message) -> None:
        """A round broadcast (init/sync/resync) from the root: hold it
        until every expected client is online (the flat server's
        presence handshake, per edge), then fan out."""
        self._pending_round = msg
        self._maybe_start_round()

    def _pending_assignment(self) -> Dict[int, int]:
        raw = self._pending_round.get(constants.MSG_ARG_KEY_HIER_ASSIGNMENT) or {}
        return {int(k): int(v) for k, v in raw.items()}

    def _maybe_start_round(self) -> None:
        if self._pending_round is None:
            return
        assignment = self._pending_assignment()
        waiting = [
            r
            for r in assignment
            if r not in self._dead_clients
            and not self.client_online.get(r, False)
        ]
        if waiting:
            logging.info(
                "edge %d: holding round %s until rank(s) %s are online",
                self.edge_rank,
                self._pending_round.get(constants.MSG_ARG_KEY_ROUND_INDEX),
                waiting,
            )
            return
        msg, self._pending_round = self._pending_round, None
        self._start_round(msg, assignment)

    def _start_round(self, msg: Message, assignment: Dict[int, int]) -> None:
        if self._round_open:
            # the root advanced without this edge's report (quorum
            # close over the other edges, or a RESYNC re-running the
            # round): the abandoned window's partial folds must never
            # mix into the new round's accumulator
            logging.warning(
                "edge %d: abandoning open round %d (%d partial fold(s)) "
                "for the root's round %s",
                self.edge_rank, self.round_idx,
                self.aggregator.num_received(),
                msg.get(constants.MSG_ARG_KEY_ROUND_INDEX),
            )
            self.telemetry.inc("hier_edge_rounds_abandoned_total")
            self.aggregator.reset_window()
        self.round_idx = int(msg.get(constants.MSG_ARG_KEY_ROUND_INDEX, 0))
        self._round_msg = msg
        self._assignment = assignment
        self._quarantined = {
            int(r) for r in (msg.get(constants.MSG_ARG_KEY_QUARANTINED) or [])
        }
        params = msg.get(constants.MSG_ARG_KEY_MODEL_PARAMS)
        # the broadcast global is BOTH the fold template and the clip
        # reference — same object the flat server would use
        self.aggregator.set_global_model_params(params)
        expected = [
            r for r in sorted(assignment) if r not in self._dead_clients
        ]
        self.aggregator.begin_round([r - 1 for r in expected])
        self._round_open = True
        if self.round_idx <= self.completed_through:
            logging.warning(
                "edge %d: re-running round %d (sub-ledger says it was "
                "already folded+shipped — the ship may not have landed; "
                "the root drops a duplicate merge)",
                self.edge_rank, self.round_idx,
            )
        for rank in expected:
            out = Message(msg.get_type(), 0, rank)
            continue_context(msg, out)
            out.add_params(constants.MSG_ARG_KEY_MODEL_PARAMS, params)
            out.add_params(
                constants.MSG_ARG_KEY_CLIENT_INDEX, assignment[rank]
            )
            out.add_params(constants.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
            self.send_message(out)
        if not expected:
            # the root still expects a report from an edge whose whole
            # partition is dead/quarantined — ship an empty one
            self._close_round()

    def handle_message_finish(self, msg: Message) -> None:
        logging.info("edge %d: finish", self.edge_rank)
        for rank in self.client_ranks:
            self.send_message(Message(constants.MSG_TYPE_S2C_FINISH, 0, rank))
        self.finish()

    # -- client -> edge: presence + liveness --------------------------
    def handle_message_client_status(self, msg: Message) -> None:
        status = msg.get(constants.MSG_ARG_KEY_CLIENT_STATUS)
        sender = int(msg.get_sender_id())
        if status == constants.CLIENT_STATUS_ONLINE:
            was_online = self.client_online.get(sender, False)
            self.client_online[sender] = True
            self._dead_clients.discard(sender)
            if self._failure_detector is not None:
                self._failure_detector.watch(sender)
            if not was_online:
                self._report_event(constants.HIER_EVENT_ONLINE, sender)
            if self._pending_round is not None:
                # a HELD round outranks the open one: the root has
                # already advanced, and this ONLINE may be exactly what
                # the hold was waiting for (_start_round abandons the
                # stale window)
                self._maybe_start_round()
                return
            if self._round_open:
                self._maybe_resync(sender)
                return
            self._maybe_start_round()
        elif status == constants.CLIENT_STATUS_OFFLINE:
            if not self.client_online.get(sender, False):
                return
            self.client_online[sender] = False
            # a leaver must not be awaited by this OR any HELD/future
            # round (same exclusion as a detector death; an ONLINE
            # re-admits) — without this a round assigned before the
            # root learned of the leave would hold forever
            self._dead_clients.add(sender)
            if self._failure_detector is not None:
                self._failure_detector.unwatch(sender)
            self.telemetry.inc("cross_silo_client_leaves_total")
            self._report_event(constants.HIER_EVENT_LEAVE, sender)
            self._drop_pending_slot(sender)

    def handle_message_heartbeat(self, msg: Message) -> None:
        sender = int(msg.get_sender_id())
        if not self.client_online.get(sender, False):
            synth = Message(constants.MSG_TYPE_C2S_CLIENT_STATUS, sender, 0)
            synth.add_params(
                constants.MSG_ARG_KEY_CLIENT_STATUS,
                constants.CLIENT_STATUS_ONLINE,
            )
            logging.info(
                "edge %d: heartbeat from offline rank %d — treating as "
                "(re)connect", self.edge_rank, sender,
            )
            self.handle_message_client_status(synth)

    def _post_client_dead(self, rank: int) -> None:
        """Detector thread -> own inbox (the flat server's loopback
        pattern): membership mutation stays on the dispatch thread."""
        msg = Message(constants.MSG_TYPE_S2S_CLIENT_DEAD, 0, 0)
        msg.add_params(constants.MSG_ARG_KEY_RANK, int(rank))
        try:
            self.send_message(msg)
        except Exception:  # noqa: BLE001 — transport tearing down
            logging.warning(
                "edge %d: death notice for rank %d could not be posted",
                self.edge_rank, rank, exc_info=True,
            )
            if self._failure_detector is not None:
                self._failure_detector.watch(rank)

    def handle_message_client_dead(self, msg: Message) -> None:
        rank = int(msg.get(constants.MSG_ARG_KEY_RANK, -1))
        if (
            self._failure_detector is not None
            and self._failure_detector.seen_recently(rank)
        ):
            self._failure_detector.watch(rank)
            return
        if not self.client_online.get(rank, False):
            return
        self.client_online[rank] = False
        self._dead_clients.add(rank)
        self.telemetry.inc("cross_silo_clients_declared_dead_total")
        logging.warning(
            "edge %d: rank %d declared DEAD at round %d; dropping its "
            "slot and reporting upstream (the root decides membership)",
            self.edge_rank, rank, self.round_idx,
        )
        self._report_event(constants.HIER_EVENT_DEAD, rank)
        self._drop_pending_slot(rank)

    def _drop_pending_slot(self, rank: int) -> None:
        if not self._round_open:
            self._maybe_start_round()  # a held round may now be startable
            return
        if self.aggregator.drop_expected(rank - 1):
            if self.aggregator.check_whether_all_receive():
                self._close_round()

    def _maybe_resync(self, rank: int) -> None:
        """A client (re)appeared mid-round: ship it the current round +
        params + its pending silo (the flat server's RESYNC, one hop
        down)."""
        silo = self._assignment.get(rank)
        if silo is None or rank in self._quarantined:
            return
        if self.aggregator.flag_client_model_uploaded_dict.get(
            rank - 1, False
        ):
            return
        logging.info(
            "edge %d: RESYNC rank %d into round %d (silo %d)",
            self.edge_rank, rank, self.round_idx, silo,
        )
        self.telemetry.inc("cross_silo_resyncs_total")
        out = Message(constants.MSG_TYPE_S2C_RESYNC, 0, rank)
        if self._round_msg is not None:
            continue_context(self._round_msg, out)
        out.add_params(
            constants.MSG_ARG_KEY_MODEL_PARAMS,
            self.aggregator.get_global_model_params(),
        )
        out.add_params(constants.MSG_ARG_KEY_CLIENT_INDEX, silo)
        out.add_params(constants.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
        self.send_message(out)

    # -- client -> edge: uploads (fold on arrival) --------------------
    def handle_message_upload(self, msg: Message) -> None:
        sender = int(msg.get_sender_id())
        upload_round = int(
            msg.get(constants.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
        )
        if not self._round_open or upload_round != self.round_idx:
            self.telemetry.inc("agg_late_uploads_total")
            logging.warning(
                "edge %d: discarding stale upload from rank %d (round %d, "
                "now %d)", self.edge_rank, sender, upload_round, self.round_idx,
            )
            return
        if sender in self._quarantined:
            # root-decided quarantine, enforced here: rejected BEFORE
            # the fold, and the slot drops so the round cannot stall
            self.telemetry.inc("defense_quarantined_rejected_total")
            logging.warning(
                "edge %d: rejecting upload from quarantined rank %d",
                self.edge_rank, sender,
            )
            self._drop_pending_slot(sender)
            return
        # named chaos barrier: the per-upload ingestion boundary — a
        # scheduled kill here models an edge dying mid-fold
        self._chaos_barrier(
            "edge.fold", round=self.round_idx, rank=self.edge_rank
        )
        model_params = msg.get(constants.MSG_ARG_KEY_MODEL_PARAMS)
        encoded = msg.get(constants.MSG_ARG_KEY_MODEL_DELTA)
        if model_params is None and encoded is None:
            logging.error(
                "edge %d: upload from rank %d carries neither model_params "
                "nor model_delta; dropping", self.edge_rank, sender,
            )
            return
        n = msg.get(constants.MSG_ARG_KEY_NUM_SAMPLES)
        status = self.aggregator.receive_upload(
            sender - 1, n, model_params=model_params, encoded=encoded
        )
        if status == "quarantined":
            # the LOCAL screen tripped: evidence goes up (the root
            # decides whether the whole federation excludes the rank);
            # this edge already rejected the upload and drops the slot
            self._report_event(
                constants.HIER_EVENT_QUARANTINE, sender,
                score=self.aggregator.screen.reputation(sender - 1),
            )
            self._drop_pending_slot(sender)
            return
        if status == "folded":
            self.uploads_folded += 1
            self.telemetry.inc(
                "hier_uploads_folded_total", edge=self.edge_rank
            )
        if self.aggregator.check_whether_all_receive():
            self._close_round()

    def _chaos_barrier(self, name: str, **ctx) -> None:
        """A scheduled kill at an edge barrier is the kill -9 analog
        for a thread-world edge: every liveness corpse (heartbeat
        emitter, failure detector, uplink receive loop) dies with the
        "process" — a beating corpse would defeat the root's failure
        detector, and a zombie uplink loop would shadow a restarted
        edge on the same fabric inbox."""
        from ...core.chaos import ProcessKilled

        try:
            chaos_barrier(name, **ctx)
        except ProcessKilled:
            if self._heartbeat is not None:
                self._heartbeat.stop()
                self._heartbeat = None
            if self._failure_detector is not None:
                self._failure_detector.stop()
            self.uplink.stop_receive_message()
            raise

    def _report_event(self, kind: str, rank: int, **extra) -> None:
        """Evidence upstream: the root decides, edges enforce."""
        out = Message(constants.MSG_TYPE_E2R_CLIENT_EVENT, self.edge_rank, 0)
        out.add_params(constants.MSG_ARG_KEY_EVENT_KIND, kind)
        out.add_params(constants.MSG_ARG_KEY_RANK, int(rank))
        for k, v in extra.items():
            out.add_params(k, v)
        self.uplink.send_message(out)

    # -- round close: ship ONE merged limb-set upstream ---------------
    def _close_round(self) -> None:
        # named chaos barrier: a scheduled kill here models an edge
        # dying between its last fold and its upstream ship — the WAL
        # sub-ledger record may or may not exist, the merge never
        # half-applies (the root takes whole reports only)
        self._chaos_barrier(
            "edge.merge_upload", round=self.round_idx, rank=self.edge_rank
        )
        folded_ranks = [i + 1 for i in self.aggregator.folded_indexes()]
        cohort_ranks = sorted(self._assignment)
        with self.profiler.span(
            "edge_merge", round=self.round_idx, rank=self.edge_rank
        ):
            state = self.aggregator.export_fold_state()
        if self._wal is not None:
            try:
                # WRITE-AHEAD of the ship: the sub-ledger must cover
                # every merge the root might hold (multi-tier
                # exactly-once evidence for `fedml-tpu check`)
                self._wal.append(
                    self.round_idx,
                    None,
                    cohort_ranks,
                    folded=folded_ranks,
                    kind="edge_fold",
                    extra={"edge": self.edge_rank},
                )
            except OSError:
                logging.exception(
                    "edge %d: WAL sub-ledger append failed for round %d",
                    self.edge_rank, self.round_idx,
                )
                self.telemetry.inc("wal_append_failures_total")
        out = Message(constants.MSG_TYPE_E2R_EDGE_REPORT, self.edge_rank, 0)
        if self._round_msg is not None:
            continue_context(self._round_msg, out)
        out.add_params(constants.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
        out.add_params(constants.MSG_ARG_KEY_EDGE_STATE, state)
        out.add_params(constants.MSG_ARG_KEY_FOLDED, folded_ranks)
        out.add_params(constants.MSG_ARG_KEY_COHORT, cohort_ranks)
        self.uplink.send_message(out)
        self.reports_shipped += 1
        self.completed_through = max(self.completed_through, self.round_idx)
        self.telemetry.inc("hier_edge_reports_total", edge=self.edge_rank)
        logging.info(
            "edge %d: round %d closed — %d/%d fold(s) shipped upstream "
            "as one limb-set",
            self.edge_rank, self.round_idx, len(folded_ranks),
            len(cohort_ranks),
        )
        self.aggregator.reset_window()
        self._round_open = False
        # a round held while this one was open (root quorum-advanced)
        # can start the moment the window closes
        self._maybe_start_round()


class _UplinkObserver:
    """Re-posts root->edge traffic into the edge's OWN downlink inbox
    (the managers' loopback idiom): every piece of edge state then
    mutates on the single downlink dispatch thread — the same
    single-thread invariant the flat managers keep — instead of racing
    the uplink receive thread against client uploads. The uplink
    channel already consumed its ACK/dedup bookkeeping, so the hop's
    comm seq/chan params are stripped before the re-post."""

    def __init__(self, manager: EdgeServerManager) -> None:
        self.manager = manager

    def receive_message(self, msg_type: int, msg_params: Message) -> None:
        for key in (
            constants.MSG_ARG_KEY_COMM_SEQ,
            constants.MSG_ARG_KEY_COMM_CHAN,
        ):
            msg_params.msg_params.pop(key, None)
        # self-addressed on the downlink fabric: receiver becomes this
        # edge's rank-0 inbox (the wrappers treat loopback as untracked)
        msg_params.msg_params[constants.MSG_ARG_KEY_SENDER] = 0
        msg_params.msg_params[constants.MSG_ARG_KEY_RECEIVER] = 0
        self.manager.com_manager.send_message(msg_params)
