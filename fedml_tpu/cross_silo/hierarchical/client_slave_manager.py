"""Silo slave: block on the round broadcast, join the silo's step.

Parity with ``cross_silo/hierarchical/client_slave_manager.py:5-54``
(``await_sync_process_group`` :39-50 blocks on the rank-0 broadcast,
then trains). The slave never talks to the FL server — its whole world
is the silo-private control fabric plus the silo's SPMD computation.

Transport-agnostic: the slave is an Observer on whatever fabric
``args.silo_backend`` selects (in-process queues for thread silos, gRPC
for one-OS-process-per-host silos), blocking in the fabric's own
receive loop rather than reaching into a queue implementation.
"""

from __future__ import annotations

import logging

from ... import constants
from ...core.comm.base import Observer
from ...core.message import Message


class ClientSlaveManager(Observer):
    def __init__(self, args, trainer, process_group) -> None:
        self.args = args
        self.trainer = trainer
        self.pg = process_group
        self._com = self.pg.build_fabric()
        self._com.add_observer(self)

    def receive_message(self, msg_type, msg: Message) -> None:
        """(client_slave_manager.py:39-50 await_sync_process_group)"""
        if msg_type == constants.MSG_TYPE_SILO_SYNC_PROCESS_GROUP:
            round_idx = int(msg.get(constants.MSG_ARG_KEY_ROUND_INDEX, 0))
            params = msg.get(constants.MSG_ARG_KEY_MODEL_PARAMS)
            client_index = msg.get(constants.MSG_ARG_KEY_CLIENT_INDEX)
            self.trainer.update_dataset(int(client_index))
            self.trainer.participate(params, round_idx)
        elif msg_type == constants.MSG_TYPE_SILO_FINISH:
            self._com.stop_receive_message()
        else:
            logging.warning("silo slave: unexpected msg_type %s", msg_type)

    def run(self) -> None:
        self._com.handle_receive_message()  # blocks until SILO_FINISH
        if hasattr(self._com, "destroy_fabric"):
            # LOCAL fabrics are process-global; drop so a later run
            # reusing this run_id doesn't inherit stale sentinels
            self._com.destroy_fabric()
        logging.info(
            "silo slave %d/%d: finish",
            self.pg.proc_rank_in_silo,
            self.pg.n_proc_in_silo,
        )
        self.pg.cleanup()
