"""Silo slave: block on the round broadcast, join the silo's step.

Parity with ``cross_silo/hierarchical/client_slave_manager.py:5-54``
(``await_sync_process_group`` :39-50 blocks on the rank-0 broadcast,
then trains). The slave never talks to the FL server — its whole world
is the silo-private control fabric plus the silo's SPMD computation.
"""

from __future__ import annotations

import logging

from ... import constants
from ...core.comm.local import LocalCommunicationManager
from ...core.message import Message


class ClientSlaveManager:
    def __init__(self, args, trainer, process_group) -> None:
        self.args = args
        self.trainer = trainer
        self.pg = process_group
        self._com = LocalCommunicationManager(
            self.pg.fabric_name, self.pg.proc_rank_in_silo, self.pg.n_proc_in_silo
        )
        self._finished = False

    def await_sync_process_group(self) -> None:
        """(client_slave_manager.py:39-50)"""
        inbox = self._com.fabric.inbox(self.pg.proc_rank_in_silo)
        msg = inbox.get()
        if not isinstance(msg, Message) or msg.get_type() == constants.MSG_TYPE_SILO_FINISH:
            self._finished = True
            return
        round_idx = int(msg.get(constants.MSG_ARG_KEY_ROUND_INDEX, 0))
        params = msg.get(constants.MSG_ARG_KEY_MODEL_PARAMS)
        client_index = msg.get(constants.MSG_ARG_KEY_CLIENT_INDEX)
        self.trainer.update_dataset(int(client_index))
        self.trainer.participate(params, round_idx)

    def run(self) -> None:
        while not self._finished:
            self.await_sync_process_group()
        logging.info(
            "silo slave %d/%d: finish",
            self.pg.proc_rank_in_silo,
            self.pg.n_proc_in_silo,
        )
        self.pg.cleanup()
