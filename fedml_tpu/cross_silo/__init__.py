"""Cross-silo scenario ("Octopus" parity, SURVEY.md §2.10).

Facades mirroring ``python/fedml/cross_silo/client.py:4-22`` /
``server.py``: a ``Server`` (rank 0) and N silo ``Client``s (ranks
1..N) speaking the 3-message FedAvg protocol over a pluggable transport
— in-process queues (single host / tests) or gRPC (DCN). The presence
handshake, silo-index indirection, and round loop live in
``horizontal/``.
"""

from __future__ import annotations

from .. import constants
from .horizontal.fedml_aggregator import FedMLAggregator
from .horizontal.fedml_client_manager import FedMLClientManager, FedMLTrainer
from .horizontal.fedml_server_manager import FedMLServerManager

__all__ = ["Client", "Server", "HierarchicalClient"]


def __getattr__(name):
    # lazy: hierarchical pulls in jax.sharding; keep the horizontal
    # import path light
    if name == "HierarchicalClient":
        from .hierarchical import HierarchicalClient

        return HierarchicalClient
    raise AttributeError(name)


def _world_size(args) -> int:
    return int(args.client_num_per_round) + 1


class Server:
    def __init__(self, args, device, dataset, model, server_aggregator=None) -> None:
        self.args = args
        aggregator = FedMLAggregator(
            args,
            model,
            test_data=dataset.test_data_global if dataset else None,
            server_aggregator=server_aggregator,
        )
        self.aggregator = aggregator
        self.manager = FedMLServerManager(
            args,
            aggregator,
            rank=0,
            size=_world_size(args),
            backend=getattr(args, "backend", constants.COMM_BACKEND_LOCAL),
        )

    def run(self) -> None:
        self.manager.run()
        com = self.manager.com_manager
        if hasattr(com, "destroy_fabric"):
            com.destroy_fabric()


class Client:
    def __init__(self, args, device, dataset, model, client_trainer=None) -> None:
        self.args = args
        rank = int(getattr(args, "rank", 1))
        if rank < 1:
            raise ValueError("cross-silo client rank must be >= 1 (0 is the server)")
        trainer = FedMLTrainer(args, dataset, model, client_trainer=client_trainer)
        self.trainer = trainer
        self.manager = FedMLClientManager(
            args,
            trainer,
            rank=rank,
            size=_world_size(args),
            backend=getattr(args, "backend", constants.COMM_BACKEND_LOCAL),
        )

    def run(self) -> None:
        self.manager.run()
