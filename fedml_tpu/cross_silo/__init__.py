"""Cross-silo scenario ("Octopus" parity, SURVEY.md §2.10).

The message-layer milestone lands the real ``Client`` / ``Server``
(gRPC + in-process transports, presence handshake, client-id
indirection). Until then the one-line entry points fail with a clear
error instead of an ImportError.
"""

from __future__ import annotations


class _NotYet:
    _msg = (
        "cross-silo is not available yet in this build; "
        "use fedml_tpu.run_simulation() (simulation scenario)"
    )

    def __init__(self, *args, **kwargs):
        raise NotImplementedError(self._msg)


class Client(_NotYet):
    pass


class Server(_NotYet):
    pass
