"""Elastic-mesh preemption tolerance (ROADMAP: robustness).

TPU fleets lose chips and whole pods mid-run — maintenance events,
spot preemption, or a flaky ICI link — and the only defensible
response is the one this module packages: get DURABLE, get OUT, come
back on whatever devices survived, and prove nothing changed.

The seam has three parts:

1. **Signal** — a pluggable :class:`PreemptionSignal` polled once per
   round at the round boundary (never inside a jit). Sources:
   :class:`SimulatedPreemption` (scripted round trigger, the bench and
   tests), :class:`FilePreemption` (touch a file from another process),
   :class:`MetadataPreemption` (the GCE metadata-server
   ``maintenance-event`` poll on real TPU VMs — stdlib urllib, absent
   server reads as "no event"), and :class:`ChaosPreemption` (the
   chaos plane's ``elastic.check`` event, so ``preempt`` /
   ``device.loss`` faults ride the deterministic schedule machinery).

2. **Drain + durable exit** — on notice the round loop finishes the
   in-flight round (the pipeline drains its depth-K deque through the
   same block-until-ready barrier it already uses before snapshots;
   quorum/partial-close worlds close their round through the existing
   machinery), then :func:`preempt_now` appends a WAL
   ``kind="preempt"`` record WRITE-AHEAD of a forced checkpoint and
   raises :class:`Preempted` — a clean controlled exit, not a crash.
   The WAL order matters: a preempt record without its checkpoint is
   detectable (invariants: ``preempt_paired_with_checkpoint``), the
   reverse — a checkpoint whose reason for existing was lost — is not.

3. **Reshaped resume** — the restart passes the *surviving* device set
   to :func:`build_fed_mesh` (``surviving_mesh``), restores the
   checkpoint device-direct onto the new layout via ``restore_target``
   NamedShardings, and reshards any in-flight streaming-accumulator
   state with :func:`reshape_limb_state`: limbs travel through
   ``export_state``/``fold_limbs``, so every fold that happened before
   the preemption is carried exactly once — never re-applied, never
   lost — across the mesh reshape. PR 15's mesh-shape bit-identity
   (every ``(data, fsdp)`` shape finalizes bitwise equal to
   single-chip) then guarantees the resumed run's final params are
   bitwise identical to an uninterrupted run: the ``detail.elastic``
   bench gates ``max_abs_diff == 0.0`` at 8->4 forced devices.

Counters: ``elastic_preemptions_total`` (on the preempt path) and
``elastic_resumes_total`` (on a resume that consumed a preempt WAL
record).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Sequence

from .layout import build_fed_mesh, is_fed_mesh, shard_tree

__all__ = [
    "PreemptionNotice",
    "Preempted",
    "PreemptionSignal",
    "SimulatedPreemption",
    "FilePreemption",
    "MetadataPreemption",
    "ChaosPreemption",
    "make_signal",
    "surviving_mesh",
    "reshape_limb_state",
    "preempt_now",
]


class PreemptionNotice:
    """An impending-eviction notice: why, and whatever the source knew.

    ``detail`` is schema-free source context (the metadata event body,
    the chaos fault step, the trigger round) — it rides into the WAL
    record's ``extra`` block verbatim, so a post-mortem can tell a
    scripted drill from a real maintenance event.
    """

    def __init__(self, reason: str, detail: Optional[Dict[str, Any]] = None):
        self.reason = str(reason)
        self.detail = dict(detail or {})

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"PreemptionNotice(reason={self.reason!r}, detail={self.detail!r})"


class Preempted(RuntimeError):
    """Clean controlled exit after a drained round + durable state.

    Raised by :func:`preempt_now` AFTER the WAL preempt record and the
    forced checkpoint are durable — the catcher (bench harness, a real
    launcher's supervisor) may exit the process knowing a restart on
    the surviving devices resumes bitwise-identically.
    """

    def __init__(self, notice: PreemptionNotice, round_idx: int, ckpt_step: int):
        self.notice = notice
        self.round_idx = int(round_idx)
        self.ckpt_step = int(ckpt_step)
        super().__init__(
            f"preempted ({notice.reason}) after round {round_idx}; "
            f"checkpoint step {ckpt_step} is durable — restart on the "
            "surviving devices to resume"
        )


class PreemptionSignal:
    """Base seam: ``poll(round_idx)`` -> notice or None.

    Polled at the ROUND BOUNDARY only — after the round's fold is
    finalized and any cadence checkpoint has fired — so a notice never
    tears a round: the drain semantics are "finish what is in flight,
    then leave".
    """

    def poll(self, round_idx: int) -> Optional[PreemptionNotice]:
        raise NotImplementedError

    def describe(self) -> str:
        return type(self).__name__


class SimulatedPreemption(PreemptionSignal):
    """Scripted maintenance-event drill: fires once ``round_idx``
    reaches ``at_round``. The bench's mid-run trigger."""

    def __init__(self, at_round: int, reason: str = "maintenance-simulated"):
        self.at_round = int(at_round)
        self.reason = str(reason)

    def poll(self, round_idx: int) -> Optional[PreemptionNotice]:
        if int(round_idx) >= self.at_round:
            return PreemptionNotice(
                self.reason, {"at_round": self.at_round, "round": int(round_idx)}
            )
        return None

    def describe(self) -> str:
        return f"round:{self.at_round}"


class FilePreemption(PreemptionSignal):
    """Fires when ``path`` exists — the cross-process scripting seam
    (an external supervisor touches the file to request drain)."""

    def __init__(self, path: str):
        self.path = str(path)

    def poll(self, round_idx: int) -> Optional[PreemptionNotice]:
        import os

        if os.path.exists(self.path):
            return PreemptionNotice(
                "preempt-file", {"path": self.path, "round": int(round_idx)}
            )
        return None

    def describe(self) -> str:
        return f"file:{self.path}"


class MetadataPreemption(PreemptionSignal):
    """GCE metadata-server maintenance-event poll (real TPU VMs).

    ``http://metadata.google.internal/computeMetadata/v1/instance/
    maintenance-event`` returns ``NONE`` between events and
    ``TERMINATE_ON_HOST_MAINTENANCE`` (or similar) when eviction is
    scheduled. Off-GCE the server is unreachable: that reads as "no
    event", never an error — the signal must not add a failure mode.
    Stdlib urllib only; no new dependencies.
    """

    URL = (
        "http://metadata.google.internal/computeMetadata/v1/"
        "instance/maintenance-event"
    )

    def __init__(self, timeout_s: float = 1.0):
        self.timeout_s = float(timeout_s)

    def poll(self, round_idx: int) -> Optional[PreemptionNotice]:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            self.URL, headers={"Metadata-Flavor": "Google"}
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                body = resp.read().decode("utf-8", "replace").strip()
        except (urllib.error.URLError, OSError, ValueError):
            return None  # off-GCE / transient: no event
        if body and body.upper() != "NONE":
            return PreemptionNotice(
                "maintenance-event", {"event": body, "round": int(round_idx)}
            )
        return None

    def describe(self) -> str:
        return "metadata"


class ChaosPreemption(PreemptionSignal):
    """Bridge from the deterministic chaos plane: a ``preempt`` or
    ``device.loss`` fault scheduled on the ``elastic.check`` event
    becomes a notice — drills ride the same reproducible
    (ChaosSchedule, seed) machinery as every other fault."""

    def poll(self, round_idx: int) -> Optional[PreemptionNotice]:
        from ..core.chaos import elastic_event

        fault = elastic_event(int(round_idx))
        if fault is None:
            return None
        return PreemptionNotice(
            str(fault.get("kind", "preempt")),
            {"chaos_fault": dict(fault), "round": int(round_idx)},
        )

    def describe(self) -> str:
        return "chaos"


def make_signal(spec) -> Optional[PreemptionSignal]:
    """Parse the ``preempt_signal`` knob into a signal source.

    ``None``/``""``/``"none"`` -> no signal; ``"round:K"`` ->
    :class:`SimulatedPreemption`; ``"file:/path"`` ->
    :class:`FilePreemption`; ``"metadata"`` ->
    :class:`MetadataPreemption`; ``"chaos"`` ->
    :class:`ChaosPreemption`. Anything else is a loud ValueError —
    a misspelled signal must not run signal-free.
    """
    if spec is None or isinstance(spec, PreemptionSignal):
        return spec
    s = str(spec).strip()
    if not s or s.lower() == "none":
        return None
    if s.startswith("round:"):
        raw = s[len("round:"):]
        try:
            at = int(raw)
        except (TypeError, ValueError):
            raise ValueError(
                f"preempt_signal={spec!r}: 'round:K' needs an integer "
                "round index"
            ) from None
        if at < 0:
            raise ValueError(
                f"preempt_signal={spec!r}: round index must be >= 0"
            )
        return SimulatedPreemption(at)
    if s.startswith("file:"):
        path = s[len("file:"):]
        if not path:
            raise ValueError(
                f"preempt_signal={spec!r}: 'file:PATH' needs a path"
            )
        return FilePreemption(path)
    if s == "metadata":
        return MetadataPreemption()
    if s == "chaos":
        return ChaosPreemption()
    raise ValueError(
        f"preempt_signal={spec!r}: expected none | round:K | file:PATH "
        "| metadata | chaos"
    )


def surviving_mesh(
    devices: Optional[Sequence] = None,
    mesh_shape: Optional[dict] = None,
    *,
    min_devices: int = 1,
):
    """Build the fed mesh over the devices that survived.

    The restart-world entry point: pass the surviving device list (or
    None for all currently-visible devices) and the reshaped
    ``mesh_shape``. ``min_devices`` (the ``elastic_min_devices`` knob)
    is the floor below which resuming is refused LOUDLY — below it the
    operator wants a page, not a crawl.
    """
    import jax

    devices = list(devices if devices is not None else jax.devices())
    floor = max(1, int(min_devices))
    if len(devices) < floor:
        raise RuntimeError(
            f"elastic resume refused: {len(devices)} surviving devices "
            f"< elastic_min_devices={floor} — not enough capacity to "
            "continue; restore on a bigger slice or lower the floor"
        )
    return build_fed_mesh(devices=devices, mesh_shape=mesh_shape)


def reshape_limb_state(state: Dict[str, Any], mesh) -> Dict[str, Any]:
    """Re-place exported streaming-accumulator limbs onto ``mesh``.

    ``state`` is ``StreamingAccumulator.export_state()`` — three
    host-numpy limb trees plus exact host-float ``total_w`` and int
    ``count``. Each limb is placed fsdp-sharded at rest on the new
    mesh (the same ``shard_tree`` placement params get); feeding the
    result to ``fold_limbs`` on a fresh accumulator carries every
    pre-preemption fold across the reshape bitwise — the limbs ARE the
    fold history, and ``fold_limbs`` re-folds each one exactly once
    through the same two-sum executable regardless of placement.
    """
    if mesh is None or not is_fed_mesh(mesh):
        return state
    out = dict(state)
    out["limbs"] = [shard_tree(limb, mesh) for limb in state["limbs"]]
    return out


def _mesh_devices(mesh) -> List[str]:
    if mesh is None:
        return []
    try:
        return [str(d) for d in mesh.devices.flatten()]
    except Exception:  # pragma: no cover - exotic mesh impls
        return []


def _mesh_shape(mesh) -> Dict[str, int]:
    """JSON-safe ``{axis: size}`` of a mesh (WAL extra blocks)."""
    if mesh is None:
        return {}
    try:
        return {str(k): int(v) for k, v in dict(mesh.shape).items()}
    except Exception:  # pragma: no cover - exotic mesh impls
        return {}


def preempt_now(
    api, ckpt, round_idx: int, notice: PreemptionNotice, *, saved: bool = False
) -> None:
    """Durable exit: WAL ``kind="preempt"`` write-ahead, forced
    checkpoint, then raise :class:`Preempted`.

    Called at the round boundary AFTER round ``round_idx`` fully
    drained (its fold finalized into ``api.global_params``). The WAL
    record lands BEFORE the checkpoint publish — the invariant checker
    pairs every preempt record with the checkpoint it promises
    (``preempt_paired_with_checkpoint``), so a crash between the two
    writes is detectable from artifacts. ``saved=True`` skips the
    forced save when the cadence block already published this round's
    step (the double-save would be wasted IO, not a correctness bug).
    """
    from ..core.checkpoint import RoundWAL

    if ckpt is None:
        raise RuntimeError(
            "preemption notice with no checkpointer: set checkpoint_dir "
            "so the drained round can be made durable before exiting"
        )
    mesh = getattr(api, "mesh", None)
    wal = RoundWAL(ckpt.dir)
    extra = {
        "reason": notice.reason,
        "devices": _mesh_devices(mesh),
        "mesh_shape": _mesh_shape(mesh),
        **notice.detail,
    }
    wal.append(
        int(round_idx), int(round_idx), [], kind="preempt", extra=extra
    )
    if not saved:
        api._save_checkpoint(ckpt, int(round_idx))
    tel = getattr(api, "telemetry", None)
    if tel is not None and getattr(tel, "enabled", False):
        tel.inc("elastic_preemptions_total")
    logging.warning(
        "preemption (%s): round %d drained, checkpoint step %d durable "
        "— exiting cleanly; resume on the surviving devices",
        notice.reason, int(round_idx), int(round_idx),
    )
    raise Preempted(notice, int(round_idx), int(round_idx))


def recovery_clock() -> float:
    """Monotonic stamp for the resume-world recovery metric (the bench
    records time from restart-world build to first completed round)."""
    return time.perf_counter()
