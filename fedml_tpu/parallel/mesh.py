"""Device-mesh construction and federation sharding.

TPU-native replacement for the reference's device layer
(``python/fedml/device/gpu_mapping.py:8-76`` maps MPI ranks to GPUs from
a YAML table): here placement is a ``jax.sharding.Mesh`` over the slice,
discovered from ``jax.devices()``, and "mapping clients to devices" is a
``NamedSharding`` on the leading client axis of the packed federation.
XLA then partitions the vmapped client-update across chips and turns the
FedAvg weighted reduction into an ICI all-reduce — the design SURVEY.md
§7 step 4 calls "the NCCL-stub done right" (the reference's
``SimulatorNCCL`` is an empty stub, simulation/simulator.py:100-108).

Mesh axes convention (2D by default):
  - ``clients``: FL process-parallelism — each group of chips trains a
    disjoint shard of the sampled cohort;
  - ``data``: in-client data parallelism — a client's per-batch examples
    are sharded within the group (the reference's in-silo DDP analog,
    §2.10 hierarchical cross-silo).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.types import Batches


def build_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    mesh_shape: Optional[dict] = None,
) -> Mesh:
    """Build a Mesh from slice topology. ``mesh_shape`` e.g.
    ``{"clients": 4, "data": 2}``; default: all devices on ``clients``."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not mesh_shape:
        mesh_shape = {"clients": n}
    axis_names = tuple(mesh_shape.keys())
    shape = tuple(int(v) for v in mesh_shape.values())
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {mesh_shape} != {n} devices")
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, axis_names)


def federation_spec(mesh: Mesh) -> P:
    """PartitionSpec for packed-federation leaves [C, nb, bs, ...].

    Legacy simulator mesh: client axis over 'clients', per-batch
    example axis over 'data'. Fed (data, fsdp) mesh
    (``parallel/layout.py``): client axis over 'data' only — a
    client's own batches never split, so per-client compute stays
    bitwise identical to the single-chip run."""
    from .layout import is_fed_mesh

    if is_fed_mesh(mesh):
        return P("data")
    has_data = "data" in mesh.axis_names
    return P("clients", None, "data") if has_data else P("clients")


def _cohort_axis_name(mesh: Mesh) -> str:
    """The mesh axis the cohort/client dimension shards over."""
    from .layout import is_fed_mesh

    return "data" if is_fed_mesh(mesh) else "clients"


def pad_federation(
    packed: Batches, num_samples, multiple: int
) -> Tuple[Batches, Any]:
    """Pad the client axis up to a multiple with zero-sample dummy
    clients (all-zero mask). Dummies are never sampled (sampling draws
    indices < real client count) and contribute nothing to masked
    metrics, so padding is semantically invisible."""
    import jax.numpy as jnp

    c = packed.mask.shape[0]
    pad = (-c) % multiple
    if pad == 0:
        return packed, num_samples

    def padleaf(a):
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths)

    return (
        Batches(x=padleaf(packed.x), y=padleaf(packed.y), mask=padleaf(packed.mask)),
        jnp.concatenate([jnp.asarray(num_samples), jnp.zeros(pad)]),
    )


def is_multi_controller(mesh: Mesh) -> bool:
    """True when the mesh spans devices of more than one host process
    (jax.distributed multi-controller run)."""
    return any(d.process_index != jax.process_index() for d in mesh.devices.flat)


def _put(a: Any, sharding: NamedSharding, multi: bool):
    """Host array -> (global) device array. Single controller:
    device_put. Multi-controller: every process holds the same full
    host copy (same seed -> same data) and ``make_array_from_callback``
    hands each process exactly the shards it owns."""
    if not multi:
        return jax.device_put(a, sharding)
    host = np.asarray(a)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx, _h=host: _h[idx]
    )


def place_global(a: Any, sharding: NamedSharding) -> jax.Array:
    """Host/device value -> global array with ``sharding``, working on
    a single controller (plain device_put) AND across a
    multi-controller process group (each process materializes only its
    addressable shards from its own full host copy — callers guarantee
    every process holds the same value, e.g. same-seed data/init)."""
    return _put(a, sharding, is_multi_controller(sharding.mesh))


def shard_federation(
    packed: Batches, num_samples, mesh: Mesh
) -> Tuple[Batches, jax.Array]:
    """Place the packed federation on the mesh (client axis sharded).
    Works on a single host and across a multi-controller process group
    (each process materializes only its addressable shards)."""
    spec = federation_spec(mesh)
    sharding = NamedSharding(mesh, spec)
    multi = is_multi_controller(mesh)
    f = lambda a: _put(a, sharding, multi)
    import jax.numpy as jnp

    ns = _put(
        jnp.asarray(num_samples),
        NamedSharding(mesh, P(_cohort_axis_name(mesh))),
        multi,
    )
    return Batches(x=f(packed.x), y=f(packed.y), mask=f(packed.mask)), ns


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Replicate a pytree (global params / opt state) across the mesh."""
    sharding = NamedSharding(mesh, P())
    multi = is_multi_controller(mesh)
    return jax.tree.map(lambda a: _put(a, sharding, multi), tree)


def pad_cohort_to_mesh(cohort_size: int, mesh: Mesh) -> int:
    """Cohort size must tile the cohort axis ('clients' legacy /
    'data' fed); callers pad sampling up to the next multiple (weights
    of repeats are zeroed)."""
    from .layout import cohort_axis_size

    n = cohort_axis_size(mesh)
    return -(-cohort_size // n) * n
