"""Pipeline parallelism: GPipe schedule over a mesh ``pp`` axis.

The reference's only model-partition story is SplitNN/FedGKT activation
exchange over the comm layer — per-batch Python round-trips, no
schedule (SURVEY.md §2.9: "split/pipeline-style model partition only as
SplitNN ... not true PP scheduling"). This is the TPU-native upgrade:
the whole pipeline is ONE jitted SPMD computation under ``shard_map`` —

- stage weights live in stacked arrays (leading axis S) sharded over
  ``pp``: each device holds exactly its stage;
- microbatches stream through a ``lax.scan`` over M + S - 1 ticks; at
  every tick each device runs its stage on what it holds, then the
  activation hops to the next stage via ``lax.ppermute`` (one ICI
  neighbor exchange — no host involvement);
- the classic GPipe bubble (S - 1 idle ticks) is the only overhead;
  arithmetic on garbage ticks is masked out of the result, and because
  masked values never reach the loss, autodiff assigns them zero
  gradient — the backward pass is the mirrored pipeline XLA derives
  from the scan/ppermute transpose rules.

Everything is static-shaped and data-independent: jit traces one tick
body; there is no per-microbatch Python.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from ._compat import pcast, shard_map


def stack_stage_params(per_stage: list) -> Any:
    """[stage0_tree, stage1_tree, ...] -> one tree with leading axis S."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_stage)


def split_microbatches(x: jax.Array, num_microbatches: int) -> jax.Array:
    """[B, ...] -> [M, B/M, ...]."""
    B = x.shape[0]
    if B % num_microbatches:
        raise ValueError(f"batch {B} not divisible by {num_microbatches} microbatches")
    return x.reshape(num_microbatches, B // num_microbatches, *x.shape[1:])


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    mesh: Mesh,
    axis: str = "pp",
    batch_axis: str = None,
) -> jax.Array:
    """Run ``y_i = stage_{S-1}(... stage_0(x_i))`` for microbatches
    ``x: [M, mb, ...]`` on an ``S``-stage pipeline; returns [M, mb, ...].

    ``stage_params`` leaves have leading axis S == mesh.shape[axis];
    ``stage_fn(params_s, h) -> h`` must preserve the activation shape
    (uniform stages — the transformer-block case).

    ``batch_axis`` composes data parallelism with the pipeline: the
    microbatch examples axis (``x`` axis 1) is sharded over that mesh
    axis, so each dp replica streams its own slice through an identical
    pipeline (stage weights replicated across dp — the spec simply
    doesn't mention it); gradient reduction across dp belongs to the
    caller's jit (XLA SPMD inserts it).
    """
    S = mesh.shape[axis]
    M = x.shape[0]
    leading = jax.tree.leaves(stage_params)[0].shape[0]
    if leading != S:
        raise ValueError(f"stage_params leading axis {leading} != pp axis {S}")
    if batch_axis is not None and x.shape[1] % mesh.shape[batch_axis]:
        raise ValueError(
            f"batch_axis {batch_axis}={mesh.shape[batch_axis]} must divide "
            f"microbatch size {x.shape[1]}"
        )
    x_spec = P(None, batch_axis) if batch_axis else P()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis), x_spec),
        out_specs=x_spec,
    )
    def run(params, x):
        params = jax.tree.map(lambda a: a[0], params)  # this device's stage
        # x arrives replicated (device-invariant); the scan carry is
        # device-varying (each stage holds different activations), so
        # mark everything feeding it as varying over the pp axis
        x = pcast(x, axis, to="varying")
        s = lax.axis_index(axis)
        perm = [(i, i + 1) for i in range(S - 1)]  # non-cyclic: stage s -> s+1

        def tick(carry, t):
            recv, outs = carry
            inp = jnp.where(
                s == 0, lax.dynamic_index_in_dim(x, jnp.minimum(t, M - 1), 0, False), recv
            )
            y = stage_fn(params, inp)
            idx = jnp.clip(t - (S - 1), 0, M - 1)
            outs = jnp.where(
                t >= S - 1, lax.dynamic_update_index_in_dim(outs, y, idx, 0), outs
            )
            return (lax.ppermute(y, axis, perm), outs), None

        outs0 = jnp.zeros_like(x)
        (_, outs), _ = lax.scan(
            tick, (jnp.zeros_like(x[0]), outs0), jnp.arange(M + S - 1)
        )
        # only the last stage holds real outputs; replicate them
        return lax.psum(jnp.where(s == S - 1, outs, jnp.zeros_like(outs)), axis)

    return run(stage_params, x)
