"""Sequence / context parallelism: ring attention + Ulysses all-to-all.

The reference has NO long-context subsystem (SURVEY.md §2.9 census /
§5: its only sequence models are small LSTMs) — this is the green-field
TPU-first design the build plan calls for. Two strategies over a mesh
``sp`` axis, both usable under ``shard_map`` with the sequence dimension
sharded:

- **Ring attention**: queries stay put; K/V shards rotate around the
  ring via ``jax.lax.ppermute`` (XLA lowers it to ICI neighbor
  exchanges) while a streaming/online softmax (flash-attention
  numerics: running max ``m``, normalizer ``l``, accumulator ``o``)
  folds in each block. Peak memory per chip is O(T/n · T/n) for scores
  — full-sequence attention never materializes. Differentiable as-is
  (``ppermute`` has a transpose rule; the scan is re-traced by autodiff).

- **Ulysses (all-to-all)**: ``lax.all_to_all`` re-shards [T/n, H] ->
  [T, H/n], runs ordinary full attention per head group, and re-shards
  back. One collective pair per layer; attention math stays dense —
  the right trade when heads >= n and T/n is small.

Both return results identical (up to fp error) to full attention on the
gathered sequence, verified in tests on the 8-device CPU mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def full_attention(q, k, v, causal: bool = True, scale: Optional[float] = None):
    """Reference dense attention (the oracle). [B, T, H, D] layout."""
    scale = scale or (q.shape[-1] ** -0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    scale: Optional[float] = None,
    block_k: Optional[int] = None,
):
    """Blockwise ring attention under ``shard_map``.

    Per-shard shapes [B, T/n, H, D] with the sequence sharded
    contiguously along ``axis_name`` (shard i holds positions
    [i*T/n, (i+1)*T/n)). K/V blocks travel the ring; the online softmax
    accumulates exactly the full-attention result.

    ``block_k`` chunks each hop's K/V shard for the score computation:
    the per-chip panel shrinks from [B, H, Tq, Tk] to [B, H, Tq, bk]
    (the same online-softmax fold, just more steps — bitwise-identical
    math in f32), so per-chip attention memory is O(Tq x bk) no matter
    how long the resident shard is. Default (None) folds the whole
    shard per hop. Pure ``lax.scan``, so autodiff needs no custom
    backward.
    """
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = scale or (D**-0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]

    bk = int(block_k) if block_k else Tk
    if bk <= 0 or Tk % bk:
        raise ValueError(
            f"ring block_k={bk} must be a positive divisor of the K/V "
            f"shard length {Tk}"
        )
    n_chunks = Tk // bk

    q_pos = my_idx * Tq + jnp.arange(Tq)  # global query positions

    def fold(acc, k_chunk, v_chunk, k_pos):
        """Fold one [bk] K/V chunk into the online-softmax state.
        Scores and the state accumulate in f32 even for bf16 inputs —
        l sums T terms and bf16's 8 mantissa bits drift."""
        o, m, l = acc
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_chunk, preferred_element_type=jnp.float32
        ) * scale  # [B,H,Tq,bk] f32
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        s_max = s.max(axis=-1)  # [B,H,Tq]
        m_new = jnp.maximum(m, s_max)
        # renormalize the running state to the new max
        correction = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])  # [B,H,Tq,bk]
        l_new = l * correction + p.sum(axis=-1)
        pv = jnp.einsum(
            "bhqk,bkhd->bqhd", p, v_chunk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
        return o_new, m_new, l_new

    def step(carry, i):
        o, m, l, k_cur, v_cur = carry
        src = (my_idx - i) % n  # owner of the block we currently hold
        base = src * Tk

        if n_chunks == 1:
            o, m, l = fold((o, m, l), k_cur, v_cur, base + jnp.arange(Tk))
        else:

            def inner(acc, j):
                kc = lax.dynamic_slice_in_dim(k_cur, j * bk, bk, axis=1)
                vc = lax.dynamic_slice_in_dim(v_cur, j * bk, bk, axis=1)
                return fold(acc, kc, vc, base + j * bk + jnp.arange(bk)), None

            (o, m, l), _ = lax.scan(inner, (o, m, l), jnp.arange(n_chunks))
        # rotate KV one hop around the ring
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_nxt, v_nxt), None

    o0 = jnp.zeros((B, Tq, H, D), jnp.float32)
    m0 = jnp.full((B, H, Tq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tq), jnp.float32)
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(n))
    l_t = l.transpose(0, 2, 1)[..., None]  # [B,Tq,H,1]
    return (o / jnp.maximum(l_t, 1e-30)).astype(q.dtype)


def _blockwise_or_full(q, k, v, causal: bool, scale: Optional[float]):
    """Per-chip attention for the gathered sequence: the pallas flash
    kernel when the shape tiles (blockwise — the [T, T] score matrix
    never hits HBM), dense attention otherwise (tiny/odd test shapes;
    non-causal, which the kernel does not implement). Numerics match
    full attention up to fp error either way."""
    from ..ops.flash_attention import flash_attention, pick_block

    b = pick_block(q.shape[1], minimum=8)
    if b is None or not causal:
        return full_attention(q, k, v, causal=causal, scale=scale)
    return flash_attention(q, k, v, causal, scale, b, b)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    scale: Optional[float] = None,
):
    """DeepSpeed-Ulysses-style all-to-all sequence parallelism under
    ``shard_map``: re-shard sequence->heads, per-chip attention on the
    full sequence for a head group (the pallas flash kernel when the
    shape tiles — without it the gathered [T, T] scores are exactly the
    memory wall sequence parallelism exists to avoid), re-shard back.
    Requires ``H % n == 0``. Per-shard input [B, T/n, H, D]."""
    n = lax.psum(1, axis_name)
    if q.shape[2] % n:
        raise ValueError(
            f"ulysses needs heads ({q.shape[2]}) divisible by the sp axis "
            f"size ({n}); use ring attention otherwise"
        )

    def a2a(x, split_head: bool):
        # [B, T/n, H, D] -> [B, T, H/n, D]  (split_head) or inverse
        if split_head:
            return lax.all_to_all(
                x, axis_name, split_axis=2, concat_axis=1, tiled=True
            )
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qg, kg, vg = a2a(q, True), a2a(k, True), a2a(v, True)
    og = _blockwise_or_full(qg, kg, vg, causal=causal, scale=scale)
    return a2a(og, False)


def make_sequence_sharded_attention(
    mesh, strategy: str = "ring", causal: bool = True, axis_name: str = "sp",
    batch_axis: str = None, ring_block_k: Optional[int] = None,
):
    """Wrap a strategy as a [B, T, H, D] -> [B, T, H, D] function whose
    sequence axis is sharded over ``mesh[axis_name]`` via shard_map —
    drop-in for dense attention inside a pjit'ed training step.

    ``batch_axis`` composes data parallelism: the batch axis is sharded
    over that mesh axis (each dp replica runs its own ring/all-to-all
    over the sp axis; without it, a multi-axis mesh would gather the
    dp-sharded batch at the shard_map boundary)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ._compat import shard_map

    strategies = {"ring": ring_attention, "ulysses": ulysses_attention}
    if strategy not in strategies:
        raise ValueError(
            f"sp_strategy {strategy!r}: pick one of {sorted(strategies)}"
        )
    fn = strategies[strategy]
    inner = functools.partial(fn, axis_name=axis_name, causal=causal)
    if ring_block_k:
        if strategy != "ring":
            # refuse loudly: the user tuned a memory cap that this
            # strategy would silently not honor
            raise ValueError(
                f"sp_ring_block={ring_block_k} only applies to "
                f"sp_strategy 'ring', not {strategy!r}"
            )
        inner = functools.partial(inner, block_k=ring_block_k)
    spec = P(batch_axis, axis_name, None, None)

    return shard_map(
        inner,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
