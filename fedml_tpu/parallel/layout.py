"""Canonical ``(data, fsdp)`` federation mesh + PartitionSpec layout.

The legacy mesh simulator (``parallel/mesh.py``) names its axes
``(clients, data)`` and only ever shards the cohort — params ride
replicated, so the largest trainable model is whatever fits one chip's
HBM. This module is the production vocabulary (ROADMAP item 1,
"Automatic Cross-Replica Sharding of Weight Update" 2004.13336):

- ``data``  — the cohort axis. The sampled clients' batches shard
  along it; each lane trains a disjoint slice of the cohort.
- ``fsdp``  — the parameter axis. Params and server-optimizer state
  are sharded AT REST along it (each chip holds ``1/fsdp`` of the
  model) and gathered at use, ZeRO-3 style — which is what unlocks
  models larger than one chip's HBM while keeping per-client compute
  bitwise identical to the single-chip run (no tensor-parallel
  partial-sum reductions are ever introduced; see
  ``simulation/fedavg_api.build_round_fn``).

The layout table is a ``SpecLayout`` (SNIPPETS [2]): one canonical
PartitionSpec per PARAMETER CLASS, where the class of a leaf is a pure
function of its name and rank (``classify_param``). The frame models'
whole vocabulary is four classes (``dense_kernel`` / ``conv_kernel`` /
``embedding`` / ``vector``, plus rank-0 ``scalar`` for optimizer
counts); an unknown leaf fails LOUDLY — silently replicating a new
parameter family would quietly forfeit the HBM win.

A spec whose fsdp axis does not divide the leaf's sharded dimension
degrades to replication for that leaf (SNIPPETS [3] ``shard_params``):
layout is a placement choice and must never constrain model geometry.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Optional, Sequence, Tuple

import numpy as np

Params = Any

# the fed-mesh axis vocabulary; a mesh carrying BOTH names is a fed
# mesh (is_fed_mesh) and routes every placement through this module
AXIS_COHORT = "data"
AXIS_PARAM = "fsdp"

# the closed parameter-class vocabulary of the frame models
# (models/*.py: flax leaves are kernel/embedding/bias/scale; optimizer
# state mirrors param shapes plus rank-0 counts)
PARAM_CLASSES = (
    "dense_kernel",  # rank >= 2 'kernel' (Dense / DenseGeneral)
    "conv_kernel",   # rank-4 'kernel' (Conv HWIO)
    "embedding",     # 'embedding' tables (vocab x width)
    "vector",        # rank-1 bias / norm scale
    "scalar",        # rank-0 (optax counts, schedules)
)


def classify_param(name: str, ndim: int) -> str:
    """Leaf (name, rank) -> parameter class. LOUD on unknowns: a new
    parameter family must be added to the layout table deliberately,
    not silently replicated."""
    if ndim == 0:
        return "scalar"
    if ndim == 1:
        return "vector"
    if name == "embedding":
        return "embedding"
    if name == "kernel":
        return "conv_kernel" if ndim == 4 else "dense_kernel"
    raise ValueError(
        f"unknown parameter class for leaf {name!r} (rank {ndim}): not in "
        f"the layout vocabulary {PARAM_CLASSES} — add a canonical "
        "PartitionSpec for this family to parallel/layout.SpecLayout"
    )


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """Canonical PartitionSpecs per parameter class on a (data, fsdp)
    mesh (SNIPPETS [2] ``SpecLayout``). One table, consulted by the
    round engine, the planet group fn, the simulators' placement and
    the layout tests — never re-derived ad hoc at a call site."""

    data_axis: str = AXIS_COHORT
    fsdp_axis: str = AXIS_PARAM

    def dense_kernel(self, ndim: int = 2):
        """[in, out] (or DenseGeneral [..., out]): shard the leading
        (reduction) axis at rest; gathered at use, so the matmul itself
        is never tensor-split."""
        from jax.sharding import PartitionSpec as P

        return P(self.fsdp_axis, *(None,) * (ndim - 1))

    def conv_kernel(self, ndim: int = 4):
        """HWIO: shard output channels — the largest axis of every
        frame conv and the one FSDP gathers cheapest."""
        from jax.sharding import PartitionSpec as P

        return P(*(None,) * (ndim - 1), self.fsdp_axis)

    def embedding(self, ndim: int = 2):
        """[vocab, width]: shard the vocab rows."""
        from jax.sharding import PartitionSpec as P

        return P(self.fsdp_axis, *(None,) * (ndim - 1))

    def vector(self, ndim: int = 1):
        from jax.sharding import PartitionSpec as P

        return P()

    def scalar(self, ndim: int = 0):
        from jax.sharding import PartitionSpec as P

        return P()

    def spec_for(self, cls: str, ndim: int):
        """Parameter class -> canonical PartitionSpec (validated
        against PARAM_CLASSES — the loud-unknown contract)."""
        if cls not in PARAM_CLASSES:
            raise ValueError(
                f"unknown parameter class {cls!r}; the layout table "
                f"covers {PARAM_CLASSES}"
            )
        return getattr(self, cls)(ndim)

    def cohort(self, ndim: int):
        """Cohort-shaped leaves [C, ...]: client axis over ``data``,
        everything within a client unsharded."""
        from jax.sharding import PartitionSpec as P

        return P(self.data_axis, *(None,) * (ndim - 1))

    def sharded_axis(self, cls: str, ndim: int) -> Optional[int]:
        """Which axis the class shards (None = replicated) — the
        divisibility check and the tests read the table through this."""
        spec = self.spec_for(cls, ndim)
        for i, s in enumerate(spec):
            if s is not None:
                return i
        return None


def _leaf_name(path) -> str:
    """Last dict key on a tree path ('' for bare leaves — classified
    by rank alone, the optimizer-state case)."""
    for entry in reversed(path):
        key = getattr(entry, "key", None)
        if isinstance(key, str):
            return key
    return ""


def param_spec(
    layout: SpecLayout, name: str, shape: Tuple[int, ...], fsdp_size: int
):
    """Canonical spec for one leaf, degraded to replication when the
    fsdp axis does not divide the sharded dimension (SNIPPETS [3]):
    placement must never constrain model geometry."""
    cls = classify_param(name, len(shape))
    spec = layout.spec_for(cls, len(shape))
    axis = layout.sharded_axis(cls, len(shape))
    if axis is not None and shape[axis] % max(fsdp_size, 1) != 0:
        return layout.vector()  # P(): replicated
    return spec


def tree_specs(tree: Params, mesh, layout: Optional[SpecLayout] = None):
    """Param pytree -> pytree of PartitionSpecs via the layout table.
    Works on concrete arrays and ShapeDtypeStructs alike (shapes only).
    """
    import jax

    layout = layout or SpecLayout()
    fsdp = int(mesh.shape.get(layout.fsdp_axis, 1))
    return jax.tree_util.tree_map_with_path(
        lambda p, leaf: param_spec(
            layout, _leaf_name(p), tuple(np.shape(leaf)), fsdp
        ),
        tree,
    )


def tree_shardings(tree: Params, mesh, layout: Optional[SpecLayout] = None):
    """Param pytree -> pytree of NamedShardings (the placement form of
    :func:`tree_specs`)."""
    import jax
    from jax.sharding import NamedSharding

    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        tree_specs(tree, mesh, layout),
        is_leaf=lambda x: not isinstance(x, (dict, list, tuple)),
    )


def shard_tree(tree: Params, mesh, layout: Optional[SpecLayout] = None) -> Params:
    """Place a param/optimizer pytree on the mesh per the layout table
    — FSDP at-rest sharding. Single- and multi-controller (reuses
    ``parallel.mesh.place_global``'s placement seam)."""
    import jax
    from jax.sharding import NamedSharding

    from .mesh import _put, is_multi_controller

    layout = layout or SpecLayout()
    fsdp = int(mesh.shape.get(layout.fsdp_axis, 1))
    multi = is_multi_controller(mesh)
    return jax.tree_util.tree_map_with_path(
        lambda p, leaf: _put(
            leaf,
            NamedSharding(
                mesh,
                param_spec(layout, _leaf_name(p), tuple(np.shape(leaf)), fsdp),
            ),
            multi,
        ),
        tree,
    )


def constrain_tree(tree: Params, mesh, layout: Optional[SpecLayout] = None) -> Params:
    """In-jit: pin a param-shaped pytree to the layout's at-rest
    shardings (``with_sharding_constraint``). The round engine applies
    this to the aggregated output so the new global params land
    fsdp-sharded without a reshard after the fact."""
    import jax
    from jax.sharding import NamedSharding

    layout = layout or SpecLayout()
    fsdp = int(mesh.shape.get(layout.fsdp_axis, 1))
    return jax.tree_util.tree_map_with_path(
        lambda p, leaf: jax.lax.with_sharding_constraint(
            leaf,
            NamedSharding(
                mesh,
                param_spec(layout, _leaf_name(p), tuple(np.shape(leaf)), fsdp),
            ),
        ),
        tree,
    )


def constrain_cohort(tree: Params, mesh, layout: Optional[SpecLayout] = None) -> Params:
    """In-jit: shard cohort-shaped leaves [C, ...] along ``data``."""
    import jax
    from jax.sharding import NamedSharding

    layout = layout or SpecLayout()
    return jax.tree.map(
        lambda leaf: jax.lax.with_sharding_constraint(
            leaf, NamedSharding(mesh, layout.cohort(leaf.ndim))
        ),
        tree,
    )


def constrain_replicated(tree: Params, mesh) -> Params:
    """In-jit: gather a pytree replicated — the FSDP all-gather at use.

    Per-client local training runs against the FULL parameter tree on
    every data lane (each lane trains its cohort slice with identical
    per-client HLO), which is what keeps the mesh round bitwise
    identical to the single-chip vmap path: no cross-client or
    cross-shard reduction is introduced anywhere in a client's
    compute."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.tree.map(
        lambda leaf: jax.lax.with_sharding_constraint(
            leaf, NamedSharding(mesh, P())
        ),
        tree,
    )


def fed_compute_constraints(mesh, params: Params, cohort: Params, *aux):
    """THE fed-mesh in-jit entry discipline, in one place (shared by
    ``fedavg_api.build_round_fn`` and ``scale.engine.build_group_fn``
    — the bitwise-identity proof depends on both engines applying the
    identical sequence, so it must never be hand-synchronized):

    - ``cohort`` (leading client axis) shards along ``data``;
    - ``params`` gather REPLICATED — the FSDP at-use gather, so every
      client's local training runs whole on its lane, never
      tensor-split;
    - every ``aux`` leaf (sample counts, validity masks, routing
      one-hots) gathers replicated too, so weight normalization sees
      lane-invariant bits.

    Returns ``(params, cohort, aux...)``. Pair with
    :func:`pin_cohort_outputs` on the vmap result."""
    out_aux = constrain_replicated(aux, mesh) if aux else ()
    return (
        constrain_replicated(params, mesh),
        constrain_cohort(cohort, mesh),
        *out_aux,
    )


def pin_cohort_outputs(mesh, stacked: Params) -> Params:
    """Pin per-client vmap outputs to cohort-only sharding: a
    downstream fsdp constraint (the aggregated carry, the groupwise
    einsum) must not propagate a param-dim sharding BACKWARD into the
    per-client matmuls — partial sums + psum there would break the
    bitwise identity with the single-chip run (measured)."""
    return constrain_cohort(stacked, mesh)


# ---------------------------------------------------------------------
# fed-mesh construction / introspection
# ---------------------------------------------------------------------


def is_fed_mesh(mesh) -> bool:
    """True for the (data, fsdp) production mesh; False for the legacy
    (clients[, data]) simulator mesh and for None."""
    if mesh is None:
        return False
    names = set(mesh.axis_names)
    return AXIS_PARAM in names and AXIS_COHORT in names


def fed_mesh_shape(mesh_shape: Optional[dict]) -> bool:
    """Does a ``mesh_shape`` knob value ask for the fed vocabulary?
    (an ``fsdp`` axis, or ``data`` without the legacy ``clients``)."""
    if not mesh_shape:
        return False
    return AXIS_PARAM in mesh_shape or (
        AXIS_COHORT in mesh_shape and "clients" not in mesh_shape
    )


def build_fed_mesh(
    devices: Optional[Sequence] = None, mesh_shape: Optional[dict] = None,
    *, warn_nonpartitionable: bool = True,
):
    """Build the named (data, fsdp) mesh. ``mesh_shape`` e.g.
    ``{"data": 4, "fsdp": 2}``; a missing axis defaults to size 1 (both
    axes always exist, so the layout table's specs always resolve).
    Default: all devices on ``data``. ``warn_nonpartitionable=False``
    is for lowering-only callers (the audit provider) where nothing
    executes and the random-stream warning below would be noise."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    if warn_nonpartitionable and not jax.config.jax_threefry_partitionable:
        # the in-client shuffle (and any other in-jit randomness) must
        # be SHARDING-INVARIANT for the mesh round to be bitwise
        # identical to the single-chip run — measured: the legacy
        # non-partitionable threefry produces different permutation
        # values when the vmapped client axis is sharded. The flag is
        # flipped by fedml_tpu.init() when args.mesh_shape asks for a
        # fed mesh — BEFORE any data synthesis, so every world of a
        # process draws from one stream. A direct build_fed_mesh
        # caller who skipped init() gets a loud warning instead of a
        # silent mid-process value shift (flipping HERE would change
        # the stream between a world built before and after).
        logging.warning(
            "fed mesh built with jax_threefry_partitionable=False: "
            "in-jit random draws (client shuffle) are NOT "
            "sharding-invariant — mesh results will not be bitwise "
            "identical to the single-chip run. Set mesh_shape in args "
            "and go through fedml_tpu.init(), or enable the flag "
            "before generating any data."
        )
    n = len(devices)
    shape = dict(mesh_shape or {})
    unknown = set(shape) - {AXIS_COHORT, AXIS_PARAM}
    if unknown:
        raise ValueError(
            f"fed mesh axes are ({AXIS_COHORT!r}, {AXIS_PARAM!r}); got "
            f"unknown axes {sorted(unknown)} — the legacy simulator "
            "vocabulary is {'clients', 'data'} (parallel/mesh.build_mesh)"
        )
    for axis in (AXIS_COHORT, AXIS_PARAM):
        if axis in shape and int(shape[axis]) < 1:
            # the null-naming rule: an explicit 0 must be rejected,
            # never silently auto-sized
            raise ValueError(
                f"fed mesh axis {axis!r}={shape[axis]!r}: must be >= 1 "
                "(omit the axis to auto-size it)"
            )
    fsdp = int(shape.get(AXIS_PARAM, 1))
    if fsdp > n:
        raise ValueError(
            f"fed mesh fsdp={fsdp} exceeds the {n} available devices"
        )
    data = int(shape.get(AXIS_COHORT, 0) or (n // max(fsdp, 1)))
    if data * fsdp > n:
        raise ValueError(
            f"fed mesh shape {{'data': {data}, 'fsdp': {fsdp}}} needs "
            f"{data * fsdp} devices, have {n}"
        )
    if data * fsdp < n and AXIS_COHORT not in shape:
        raise ValueError(
            f"fed mesh shape {{'data': {data}, 'fsdp': {fsdp}}} != "
            f"{n} devices"
        )
    # an EXPLICIT smaller shape takes a device-prefix sub-mesh — the
    # single-chip {'data': 1, 'fsdp': 1} baseline world the multichip
    # bench compares every sharded shape against bitwise
    arr = np.array(devices[: data * fsdp]).reshape((data, fsdp))
    return Mesh(arr, (AXIS_COHORT, AXIS_PARAM))


def cohort_axis_size(mesh) -> int:
    """How many lanes the cohort shards over — 'data' on a fed mesh,
    'clients' on the legacy simulator mesh, 1 otherwise. Cohort sizes
    and compile buckets must tile this."""
    if mesh is None:
        return 1
    if is_fed_mesh(mesh):
        return int(mesh.shape[AXIS_COHORT])
    return int(mesh.shape.get("clients", 1))
