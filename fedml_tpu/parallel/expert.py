"""Expert parallelism: shard stacked expert weights over a mesh ``ep`` axis.

Companion to ``models.moe`` (Switch-style MoE). The TPU idiom mirrors
``parallel.tensor``: no hand-written all-to-alls — the stacked expert
arrays (leading dim E) get ``NamedSharding(P("ep", ...))`` and XLA's
SPMD partitioner splits the dispatch einsums
(``[N,E,cap] x [N,C] -> [E,cap,C]`` etc.) across the axis, inserting
the token all-to-all exactly where GShard places it manually. SPMD is
semantics-preserving, so an ep-sharded layer computes the same function
as the replicated one (asserted in tests).

Composes with the Megatron tp rules: apply ``tensor.tp_specs`` to the
dense blocks and these rules to the expert stacks on a
``{dp, tp/ep}``-axis mesh.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_EXPERT_LEAVES = {"wi", "bi", "wo", "bo"}


def _spec_for(path, leaf, axis: str) -> P:
    names = [p.key if hasattr(p, "key") else str(p) for p in path]
    if names[-1] in _EXPERT_LEAVES and any("SwitchFFN" in n for n in names):
        return P(axis, *([None] * (leaf.ndim - 1)))
    return P()


def ep_specs(params: Any, axis: str = "ep") -> Any:
    """PartitionSpec pytree: expert stacks sharded on E, rest replicated."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: _spec_for(path, leaf, axis), params
    )


def shard_params_ep(params: Any, mesh: Mesh, axis: str = "ep") -> Any:
    """Place an MoE param tree on ``mesh`` with experts split over
    ``axis``. Expert counts that don't divide the axis — or a mesh
    without the axis at all — fall back to replicated (same policy as
    ``tensor.shard_params_tp``)."""
    if axis not in mesh.axis_names:
        from .mesh import replicate

        return replicate(params, mesh)
    ep = mesh.shape[axis]

    from .mesh import place_global

    def place(path, leaf):
        spec = _spec_for(path, leaf, axis)
        if spec and spec[0] == axis and leaf.shape[0] % ep != 0:
            spec = P()
        return place_global(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


def tp_ep_specs(params: Any, tp_axis: str = "tp", ep_axis: str = "ep") -> Any:
    """Composed layout for an MoE transformer: expert stacks ride
    ``ep``, dense layers ride the Megatron ``tp`` rules, the rest is
    replicated. (Chaining ``shard_params_tp`` THEN ``shard_params_ep``
    would clobber the tp placement — ep's P() re-placement of every
    non-expert leaf wins — hence a single merged spec tree.)"""
    from .tensor import tp_specs

    return jax.tree.map(
        lambda t, e: e if e != P() else t,
        tp_specs(params, tp_axis),
        ep_specs(params, ep_axis),
        is_leaf=lambda s: isinstance(s, P),
    )


def shard_params_tp_ep(
    params: Any, mesh: Mesh, tp_axis: str = "tp", ep_axis: str = "ep"
) -> Any:
    """Place an MoE transformer param tree with the composed tp x ep
    layout; any dim that doesn't divide its mesh axis falls back to
    replicated for that leaf."""

    from .mesh import place_global

    def place(leaf, spec):
        for dim, name in enumerate(spec):
            if name is not None and (
                name not in mesh.axis_names
                or leaf.shape[dim] % mesh.shape[name] != 0
            ):
                spec = P()
                break
        return place_global(leaf, NamedSharding(mesh, spec))

    return jax.tree.map(place, params, tp_ep_specs(params, tp_axis, ep_axis))
