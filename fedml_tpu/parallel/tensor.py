"""Tensor parallelism: Megatron-style sharding rules for TransformerLM.

The reference has NO tensor parallelism (SURVEY.md §2.9 census: its
model-partition vocabulary stops at SplitNN/VFL activation exchange) —
this is green-field TPU design. The TPU idiom is NOT hand-written
collectives: weights get ``NamedSharding``s over a mesh ``tp`` axis,
activations get ``with_sharding_constraint`` hints, and XLA's SPMD
partitioner inserts the all-reduces exactly where Megatron-LM places
them by hand (one psum after attention proj, one after the MLP down
projection — the classic column-parallel -> row-parallel pairing):

- qkv projection   (``Block_*/Dense_0``): column-parallel — kernel
  sharded on the OUTPUT dim (head math is embarrassingly parallel;
  XLA re-shards across the packed q/k/v split as needed);
- attention proj   (``Block_*/Dense_1``): row-parallel — kernel sharded
  on the INPUT dim; XLA emits the psum that merges head groups;
- MLP up           (``Block_*/Dense_2``): column-parallel;
- MLP down         (``Block_*/Dense_3``): row-parallel;
- LM head          (top-level ``Dense_0``): column-parallel over the
  vocab — the cross-entropy then runs on vocab-sharded logits;
- embeddings / LayerNorms: replicated (tiny).

Because SPMD partitioning is semantics-preserving, a tp-sharded step
computes bit-for-bit the same function as a replicated one — the tests
assert that equality AND that the weights are genuinely sharded (the
addressable shard of each column-parallel kernel is 1/tp of the full
kernel).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (shard_output_dim?, shard_input_dim?) per Dense index inside a Block
_BLOCK_DENSE_RULES = {
    "Dense_0": "column",  # qkv
    "Dense_1": "row",     # attention output proj
    "Dense_2": "column",  # mlp up
    "Dense_3": "row",     # mlp down
}


def _spec_for(path: Tuple[str, ...], leaf, axis: str) -> P:
    names = [p.key if hasattr(p, "key") else str(p) for p in path]
    in_block = any("Block_" in n for n in names)  # Block_* and MoEBlock_*
    dense = next((n for n in names if n.startswith("Dense_")), None)
    kind = names[-1]  # "kernel" | "bias" | "embedding" | "scale" ...
    if dense is None:
        return P()  # embeddings, layernorms
    if in_block:
        rule = _BLOCK_DENSE_RULES.get(dense)
        if rule is None:
            return P()
    else:
        rule = "column"  # top-level LM head: vocab-sharded
    if rule == "column":
        if kind == "kernel":
            return P(None, axis)
        if kind == "bias":
            return P(axis)
        return P()
    # row-parallel: kernel sharded on input dim, bias replicated (it is
    # added AFTER the psum merges partial sums)
    if kind == "kernel":
        return P(axis, None)
    return P()


def tp_specs(params: Any, axis: str = "tp") -> Any:
    """PartitionSpec pytree for a ``TransformerLM`` param tree."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: _spec_for(path, leaf, axis), params
    )


def shard_params_tp(params: Any, mesh: Mesh, axis: str = "tp") -> Any:
    """Place a TransformerLM param tree on ``mesh`` with the Megatron
    layout. Dims that don't divide the tp axis fall back to replicated
    (XLA would error on ragged shards; a warning-free fallback keeps
    tiny test models usable on big meshes). A mesh without the axis
    replicates everything."""
    if axis not in mesh.axis_names:
        from .mesh import replicate

        return replicate(params, mesh)
    tp = mesh.shape[axis]

    from .mesh import place_global

    def place(path, leaf):
        spec = _spec_for(path, leaf, axis)
        for dim, name in enumerate(spec):
            if name == axis and leaf.shape[dim] % tp != 0:
                spec = P()
                break
        return place_global(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


def shard_batch_dp(batch: Any, mesh: Mesh, axis: str = "dp") -> Any:
    """Shard the leading (batch) axis of every leaf over ``axis``."""
    if axis not in mesh.axis_names:
        return batch
    from .mesh import place_global

    return jax.tree.map(
        lambda a: place_global(a, NamedSharding(mesh, P(axis))), batch
    )


