"""jax version compatibility for the parallel package.

``shard_map`` graduated from ``jax.experimental.shard_map`` to
``jax.shard_map`` (and its replication-check kwarg was renamed
``check_rep`` -> ``check_vma``) across jax releases. The parallel
modules import it from here so the package imports — and the rest of
the simulator with it — on either side of that move.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.6
    from jax import shard_map as _shard_map
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

_HAS_VMA = "check_vma" in inspect.signature(_shard_map).parameters


def shard_map(f, **kwargs):
    """``jax.shard_map`` with the ``check_vma``/``check_rep`` kwarg
    translated to whatever the installed jax understands.

    On pre-vma jax the replication checker predates the ``pcast``-based
    varying annotations this package's kernels carry, so bodies that
    type-check under vma can raise spurious rep errors — default the
    legacy checker off unless the caller asked for it explicitly."""
    if not _HAS_VMA:
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        kwargs.setdefault("check_rep", False)
    elif "check_rep" in kwargs:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map(f, **kwargs)


def pcast(x, axis_name, *, to):
    """``jax.lax.pcast`` where it exists (the vma type system); identity
    on older jax, whose shard_map has no varying/invariant typing."""
    import jax

    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_name, to=to)
    return x
