"""Parallelism layer: mesh construction, sharded FL, in-silo SPMD."""

from .mesh import build_mesh, shard_federation, replicate  # noqa: F401
