"""Parallelism layer: mesh construction, sharded FL, in-silo SPMD.

Axes vocabulary (compose freely on one Mesh):
  data/fsdp    — the fed production mesh: cohort lanes x at-rest
                 parameter shards (layout.py, docs/multichip.md)
  clients/data — legacy FL process-parallelism / in-client DP (mesh.py)
  sp           — sequence/context parallelism: ring + Ulysses (sequence.py)
  tp           — Megatron-style tensor parallelism (tensor.py)
  pp           — GPipe pipeline schedule under shard_map (pipeline.py)
  ep           — expert parallelism for MoE stacks (expert.py)
"""

from .layout import (  # noqa: F401
    SpecLayout,
    build_fed_mesh,
    is_fed_mesh,
    shard_tree,
    tree_specs,
)
from .mesh import build_mesh, shard_federation, replicate  # noqa: F401
from .tensor import shard_params_tp, tp_specs  # noqa: F401
from .expert import (  # noqa: F401
    ep_specs,
    shard_params_ep,
    shard_params_tp_ep,
    tp_ep_specs,
)
from .pipeline import (  # noqa: F401
    pipeline_apply,
    split_microbatches,
    stack_stage_params,
)
