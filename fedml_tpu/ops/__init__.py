"""TPU hot-op kernels (pallas).

The reference has no custom kernels (pure torch ops); these are the
TPU-first replacements for the ops that dominate the new framework's
workloads. See ``flash_attention`` for the long-context attention
block.
"""

from .flash_attention import flash_attention  # noqa: F401
