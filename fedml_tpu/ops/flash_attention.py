"""Pallas flash-attention kernel (single-chip hot op).

Blockwise attention with online softmax, tiled for the MXU: the
[T, T] score matrix never hits HBM — each (q-block, k-block) tile of
scores lives in VMEM, and the running (max, normalizer, accumulator)
state carries across k-blocks. Grid: (batch*heads, q-blocks); the
k-loop is a ``fori_loop`` inside the kernel.

Backward: ``jax.custom_vjp`` with the standard flash residuals
(output + per-row logsumexp) and a BLOCKWISE recompute — a ``lax.scan``
over k-blocks that rebuilds one [T, bk] score panel at a time, so the
backward peak is O(T·bk) like the forward, never the dense [T, T]
matrix. Pair with ``parallel.sequence.ring_attention`` across chips:
ring for the sequence axis, this kernel for the per-chip block.

On non-TPU backends the kernel runs in interpreter mode so tests
validate the same code path numerically.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, bq, bk, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # [bq, D]
    d = q.shape[-1]
    n_kb = seq_len // bk

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)  # [bk, D]
        v = v_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        s_max = s.max(axis=-1)
        m_new = jnp.maximum(m, s_max)
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    upper = n_kb if not causal else ((qi + 1) * bq + bk - 1) // bk
    upper = jnp.minimum(upper, n_kb)
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
    # per-row logsumexp: the backward residual (flash convention)
    lse_ref[0] = m + jnp.log(jnp.maximum(l, 1e-30))


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    B, T, H, D = q.shape
    bq = min(block_q, T)
    bk = min(block_k, T)
    if T % bq or T % bk:
        raise ValueError(f"seq len {T} must divide block sizes ({bq}, {bk})")
    scale = scale or (D**-0.5)

    def reshaped(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)

    qf, kf, vf = reshaped(q), reshaped(k), reshaped(v)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, bq=bq, bk=bk, seq_len=T
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, T // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, T, D), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, T, D), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, bq), lambda i, j: (i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return (
        out.reshape(B, H, T, D).transpose(0, 2, 1, 3),
        lse.reshape(B, H, T),
    )


def pick_block(t: int, minimum: int = 8) -> Optional[int]:
    """Largest power-of-two block <= 128 that divides ``t`` — the one
    block-size policy every flash call site uses. Returns None when the
    only dividing blocks are smaller than ``minimum`` (callers fall
    back to dense attention rather than running degenerate tiles)."""
    for b in (128, 64, 32, 16, 8, 4, 2, 1):
        if t % b == 0:
            return b if b >= minimum else None
    return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
):
    """Flash attention, [B, T, H, D] layout. Differentiable."""
    interpret = jax.default_backend() != "tpu"
    out, _ = _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)
    return out


def _fwd(q, k, v, causal, scale, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    out, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _bwd(causal, scale, block_q, block_k, res, g):
    """Blockwise backward (FlashAttention-2 recompute): scan over
    k-blocks rebuilding [T, bk] score panels from the saved logsumexp —
    peak memory O(B·H·T·bk), never the dense [T, T] matrix."""
    q, k, v, o, lse = res
    B, T, H, Dh = q.shape
    sc = scale or (Dh**-0.5)
    bk = min(block_k, T)
    f32 = lambda x: x.astype(jnp.float32)
    qf, kf, vf, of, gf = f32(q), f32(k), f32(v), f32(o), f32(g)
    # D_i = do_i · o_i  [B,H,T]
    d_sum = (gf * of).sum(-1).transpose(0, 2, 1)
    q_pos = jnp.arange(T)

    def body(dq_acc, j):
        ks = jax.lax.dynamic_slice_in_dim(kf, j * bk, bk, axis=1)  # [B,bk,H,D]
        vs = jax.lax.dynamic_slice_in_dim(vf, j * bk, bk, axis=1)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, ks) * sc  # [B,H,T,bk]
        if causal:
            k_pos = j * bk + jnp.arange(bk)
            s = jnp.where((q_pos[:, None] >= k_pos[None, :])[None, None], s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])  # [B,H,T,bk]
        dp = jnp.einsum("bqhd,bkhd->bhqk", gf, vs)
        ds = p * (dp - d_sum[..., None]) * sc
        dq_acc = dq_acc + jnp.einsum("bhqk,bkhd->bqhd", ds, ks)
        dk_j = jnp.einsum("bhqk,bqhd->bkhd", ds, qf)
        dv_j = jnp.einsum("bhqk,bqhd->bkhd", p, gf)
        return dq_acc, (dk_j, dv_j)

    dq, (dks, dvs) = jax.lax.scan(
        body, jnp.zeros_like(qf), jnp.arange(T // bk)
    )
    # [nkb, B, bk, H, D] -> [B, T, H, D]
    merge = lambda blocks: jnp.moveaxis(blocks, 0, 1).reshape(B, T, H, Dh)
    return dq.astype(q.dtype), merge(dks).astype(k.dtype), merge(dvs).astype(v.dtype)


flash_attention.defvjp(_fwd, _bwd)
