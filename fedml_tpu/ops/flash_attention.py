"""Pallas flash-attention kernel (single-chip hot op).

Blockwise attention with online softmax, tiled for the MXU: the
[T, T] score matrix never hits HBM — each (q-block, k-block) tile of
scores lives in VMEM, and the running (max, normalizer, accumulator)
state carries across k-blocks. Grid: (batch*heads, q-blocks); the
k-loop is a ``fori_loop`` inside the kernel.

Backward: ``jax.custom_vjp`` recomputes gradients through the dense
reference attention (mathematically identical); the forward pallas
kernel is the memory/bandwidth win — O(T) activation residency instead
of O(T^2). Pair with ``parallel.sequence.ring_attention`` across chips:
ring for the sequence axis, this kernel for the per-chip block.

On non-TPU backends the kernel runs in interpreter mode so tests
validate the same code path numerically.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, bq, bk, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # [bq, D]
    d = q.shape[-1]
    n_kb = seq_len // bk

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)  # [bk, D]
        v = v_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        s_max = s.max(axis=-1)
        m_new = jnp.maximum(m, s_max)
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    upper = n_kb if not causal else ((qi + 1) * bq + bk - 1) // bk
    upper = jnp.minimum(upper, n_kb)
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    B, T, H, D = q.shape
    bq = min(block_q, T)
    bk = min(block_k, T)
    if T % bq or T % bk:
        raise ValueError(f"seq len {T} must divide block sizes ({bq}, {bk})")
    scale = scale or (D**-0.5)

    def reshaped(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)

    qf, kf, vf = reshaped(q), reshaped(k), reshaped(v)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, bq=bq, bk=bk, seq_len=T
    )
    out = pl.pallas_call(
        kernel,
        grid=(B * H, T // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, T, D), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, T, D), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
):
    """Flash attention, [B, T, H, D] layout. Differentiable."""
    interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)


def _fwd(q, k, v, causal, scale, block_q, block_k):
    return flash_attention(q, k, v, causal, scale, block_q, block_k), (q, k, v)


def _bwd(causal, scale, block_q, block_k, res, g):
    from ..parallel.sequence import full_attention

    q, k, v = res
    _, vjp = jax.vjp(lambda q_, k_, v_: full_attention(q_, k_, v_, causal, scale), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
