// fedml_tpu native scheduler.
//
// Native implementation of the heterogeneity-aware workload scheduler
// (reference: python/fedml/core/schedule/scheduler.py — DP /
// branch-and-bound makespan minimization). Two entry points exported
// with C linkage for the ctypes binding (fedml_tpu/core/native.py):
//
//   lpt_makespan  — heap-based LPT greedy, O(n log n + n log m)
//   bnb_makespan  — exact branch & bound (LPT seed as incumbent,
//                   load-max + remaining-work lower bounds, symmetry
//                   breaking on empty resources, node budget cap)
//
// Assignments are returned as per-job resource ids.

#include <algorithm>
#include <cstdint>
#include <numeric>
#include <queue>
#include <vector>

namespace {

struct Res {
  double load;
  int id;
  bool operator>(const Res& o) const { return load > o.load; }
};

double lpt(const double* w, int n, int m, int* assign) {
  std::vector<int> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::sort(order.begin(), order.end(),
            [&](int a, int b) { return w[a] > w[b]; });
  std::priority_queue<Res, std::vector<Res>, std::greater<Res>> heap;
  for (int r = 0; r < m; ++r) heap.push({0.0, r});
  double makespan = 0.0;
  for (int j : order) {
    Res r = heap.top();
    heap.pop();
    assign[j] = r.id;
    r.load += w[j];
    makespan = std::max(makespan, r.load);
    heap.push(r);
  }
  return makespan;
}

struct BnB {
  const double* w;
  int n, m;
  std::vector<int> order;       // jobs sorted descending
  std::vector<double> suffix;   // remaining work from position i
  std::vector<int> best_assign; // per sorted-position resource
  double best;
  int64_t nodes, node_budget;

  void dfs(int pos, std::vector<double>& loads, std::vector<int>& cur) {
    if (nodes++ > node_budget) return;
    if (pos == n) {
      double ms = *std::max_element(loads.begin(), loads.end());
      if (ms < best) {
        best = ms;
        best_assign = cur;
      }
      return;
    }
    // lower bound: max(current max load, avg of remaining over gaps)
    double mx = *std::max_element(loads.begin(), loads.end());
    double total = std::accumulate(loads.begin(), loads.end(), 0.0) + suffix[pos];
    double lb = std::max(mx, total / m);
    if (lb >= best) return;
    int job = order[pos];
    bool tried_empty = false;
    for (int r = 0; r < m; ++r) {
      if (loads[r] == 0.0) {
        if (tried_empty) continue;  // symmetry: all empty resources equal
        tried_empty = true;
      }
      if (loads[r] + w[job] >= best) continue;
      loads[r] += w[job];
      cur[pos] = r;
      dfs(pos + 1, loads, cur);
      loads[r] -= w[job];
    }
  }
};

}  // namespace

extern "C" {

// Returns the makespan; fills assign[n] with resource ids.
double lpt_makespan(const double* workloads, int n_jobs, int n_resources,
                    int* assign) {
  if (n_jobs <= 0 || n_resources <= 0) return 0.0;
  return lpt(workloads, n_jobs, n_resources, assign);
}

// Exact (within node budget) makespan. Returns achieved makespan and
// fills assign. Falls back to the LPT incumbent when the budget trips.
double bnb_makespan(const double* workloads, int n_jobs, int n_resources,
                    int64_t node_budget, int* assign) {
  if (n_jobs <= 0 || n_resources <= 0) return 0.0;
  std::vector<int> lpt_assign(n_jobs);
  double ub = lpt(workloads, n_jobs, n_resources, lpt_assign.data());

  BnB b;
  b.w = workloads;
  b.n = n_jobs;
  b.m = n_resources;
  b.order.resize(n_jobs);
  std::iota(b.order.begin(), b.order.end(), 0);
  std::sort(b.order.begin(), b.order.end(),
            [&](int x, int y) { return workloads[x] > workloads[y]; });
  b.suffix.assign(n_jobs + 1, 0.0);
  for (int i = n_jobs - 1; i >= 0; --i)
    b.suffix[i] = b.suffix[i + 1] + workloads[b.order[i]];
  b.best = ub + 1e-12;
  b.nodes = 0;
  b.node_budget = node_budget > 0 ? node_budget : (1 << 22);
  std::vector<double> loads(n_resources, 0.0);
  std::vector<int> cur(n_jobs, 0);
  b.dfs(0, loads, cur);

  if (b.best_assign.empty()) {
    std::copy(lpt_assign.begin(), lpt_assign.end(), assign);
    return ub;
  }
  for (int pos = 0; pos < n_jobs; ++pos) assign[b.order[pos]] = b.best_assign[pos];
  return b.best;
}
}
