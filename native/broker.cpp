// fedml_tpu native topic broker.
//
// C++ implementation of the message-fabric broker (same wire protocol
// as fedml_tpu/core/comm/broker.py — u32 frame_len | u8 verb
// (0=sub 1=pub 2=msg) | u16 topic_len | topic utf8 | payload). The
// reference framework rides an external MQTT broker for its control
// plane; this is the self-hosted native runtime piece: the Python
// broker is the in-process/test fabric, this binary is the deployment
// one (thread-per-connection, per-socket write mutex so concurrent
// fan-out never interleaves frames).
//
// Usage: fedml_broker [port]   (0 or absent = ephemeral)
// Prints "LISTENING <port>" on stdout once ready.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <memory>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint8_t kVerbSub = 0;
constexpr uint8_t kVerbPub = 1;
constexpr uint8_t kVerbMsg = 2;
constexpr uint32_t kMaxFrame = 1u << 30;  // 1 GB (reference gRPC cap)

struct Conn {
  int fd;
  std::mutex write_mu;
  explicit Conn(int f) : fd(f) {}
};

std::mutex g_mu;
std::map<std::string, std::set<std::shared_ptr<Conn>>> g_subs;

bool read_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_all(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t w = ::write(fd, p, n);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

// Deliver one already-encoded frame to a subscriber (frame interleaving
// guarded by the per-socket mutex; fd may have been invalidated by the
// owner's close — never write to a recycled descriptor).
bool send_frame(const std::shared_ptr<Conn>& c, const std::vector<uint8_t>& frame) {
  std::lock_guard<std::mutex> lk(c->write_mu);
  if (c->fd < 0) return false;
  return write_all(c->fd, frame.data(), frame.size());
}

void drop_conn(const std::shared_ptr<Conn>& c) {
  std::lock_guard<std::mutex> lk(g_mu);
  for (auto& [topic, subs] : g_subs) subs.erase(c);
}

void serve(std::shared_ptr<Conn> c) {
  for (;;) {
    uint32_t len_be;
    if (!read_exact(c->fd, &len_be, 4)) break;
    uint32_t len = ntohl(len_be);
    if (len < 3 || len > kMaxFrame) break;
    std::vector<uint8_t> body(len);
    if (!read_exact(c->fd, body.data(), len)) break;
    uint8_t verb = body[0];
    uint16_t tlen = static_cast<uint16_t>((body[1] << 8) | body[2]);
    if (static_cast<size_t>(3 + tlen) > body.size()) break;
    std::string topic(reinterpret_cast<char*>(body.data()) + 3, tlen);

    if (verb == kVerbSub) {
      std::lock_guard<std::mutex> lk(g_mu);
      g_subs[topic].insert(c);
    } else if (verb == kVerbPub) {
      // re-frame as a DELIVER with identical topic/payload
      std::vector<uint8_t> frame(4 + body.size());
      uint32_t out_be = htonl(static_cast<uint32_t>(body.size()));
      std::memcpy(frame.data(), &out_be, 4);
      std::memcpy(frame.data() + 4, body.data(), body.size());
      frame[4] = kVerbMsg;
      std::vector<std::shared_ptr<Conn>> targets;
      {
        std::lock_guard<std::mutex> lk(g_mu);
        auto it = g_subs.find(topic);
        if (it != g_subs.end())
          targets.assign(it->second.begin(), it->second.end());
      }
      for (auto& t : targets) {
        if (!send_frame(t, frame)) drop_conn(t);
      }
    }
    // unknown verbs are ignored (forward compatibility)
  }
  drop_conn(c);
  // invalidate under the write mutex so a publisher mid-fan-out can't
  // write to a recycled fd number
  {
    std::lock_guard<std::mutex> lk(c->write_mu);
    ::shutdown(c->fd, SHUT_RDWR);
    ::close(c->fd);
    c->fd = -1;
  }
}

}  // namespace

int main(int argc, char** argv) {
  int port = argc > 1 ? std::atoi(argv[1]) : 0;
  int srv = ::socket(AF_INET, SOCK_STREAM, 0);
  if (srv < 0) return 1;
  int one = 1;
  ::setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) return 2;
  if (::listen(srv, 128) != 0) return 3;
  socklen_t alen = sizeof(addr);
  ::getsockname(srv, reinterpret_cast<sockaddr*>(&addr), &alen);
  std::printf("LISTENING %d\n", ntohs(addr.sin_port));
  std::fflush(stdout);
  for (;;) {
    int fd = ::accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::thread(serve, std::make_shared<Conn>(fd)).detach();
  }
}
