"""MoE (models/moe.py) + expert parallelism (parallel/expert.py).

Green-field vs the reference (SURVEY.md §2.9 census: no MoE, no expert
parallelism). Oracles: single-expert == dense MLP; full-capacity
routing == per-token gated expert FFN computed by hand; overflow
dropping; ep-sharded step == replicated step on the 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.core.losses import token_cross_entropy
from fedml_tpu.models.moe import MoETransformerLM, SwitchFFN
from fedml_tpu.parallel.expert import (
    ep_specs,
    shard_params_ep,
    shard_params_tp_ep,
    tp_ep_specs,
)

pytestmark = pytest.mark.smoke

B, T, C = 2, 8, 16


def _x(seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=(B, T, C)), jnp.float32
    )


class TestSwitchFFN:
    def test_single_expert_equals_dense_mlp(self):
        m = SwitchFFN(num_experts=1, capacity_factor=1.0, mlp_ratio=2)
        x = _x()
        params = m.init(jax.random.PRNGKey(0), x)["params"]
        y = m.apply({"params": params}, x)
        p = params
        xf = np.asarray(x).reshape(-1, C)
        h = jax.nn.gelu(xf @ p["wi"][0] + p["bi"][0])
        expected = (h @ p["wo"][0] + p["bo"][0]).reshape(B, T, C)
        np.testing.assert_allclose(np.asarray(y), np.asarray(expected), atol=1e-5)

    @pytest.mark.slow
    def test_full_capacity_routing_matches_manual(self):
        E = 4
        m = SwitchFFN(num_experts=E, capacity_factor=float(E), mlp_ratio=2)
        x = _x(1)
        params = m.init(jax.random.PRNGKey(1), x)["params"]
        y = np.asarray(m.apply({"params": params}, x)).reshape(-1, C)
        xf = np.asarray(x).reshape(-1, C)
        probs = jax.nn.softmax(xf @ np.asarray(params["router"]["kernel"]), axis=-1)
        for n in range(xf.shape[0]):
            e = int(np.argmax(probs[n]))
            h = jax.nn.gelu(xf[n] @ params["wi"][e] + params["bi"][e])
            expected = float(probs[n, e]) * (h @ params["wo"][e] + params["bo"][e])
            np.testing.assert_allclose(y[n], np.asarray(expected), atol=1e-4)

    @pytest.mark.slow
    def test_overflow_tokens_dropped(self):
        # capacity 1 with every token routed to the same expert: only
        # the first token per expert produces output, the rest fall
        # back to zero (residual carries them in a full block)
        E = 2
        m = SwitchFFN(num_experts=E, capacity_factor=1e-9, mlp_ratio=2)
        x = _x(2)
        params = m.init(jax.random.PRNGKey(2), x)["params"]
        y = np.asarray(m.apply({"params": params}, x)).reshape(-1, C)
        nonzero = np.abs(y).sum(-1) > 1e-9
        assert nonzero.sum() <= E  # capacity 1 per expert

    @pytest.mark.slow
    def test_bf16_dispatch_exact_past_256_tokens_per_expert(self):
        # routing math must run in f32/int32 regardless of compute
        # dtype: bf16 only represents integers exactly up to 256, so a
        # bf16 cumsum collides capacity positions past slot 256 —
        # occupancy on the sown seam would exceed 1. 1024 tokens over 2
        # experts ≈ 512/expert, well past the bf16 integer cliff.
        E, n, c = 2, 1024, 16
        m = SwitchFFN(num_experts=E, capacity_factor=2.0, mlp_ratio=2)
        x = jnp.asarray(
            np.random.default_rng(7).normal(size=(4, n // 4, c)), jnp.bfloat16
        )
        params = m.init(jax.random.PRNGKey(7), x)["params"]
        _, state = m.apply({"params": params}, x, mutable=["intermediates"])
        (occ,) = state["intermediates"]["moe_slot_occupancy"]  # [E, cap]
        occ = np.asarray(occ, np.float32)
        assert occ.max() <= 1.0 + 1e-6, "capacity slot collision"
        # every expert filled well past the 256-slot bf16 cliff, and
        # every routed token landed in a distinct slot
        per_expert = occ.sum(axis=1)
        assert per_expert.min() > 256 or per_expert.sum() == n
        assert occ.sum() == n  # cap=2x: nothing dropped

    def test_aux_loss_sown(self):
        m = SwitchFFN(num_experts=4, capacity_factor=2.0)
        x = _x(3)
        params = m.init(jax.random.PRNGKey(3), x)["params"]
        _, state = m.apply({"params": params}, x, mutable=["intermediates"])
        (aux,) = state["intermediates"]["moe_aux_loss"]
        assert float(aux) >= 1.0 - 1e-6  # ==1 iff perfectly balanced


class TestExpertParallel:
    def _model_and_batch(self):
        model = MoETransformerLM(
            vocab_size=64, num_layers=2, num_heads=4, embed_dim=32,
            max_len=16, num_experts=8, capacity_factor=2.0, moe_every=2,
        )
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 64, (4, 16)), jnp.int32
        )
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        return model, params, tokens

    @pytest.mark.slow
    def test_specs_target_expert_stacks_only(self):
        _, params, _ = self._model_and_batch()
        specs = ep_specs(params)
        moe = specs["Block_1"]["SwitchFFN_0"]
        assert moe["wi"] == P("ep", None, None)
        assert moe["bo"] == P("ep", None)
        assert moe["router"]["kernel"] == P()
        assert specs["Block_0"]["Dense_0"]["kernel"] == P()

    @pytest.mark.slow
    def test_ep_sharded_step_matches_replicated(self):
        model, params, tokens = self._model_and_batch()
        opt = optax.sgd(0.1)

        def step(params, opt_state, tokens):
            def loss_fn(p):
                logits = model.apply({"params": p}, tokens)
                labels = jnp.roll(tokens, -1, axis=1)
                mask = jnp.ones_like(tokens, jnp.float32).at[:, -1].set(0.0)
                loss, _ = token_cross_entropy(logits, labels, mask)
                return loss

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        ref_params, _, ref_loss = jax.jit(step)(params, opt.init(params), tokens)

        mesh = Mesh(np.array(jax.devices()[:8]), ("ep",))
        ep_params = shard_params_ep(params, mesh)
        wi = ep_params["Block_1"]["SwitchFFN_0"]["wi"]
        assert wi.addressable_shards[0].data.shape[0] == 1  # 8 experts / 8
        with mesh:
            out_params, _, loss = jax.jit(step)(
                ep_params, opt.init(ep_params), tokens
            )
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            ),
            out_params, ref_params,
        )

    @pytest.mark.slow
    def test_tp_ep_composition(self):
        """One merged layout: dense layers on tp, expert stacks on ep."""
        _, params, tokens = self._model_and_batch()
        specs = tp_ep_specs(params)
        assert specs["Block_1"]["Dense_0"]["kernel"] == P(None, "tp")  # qkv
        assert specs["Block_1"]["SwitchFFN_0"]["wi"] == P("ep", None, None)
        assert specs["Dense_0"]["kernel"] == P(None, "tp")  # vocab head

        mesh = Mesh(
            np.array(jax.devices()[:8]).reshape(2, 2, 2), ("dp", "tp", "ep")
        )
        placed = shard_params_tp_ep(params, mesh)
        qkv = placed["Block_1"]["Dense_0"]["kernel"]
        assert qkv.addressable_shards[0].data.shape[1] == qkv.shape[1] // 2
        wi = placed["Block_1"]["SwitchFFN_0"]["wi"]
        assert wi.addressable_shards[0].data.shape[0] == wi.shape[0] // 2

    def test_indivisible_expert_count_falls_back(self):
        m = SwitchFFN(num_experts=6, capacity_factor=2.0)
        x = _x()
        params = {"SwitchFFN_0": m.init(jax.random.PRNGKey(0), x)["params"]}
        mesh = Mesh(np.array(jax.devices()[:8]), ("ep",))
        placed = shard_params_ep(params, mesh)
        wi = placed["SwitchFFN_0"]["wi"]
        assert wi.addressable_shards[0].data.shape == wi.shape
