"""The reference CI's numeric-equivalence oracles, as proper tests.

Oracle 1 (ci/CI-script-fedavg.sh:44-50): with full-batch clients, 1
local epoch, all clients participating, plain SGD — FedAvg equals
centralized full-batch gradient descent (weighted average of per-client
full-batch steps == one global full-batch step). Asserted here both on
parameters (atol 1e-5) and on train accuracy to 3 decimals, stronger
than the reference's accuracy-only check.

Oracle 2: vectorized (vmap) simulation == sequential simulation — the
backend-independence property the reference gets from running the same
algorithm under SP and MPI simulators (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.smoke

import fedml_tpu
from fedml_tpu import models
from fedml_tpu.data import load
from fedml_tpu.data.packing import pack_one
from fedml_tpu.simulation import FedAvgAPI


def _make_args(make, **kw):
    base = dict(
        dataset="mnist",
        synthetic_train_size=400,
        synthetic_test_size=100,
        model="lr",
        partition_method="homo",
        client_num_in_total=4,
        client_num_per_round=4,
        comm_round=3,
        epochs=1,
        batch_size=100,  # = client size -> full batch
        learning_rate=0.1,
        momentum=0.0,
        weight_decay=0.0,
        frequency_of_the_test=1,
        shuffle=False,
    )
    base.update(kw)
    return make(**base)


def _centralized_gd(model, params, x, y, lr, steps):
    """Full-batch GD on the union dataset."""
    b = pack_one(np.asarray(x), np.asarray(y), batch_size=len(x))

    def loss(p):
        logits = model.apply(p, b.x[0])
        l, _ = model.loss_fn(logits, b.y[0], b.mask[0])
        return l

    grad = jax.jit(jax.grad(loss))
    for _ in range(steps):
        g = grad(params)
        params = jax.tree.map(lambda p, gg: p - lr * gg, params, g)
    return params


class TestFederatedEqualsCentralized:
    def test_params_match(self, args_factory):
        args = _make_args(args_factory)
        args = fedml_tpu.init(args)
        dataset = load(args)
        model = models.create(args, dataset.class_num)
        api = FedAvgAPI(args, None, dataset, model)
        init_params = jax.tree.map(jnp.array, api.global_params)  # donation-safe copy
        api.train()

        # centralized: same init, 3 full-batch GD steps on the union
        from fedml_tpu.core.types import flat_examples

        g = flat_examples(dataset.train_data_global)
        keep = np.asarray(g.mask) > 0
        x = np.asarray(g.x)[keep]
        y = np.asarray(g.y)[keep]
        central = _centralized_gd(
            model, init_params, x, y, args.learning_rate, steps=args.comm_round
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
            api.global_params,
            central,
        )

    def test_train_accuracy_matches_3_decimals(self, args_factory):
        args = _make_args(args_factory, comm_round=5)
        args = fedml_tpu.init(args)
        dataset = load(args)
        model = models.create(args, dataset.class_num)
        api = FedAvgAPI(args, None, dataset, model)
        init_params = jax.tree.map(jnp.array, api.global_params)  # donation-safe copy
        stats = api.train()

        from fedml_tpu.core.types import flat_examples

        g = flat_examples(dataset.train_data_global)
        keep = np.asarray(g.mask) > 0
        x, y = np.asarray(g.x)[keep], np.asarray(g.y)[keep]
        central = _centralized_gd(model, init_params, x, y, args.learning_rate, 5)
        logits = model.apply(central, jnp.asarray(x))
        central_acc = float((jnp.argmax(logits, -1) == jnp.asarray(y)).mean())
        assert round(stats["train_acc"], 3) == round(central_acc, 3)


class TestBackendEquivalence:
    @pytest.mark.slow
    def test_vectorized_equals_sequential(self, args_factory):
        results = {}
        for mode in ("vectorized", "sequential"):
            args = _make_args(
                args_factory,
                partition_method="hetero",
                batch_size=20,
                comm_round=2,
                epochs=2,
            )
            args.sim_mode = mode
            args = fedml_tpu.init(args)
            dataset = load(args)
            model = models.create(args, dataset.class_num)
            api = FedAvgAPI(args, None, dataset, model)
            api.train()
            results[mode] = api.global_params
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
            results["vectorized"],
            results["sequential"],
        )
