"""Flight-recorder telemetry (core/telemetry.py, core/comm/instrument.py).

Covers the PR 3 acceptance contract:
- registry primitives (tagged counters/gauges/histograms), Prometheus
  text exposition, snapshots through the MetricsReporter sink seam;
- singleton hygiene: reset() + late-args adoption for Telemetry,
  ProfilerEvent and RunLogger;
- trace.json schema: valid Chrome trace event JSON, monotonic ts,
  matched B/E pairs — from both the unit recorder and a real pipelined
  train() run;
- comm instrumentation composed with FaultInjector in BOTH wrap
  orders: injected drops/delays appear in counters, bytes are never
  double-counted;
- the hot-loop contract: host_syncs_per_round is bit-identical with
  telemetry on and off;
- a forced stall produces a debug bundle with open spans, the pending
  deferred-metric count, and a host+device sys_stats snapshot.
"""

import json
import os
import re
import time

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.smoke

from fedml_tpu.core.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.core.comm.faults import FaultInjector
from fedml_tpu.core.comm.instrument import (
    InstrumentedCommunicationManager,
    payload_nbytes,
    wrap_instrumented,
)
from fedml_tpu.core.message import Message
from fedml_tpu.core.telemetry import FlightRecorder, Telemetry
from fedml_tpu.core.tracking import DeferredMetrics, ProfilerEvent, RunLogger

from test_round_pipeline import _build


class _FakeTransport(BaseCommunicationManager):
    """Records sends and can deliver inbound messages to observers."""

    def __init__(self):
        self.sent = []
        self.observers = []

    def send_message(self, msg):
        self.sent.append(msg)

    def add_observer(self, o):
        self.observers.append(o)

    def remove_observer(self, o):
        self.observers.remove(o)

    def handle_receive_message(self):
        pass

    def stop_receive_message(self):
        pass

    def deliver(self, msg):
        for o in self.observers:
            o.receive_message(msg.get_type(), msg)


def _msg(t=3, payload=None, sender=1, receiver=0):
    m = Message(t, sender, receiver)
    if payload is not None:
        m.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, payload)
    return m


class TestRegistry:
    def test_counters_gauges_histograms_tagged(self):
        tel = Telemetry.get_instance()
        tel.inc("msgs_total", msg_type=3)
        tel.inc("msgs_total", 2, msg_type=3)
        tel.inc("msgs_total", msg_type=5)
        tel.set_gauge("depth", 4)
        tel.observe("lat_s", 0.5)
        tel.observe("lat_s", 1.5)
        assert tel.get_counter("msgs_total", msg_type=3) == 3
        assert tel.get_counter("msgs_total", msg_type=5) == 1
        snap = tel.snapshot()
        assert snap["counters"]["msgs_total{msg_type=3}"] == 3
        assert snap["gauges"]["depth"] == 4
        h = snap["histograms"]["lat_s"]
        assert h["count"] == 2 and h["sum"] == 2.0
        assert h["min"] == 0.5 and h["max"] == 1.5

    def test_disabled_registry_is_inert(self):
        tel = Telemetry.get_instance()
        tel.enabled = False
        tel.inc("n")
        tel.heartbeat("hb")
        tel.recorder.instant("x")
        assert tel.get_counter("n") == 0
        assert tel.heartbeats() == {}
        assert len(tel.recorder) == 0

    def test_prometheus_text_exposition(self, args_factory):
        args = args_factory(run_id="promrun")
        args.rank = 2
        tel = Telemetry.get_instance(args)
        tel.inc("comm_messages_sent_total", 7, msg_type=3)
        tel.set_gauge("pipeline_depth", 4)
        tel.observe("comm_send_latency_s", 0.25, msg_type=3)
        text = tel.prometheus_text()
        assert "# TYPE comm_messages_sent_total counter" in text
        assert re.search(
            r'comm_messages_sent_total\{[^}]*msg_type="3"[^}]*\} 7\.0', text
        )
        assert 'run_id="promrun"' in text and 'rank="2"' in text
        assert "comm_send_latency_s_count" in text
        assert "comm_send_latency_s_sum" in text
        # every sample line is NAME{LABELS} VALUE
        for line in text.strip().splitlines():
            if line.startswith("#"):
                continue
            assert re.fullmatch(
                r"[a-zA-Z_:][a-zA-Z0-9_:]*\{[^}]*\} [-0-9.e+]+", line
            ), line

    def test_prometheus_label_values_escaped(self, args_factory):
        # a quote/backslash/newline in a tag value must not corrupt
        # the exposition
        args = args_factory(run_id='exp"A')
        tel = Telemetry.get_instance(args)
        tel.inc("x_total", path="a\\b\nc")
        text = tel.prometheus_text()
        assert 'run_id="exp\\"A"' in text
        assert 'path="a\\\\b\\nc"' in text

    def test_snapshot_through_metricsreporter_sink_seam(self, tmp_path):
        tel = Telemetry.get_instance()
        tel.inc("x_total")
        got = []
        tel.add_sink(got.append)
        path = str(tmp_path / "tel.jsonl")
        tel.add_jsonl_sink(path)
        tel.publish_snapshot()
        assert got and got[0]["kind"] == "telemetry_snapshot"
        rec = json.loads(open(path).read().strip())
        assert rec["counters"]["x_total"] == 1

    def test_singleton_reset_and_late_args_adoption(self, args_factory):
        # late args no longer silently ignored by any of the singletons
        tel = Telemetry.get_instance()
        assert tel.run_id == "0"
        args = args_factory(run_id="later")
        assert Telemetry.get_instance(args) is tel
        assert tel.run_id == "later"
        Telemetry.reset()
        assert Telemetry.get_instance() is not tel

        pe = ProfilerEvent.get_instance()
        assert ProfilerEvent.get_instance(args).run_id == "later"
        ProfilerEvent.reset()
        assert ProfilerEvent.get_instance() is not pe

        rl = RunLogger.get_instance()
        assert RunLogger.get_instance(args).args is args
        RunLogger.reset()
        assert RunLogger.get_instance() is not rl


def _check_trace_schema(payload):
    """Valid Chrome trace JSON: known phases (incl. the tracing layer's
    flow events and the stitcher's process metadata), monotonic ts,
    matched B/E pairs per (tid, name)."""
    evs = payload["traceEvents"]
    assert evs, "empty trace"
    data = [e for e in evs if e["ph"] != "M"]
    for ev in data:
        assert ev["ph"] in ("B", "E", "i", "C", "s", "f"), ev
        for key in ("name", "cat", "ts", "pid", "tid"):
            assert key in ev, ev
        if ev["ph"] in ("s", "f"):
            assert "id" in ev, ev
    assert all(
        data[i]["ts"] <= data[i + 1]["ts"] for i in range(len(data) - 1)
    ), "timestamps not monotonic"
    depth = {}
    for ev in data:
        k = (ev["tid"], ev["name"])
        if ev["ph"] == "B":
            depth[k] = depth.get(k, 0) + 1
        elif ev["ph"] == "E":
            depth[k] = depth.get(k, 0) - 1
            assert depth[k] >= 0, f"E without B: {k}"
    assert all(d == 0 for d in depth.values()), f"unmatched B/E: {depth}"
    return evs


class TestFlightRecorder:
    def test_export_schema_and_pairing(self, tmp_path):
        rec = FlightRecorder()
        rec.begin("round", round=0)
        rec.instant("pipeline.dispatch", round=0)
        rec.end("round")
        rec.counter("inflight", 2)
        rec.end("never_began")  # orphan E: must be dropped at export
        rec.begin("left_open")  # must be force-closed at export
        path = rec.export(str(tmp_path / "trace.json"), meta={"run_id": "t"})
        payload = json.load(open(path))
        evs = _check_trace_schema(payload)
        names = [e["name"] for e in evs]
        assert "round" in names and "pipeline.dispatch" in names
        assert "never_began" not in names  # orphan E dropped entirely
        closer = [e for e in evs if e.get("args", {}).get("forced_close")]
        assert len(closer) == 1 and closer[0]["name"] == "left_open"
        assert payload["otherData"]["run_id"] == "t"

    def test_profiler_spans_land_in_recorder(self):
        tel = Telemetry.get_instance()
        prof = ProfilerEvent()
        tel.attach_profiler(prof)
        with prof.span("train"):
            pass
        phases = [(e["name"], e["ph"]) for e in tel.recorder.tail()]
        assert ("train", "B") in phases and ("train", "E") in phases


class TestCommInstrumentation:
    def test_send_receive_counters_bytes_latency(self):
        tel = Telemetry.get_instance()
        rec = _FakeTransport()
        inst = InstrumentedCommunicationManager(rec, tel)
        payload = {"w": np.zeros((10, 4), dtype=np.float32)}
        m = _msg(3, payload)
        nb = payload_nbytes(m)
        assert nb >= 160  # the array alone
        inst.send_message(m)
        inst.send_message(_msg(5))
        assert len(rec.sent) == 2
        assert tel.get_counter("comm_messages_sent_total", msg_type=3) == 1
        assert tel.get_counter("comm_bytes_sent_total", msg_type=3) == nb
        lat = tel.snapshot()["histograms"]["comm_send_latency_s{msg_type=3}"]
        assert lat["count"] == 1

        class _Obs(Observer):
            def __init__(self):
                self.got = []

            def receive_message(self, t, m):
                self.got.append(t)

        obs = _Obs()
        inst.add_observer(obs)
        rec.deliver(_msg(3))
        assert obs.got == [3]
        assert tel.get_counter("comm_messages_received_total", msg_type=3) == 1
        inst.remove_observer(obs)
        assert rec.observers == []

    def test_send_lands_on_trace_timeline(self):
        tel = Telemetry.get_instance()
        inst = InstrumentedCommunicationManager(_FakeTransport(), tel)
        inst.send_message(_msg(3))
        evs = [e for e in tel.recorder.tail() if e["name"] == "comm.send"]
        assert evs and evs[0]["args"]["msg_type"] == 3

    def test_queue_depth_probe_on_local_fabric(self, args_factory):
        from fedml_tpu.core.comm.local import LocalCommunicationManager

        com = LocalCommunicationManager("tel_qd_fab", rank=0, size=2)
        inst = wrap_instrumented(com, args_factory())
        assert isinstance(inst, InstrumentedCommunicationManager)
        assert inst.queue_depth() == 0
        inst.send_message(_msg(3, receiver=0))
        assert inst.queue_depth() == 1
        com.destroy_fabric()

    def test_wrap_disabled_returns_untouched(self, args_factory):
        args = args_factory(telemetry=False)
        com = _FakeTransport()
        assert wrap_instrumented(com, args) is com


class TestFaultInjectorComposition:
    """Both wrap orders: injections visible in counters, bytes never
    double-counted. Sent counters mean ACTUAL wire sends (the managers
    stack instrumentation inside fault injection)."""

    def _fresh(self, args_factory):
        Telemetry.reset()
        return Telemetry.get_instance(args_factory()), _FakeTransport()

    def test_drop_instrumented_inner(self, args_factory):
        tel, rec = self._fresh(args_factory)
        com = FaultInjector(
            InstrumentedCommunicationManager(rec, tel), drop_prob=1.0
        )
        m = _msg(3, {"w": np.ones((8,), np.float32)})
        com.send_message(m)
        assert rec.sent == []  # dropped before the wire
        assert tel.get_counter(
            "comm_faults_injected_total", fault="drop", msg_type=3
        ) == 1
        # a dropped message never left this process: zero wire bytes
        assert tel.get_counter("comm_messages_sent_total", msg_type=3) == 0
        assert tel.get_counter("comm_bytes_sent_total", msg_type=3) == 0

    def test_drop_instrumented_outer(self, args_factory):
        tel, rec = self._fresh(args_factory)
        com = InstrumentedCommunicationManager(
            FaultInjector(rec, drop_prob=1.0), tel
        )
        m = _msg(3, {"w": np.ones((8,), np.float32)})
        nb = payload_nbytes(m)
        com.send_message(m)
        assert rec.sent == []
        assert tel.get_counter(
            "comm_faults_injected_total", fault="drop", msg_type=3
        ) == 1
        # outer layer counts the attempt exactly once — never twice
        assert tel.get_counter("comm_messages_sent_total", msg_type=3) == 1
        assert tel.get_counter("comm_bytes_sent_total", msg_type=3) == nb

    def test_duplicate_counts_each_wire_send_once(self, args_factory):
        tel, rec = self._fresh(args_factory)
        com = FaultInjector(
            InstrumentedCommunicationManager(rec, tel),
            duplicate_prob=1.0, max_faults=1,
        )
        m = _msg(3, {"w": np.ones((8,), np.float32)})
        nb = payload_nbytes(m)
        com.send_message(m)
        assert len(rec.sent) == 2  # at-least-once delivery
        assert tel.get_counter(
            "comm_faults_injected_total", fault="duplicate", msg_type=3
        ) == 1
        # two wire sends -> exactly 2x bytes, one count per send, no
        # per-layer double count on top
        assert tel.get_counter("comm_messages_sent_total", msg_type=3) == 2
        assert tel.get_counter("comm_bytes_sent_total", msg_type=3) == 2 * nb

    def test_delay_counted_when_it_actually_sends(self, args_factory):
        tel, rec = self._fresh(args_factory)
        com = FaultInjector(
            InstrumentedCommunicationManager(rec, tel),
            delay_prob=1.0, delay_s=0.05, max_faults=1,
        )
        com.send_message(_msg(3))
        assert tel.get_counter(
            "comm_faults_injected_total", fault="delay", msg_type=3
        ) == 1
        assert tel.get_counter("comm_messages_sent_total", msg_type=3) == 0
        deadline = time.time() + 2
        while time.time() < deadline and not rec.sent:
            time.sleep(0.01)
        assert len(rec.sent) == 1
        assert tel.get_counter("comm_messages_sent_total", msg_type=3) == 1


class TestPipelineTraceExport:
    def test_train_writes_valid_trace_json(self, tmp_path, args_factory):
        """A pipelined run with telemetry_dir set leaves a perfetto-
        loadable trace.json carrying profiler spans AND pipeline
        events on one timeline (the CI schema gate)."""
        tdir = str(tmp_path / "tel")
        _, _, _, api = _build(
            args_factory, depth=2, comm_round=4, telemetry_dir=tdir
        )
        api.train()
        payload = json.load(open(os.path.join(tdir, "trace.json")))
        evs = _check_trace_schema(payload)
        names = {e["name"] for e in evs}
        assert "round" in names  # profiler span (B/E pair)
        assert "pipeline.dispatch" in names  # pipeline instant
        assert "pipeline.flush" in names or "pipeline.drain" in names
        # registry exposition rides along
        assert os.path.exists(os.path.join(tdir, "metrics.prom"))
        assert os.path.exists(os.path.join(tdir, "telemetry.jsonl"))
        assert api.telemetry.get_counter("pipeline_rounds_dispatched_total") == 4

    def test_nonzero_rank_exports_suffixed_files(self, tmp_path, args_factory):
        """Ranks sharing one telemetry_dir must not clobber each other:
        non-zero ranks write trace_rankN.json / metrics_rankN.prom."""
        args = args_factory()
        args.rank = 2
        tel = Telemetry.get_instance(args)
        tel.inc("x_total")
        tel.export_run_artifacts(str(tmp_path))
        assert (tmp_path / "trace_rank2.json").exists()
        assert (tmp_path / "metrics_rank2.prom").exists()
        assert not (tmp_path / "trace.json").exists()

    def test_host_syncs_identical_telemetry_on_vs_off(self, args_factory):
        """The hot-loop contract: telemetry never adds a device fetch,
        so host_syncs_per_round is bit-identical on vs off."""
        stats = {}
        for enabled in (True, False):
            Telemetry.reset()
            _, _, _, api = _build(
                args_factory, depth=4, comm_round=8,
                frequency_of_the_test=2, telemetry=enabled,
            )
            api.train()
            stats[enabled] = api.pipeline_stats
        assert (
            stats[True]["host_syncs_per_round"]
            == stats[False]["host_syncs_per_round"]
        )
        assert stats[True]["host_syncs"] == stats[False]["host_syncs"]

    def test_retrace_storm_is_visible(self, args_factory):
        """Every jit retrace lands as a counter + a timeline instant
        with the cohort bucket."""
        args, _, _, api = _build(args_factory, comm_round=2)
        api.train()
        tel = api.telemetry
        assert tel.get_counter("pipeline_retraces_total") == 1
        args.client_num_per_round = 6  # bucket 6 (pow2 capped): retrace
        api.train()
        assert tel.get_counter("pipeline_retraces_total") == 2
        buckets = [
            e["args"]["bucket"] for e in tel.recorder.tail()
            if e["name"] == "jit.retrace"
        ]
        assert buckets == [4, 6]


class TestStallWatchdog:
    def test_forced_stall_dumps_debug_bundle(self, tmp_path, args_factory):
        """Acceptance: a forced stall produces a bundle containing open
        spans, the pending-metric count, and a host+device stats
        snapshot — and fires once per stall episode, not per poll."""
        tdir = str(tmp_path / "bundles")
        args = args_factory(stall_timeout_s=0.3, telemetry_dir=tdir)
        tel = Telemetry.get_instance(args)
        prof = ProfilerEvent(args)
        tel.attach_profiler(prof)
        prof.log_event_started("train")  # a span left open = the hang
        ring = DeferredMetrics()
        ring.push(7, {"loss": jnp.float32(1.0)})
        tel.attach_deferred(ring)
        tel.add_probe("comm_rank0", lambda: {"queue_depth": 5})
        wd = tel.maybe_start_watchdog(args)
        assert wd is not None
        tel.heartbeat("pipeline.round", 17)  # ...then progress stops
        deadline = time.time() + 10
        while time.time() < deadline and not wd.bundles:
            time.sleep(0.05)
        assert wd.bundles, "watchdog never fired"
        bundle = json.load(open(wd.bundles[0]))
        assert bundle["kind"] == "stall_bundle"
        assert bundle["heartbeats"]["pipeline.round"]["value"] == 17
        assert [s["name"] for s in bundle["open_spans"]] == ["train"]
        assert bundle["pending_deferred_metrics"] == 1
        assert "host_stats" in bundle and "device_stats" in bundle
        assert bundle["probes"]["comm_rank0"] == {"queue_depth": 5}
        # one bundle per episode: still stalled, but no second dump
        time.sleep(0.5)
        assert len(wd.bundles) == 1
        tel.stop_watchdog()

    def test_stale_marks_get_grace_but_first_heartbeat_hang_fires(
        self, tmp_path, args_factory
    ):
        """The singleton outlives train() calls: marks left by a
        finished run must not read as an INSTANT stall at restart (the
        new run gets one full timeout of grace from watchdog start) —
        but a run that hangs before its first heartbeat (compile
        deadlock) still dumps a bundle once the grace expires."""
        args = args_factory(
            stall_timeout_s=1.0, telemetry_dir=str(tmp_path / "b2")
        )
        tel = Telemetry.get_instance(args)
        tel.heartbeat("pipeline.round", 99)  # previous run's mark
        wd = tel.maybe_start_watchdog(args)  # new run starts (compiling)
        time.sleep(0.4)
        assert wd.bundles == []  # stale mark ignored, grace not expired
        deadline = time.time() + 10
        while time.time() < deadline and not wd.bundles:
            time.sleep(0.05)
        # no fresh heartbeat ever arrived: the first-compile hang fires
        assert len(wd.bundles) == 1
        tel.stop_watchdog()

    def test_watchdog_disabled_by_default(self, args_factory):
        args = args_factory()  # stall_timeout_s defaults to 0
        assert Telemetry.get_instance(args).maybe_start_watchdog(args) is None

    def test_negative_timeout_rejected(self, args_factory):
        with pytest.raises(ValueError, match="stall_timeout_s"):
            args_factory(stall_timeout_s=-1)


class TestDeferredMetricsSinglePass:
    def test_flush_preserves_push_order_one_pass(self):
        ring = DeferredMetrics()
        ring.push(4, {"a": jnp.float32(1.0)})
        ring.push(0, {"a": jnp.float32(2.0)})
        ring.push(2, {"a": jnp.float32(3.0)})
        out = ring.flush(upto=4)
        # push order, NOT round order — the reporter replays history
        # exactly as the synchronous loop would have produced it
        assert [r for r, _ in out] == [4, 0, 2]
        assert [float(t["a"]) for _, t in out] == [1.0, 2.0, 3.0]
        # invariant: every flush that returned records is exactly one
        # device fetch
        assert ring.host_syncs == ring.flushes == 1


class TestSysStatsDeviceGauges:
    class _Dev:
        def __init__(self, ms):
            self._ms = ms

        def memory_stats(self):
            if isinstance(self._ms, Exception):
                raise self._ms
            return self._ms

    def test_bytes_limit_exported(self, monkeypatch):
        import jax

        from fedml_tpu.core import sys_stats

        dev = self._Dev(
            {"bytes_in_use": 10, "peak_bytes_in_use": 20, "bytes_limit": 100}
        )
        monkeypatch.setattr(jax, "local_devices", lambda: [dev])
        s = sys_stats.sample_device_stats()
        assert s == {
            "device0_bytes_in_use": 10,
            "device0_peak_bytes": 20,
            "device0_bytes_limit": 100,
        }

    def test_sample_system_gauges_lands_in_registry_and_prom(self):
        from fedml_tpu.core.sys_stats import sample_host_stats

        if not sample_host_stats():
            pytest.skip("psutil unavailable")
        tel = Telemetry.get_instance()
        tel.sample_system_gauges()  # the export_run_artifacts path
        snap = tel.snapshot()
        assert "sys_cpu_util_pct" in snap["gauges"]
        assert "sys_cpu_util_pct" in tel.prometheus_text()

    def test_sysstats_sampler_streams_gauges(self):
        from fedml_tpu.core.sys_stats import SysStats, sample_host_stats
        from fedml_tpu.core.tracking import MetricsReporter

        if not sample_host_stats():
            pytest.skip("psutil unavailable")
        tel = Telemetry.get_instance()
        reporter = MetricsReporter(keep_history=True)
        s = SysStats(reporter, interval_s=0.05, telemetry=tel).start()
        deadline = time.time() + 5
        while time.time() < deadline and not reporter.history:
            time.sleep(0.02)
        s.stop()
        assert "sys_cpu_util_pct" in tel.snapshot()["gauges"]

    def test_backend_without_stats_logs_debug_once(self, monkeypatch, caplog):
        import logging

        import jax

        from fedml_tpu.core import sys_stats

        monkeypatch.setattr(sys_stats, "_DEVICE_STATS_LOGGED", False)
        monkeypatch.setattr(
            jax, "local_devices",
            lambda: [self._Dev(NotImplementedError("no stats"))] * 2,
        )
        with caplog.at_level(logging.DEBUG, logger=""):
            assert sys_stats.sample_device_stats() == {}
            assert sys_stats.sample_device_stats() == {}
        hits = [r for r in caplog.records if "memory stats unavailable" in r.message]
        assert len(hits) == 1 and hits[0].levelno == logging.DEBUG
