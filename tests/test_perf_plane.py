"""The performance-attribution plane (ISSUE 18): core/devtime.py
device-time accounting, the analysis/perf.py roofline join + idle
ledger, and the bench-trajectory ratchet.

Oracle-style where it matters: the idle-gap test feeds a synthetic
timeline with KNOWN gaps through the same `attribute_idle` the live
cross-silo server calls; the roofline test hand-builds an audit report
and asserts the EXACT MFU arithmetic; the ratchet matrix plants a
regression and proves the gate trips (and never cross-compares CPU
smoke against TPU captures).
"""

import argparse
import json
import os

import pytest

from fedml_tpu.analysis import perf
from fedml_tpu.core import devtime
from fedml_tpu.core.telemetry import Telemetry

pytestmark = pytest.mark.smoke

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- series-key parsing ------------------------------------------------


class TestSeriesKey:
    def test_tagged_series_round_trips(self):
        name, tags = perf.parse_series_key(
            "exec_device_seconds{bucket=b8,executable=simulation.round_fn}"
        )
        assert name == "exec_device_seconds"
        assert tags == {"bucket": "b8", "executable": "simulation.round_fn"}

    def test_untagged_series(self):
        assert perf.parse_series_key("round_wall_seconds") == (
            "round_wall_seconds", {}
        )


# -- idle-gap attribution oracle ---------------------------------------


class TestIdleOracle:
    def test_synthetic_timeline_yields_known_gaps(self):
        """t=100 broadcast, t=103 last arrival, aggregate takes 0.5s,
        round closes t=104 -> arrival_to_aggregate is exactly the
        0.5s the server sat on a full cohort before folding."""
        idle = perf.attribute_idle(
            now=104.0, bcast_t0=100.0, last_arrival=103.0,
            aggregate_s=0.5, prev_close=99.0,
        )
        assert idle["arrival_to_aggregate"] == pytest.approx(0.5)
        assert idle["close_to_broadcast"] == pytest.approx(1.0)

    def test_first_round_has_no_inter_round_gap(self):
        idle = perf.attribute_idle(
            now=10.0, bcast_t0=9.0, last_arrival=9.5, aggregate_s=0.1
        )
        assert "close_to_broadcast" not in idle

    def test_gaps_clamp_at_zero(self):
        # aggregation starting before the last arrival (streaming
        # folds) must not produce negative idle
        idle = perf.attribute_idle(
            now=10.0, bcast_t0=9.0, last_arrival=9.99,
            aggregate_s=5.0, prev_close=9.5,
        )
        assert idle["arrival_to_aggregate"] == 0.0
        assert idle["close_to_broadcast"] == 0.0

    def test_ledger_reconciles_to_wall(self):
        """segments + intra-round idle == wall -> recon_frac 1.0; the
        inter-round gap is excluded from intra-round reconciliation."""
        ledger = perf.summarize_ledger([
            {
                "round": 0,
                "wall_s": 2.0,
                "segments": {"broadcast_send": 0.2, "wait": 1.0,
                             "aggregate": 0.3},
                "idle": {"arrival_to_aggregate": 0.5},
                "wire_utilization_frac": 0.6,
            },
            {
                "round": 1,
                "wall_s": 1.0,
                "segments": {"broadcast_send": 0.1, "wait": 0.5,
                             "aggregate": 0.2},
                "idle": {"arrival_to_aggregate": 0.2,
                         "close_to_broadcast": 10.0},
                "wire_utilization_frac": 0.4,
            },
        ])
        assert ledger["rounds"][0]["recon_frac"] == 1.0
        assert ledger["rounds"][1]["recon_frac"] == 1.0
        assert ledger["total_wall_s"] == 3.0
        assert ledger["idle_totals_s"]["arrival_to_aggregate"] == 0.7
        assert ledger["idle_totals_s"]["close_to_broadcast"] == 10.0
        assert ledger["mean_wire_utilization_frac"] == 0.5

    def test_unaccounted_time_shows_as_low_recon(self):
        ledger = perf.summarize_ledger([
            {"round": 0, "wall_s": 2.0,
             "segments": {"aggregate": 0.5},
             "idle": {"arrival_to_aggregate": 0.5}},
        ])
        assert ledger["rounds"][0]["recon_frac"] == 0.5


# -- roofline join -----------------------------------------------------

# one executable whose arithmetic is trivially checkable by hand:
# 1000 calls x 2e9 FLOPs in 2.0 measured seconds = 1e12 FLOP/s; on a
# "TPU v5 lite" (197 TF/s bf16 peak) that is an MFU of 1/197.
_AUDIT = {
    "version": 1,
    "platform": "tpu",
    "executables": [
        {"executable": "simulation.round_fn", "case": "b8",
         "round_shaped": True, "hot": True,
         "flops": 2.0e9, "bytes_accessed": 1.0e9},
        {"executable": "simulation.round_fn", "case": "b32",
         "round_shaped": True, "hot": True,
         "flops": 8.0e9, "bytes_accessed": 2.0e9},
        {"executable": "agg.weighted_term", "case": None,
         "round_shaped": False, "hot": False,
         "flops": 36.0, "bytes_accessed": 72.0},
    ],
}


class TestRooflineJoin:
    def test_exact_mfu_arithmetic(self):
        measured = {
            ("simulation.round_fn", "b8"): {
                "count": 1000.0, "sum": 2.0, "min": 0.001, "max": 0.01,
            },
        }
        roof = perf.join_roofline(_AUDIT, measured, "TPU v5 lite")
        row = roof["rows"][0]
        assert row["joined"] is True and row["case_matched"] is True
        assert row["achieved_flops_per_sec"] == pytest.approx(1.0e12)
        peak = 197.0e12
        assert roof["peak_bf16_flops"] == pytest.approx(peak)
        # the report rounds MFU to 6 decimals
        assert row["mfu_vs_bf16_peak"] == round(1.0e12 / peak, 6)
        assert roof["coverage"] == 1.0

    def test_bucket_matches_audit_case_exactly(self):
        measured = {
            ("simulation.round_fn", "b32"): {
                "count": 10.0, "sum": 1.0, "min": 0.1, "max": 0.1,
            },
        }
        roof = perf.join_roofline(_AUDIT, measured, "TPU v5 lite")
        row = roof["rows"][0]
        assert row["case"] == "b32"
        assert row["flops_per_call"] == 8.0e9  # b32, not the b8 row

    def test_bound_verdict_from_arithmetic_intensity(self):
        # AI = 2e9/1e9 = 2 FLOP/byte, far below the v5 lite ridge
        # (197e12 / 0.82e12 ≈ 240) -> memory-bound
        measured = {
            ("simulation.round_fn", "b8"): {
                "count": 1.0, "sum": 1.0, "min": 1.0, "max": 1.0,
            },
        }
        roof = perf.join_roofline(_AUDIT, measured, "TPU v5 lite")
        assert roof["rows"][0]["bound"] == "memory"
        assert roof["ridge_flops_per_byte"] == pytest.approx(
            197.0 / 0.82, rel=1e-3
        )

    def test_unknown_executable_drags_coverage(self):
        measured = {
            ("simulation.round_fn", "b8"): {
                "count": 1.0, "sum": 3.0, "min": 3.0, "max": 3.0,
            },
            ("not.in.audit", ""): {
                "count": 1.0, "sum": 1.0, "min": 1.0, "max": 1.0,
            },
        }
        roof = perf.join_roofline(_AUDIT, measured, "TPU v5 lite")
        assert roof["coverage"] == 0.75  # 3 of 4 measured seconds joined
        assert roof["series_join_rate"] == 0.5

    def test_cpu_kind_reports_seconds_without_mfu(self):
        measured = {
            ("agg.weighted_term", ""): {
                "count": 4.0, "sum": 0.01, "min": 0.001, "max": 0.005,
            },
        }
        roof = perf.join_roofline(_AUDIT, measured, "cpu")
        assert roof["peak_bf16_flops"] is None
        assert "mfu_vs_bf16_peak" not in roof["rows"][0]
        assert roof["rows"][0]["joined"] is True

    def test_checked_in_audit_report_joins(self):
        """The REAL audit_report.json: every registry executable the
        devtime plane instruments is joinable (the acceptance gate's
        coverage can reach 0.9 on an instrumented run)."""
        audit = perf.load_audit_report(
            os.path.join(REPO, "audit_report.json")
        )
        names = {r["executable"] for r in audit["executables"]}
        for exe in ("simulation.round_fn", "agg.weighted_term",
                    "agg.fold_tree", "serving.forward",
                    "planet.group_fn"):
            assert exe in names, exe
            measured = {(exe, ""): {"count": 2.0, "sum": 0.5,
                                    "min": 0.2, "max": 0.3}}
            roof = perf.join_roofline(audit, measured, "TPU v5 lite")
            assert roof["rows"][0]["joined"] is True, exe


# -- bench ratchet matrix ----------------------------------------------


def _bench_file(tmp_path, name, phase, kind, smoke, value,
                unit="rounds/s", omit_meta=False, crashed=False):
    rec = {"n": 1, "cmd": "bench", "rc": 0}
    if crashed:
        rec["parsed"] = None
    elif omit_meta:
        rec["parsed"] = {"metric": phase, "value": value, "unit": unit,
                         "detail": {}}
    else:
        rec["parsed"] = {
            "metric": phase, "value": value, "unit": unit, "detail": {},
            "meta": {"schema": 1, "phase": phase, "device_kind": kind,
                     "backend": "cpu" if kind == "cpu" else "tpu",
                     "smoke": smoke, "value": value, "metric": phase,
                     "unit": unit},
        }
    path = tmp_path / name
    path.write_text(json.dumps(rec))
    return str(path)


class TestRatchet:
    def test_planted_regression_fails(self, tmp_path):
        paths = [
            _bench_file(tmp_path, "BENCH_r01.json", "headline",
                        "TPU v5 lite", False, 1.14),
            _bench_file(tmp_path, "BENCH_r02.json", "headline",
                        "TPU v5 lite", False, 0.50),  # -56%: planted
        ]
        report = perf.run_ratchet(paths)
        assert report["regressions"] == 1
        assert report["ok"] is False
        g = report["groups"][0]
        assert g["verdict"] == "REGRESSION"
        assert g["best_prior"] == 1.14
        # the CLI exits 1 on exactly this report
        rc = perf.run_cli(argparse.Namespace(
            ratchet=paths, tolerance=perf.DEFAULT_TOLERANCE, quiet=True,
        ))
        assert rc == 1

    def test_improvement_and_jitter_pass(self, tmp_path):
        paths = [
            _bench_file(tmp_path, "BENCH_r01.json", "headline",
                        "TPU v5 lite", False, 1.00),
            _bench_file(tmp_path, "BENCH_r02.json", "headline",
                        "TPU v5 lite", False, 0.95),  # within 10%
            _bench_file(tmp_path, "BENCH_r03.json", "headline",
                        "TPU v5 lite", False, 1.30),  # improvement
        ]
        report = perf.run_ratchet(paths)
        assert report["ok"] is True
        assert report["groups"][0]["verdict"] == "ok"
        # best prior is the historical BEST, not the previous record
        assert report["groups"][0]["best_prior"] == 1.00

    def test_smoke_and_tpu_never_cross_compare(self, tmp_path):
        """A CPU smoke record 20x below the TPU capture is NOT a
        regression — the groups are disjoint by construction."""
        paths = [
            _bench_file(tmp_path, "BENCH_r01.json", "headline",
                        "TPU v5 lite", False, 1.14),
            _bench_file(tmp_path, "BENCH_r02.json", "headline",
                        "cpu", True, 0.05),
        ]
        report = perf.run_ratchet(paths)
        assert report["ok"] is True
        verdicts = {
            (g["phase"], g["device_kind"], g["smoke"]): g["verdict"]
            for g in report["groups"]
        }
        assert verdicts[("headline", "TPU v5 lite", False)] == "seeded"
        assert verdicts[("headline", "cpu", True)] == "seeded"

    def test_missing_meta_fails_loudly(self, tmp_path):
        paths = [
            _bench_file(tmp_path, "BENCH_r01.json", "headline",
                        "cpu", False, 1.0),
            _bench_file(tmp_path, "BENCH_r02.json", "headline",
                        "cpu", False, 1.0, omit_meta=True),
        ]
        report = perf.run_ratchet(paths)
        assert report["violations"], report
        assert report["ok"] is False
        rc = perf.run_cli(argparse.Namespace(
            ratchet=paths, tolerance=perf.DEFAULT_TOLERANCE, quiet=True,
        ))
        assert rc == 2

    def test_crashed_record_skipped_not_violated(self, tmp_path):
        paths = [
            _bench_file(tmp_path, "BENCH_r01.json", "headline",
                        "cpu", False, 1.0, crashed=True),
            _bench_file(tmp_path, "BENCH_r02.json", "headline",
                        "cpu", False, 1.0),
        ]
        report = perf.run_ratchet(paths)
        assert report["ok"] is True
        assert len(report["skipped"]) == 1
        assert "parsed=null" in report["skipped"][0]

    def test_latency_metrics_ratchet_downward(self, tmp_path):
        paths = [
            _bench_file(tmp_path, "BENCH_r01.json", "serving",
                        "cpu", False, 10.0, unit="p99_ms"),
            _bench_file(tmp_path, "BENCH_r02.json", "serving",
                        "cpu", False, 20.0, unit="p99_ms"),  # 2x slower
        ]
        report = perf.run_ratchet(paths)
        assert report["groups"][0]["verdict"] == "REGRESSION"
        # and an improvement (lower) passes
        paths[1] = _bench_file(tmp_path, "BENCH_r03.json", "serving",
                               "cpu", False, 5.0, unit="p99_ms")
        assert perf.run_ratchet(paths)["ok"] is True

    def test_checked_in_trajectory_is_green(self):
        """The CI gate at HEAD: the real BENCH history must pass its
        own ratchet (a planted regression is the only way to trip it)."""
        import glob

        paths = sorted(
            glob.glob(os.path.join(REPO, "BENCH_r0*.json"))
            + glob.glob(os.path.join(REPO, "BENCH_TPU_CAPTURE_*.json"))
        )
        report = perf.run_ratchet(paths)
        assert report["violations"] == []
        assert report["ok"] is True, report["groups"]
        assert len(report["groups"]) >= 3


# -- devtime measurement -----------------------------------------------


class TestDevtime:
    def test_telemetry_on_emits_histogram_spans_and_ring(self):
        tel = Telemetry.get_instance()
        tel.enabled = True
        with devtime.measure("simulation.round_fn", bucket="b8"):
            pass
        snap = tel.snapshot()
        key = ("exec_device_seconds"
               "{bucket=b8,executable=simulation.round_fn}")
        assert key in snap["histograms"]
        assert snap["histograms"][key]["count"] == 1
        names = [e.get("name") for e in tel.recorder.tail(10)]
        assert names.count("exec.simulation.round_fn") == 2  # B + E
        ring = devtime.ring_snapshot()
        assert len(ring) == 1
        assert ring[0]["executable"] == "simulation.round_fn"
        assert ring[0]["bucket"] == "b8"
        assert ring[0]["seconds"] >= 0.0

    def test_telemetry_off_still_records_the_fallback_ring(self):
        tel = Telemetry.get_instance()
        tel.enabled = False
        with devtime.measure("agg.fold_tree"):
            pass
        assert "exec_device_seconds" not in str(
            tel.snapshot()["histograms"]
        )
        ring = devtime.ring_snapshot()
        assert [e["executable"] for e in ring] == ["agg.fold_tree"]
        assert ring[0]["bucket"] is None
        assert devtime.measured_executables() == ["agg.fold_tree"]

    def test_ring_size_knob_adopted(self):
        ns = argparse.Namespace(devtime_ring_size=2)
        devtime.configure(ns)
        tel = Telemetry.get_instance()
        tel.enabled = False
        for i in range(5):
            with devtime.measure("agg.weighted_term", bucket=f"b{i}"):
                pass
        ring = devtime.ring_snapshot()
        assert len(ring) == 2  # bounded by the knob
        assert [e["bucket"] for e in ring] == ["b3", "b4"]  # newest kept

    def test_measure_reraises_but_always_accounts(self):
        tel = Telemetry.get_instance()
        tel.enabled = True
        with pytest.raises(RuntimeError):
            with devtime.measure("serving.forward", bucket="b4"):
                raise RuntimeError("dispatch failed")
        # the span closed and the time was still accounted
        assert len(devtime.ring_snapshot()) == 1
        key = "exec_device_seconds{bucket=b4,executable=serving.forward}"
        assert key in tel.snapshot()["histograms"]


# -- perf CLI over synthetic run artifacts ------------------------------


def _synth_run_dir(tmp_path):
    """A minimal telemetry_dir: one snapshot with exec histograms and
    one trace shard with two round.ledger instants."""
    hist_key = "exec_device_seconds{bucket=b8,executable=simulation.round_fn}"
    (tmp_path / "telemetry.jsonl").write_text(json.dumps({
        "kind": "telemetry_snapshot", "run_id": "t", "rank": 0,
        "histograms": {
            hist_key: {"count": 4, "sum": 2.0, "min": 0.4, "max": 0.6},
        },
    }) + "\n")
    events = [
        {"name": "round.ledger", "ph": "i", "ts": 1.0, "pid": 1,
         "args": {"round": r, "wall_s": 1.0,
                  "segments": {"broadcast_send": 0.2, "wait": 0.5,
                               "aggregate": 0.2},
                  "idle": {"arrival_to_aggregate": 0.1},
                  "wire_utilization_frac": 0.5}}
        for r in range(2)
    ]
    (tmp_path / "trace.json").write_text(
        json.dumps({"traceEvents": events, "otherData": {}})
    )
    return str(tmp_path)


def _report_ns(**kw):
    ns = argparse.Namespace(
        telemetry_dir=None, audit_report=None, device_kind=None,
        n_chips=1, min_coverage=perf.DEFAULT_MIN_COVERAGE, ratchet=None,
        tolerance=perf.DEFAULT_TOLERANCE, out=None, root=None, quiet=True,
    )
    for k, v in kw.items():
        setattr(ns, k, v)
    return ns


class TestPerfCli:
    def test_report_mode_emits_roofline_and_ledger(self, tmp_path, capsys):
        tdir = _synth_run_dir(tmp_path)
        rc = perf.run_cli(_report_ns(
            telemetry_dir=tdir, device_kind="TPU v5 lite", root=REPO,
        ))
        assert rc == 0
        report = json.load(open(os.path.join(tdir, "perf_report.json")))
        roof = report["roofline"]
        assert roof["coverage"] == 1.0
        assert roof["rows"][0]["executable"] == "simulation.round_fn"
        assert roof["rows"][0]["mfu_vs_bf16_peak"] is not None
        ledger = report["ledger"]
        assert len(ledger["rounds"]) == 2
        # the acceptance bar: accounted time reconciles within 5%
        assert all(r["recon_frac"] >= 0.95 for r in ledger["rounds"])
        assert ledger["mean_wire_utilization_frac"] == 0.5
        out = capsys.readouterr().out
        assert json.loads(out.strip().splitlines()[-1])["ok"] is True

    def test_low_coverage_fails_the_gate(self, tmp_path):
        tdir = _synth_run_dir(tmp_path)
        # an unregistered executable dominating measured seconds
        hist_key = "exec_device_seconds{executable=rogue.exec}"
        with open(os.path.join(tdir, "telemetry.jsonl"), "a") as fh:
            fh.write(json.dumps({
                "kind": "telemetry_snapshot", "run_id": "t2", "rank": 0,
                "histograms": {
                    hist_key: {"count": 1, "sum": 98.0,
                               "min": 98.0, "max": 98.0},
                },
            }) + "\n")
        rc = perf.run_cli(_report_ns(
            telemetry_dir=tdir, device_kind="TPU v5 lite", root=REPO,
        ))
        assert rc == 1

    def test_missing_inputs_exit_2(self, tmp_path):
        assert perf.run_cli(_report_ns()) == 2
        assert perf.run_cli(_report_ns(
            telemetry_dir=str(tmp_path / "nope")
        )) == 2
        tdir = _synth_run_dir(tmp_path)
        assert perf.run_cli(_report_ns(
            telemetry_dir=tdir,
            audit_report=str(tmp_path / "no_audit.json"),
        )) == 2

    def test_cli_subcommand_is_wired(self):
        from fedml_tpu import cli

        parser = cli.build_parser()
        ns = parser.parse_args(["perf", "--ratchet", "x.json"])
        assert ns.ratchet == ["x.json"]
        assert callable(ns.fn)
