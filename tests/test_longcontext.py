"""Long-context subsystem: ring/Ulysses sequence parallelism + flash kernel."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.ops.flash_attention import flash_attention
from fedml_tpu.parallel.sequence import (
    full_attention,
    make_sequence_sharded_attention,
    ring_attention,
    ulysses_attention,
)

B, T, H, D = 2, 64, 4, 16


def _qkv(seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def sp_mesh():
    devs = jax.devices()
    assert len(devs) == 8
    return Mesh(np.array(devs), ("sp",))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, sp_mesh, causal):
        q, k, v = _qkv()
        want = full_attention(q, k, v, causal=causal)
        attn = make_sequence_sharded_attention(
            sp_mesh, strategy="ring", causal=causal
        )
        got = jax.jit(attn)(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_chunked_hops_match_full_attention(self, sp_mesh, causal):
        """sp_ring_block chunks each hop's K/V shard — same online
        softmax in more steps; must be exact vs the dense oracle AND
        vs the unchunked ring (per-chip panel [Tq, bk] not [Tq, Tk])."""
        q, k, v = _qkv()
        want = full_attention(q, k, v, causal=causal)
        bk = (T // 8) // 2  # two chunks per hop
        attn = make_sequence_sharded_attention(
            sp_mesh, strategy="ring", causal=causal, ring_block_k=bk
        )
        got = jax.jit(attn)(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_chunked_rejects_indivisible_block(self, sp_mesh):
        q, k, v = _qkv()
        attn = make_sequence_sharded_attention(
            sp_mesh, strategy="ring", ring_block_k=(T // 8) - 1
        )
        with pytest.raises(ValueError, match="block_k"):
            jax.jit(attn)(q, k, v)

    def test_chunked_gradients_match(self, sp_mesh):
        q, k, v = _qkv(1)
        bk = (T // 8) // 2
        attn = make_sequence_sharded_attention(
            sp_mesh, strategy="ring", causal=True, ring_block_k=bk
        )

        def loss_ring(q, k, v):
            return (attn(q, k, v) ** 2).sum()

        def loss_full(q, k, v):
            return (full_attention(q, k, v, causal=True) ** 2).sum()

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_full):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)

    def test_gradients_match(self, sp_mesh):
        q, k, v = _qkv(1)
        attn = make_sequence_sharded_attention(sp_mesh, strategy="ring", causal=True)

        def loss_ring(q, k, v):
            return (attn(q, k, v) ** 2).sum()

        def loss_full(q, k, v):
            return (full_attention(q, k, v, causal=True) ** 2).sum()

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_full):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)

    def test_memory_shape_is_blockwise(self, sp_mesh):
        """The jaxpr under shard_map only ever holds [Tq/n, Tk/n] score
        blocks — full [T, T] never materializes per shard. Recurses into
        every sub-jaxpr (shard_map body, scan body, ...)."""

        def all_shapes(jaxpr):
            for eqn in jaxpr.eqns:
                for var in eqn.outvars:
                    if hasattr(var.aval, "shape"):
                        yield tuple(var.aval.shape)
                for p in eqn.params.values():
                    inner = getattr(p, "jaxpr", p)
                    if hasattr(inner, "eqns"):
                        yield from all_shapes(inner)

        q, k, v = _qkv(2)
        attn = make_sequence_sharded_attention(sp_mesh, strategy="ring", causal=True)
        shapes = list(all_shapes(jax.make_jaxpr(attn)(q, k, v).jaxpr))
        score_like = [s for s in shapes if len(s) >= 2 and s[-2:] == (T, T)]
        assert not score_like, score_like
        # sanity: the recursion actually saw the per-shard blocks
        n = 8
        assert any(s[-2:] == (T // n, T // n) for s in shapes if len(s) >= 2)


class TestUlysses:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, sp_mesh, causal):
        # Ulysses re-shards heads over the axis: H must divide n
        rng = np.random.default_rng(3)
        mk = lambda: jnp.asarray(rng.normal(size=(B, T, 8, D)).astype(np.float32))
        q, k, v = mk(), mk(), mk()
        want = full_attention(q, k, v, causal=causal)
        attn = make_sequence_sharded_attention(
            sp_mesh, strategy="ulysses", causal=causal
        )
        got = jax.jit(attn)(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_rejects_indivisible_heads(self, sp_mesh):
        q, k, v = _qkv(3)  # H=4 over 8 devices
        attn = make_sequence_sharded_attention(sp_mesh, strategy="ulysses")
        with pytest.raises(ValueError, match="divisible"):
            jax.jit(attn)(q, k, v)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full(self, causal):
        q, k, v = _qkv(4)
        want = full_attention(q, k, v, causal=causal)
        got = flash_attention(q, k, v, causal, None, 16, 16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_gradients(self):
        q, k, v = _qkv(5)

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, True, None, 16, 16) ** 2).sum()

        def loss_full(q, k, v):
            return (full_attention(q, k, v, causal=True) ** 2).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)

    def test_rejects_indivisible_blocks(self):
        q, k, v = _qkv(6)
        with pytest.raises(ValueError, match="divide"):
            flash_attention(q, k, v, True, None, 48, 48)

    def test_backward_is_blockwise(self):
        """The custom backward's jaxpr never materializes a [T, T]
        score matrix — only [T, bk] panels per scan step."""

        def all_shapes(jaxpr):
            for eqn in jaxpr.eqns:
                for var in eqn.outvars:
                    if hasattr(var.aval, "shape"):
                        yield tuple(var.aval.shape)
                for p in eqn.params.values():
                    inner = getattr(p, "jaxpr", p)
                    if hasattr(inner, "eqns"):
                        yield from all_shapes(inner)

        q, k, v = _qkv(7)
        bk = 16

        def loss(q, k, v):
            return (flash_attention(q, k, v, True, None, bk, bk) ** 2).sum()

        jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
        shapes = list(all_shapes(jaxpr.jaxpr))
        assert not any(s[-2:] == (T, T) for s in shapes if len(s) >= 2)
        assert any(s[-2:] == (T, bk) for s in shapes if len(s) >= 2)


class TestBf16Ring:
    def test_bf16_ring_tracks_f32_oracle(self, sp_mesh):
        rng = np.random.default_rng(8)
        mk = lambda: jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
        q, k, v = mk(), mk(), mk()
        want = full_attention(q, k, v, causal=True)
        attn = make_sequence_sharded_attention(sp_mesh, strategy="ring", causal=True)
        got = jax.jit(attn)(
            q.astype(jnp.bfloat16), k.astype(jnp.bfloat16), v.astype(jnp.bfloat16)
        )
        assert got.dtype == jnp.bfloat16
        # f32 accumulation keeps bf16 inputs within bf16 rounding of the
        # f32 oracle (pure-bf16 accumulation drifts ~10x worse)
        err = np.abs(np.asarray(got, np.float32) - np.asarray(want)).max()
        assert err < 0.05, err


class TestTransformerFL:
    @pytest.mark.slow  # re-tiered by measurement (>4s fast-gate budget)
    def test_transformer_federated_training(self, args_factory):
        from fedml_tpu import models
        from fedml_tpu.data import load
        from fedml_tpu.simulation import FedAvgAPI

        args = args_factory(
            dataset="shakespeare",
            synthetic_train_size=160,
            synthetic_test_size=40,
            model="transformer",
            vocab_size=90,
            seq_len=32,
            num_layers=1,
            num_heads=2,
            embed_dim=32,
            client_num_in_total=4,
            client_num_per_round=4,
            comm_round=2,
            epochs=1,
            batch_size=8,
            learning_rate=0.1,
            frequency_of_the_test=1,
        )
        dataset = load(args)
        model = models.create(args, dataset.class_num)
        api = FedAvgAPI(args, None, dataset, model)
        stats = api.train()
        assert np.isfinite(stats["test_loss"])
        assert api.history[-1]["train_loss"] < api.history[0]["train_loss"] * 1.2

    def test_flash_variant_same_loss(self, args_factory):
        from fedml_tpu import models

        common = dict(
            dataset="shakespeare", model="transformer", vocab_size=50,
            seq_len=16, num_layers=1, num_heads=2, embed_dim=32,
        )
        m_full = models.create(args_factory(**common, attention_impl="full"), 50)
        m_flash = models.create(args_factory(**common, attention_impl="flash"), 50)
        params = m_full.init(jax.random.PRNGKey(0))
        x = jnp.asarray(np.random.default_rng(0).integers(0, 50, (4, 16)))
        np.testing.assert_allclose(
            np.asarray(m_full.apply(params, x)),
            np.asarray(m_flash.apply(params, x)),
            atol=2e-5,
        )
