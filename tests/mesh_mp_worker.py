"""One host process of a multi-controller MESH simulation (test worker).

The v4-64 north-star seam: the client-parallel simulator's global mesh
spans several host processes (``jax.distributed``); every process runs
the SAME jitted FedAvg round, XLA runs it as one SPMD computation with
the weighted reduction as a cross-process all-reduce. Spawned by
``tests/test_multiprocess_mesh.py``.
"""

import argparse
import sys


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--proc_rank", type=int, required=True)
    p.add_argument("--n_proc", type=int, required=True)
    p.add_argument("--coordinator", required=True)
    p.add_argument("--out", default="")
    ns = p.parse_args()

    import jax

    jax.distributed.initialize(
        coordinator_address=ns.coordinator,
        num_processes=ns.n_proc,
        process_id=ns.proc_rank,
    )
    assert len(jax.devices()) == 8, jax.devices()
    assert jax.process_count() == ns.n_proc

    import numpy as np

    import fedml_tpu
    from fedml_tpu import models
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.data import load
    from fedml_tpu.simulation.simulator import SimulatorMesh

    args = Arguments()
    for k, v in dict(
        training_type="simulation",
        backend="MESH",
        dataset="mnist",
        synthetic_train_size=512,
        synthetic_test_size=128,
        model="lr",
        partition_method="hetero",
        client_num_in_total=8,
        client_num_per_round=8,
        comm_round=2,
        epochs=1,
        batch_size=16,
        learning_rate=0.1,
        frequency_of_the_test=1,
        shuffle=False,
        mesh_shape={"clients": 8},
    ).items():
        setattr(args, k, v)
    args._validate()
    args = fedml_tpu.init(args)
    dataset = load(args)
    model = models.create(args, dataset.class_num)
    sim = SimulatorMesh(args, None, dataset, model)
    sim.run()

    if ns.proc_rank == 0 and ns.out:
        params = sim.fl_trainer.global_params
        flat = {f"p{i}": np.asarray(x) for i, x in enumerate(jax.tree.leaves(params))}
        np.savez(ns.out, **flat)
    print("MESH_WORKER_DONE", ns.proc_rank, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
