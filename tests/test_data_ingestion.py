"""Real on-disk dataset ingestion (VERDICT r2 #4).

- a REAL-format LEAF json split (checked into tests/data/mnist) flows
  through ``load(args)`` end to end with NO synthetic stand-in warning;
- TFF h5 (fed_cifar100 / fed_shakespeare shapes, reference
  ``data/fed_cifar100/data_loader.py``) written by h5py in the
  canonical layout loads as a natural federation;
- CIFAR python batches (``cifar10/data_loader.py:106-120`` format) load
  globally and LDA-partition;
- user folding (regroup_clients) keeps any client_num runnable.
"""

import logging
import os
import pickle

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu import models
from fedml_tpu.data import load
from fedml_tpu.data.ingest import (
    SHAKESPEARE_VOCAB,
    load_cifar_batches,
    load_tff_h5,
    regroup_clients,
    shakespeare_to_sequences,
)
from fedml_tpu.simulation import FedAvgAPI

pytestmark = pytest.mark.smoke

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")


def _args(make, **kw):
    base = dict(
        dataset="mnist",
        model="lr",
        client_num_in_total=4,
        client_num_per_round=4,
        comm_round=2,
        epochs=1,
        batch_size=8,
        learning_rate=0.1,
        frequency_of_the_test=1,
        shuffle=False,
    )
    base.update(kw)
    return make(**base)


class TestLeafJson:
    def test_loads_real_leaf_no_synthetic_fallback(self, args_factory, caplog):
        args = _args(args_factory, data_cache_dir=FIXTURES)
        args = fedml_tpu.init(args)
        with caplog.at_level(logging.WARNING):
            ds = load(args)
        assert "synthetic stand-in" not in caplog.text
        # natural federation: 4 LEAF users, ragged sizes 10..13
        assert ds.client_num == 4
        assert sorted(ds.train_data_local_num_dict.values()) == [10, 11, 12, 13]
        assert ds.class_num == 10
        assert ds.packed_train.x.shape[-3:] == (8, 28, 28) or ds.packed_train.x.shape[-4:-1] == (8, 28, 28)

    def test_trains_end_to_end(self, args_factory):
        args = _args(args_factory, data_cache_dir=FIXTURES)
        args = fedml_tpu.init(args)
        ds = load(args)
        model = models.create(args, ds.class_num)
        api = FedAvgAPI(args, None, ds, model)
        stats = api.train()
        assert np.isfinite(stats["train_loss"])

    def test_user_folding_when_fewer_clients_requested(self, args_factory):
        args = _args(
            args_factory, data_cache_dir=FIXTURES,
            client_num_in_total=2, client_num_per_round=2,
        )
        args = fedml_tpu.init(args)
        ds = load(args)
        assert ds.client_num == 2
        # all 46 samples survive the fold
        assert sum(ds.train_data_local_num_dict.values()) == 46

    def test_caps_when_more_clients_requested(self, args_factory):
        args = _args(
            args_factory, data_cache_dir=FIXTURES,
            client_num_in_total=9, client_num_per_round=9,
        )
        args = fedml_tpu.init(args)
        ds = load(args)
        assert ds.client_num == 4
        assert args.client_num_in_total == 4
        assert args.client_num_per_round == 4


def _write_tff_cifar100(dirpath, n_clients=3):
    import h5py

    os.makedirs(dirpath, exist_ok=True)
    rng = np.random.RandomState(0)
    for split, n_img in (("train", 10), ("test", 4)):
        with h5py.File(os.path.join(dirpath, f"fed_cifar100_{split}.h5"), "w") as f:
            g = f.create_group("examples")
            for c in range(n_clients):
                cg = g.create_group(f"client_{c}")
                cg.create_dataset(
                    "image", data=rng.randint(0, 256, (n_img, 32, 32, 3), np.uint8)
                )
                cg.create_dataset(
                    "label", data=rng.randint(0, 100, (n_img, 1), np.int64)
                )


class TestTffH5:
    def test_fed_cifar100_loads(self, tmp_path, args_factory):
        d = tmp_path / "fed_cifar100"
        _write_tff_cifar100(str(d))
        args = _args(
            args_factory,
            dataset="fed_cifar100",
            data_cache_dir=str(tmp_path),
            client_num_in_total=3,
            client_num_per_round=3,
            model="cnn",
        )
        args = fedml_tpu.init(args)
        ds = load(args)
        assert ds.client_num == 3
        assert ds.class_num == 100
        assert ds.packed_train.x.shape[-3:] == (32, 32, 3)
        # [0,1] scaling applied
        assert float(ds.packed_train.x.max()) <= 1.0

    def test_fed_shakespeare_loads(self, tmp_path, args_factory):
        import h5py

        d = tmp_path / "fed_shakespeare"
        os.makedirs(d)
        lines = [
            b"To be, or not to be, that is the question:",
            b"Whether 'tis nobler in the mind to suffer",
            b"The slings and arrows of outrageous fortune,",
        ]
        for split, k in (("train", 3), ("test", 1)):
            with h5py.File(os.path.join(d, f"shakespeare_{split}.h5"), "w") as f:
                g = f.create_group("examples")
                for c in range(2):
                    cg = g.create_group(f"bard_{c}")
                    cg.create_dataset("snippets", data=lines[:k])
        args = _args(
            args_factory,
            dataset="fed_shakespeare",
            data_cache_dir=str(tmp_path),
            client_num_in_total=2,
            client_num_per_round=2,
            model="rnn",
        )
        args = fedml_tpu.init(args)
        ds = load(args)
        assert ds.client_num == 2
        assert ds.task == "nwp"
        assert ds.packed_train.x.shape[-1] == 80
        assert ds.packed_train.x.dtype == np.int32


def _write_stackoverflow(dirpath, n_clients=3):
    """Real-format stackoverflow artifacts: stackoverflow_{split}.h5
    (examples/<client>/{tokens,title,tags}) + the word_count/tag_count
    side files (reference stackoverflow_nwp/utils.py:20-28,
    stackoverflow_lr/utils.py:35-45)."""
    import json

    import h5py

    os.makedirs(dirpath, exist_ok=True)
    words = ["how", "to", "use", "python", "list", "sort", "fast", "index"]
    with open(os.path.join(dirpath, "stackoverflow.word_count"), "w") as f:
        for i, w in enumerate(words):
            f.write(f"{w} {1000 - i}\n")
    tags = {"python": 900, "sorting": 500, "performance": 300}
    with open(os.path.join(dirpath, "stackoverflow.tag_count"), "w") as f:
        json.dump(tags, f)
    sentences = [
        b"how to sort a python list",
        b"use index to find fast",
        b"python list sort",
    ]
    titles = [b"sorting question", b"index question", b"sort help"]
    tag_rows = [b"python|sorting", b"performance", b"python"]
    for split, k in (("train", 3), ("test", 2)):
        with h5py.File(
            os.path.join(dirpath, f"stackoverflow_{split}.h5"), "w"
        ) as f:
            g = f.create_group("examples")
            for c in range(n_clients):
                cg = g.create_group(f"user_{c}")
                cg.create_dataset("tokens", data=sentences[:k])
                cg.create_dataset("title", data=titles[:k])
                cg.create_dataset("tags", data=tag_rows[:k])


class TestStackoverflow:
    def test_nwp_loads(self, tmp_path, args_factory):
        d = tmp_path / "stackoverflow_nwp"
        _write_stackoverflow(str(d))
        args = _args(
            args_factory,
            dataset="stackoverflow_nwp",
            data_cache_dir=str(tmp_path),
            client_num_in_total=3,
            client_num_per_round=3,
            model="rnn",
        )
        args = fedml_tpu.init(args)
        ds = load(args)
        assert ds.client_num == 3
        assert ds.task == "nwp"
        assert ds.packed_train.x.shape[-1] == 20  # SO_SEQ_LEN
        assert ds.packed_train.x.dtype == np.int32

    def test_lr_loads(self, tmp_path, args_factory):
        d = tmp_path / "stackoverflow_lr"
        _write_stackoverflow(str(d))
        args = _args(
            args_factory,
            dataset="stackoverflow_lr",
            data_cache_dir=str(tmp_path),
            client_num_in_total=3,
            client_num_per_round=3,
            model="lr",
        )
        args = fedml_tpu.init(args)
        ds = load(args)
        assert ds.client_num == 3
        assert ds.task == "tag_prediction"
        # bag-of-words over the 8-word fixture vocab
        assert ds.packed_train.x.shape[-1] == 8
        assert args.input_dim == 8
        # multi-hot over the 3 fixture label tags
        assert ds.packed_train.y.shape[-1] == 3
        assert set(np.unique(ds.packed_train.y)) <= {0.0, 1.0}

    def test_nwp_token_ids(self):
        from fedml_tpu.data.ingest import so_nwp_to_sequences

        words = ["how", "to", "sort"]
        bos, eos, oov = 4, 5, 6
        x, y = so_nwp_to_sequences(["how to sort quickly"], words)
        assert x.shape == (1, 20) and y.shape == (1, 20)
        # x = [bos how to sort oov eos pad...]; y shifted by one
        assert x[0, 0] == bos
        assert list(x[0, 1:5]) == [1, 2, 3, oov]
        assert y[0, 4] == eos  # short sentence gets EOS
        assert (y[0, 5:] == 0).all()
        assert y[0, 0] == x[0, 1]

    def test_nwp_truncates_to_20(self):
        from fedml_tpu.data.ingest import so_nwp_to_sequences

        x, y = so_nwp_to_sequences(["w " * 50], ["w"])
        assert x.shape == (1, 20)
        # truncated sentences get no EOS (reference tokenizer: EOS only
        # when shorter than max_seq_len); eos id = len(vocab)+2 = 3
        assert (y[0] != 0).all() and 3 not in y[0]

    def test_lr_feature_and_target_math(self):
        from fedml_tpu.data.ingest import so_lr_features, so_lr_targets

        f = so_lr_features(["a b unknown"], ["a", "b"])
        # mean over ALL 3 tokens (OOV participates in the denominator)
        np.testing.assert_allclose(f, [[1 / 3, 1 / 3]])
        t = so_lr_targets(["a|c|a"], ["a", "b"])
        np.testing.assert_array_equal(t, [[1.0, 0.0]])


class TestShakespearePreprocess:
    def test_windows_and_specials(self):
        x, y = shakespeare_to_sequences(["ab"])
        assert x.shape == (1, 80) and y.shape == (1, 80)
        # y is x shifted by one: tokens are [bos a b eos pad...]
        assert y[0, 0] == x[0, 1]
        assert x[0, 0] == SHAKESPEARE_VOCAB - 3  # bos
        assert y[0, 2] == SHAKESPEARE_VOCAB - 2  # eos after 'a','b'
        assert (x[0, 4:] == 0).all()  # padded

    def test_long_snippet_splits(self):
        x, _ = shakespeare_to_sequences(["z" * 200])
        assert x.shape[0] == 3  # 202 tokens -> 3 windows of 81


def _write_cifar10_batches(dirpath):
    d = os.path.join(dirpath, "cifar-10-batches-py")
    os.makedirs(d, exist_ok=True)
    rng = np.random.RandomState(0)
    for name, n in [("data_batch_1", 40), ("data_batch_2", 40), ("test_batch", 20)]:
        blob = {
            b"data": rng.randint(0, 256, (n, 3072), np.uint8),
            b"labels": rng.randint(0, 10, n).tolist(),
        }
        with open(os.path.join(d, name), "wb") as f:
            pickle.dump(blob, f)


class TestCifarBinary:
    def test_loads_and_partitions(self, tmp_path, args_factory):
        d = tmp_path / "cifar10"
        _write_cifar10_batches(str(d))
        args = _args(
            args_factory,
            dataset="cifar10",
            data_cache_dir=str(tmp_path),
            client_num_in_total=4,
            client_num_per_round=4,
            model="cnn",
            partition_method="homo",
        )
        args = fedml_tpu.init(args)
        ds = load(args)
        assert ds.train_data_num == 80
        assert ds.test_data_num == 20
        assert ds.packed_train.x.shape[-3:] == (32, 32, 3)
        assert float(ds.packed_train.x.max()) <= 1.0

    def test_reader_shapes(self, tmp_path):
        _write_cifar10_batches(str(tmp_path))
        x_tr, y_tr, x_te, y_te = load_cifar_batches(str(tmp_path), "cifar10")
        assert x_tr.shape == (80, 32, 32, 3)
        assert y_te.shape == (20,)


def _write_png(path, rng, hw=(48, 40)):
    from PIL import Image

    arr = rng.randint(0, 256, hw + (3,), dtype=np.uint8)
    Image.fromarray(arr).save(path)


class TestImageFolder:
    @pytest.mark.slow
    def test_imagenet_style_folder(self, tmp_path, args_factory):
        rng = np.random.RandomState(0)
        d = tmp_path / "imagenet"
        for split, n in (("train", 6), ("val", 2)):
            for cls in ("n01440764", "n01443537", "n01484850"):
                cdir = d / split / cls
                cdir.mkdir(parents=True, exist_ok=True)
                for i in range(n):
                    _write_png(str(cdir / f"img_{i}.png"), rng)
        args = _args(
            args_factory,
            dataset="imagenet",
            data_cache_dir=str(tmp_path),
            client_num_in_total=3,
            client_num_per_round=3,
            model="cnn",
            partition_method="homo",
            image_size=32,
        )
        args = fedml_tpu.init(args)
        ds = load(args)
        assert ds.class_num == 3  # folder structure is authoritative
        assert ds.train_data_num == 18
        assert ds.test_data_num == 6
        assert ds.packed_train.x.shape[-3:] == (32, 32, 3)
        # trains end to end with the class count from the folder
        model = models.create(args, ds.class_num)
        api = FedAvgAPI(args, None, ds, model)
        stats = api.train()
        assert np.isfinite(stats["train_loss"])


class TestLandmarksCsv:
    def test_user_csv_natural_federation(self, tmp_path, args_factory):
        import csv

        rng = np.random.RandomState(1)
        d = tmp_path / "gld23k"
        (d / "images").mkdir(parents=True)
        rows = []
        for u in range(3):
            for i in range(4 + u):  # ragged users
                img_id = f"u{u}_img{i}"
                _write_png(str(d / "images" / f"{img_id}.jpg"), rng)
                rows.append({"user_id": str(u), "image_id": img_id,
                             "class": str(rng.randint(0, 5))})
        with open(d / "train.csv", "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=["user_id", "image_id", "class"])
            w.writeheader()
            w.writerows(rows)
        args = _args(
            args_factory,
            dataset="gld23k",
            data_cache_dir=str(tmp_path),
            client_num_in_total=3,
            client_num_per_round=3,
            model="cnn",
            image_size=32,
        )
        args = fedml_tpu.init(args)
        ds = load(args)
        assert ds.client_num == 3
        # natural federation preserved the ragged per-user sizes
        assert sorted(ds.train_data_local_num_dict.values()) == [4, 5, 6]
        assert ds.packed_train.x.shape[-3:] == (32, 32, 3)


class TestVflPartyCsv:
    def _write_parties(self, d, n=80, seed=0):
        import csv

        rng = np.random.RandomState(seed)
        d.mkdir(parents=True, exist_ok=True)
        y = rng.randint(0, 2, n)
        # learnable: party features correlate with the label
        f0 = y[:, None] + 0.3 * rng.randn(n, 2)
        f1 = -y[:, None] + 0.3 * rng.randn(n, 3)
        f2 = 0.3 * rng.randn(n, 1)
        for k, (f, lab) in enumerate([(f0, y), (f1, None), (f2, None)]):
            cols = [f"x{k}_{j}" for j in range(f.shape[1])]
            with open(d / f"party_{k}.csv", "w", newline="") as fh:
                names = (["label"] if lab is not None else []) + cols
                w = csv.DictWriter(fh, fieldnames=names)
                w.writeheader()
                for i in range(n):
                    row = {c: f"{f[i, j]:.4f}" for j, c in enumerate(cols)}
                    if lab is not None:
                        row["label"] = str(int(lab[i]))
                    w.writerow(row)
        return y

    def test_reader(self, tmp_path):
        from fedml_tpu.data.ingest import load_vfl_party_csvs

        y = self._write_parties(tmp_path / "nus_wide")
        feats, labels = load_vfl_party_csvs(str(tmp_path / "nus_wide"))
        assert [f.shape[1] for f in feats] == [2, 3, 1]
        np.testing.assert_array_equal(labels, y)

    @pytest.mark.slow
    def test_vfl_api_consumes_party_csvs(self, tmp_path, args_factory):
        """The NORMAL entry path: load(args) detects the party CSVs for
        any dataset name and the VFL engine uses the real per-party
        columns as the vertical split."""
        self._write_parties(tmp_path / "nus_wide")
        args = _args(
            args_factory,
            dataset="nus_wide",
            federated_optimizer="VFL",
            data_cache_dir=str(tmp_path),
            comm_round=8,
            batch_size=16,
            learning_rate=0.3,
            frequency_of_the_test=1,
        )
        args = fedml_tpu.init(args)
        ds = load(args)  # no _DATASET_META entry needed: CSVs define it
        assert ds.vfl_parties is not None
        assert ds.class_num == 2  # from the labels, not any meta table
        from fedml_tpu.simulation.simulator import SimulatorSingleProcess

        model = models.create(args, ds.class_num)
        sim = SimulatorSingleProcess(args, None, ds, model)
        api = sim.fl_trainer
        assert api.n_parties == 3  # from the party files, not vfl_parties
        stats = sim.run()
        assert np.isfinite(stats["train_loss"])
        assert stats["test_acc"] > 0.6  # the split features are informative

    def test_party_csv_gap_rejected(self, tmp_path):
        import csv

        from fedml_tpu.data.ingest import load_vfl_party_csvs

        d = tmp_path / "gappy"
        d.mkdir()
        for k in (0, 1, 3):  # party_2 missing
            with open(d / f"party_{k}.csv", "w", newline="") as f:
                w = csv.DictWriter(
                    f, fieldnames=(["label"] if k == 0 else []) + ["x0"]
                )
                w.writeheader()
                row = {"x0": "1.0"}
                if k == 0:
                    row["label"] = "0"
                w.writerow(row)
        with pytest.raises(ValueError, match="contiguously"):
            load_vfl_party_csvs(str(d))


class TestRegroup:
    def test_round_robin_fold(self):
        xs = [np.full((i + 1, 2), i, np.float32) for i in range(5)]
        ys = [np.full((i + 1,), i, np.int64) for i in range(5)]
        fx, fy = regroup_clients(xs, ys, 2)
        assert len(fx) == 2
        assert sum(len(a) for a in fx) == 15
        # user 0 and 2 and 4 land on client 0
        assert set(np.unique(fy[0])) == {0, 2, 4}


class TestEdgeCaseArrays:
    """Real edge-case attack arrays (reference edge_case_examples
    get_data.sh archive): .pkl numpy images and torch-saved .pt sets
    both ingest, and the edge_case poison type uses them when the
    archive is present (synthetic far-tail noise otherwise)."""

    def _write_archive(self, cache, southwest=True, ardis=False):
        d = cache / "edge_case_examples"
        d.mkdir(parents=True, exist_ok=True)
        rng = np.random.RandomState(0)
        if southwest:
            imgs = rng.randint(0, 256, (12, 32, 32, 3), dtype=np.uint8)
            with open(d / "southwest_images_new_train.pkl", "wb") as f:
                pickle.dump(imgs, f)
        if ardis:
            import torch

            t = torch.from_numpy(
                rng.randint(0, 256, (9, 28, 28), dtype=np.uint8)
            )
            torch.save(t, d / "ardis_test_dataset.pt")
        return d

    def test_pkl_and_pt_ingest(self, tmp_path):
        from fedml_tpu.data.poison import load_edge_case_arrays

        self._write_archive(tmp_path, southwest=True, ardis=True)
        sw = load_edge_case_arrays(str(tmp_path), "southwest")
        assert sw.shape == (12, 32, 32, 3) and sw.dtype == np.float32
        # [0,1] — the same scale ingest.py gives real clean data, so
        # poisoned rows do not betray themselves by value range
        assert 0.0 <= float(sw.min()) and float(sw.max()) <= 1.0
        ar = load_edge_case_arrays(str(tmp_path), "ardis")
        assert ar.shape == (9, 28, 28, 1)
        assert load_edge_case_arrays(str(tmp_path), "howto") is None
        assert load_edge_case_arrays(None, "southwest") is None

    def test_edge_case_poison_uses_real_arrays(self, tmp_path):
        from fedml_tpu.data.poison import load_edge_case_arrays, poison_dataset

        self._write_archive(tmp_path, southwest=True)
        real = load_edge_case_arrays(str(tmp_path), "southwest")
        x = np.zeros((20, 32, 32, 3), np.float32)
        y = np.arange(20) % 10
        px, py = poison_dataset(
            x, y, "edge_case", num_classes=10, target_label=3,
            fraction=0.5, data_cache_dir=str(tmp_path),
        )
        changed = np.where((px != x).any(axis=(1, 2, 3)))[0]
        assert len(changed) == 10
        assert (py[changed] == 3).all()
        # every poisoned row is one of the REAL images, not noise
        flat_real = real.reshape(len(real), -1)
        for i in changed:
            assert (
                np.abs(flat_real - px[i].reshape(1, -1)).max(axis=1).min() < 1e-6
            )
        # shape mismatch (mnist-shaped x vs 32x32 southwest) falls back
        xm = np.zeros((8, 28, 28, 1), np.float32)
        pm, _ = poison_dataset(
            xm, np.zeros(8, np.int64), "edge_case", num_classes=10,
            data_cache_dir=str(tmp_path), fraction=1.0,
        )
        assert float(pm.mean()) > 1.0  # far-tail noise branch


    def test_download_seam_invoked_when_missing(self, tmp_path, monkeypatch):
        """download=True routes through the download seam exactly once
        when the archive dir is absent (offline grace: a failed fetch
        leaves the synthetic fallback)."""
        from fedml_tpu.data import download as dl
        from fedml_tpu.data import poison

        calls = []

        def fake_download(name, cache_dir):
            calls.append(name)
            d = os.path.join(cache_dir, "edge_case_examples")
            os.makedirs(d, exist_ok=True)
            imgs = np.random.RandomState(0).randint(
                0, 256, (4, 32, 32, 3), dtype=np.uint8
            )
            with open(os.path.join(d, "southwest_images_new_train.pkl"), "wb") as f:
                pickle.dump(imgs, f)
            return True

        monkeypatch.setattr(dl, "download_dataset", fake_download)
        poison.load_edge_case_arrays.cache_clear()
        got = poison.load_edge_case_arrays(
            str(tmp_path), "southwest", download=True
        )
        assert calls == ["edge_case_examples"]
        assert got is not None and got.shape == (4, 32, 32, 3)
        poison.load_edge_case_arrays.cache_clear()


class TestFets2021:
    @pytest.mark.slow  # deeplab conv training is ~2 min on the 1-core box
    def test_standin_loads_and_trains(self, args_factory):
        """FeTS2021 (data/FeTS2021/download.sh): 4-channel MRI-modality
        segmentation federation; the stand-in exercises the full
        pipeline shape (real extracted copies override via
        data_cache_dir like every other dataset)."""
        args = _args(
            args_factory,
            dataset="fets2021",
            model="deeplab",
            synthetic_train_size=64,
            synthetic_test_size=16,
            batch_size=8,
            comm_round=1,
        )
        ds = load(args)
        assert ds.task == "segmentation" and ds.class_num == 4
        assert ds.packed_train.x.shape[-1] == 4  # modality channels
        model = models.create(args, ds.class_num)
        api = FedAvgAPI(args, None, ds, model)
        stats = api.train()
        assert np.isfinite(stats["test_acc"])
