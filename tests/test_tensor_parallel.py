"""Tensor parallelism (parallel/tensor.py) on the 8-device CPU mesh.

Green-field vs the reference (SURVEY.md §2.9 census: no TP anywhere).
Two oracles: (1) the Megatron layout genuinely shards the weights —
addressable shards are 1/tp of the kernel; (2) a dp x tp jitted train
step computes the SAME loss and updated params as a fully replicated
one (SPMD partitioning is semantics-preserving).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.core.losses import token_cross_entropy
from fedml_tpu.models.transformer import TransformerLM
from fedml_tpu.parallel.tensor import (
    shard_batch_dp,
    shard_params_tp,
    tp_specs,
)

pytestmark = pytest.mark.smoke

VOCAB, LAYERS, HEADS, DIM, B, T = 64, 2, 4, 32, 8, 16


def _model_and_batch():
    model = TransformerLM(
        vocab_size=VOCAB, num_layers=LAYERS, num_heads=HEADS,
        embed_dim=DIM, max_len=T,
    )
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, VOCAB, (B, T)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    return model, params, tokens


def _train_step(model, opt):
    def step(params, opt_state, tokens):
        def loss_fn(p):
            logits = model.apply({"params": p}, tokens)
            labels = jnp.roll(tokens, -1, axis=1)
            mask = jnp.ones_like(tokens, jnp.float32).at[:, -1].set(0.0)
            loss, _ = token_cross_entropy(logits, labels, mask)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return step


class TestSpecs:
    @pytest.mark.slow  # >4s on the 1-core gate box; full tier
    def test_megatron_layout(self):
        _, params, _ = _model_and_batch()
        specs = tp_specs(params)
        blk = specs["Block_0"]
        assert blk["Dense_0"]["kernel"] == P(None, "tp")  # qkv: column
        assert blk["Dense_0"]["bias"] == P("tp")
        assert blk["Dense_1"]["kernel"] == P("tp", None)  # proj: row
        assert blk["Dense_1"]["bias"] == P()
        assert blk["Dense_2"]["kernel"] == P(None, "tp")  # mlp up
        assert blk["Dense_3"]["kernel"] == P("tp", None)  # mlp down
        assert specs["Dense_0"]["kernel"] == P(None, "tp")  # vocab head
        assert specs["Embed_0"]["embedding"] == P()
        assert specs["LayerNorm_0"]["scale"] == P()

    def test_weights_genuinely_sharded(self):
        devs = jax.devices()[:8]
        mesh = Mesh(np.array(devs).reshape(2, 4), ("dp", "tp"))
        _, params, _ = _model_and_batch()
        tp_params = shard_params_tp(params, mesh)
        qkv = tp_params["Block_0"]["Dense_0"]["kernel"]
        assert qkv.shape == (DIM, 3 * DIM)
        shard = qkv.addressable_shards[0].data
        assert shard.shape == (DIM, 3 * DIM // 4)
        down = tp_params["Block_0"]["Dense_3"]["kernel"]
        assert down.addressable_shards[0].data.shape == (DIM, DIM)  # 4C/tp x C
        # replicated leaves stay whole
        ln = tp_params["LayerNorm_0"]["scale"]
        assert ln.addressable_shards[0].data.shape == ln.shape

    @pytest.mark.slow
    def test_indivisible_dim_falls_back_to_replicated(self):
        devs = jax.devices()[:8]
        mesh = Mesh(np.array(devs), ("tp",))  # tp=8; 3*DIM=96 divides, DIM=32 divides
        model = TransformerLM(vocab_size=30, num_layers=1, num_heads=3,
                              embed_dim=30, max_len=T)  # 30 % 8 != 0
        tokens = jnp.zeros((2, T), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        tp_params = shard_params_tp(params, mesh)
        k = tp_params["Block_0"]["Dense_0"]["kernel"]
        assert k.addressable_shards[0].data.shape == k.shape


class TestNumericEquivalence:
    @pytest.mark.slow
    def test_dp_x_tp_step_matches_replicated(self):
        model, params, tokens = _model_and_batch()
        opt = optax.sgd(0.1)
        step = _train_step(model, opt)

        ref_params, ref_ostate, ref_loss = jax.jit(step)(
            params, opt.init(params), tokens
        )

        devs = jax.devices()[:8]
        mesh = Mesh(np.array(devs).reshape(2, 4), ("dp", "tp"))
        tp_params = shard_params_tp(params, mesh)
        tp_tokens = shard_batch_dp(tokens, mesh)
        with mesh:
            out_params, _, loss = jax.jit(step)(
                tp_params, opt.init(tp_params), tp_tokens
            )
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            ),
            out_params, ref_params,
        )
        # the update preserved the Megatron layout (no silent gather)
        qkv = out_params["Block_0"]["Dense_0"]["kernel"]
        assert qkv.addressable_shards[0].data.shape == (DIM, 3 * DIM // 4)
