"""Cross-device Beehive plane (fedml_tpu/cross_device/, docs/cross_device.md).

Covers the ISSUE-16 acceptance contract:
- pairwise-mask algebra: masks cancel bitwise in the mod-p fold, the
  masked world's final params are BITWISE identical to an unmasked
  world under the same churn schedule (raw and through the int8 offer
  codec), and Shamir dropout recovery restores exact cancellation when
  maskers vanish mid-round;
- churn is normal: rounds close on their fold target (never cohort
  completeness) within the report window, with a window close when the
  target is unreachable, and stragglers fold async FedBuff-style with
  oracle-checked staleness discounts;
- the ledger discipline: at-most-once fold (dedup counted), no fold
  without a ledgered check-in, WAL fold counts == telemetry counters,
  and a planted bad Shamir share is flagged by the InvariantChecker
  (pubkey verification), never silently folded;
- device-class compile buckets: one jit trace per (speed tier, pow2
  bucket), asserted over a heterogeneous cohort;
- the `fedml-tpu device` CLI smoke seam.
"""

import json
import tempfile

import numpy as np
import pytest

pytestmark = pytest.mark.smoke

import fedml_tpu
from fedml_tpu.core import secure_agg as sa
from fedml_tpu.core.chaos import reset_chaos
from fedml_tpu.core.invariants import InvariantChecker
from fedml_tpu.core.telemetry import Telemetry
from fedml_tpu.cross_device import run_beehive_world
from fedml_tpu.cross_device.protocol import (
    decode_offer_params,
    encode_offer_params,
    flat_dim,
    linear_template,
    pack_participants,
    pack_reveals,
    unpack_participants,
    unpack_reveals,
)
from fedml_tpu.scale.registry import ClientRegistry

from tests.conftest import make_args


REG_SIZE = 2_000
COHORT = 16
P = sa.FIELD_PRIME


def beehive_args(**kw):
    kw.setdefault("training_type", "simulation")
    kw.setdefault("client_registry_size", REG_SIZE)
    kw.setdefault("crossdevice_cohort", COHORT)
    kw.setdefault("comm_round", 2)
    kw.setdefault("telemetry_dir", tempfile.mkdtemp(prefix="beehive_td_"))
    kw.setdefault("checkpoint_dir", tempfile.mkdtemp(prefix="beehive_ck_"))
    kw.setdefault("run_id", f"beehive-{abs(hash(tuple(sorted(kw)))) % 10**8}")
    a = make_args(**kw)
    fedml_tpu.init(a)
    return a


def run_world(**kw):
    a = beehive_args(**kw)
    Telemetry.reset()
    reset_chaos()
    out = run_beehive_world(a, feature_dim=8, class_num=4)
    out["args"] = a
    return out


def vanish_schedule(rounds, frac=0.3, fault=None):
    """Schedule ``frac`` of each round's (precomputed) cohort to vanish
    at upload time."""
    reg = ClientRegistry(REG_SIZE, seed=0, duty_hours=14)
    steps = []
    for r in range(rounds):
        ids = reg.sample_available_cohort(r, COHORT)
        k = max(1, int(frac * len(ids)))
        for d in ids[:k]:
            steps.append(
                {
                    "at": {
                        "event": "device.upload",
                        "device": int(d),
                        "round": r,
                    },
                    "fault": dict(fault or {"kind": "vanish"}),
                }
            )
    return steps


class TestMaskAlgebra:
    """The secure-agg primitives, independent of the protocol."""

    def test_pairwise_masks_cancel_bitwise_over_full_set(self):
        rng = np.random.default_rng(0)
        ids = [3, 11, 42, 99]
        secrets = {i: sa.derive_mask_secret(i * 7 + 1, 0) for i in ids}
        pubs = {i: sa.mask_public_key(secrets[i]) for i in ids}
        dim = 24
        qs = {
            i: rng.integers(0, P, size=dim, dtype=np.int64) for i in ids
        }
        masked_sum = np.zeros(dim, dtype=np.int64)
        plain_sum = np.zeros(dim, dtype=np.int64)
        for i in ids:
            m = sa.pairwise_mask_vector(i, secrets[i], pubs, dim)
            masked_sum = np.mod(masked_sum + qs[i] + m, P)
            plain_sum = np.mod(plain_sum + qs[i], P)
        assert np.array_equal(masked_sum, plain_sum)

    def test_dropout_residue_equals_unmask_correction(self):
        ids = [1, 5, 8, 13, 21]
        secrets = {i: sa.derive_mask_secret(i * 31 + 5, 2) for i in ids}
        pubs = {i: sa.mask_public_key(secrets[i]) for i in ids}
        dim = 10
        vanished = 8
        folded = [i for i in ids if i != vanished]
        acc = np.zeros(dim, dtype=np.int64)
        for i in folded:
            acc = np.mod(
                acc + sa.pairwise_mask_vector(i, secrets[i], pubs, dim), P
            )
        # the folded masks' residue is exactly the vanished device's
        # dangling pairwise terms...
        corr = sa.unmask_correction(
            vanished, secrets[vanished],
            {i: pubs[i] for i in folded}, dim,
        )
        # ...minus the terms among the folded themselves (which cancel)
        assert np.array_equal(np.mod(acc - corr, P), np.zeros(dim))

    def test_shamir_recovers_mask_secret_and_poison_breaks_pubkey(self):
        secret = sa.derive_mask_secret(12345, 7)
        pub = sa.mask_public_key(secret)
        rng = np.random.default_rng(3)
        shares = sa.shamir_share(np.int64(secret), 5, 2, rng)
        back = int(sa.shamir_reconstruct(shares[:3], [1, 2, 3]))
        assert back == secret
        assert sa.mask_public_key(back) == pub
        # poison every revealed share by +1: Lagrange weights sum to 1,
        # so the reconstruction is secret+1 — and the pubkey catches it
        bad = int(
            sa.shamir_reconstruct(np.mod(shares[:3] + 1, P), [1, 2, 3])
        )
        assert bad == (secret + 1) % P
        assert sa.mask_public_key(bad) != pub


class TestProtocolCodecs:
    def test_offer_codec_is_deterministic_and_int8(self):
        params = linear_template(6, 3)
        params["w"] = params["w"] + np.float32(0.25)
        enc = encode_offer_params(params)
        assert enc["w"]["q"].dtype == np.int8
        dec1 = decode_offer_params(enc)
        dec2 = decode_offer_params(encode_offer_params(params))
        for k in ("b", "w"):
            assert np.array_equal(dec1[k], dec2[k])
        assert flat_dim(6, 3) == 6 * 3 + 3

    def test_participants_and_reveals_round_trip(self):
        roster = {42: 7, 3: 99, 17: 1}
        packed = pack_participants(roster)
        assert list(packed["ids"]) == [3, 17, 42]  # sorted is normative
        assert unpack_participants(packed) == roster
        reveals = {8: [(1, 100), (3, 200)], 2: [(2, 50)]}
        assert unpack_reveals(pack_reveals(reveals)) == reveals


class TestBeehiveWorld:
    def test_clean_world_closes_every_round_on_target(self):
        out = run_world(comm_round=3)
        recs = out["round_records"]
        assert len(recs) == 3
        for rec in recs:
            assert rec["close_reason"] == "target"
            assert rec["folds"] >= rec["fold_target"]
        tel = Telemetry.get_instance()
        assert tel.get_counter("device_uploads_folded_total") == sum(
            r["folds"] for r in recs
        )
        rep = InvariantChecker(
            telemetry_dir=out["args"].telemetry_dir,
            checkpoint_dir=out["args"].checkpoint_dir,
        ).check()
        assert rep.ok, rep.to_dict()
        assert "device_masked_folds_balance" in rep.to_dict()["checked"]

    def test_masked_equals_unmasked_bitwise_under_churn(self):
        steps = vanish_schedule(rounds=3)
        m = run_world(comm_round=3, chaos_schedule=steps)
        assert any(r["recovered"] > 0 for r in m["round_records"])
        u = run_world(
            comm_round=3, chaos_schedule=steps, crossdevice_secure_agg=False
        )
        assert all(r["recovered"] == 0 for r in u["round_records"])
        assert np.array_equal(m["final_flat"], u["final_flat"])
        assert float(
            np.max(np.abs(m["final_flat"] - u["final_flat"]))
        ) == 0.0

    def test_churn_rounds_still_close_on_target(self):
        steps = vanish_schedule(rounds=2, frac=0.3)
        out = run_world(comm_round=2, chaos_schedule=steps)
        for rec in out["round_records"]:
            assert rec["close_reason"] == "target"
            assert rec["folds"] >= rec["fold_target"]

    def test_unreachable_target_closes_on_window_not_stall(self):
        # fold target = 100% of the roster, but one device vanishes:
        # the target is unreachable, so the report window must close
        # the round (churn != stall)
        steps = vanish_schedule(rounds=1, frac=0.05)
        out = run_world(
            comm_round=1,
            chaos_schedule=steps,
            crossdevice_fold_target_frac=1.0,
        )
        rec = out["round_records"][0]
        assert rec["close_reason"] == "window"
        assert rec["folds"] < rec["fold_target"]
        tel = Telemetry.get_instance()
        assert (
            tel.get_counter("device_rounds_closed_total", reason="window")
            == 1.0
        )

    def test_late_upload_folds_with_staleness_discount(self):
        # an after_close vanish delivers its (already-masked) upload
        # after the round closed; it must fold into the NEXT round's
        # finalize as FedBuff food, not be dropped
        steps = vanish_schedule(
            rounds=1, frac=0.2, fault={"kind": "vanish", "after_close": True}
        )
        out = run_world(comm_round=2, chaos_schedule=steps)
        recs = out["round_records"]
        assert recs[0]["late_folded"] == 0
        assert recs[1]["late_folded"] >= 1
        tel = Telemetry.get_instance()
        assert tel.get_counter("device_uploads_late_total") >= 1.0

    def test_bad_share_world_is_flagged_by_checker(self):
        reg = ClientRegistry(REG_SIZE, seed=0, duty_hours=14)
        ids = reg.sample_available_cohort(0, COHORT)
        steps = [
            {
                "at": {
                    "event": "device.upload",
                    "device": int(ids[0]),
                    "round": 0,
                },
                "fault": {"kind": "vanish"},
            }
        ] + [
            {
                "at": {
                    "event": "device.upload",
                    "device": int(d),
                    "round": 0,
                },
                "fault": {"kind": "bad_share"},
            }
            for d in ids[1:]
        ]
        out = run_world(comm_round=1, chaos_schedule=steps)
        tel = Telemetry.get_instance()
        assert tel.get_counter("device_mask_recovery_failures_total") >= 1.0
        rep = InvariantChecker(
            telemetry_dir=out["args"].telemetry_dir,
            checkpoint_dir=out["args"].checkpoint_dir,
        ).check()
        assert not rep.ok
        assert any(
            v["invariant"] == "device_mask_recovery_verified"
            for v in rep.to_dict()["violations"]
        )

    def test_one_trace_per_tier_bucket(self):
        out = run_world(comm_round=3)
        assert out["trace_count"] == len(out["shape_keys"])
        reg = ClientRegistry(REG_SIZE, seed=0, duty_hours=14)
        tiers = {int(t) for t in reg.speed_tier}
        assert {k[0] for k in out["shape_keys"]} <= tiers

    def test_fold_ledger_in_wal_matches_counters_and_checkins(self):
        from fedml_tpu.core.checkpoint import RoundWAL

        steps = vanish_schedule(rounds=2)
        out = run_world(comm_round=2, chaos_schedule=steps)
        recs = [
            r
            for r in RoundWAL(out["args"].checkpoint_dir).records()
            if r.get("kind") == "crossdevice"
        ]
        assert len(recs) == 2
        tel = Telemetry.get_instance()
        assert tel.get_counter("device_uploads_folded_total") == sum(
            len(r["folded"]) for r in recs
        )
        for r in recs:
            assert set(r["folded"]) <= set(r["checkins"])
            assert set(r["checkins"]) <= set(r["cohort"])
            # masked-folds balance, re-added by hand
            ups = sum(int(v) for v in r["upload_checksums"].values())
            corrs = sum(int(v) for v in r["correction_checksums"].values())
            assert int(r["field_checksum"]) == (ups - corrs) % P


class TestKnobValidation:
    def test_named_errors(self):
        with pytest.raises(ValueError, match="crossdevice_fold_target_frac"):
            make_args(crossdevice_fold_target_frac=0.0)
        with pytest.raises(ValueError, match="crossdevice_fold_target_frac"):
            make_args(crossdevice_fold_target_frac=1.5)
        with pytest.raises(ValueError, match="crossdevice_report_window_s"):
            make_args(crossdevice_report_window_s=-1)
        with pytest.raises(ValueError, match="crossdevice_quant_scale"):
            make_args(crossdevice_quant_scale=0)
        with pytest.raises(ValueError, match="crossdevice_mask_threshold"):
            make_args(crossdevice_mask_threshold=0)
        with pytest.raises(ValueError, match="crossdevice_duty_hours"):
            make_args(crossdevice_duty_hours=25)
        with pytest.raises(ValueError, match="crossdevice_cohort"):
            make_args(crossdevice_cohort="nope")

    def test_defaults_validate(self):
        a = make_args()
        assert a.crossdevice_fold_target_frac == 0.6
        assert a.crossdevice_secure_agg is True
        assert a.crossdevice_mask_threshold == 2


class TestDeviceCli:
    def test_dry_run_prints_status_json(self, capsys):
        from fedml_tpu.cli import main as cli_main

        rc = cli_main(["device", "--dry-run"])
        assert rc == 0
        status = json.loads(capsys.readouterr().out.strip())
        assert status["plane"] == "crossdevice"
        assert status["registry_size"] > 0
        assert status["secure_agg"] is True
        assert status["update_dim"] == flat_dim(8, 4)