"""Round-pipeline executor tests (core/round_pipeline.py).

Covers the PR 2 acceptance contract:
- sampling never clobbers the global NumPy RNG (and draws are identical
  to the reference's ``np.random.seed(round_idx)`` contract);
- a 10-round run traces the round fn exactly once; cohort-size changes
  retrace at most once per power-of-two bucket, and the 8→512 sweep
  needs at most ⌈log2(512/8)⌉+1 buckets;
- K=4 produces bit-identical final params and metrics to K=1,
  including checkpoint/restore mid-pipeline (drain before save);
- the hot loop performs zero device fetches between metric flushes.
"""

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.smoke

import fedml_tpu
from fedml_tpu import models
from fedml_tpu.core.round_pipeline import bucket_cohort, pad_cohort_idx
from fedml_tpu.data import load
from fedml_tpu.simulation import FedAvgAPI
from fedml_tpu.simulation.fedavg_api import deterministic_client_sampling


def _build(make, depth=1, **kw):
    base = dict(
        dataset="mnist",
        synthetic_train_size=240,
        synthetic_test_size=60,
        model="lr",
        partition_method="hetero",
        client_num_in_total=6,
        client_num_per_round=4,
        comm_round=5,
        epochs=1,
        batch_size=20,
        learning_rate=0.1,
        frequency_of_the_test=2,
        shuffle=False,
        pipeline_depth=depth,
    )
    base.update(kw)
    args = make(**base)
    args = fedml_tpu.init(args)
    ds = load(args)
    model = models.create(args, ds.class_num)
    return args, ds, model, FedAvgAPI(args, None, ds, model)


def _det_history(api):
    """History minus wall-clock keys — the deterministic metric record."""
    return [
        {k: v for k, v in h.items() if k != "round_time_s"} for h in api.history
    ]


class TestSamplingRngHygiene:
    def test_sampling_does_not_touch_global_rng(self):
        np.random.seed(777)
        before = np.random.get_state()
        deterministic_client_sampling(3, 100, 10)
        after = np.random.get_state()
        assert before[0] == after[0]
        assert np.array_equal(before[1], after[1])
        assert before[2:] == after[2:]

    def test_sampling_draws_match_reference_seed_contract(self):
        """RandomState(round_idx) must reproduce np.random.seed(round_idx)
        exactly (same MT19937 stream — FedAVGAggregator.py:99-113)."""
        for r in (0, 1, 7, 42):
            got = deterministic_client_sampling(r, 50, 8)
            saved = np.random.get_state()
            try:
                np.random.seed(r)
                want = np.asarray(
                    np.random.choice(range(50), 8, replace=False), dtype=np.int32
                )
            finally:
                np.random.set_state(saved)
            assert np.array_equal(got, want)

    def test_user_rng_state_survives_a_round(self, args_factory):
        """Regression: training a round must not move the user's global
        NumPy RNG (the old np.random.seed(round_idx) did)."""
        _, _, _, api = _build(args_factory, comm_round=2)
        np.random.seed(12345)
        marker = np.random.get_state()
        api.train()
        assert np.array_equal(np.random.get_state()[1], marker[1])
        # and the user's next draw is what it would have been
        expected = np.random.RandomState(12345).random(4)
        assert np.allclose(np.random.random(4), expected)


class TestBucketing:
    def test_pow2_buckets(self):
        assert bucket_cohort(8) == 8
        assert bucket_cohort(9) == 16
        assert bucket_cohort(3) == 4
        assert bucket_cohort(1) == 1

    def test_bucket_capped_at_total_clients(self):
        # a bucket can never exceed the federation: cap falls back to
        # the exact size when the pow2 would overshoot the total
        assert bucket_cohort(6, max_size=6) == 6
        assert bucket_cohort(6, max_size=16) == 8

    def test_bucket_respects_mesh_shard_multiple(self):
        # pow2 incompatible with a 3-way clients axis -> exact size
        assert bucket_cohort(6, shard_multiple=3) == 6
        assert bucket_cohort(6, shard_multiple=2) == 8

    def test_exact_policy_and_bad_policy(self):
        assert bucket_cohort(6, policy="exact") == 6
        with pytest.raises(ValueError, match="pipeline_bucket"):
            bucket_cohort(6, policy="bogus")

    def test_sweep_8_to_512_needs_at_most_7_buckets(self):
        # acceptance: ⌈log2(512/8)⌉+1 = 7 round variants for the sweep
        buckets = {bucket_cohort(c, max_size=512) for c in range(8, 513)}
        assert buckets == {8, 16, 32, 64, 128, 256, 512}

    def test_pad_cohort_idx(self):
        idx, valid = pad_cohort_idx(np.array([5, 2, 9], dtype=np.int32), 4)
        assert idx.tolist() == [5, 2, 9, 5]
        assert valid.tolist() == [1.0, 1.0, 1.0, 0.0]
        idx2, valid2 = pad_cohort_idx(np.array([1, 2], dtype=np.int32), 2)
        assert idx2.tolist() == [1, 2] and valid2.tolist() == [1.0, 1.0]

    def test_padded_bucket_matches_exact_cohort(self, args_factory):
        """Padding invisibility: a 3-client cohort padded to bucket 4
        trains to the same params as the exact-size run (shuffle off so
        the per-client RNG count is the only split-shape difference)."""
        results = {}
        for policy in ("pow2", "exact"):
            _, _, _, api = _build(
                args_factory,
                client_num_in_total=8,
                client_num_per_round=3,
                comm_round=3,
                pipeline_bucket=policy,
            )
            api.train()
            results[policy] = jax.tree.map(np.asarray, api.global_params)
        assert api.pipeline_stats["bucket"] == 3  # exact run, sanity
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6),
            results["pow2"],
            results["exact"],
        )


class TestCompileCount:
    def test_ten_round_run_traces_once(self, args_factory):
        _, _, _, api = _build(args_factory, comm_round=10, frequency_of_the_test=3)
        api.train()
        assert api._round_trace_count == 1

    def test_cohort_changes_retrace_once_per_bucket(self, args_factory):
        """Mid-run cohort-size changes hit the jit cache: cohorts
        {3,4,6,8} share buckets {4,8} -> at most 2 traces."""
        args, _, _, api = _build(
            args_factory,
            client_num_in_total=8,
            client_num_per_round=3,
            comm_round=2,
        )
        for c in (3, 4, 6, 8):
            args.client_num_per_round = c
            api.train()
        assert api._round_trace_count == 2, api._round_trace_count


class TestPipelineEquivalence:
    def test_k4_bit_identical_to_k1(self, args_factory):
        apis = {}
        for depth in (1, 4):
            _, _, _, api = _build(args_factory, depth=depth, comm_round=6)
            api.train()
            apis[depth] = api
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            apis[1].global_params,
            apis[4].global_params,
        )
        assert _det_history(apis[1]) == _det_history(apis[4])
        assert apis[4].pipeline_stats["depth"] == 4

    def test_k4_with_lr_schedule_matches_k1(self, args_factory):
        """The precomputed LR-multiplier plan must feed the round fn the
        same per-round multipliers the synchronous loop would."""
        apis = {}
        for depth in (1, 4):
            _, _, _, api = _build(
                args_factory,
                depth=depth,
                comm_round=6,
                lr_schedule="cosine",
                lr_total_rounds=6,
            )
            api.train()
            apis[depth] = api
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            apis[1].global_params,
            apis[4].global_params,
        )
        assert _det_history(apis[1]) == _det_history(apis[4])

    def test_checkpoint_restore_mid_pipeline(self, tmp_path, args_factory):
        """K=4 run checkpointed at round 2 (pipeline drains before the
        save), restored, and run to completion == uninterrupted K=1 run:
        bit-identical params, identical metric history."""
        d = str(tmp_path / "ck_pipe")

        def run(depth, rounds, ckpt=True):
            _, _, _, api = _build(args_factory, depth=depth, comm_round=rounds)
            if ckpt:
                api.args.checkpoint_dir = d
                api.args.checkpoint_freq = 2
            api.train()
            return api

        run(4, rounds=2)                      # interrupted mid-horizon
        resumed = run(4, rounds=6)            # restores at round 2
        straight = run(1, rounds=6, ckpt=False)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            resumed.global_params,
            straight.global_params,
        )
        # resumed history covers rounds >= 2; the straight run's tail
        # must match it exactly
        resumed_hist = _det_history(resumed)
        straight_tail = [
            h for h in _det_history(straight)
            if h["round"] >= resumed_hist[0]["round"]
        ]
        assert resumed_hist == straight_tail


class TestZeroHostSyncHotLoop:
    def test_no_device_fetch_between_flushes(self, args_factory, monkeypatch):
        """Instrument device fetches: during a pipelined run every
        device->host materialization must happen inside a deferred-
        metrics flush — zero in the hot loop. Counts BOTH the explicit
        ``jax.device_get`` path and implicit ``__array__``
        materializations (``float(...)``, ``np.asarray(...)`` on device
        arrays), so a reintroduced per-round host conversion cannot
        slip past the explicit-path counter."""
        from jax._src import array as jax_array

        from fedml_tpu.core.tracking import DeferredMetrics

        fetches = {"n": 0}
        stray = {"n": 0}
        in_flush = {"v": False}
        real_get = jax.device_get

        def counting_get(*a, **kw):
            fetches["n"] += 1
            return real_get(*a, **kw)

        real_flush = DeferredMetrics.flush

        def flagged_flush(self, upto=None):
            in_flush["v"] = True
            try:
                return real_flush(self, upto)
            finally:
                in_flush["v"] = False

        real_array = jax_array.ArrayImpl.__array__

        def counting_array(self, *a, **kw):
            if not in_flush["v"]:
                stray["n"] += 1
            return real_array(self, *a, **kw)

        _, _, _, api = _build(
            args_factory, depth=4, comm_round=8, frequency_of_the_test=2
        )
        monkeypatch.setattr(jax, "device_get", counting_get)
        monkeypatch.setattr(DeferredMetrics, "flush", flagged_flush)
        monkeypatch.setattr(jax_array.ArrayImpl, "__array__", counting_array)
        api.train()
        stats = api.pipeline_stats
        # every explicit fetch is a flush; no stray fetches in the hot
        # loop, explicit or implicit — and one device fetch per
        # non-empty flush (a second fetch inside flush() breaks this)
        assert fetches["n"] == stats["flushes"] == stats["host_syncs"]
        assert stray["n"] == 0, f"{stray['n']} device->host fetches outside flush"
        # eval every 2 rounds over 8 rounds -> 5 records but fewer
        # flushes than rounds; strictly below one sync per round
        assert stats["host_syncs_per_round"] < 1.0
        # all eval records still reach the history exactly once
        assert [h["round"] for h in api.history] == [0, 2, 4, 6, 7]

    def test_deferred_metrics_ring_contract(self):
        import jax.numpy as jnp

        from fedml_tpu.core.tracking import DeferredMetrics

        ring = DeferredMetrics()
        ring.push(0, {"a": jnp.float32(1.0)})
        ring.push(2, {"a": jnp.float32(2.0)})
        ring.push(4, {"a": jnp.float32(3.0)})
        out = ring.flush(upto=2)
        assert [r for r, _ in out] == [0, 2]
        assert [float(t["a"]) for _, t in out] == [1.0, 2.0]
        assert len(ring) == 1 and ring.host_syncs == 1
        assert ring.flush(upto=1) == []       # nothing ready: no fetch
        assert ring.host_syncs == 1
        out = ring.flush(None)                # drain
        assert [r for r, _ in out] == [4] and ring.host_syncs == 2
        assert ring.host_syncs == ring.flushes  # one fetch per flush
