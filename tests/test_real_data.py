"""Real-data path: download seam with offline grace + the bundled
real-digits LEAF fixture and its learning trajectory (VERDICT r3 #2 —
no "synthetic stand-in" anywhere in this path).
"""

import json
import logging
import os
import zipfile

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.data import load
from fedml_tpu.data.download import download_mnist, materialize_real_digits
from fedml_tpu.data.leaf import leaf_available
from tests.conftest import make_args

pytestmark = pytest.mark.smoke


class TestDownloadSeam:
    def test_offline_grace_returns_false(self, tmp_path):
        # connection-refused fails fast; no exception escapes
        ok = download_mnist(str(tmp_path), url="http://127.0.0.1:9/MNIST.zip")
        assert ok is False

    def test_file_url_download_extract_and_load(self, tmp_path):
        # a real-format archive served via file:// exercises the whole
        # seam (fetch -> extract -> MNIST/ -> mnist/ rename) offline
        src = tmp_path / "src"
        os.makedirs(src / "MNIST" / "train")
        os.makedirs(src / "MNIST" / "test")
        rng = np.random.RandomState(0)
        for split, n in (("train", 20), ("test", 8)):
            blob = {"users": ["u0", "u1"], "num_samples": [n, n], "user_data": {}}
            for u in ("u0", "u1"):
                blob["user_data"][u] = {
                    "x": rng.rand(n, 784).round(3).tolist(),
                    "y": rng.randint(0, 10, n).tolist(),
                }
            with open(src / "MNIST" / split / "all_data_0.json", "w") as f:
                json.dump(blob, f)
        zip_path = tmp_path / "archive.zip"
        with zipfile.ZipFile(zip_path, "w") as zf:
            for split in ("train", "test"):
                zf.write(
                    src / "MNIST" / split / "all_data_0.json",
                    f"MNIST/{split}/all_data_0.json",
                )
        cache = tmp_path / "cache"
        ok = download_mnist(str(cache), url=f"file://{zip_path}")
        assert ok is True
        assert leaf_available(str(cache / "mnist"))

    def test_tff_tarball_download_extract_and_load(self, tmp_path, args_factory):
        """The generalized seam handles the reference's tar.bz2 TFF
        archives (fed_cifar100 here), incl. hoisting a nested top-level
        dir, via a file:// URL — fully offline."""
        import tarfile

        import h5py

        from fedml_tpu.data.download import DATASET_ARCHIVES, download_dataset
        from fedml_tpu.data.ingest import tff_h5_available

        src = tmp_path / "src" / "nested"
        os.makedirs(src)
        rng = np.random.RandomState(0)
        for split, n in (("train", 6), ("test", 2)):
            with h5py.File(str(src / f"fed_cifar100_{split}.h5"), "w") as f:
                g = f.create_group("examples")
                for c in range(2):
                    cg = g.create_group(f"client_{c}")
                    cg.create_dataset(
                        "image",
                        data=rng.randint(0, 256, (n, 32, 32, 3), np.uint8),
                    )
                    cg.create_dataset(
                        "label", data=rng.randint(0, 100, (n, 1), np.int64)
                    )
        tar_path = tmp_path / "fed_cifar100.tar.bz2"
        with tarfile.open(tar_path, "w:bz2") as tf:
            tf.add(str(src), arcname="nested")

        cache = tmp_path / "cache"
        os.makedirs(cache)
        saved = DATASET_ARCHIVES["fed_cifar100"]
        DATASET_ARCHIVES["fed_cifar100"] = (f"file://{tar_path}",)
        try:
            assert download_dataset("fed_cifar100", str(cache)) is True
        finally:
            DATASET_ARCHIVES["fed_cifar100"] = saved
        assert tff_h5_available(str(cache / "fed_cifar100"), "fed_cifar100")

        from fedml_tpu.data import load

        args = make_args(
            dataset="fed_cifar100", data_cache_dir=str(cache),
            client_num_in_total=2, client_num_per_round=2,
            model="cnn", batch_size=4,
        )
        ds = load(args)
        assert ds.client_num == 2 and ds.class_num == 100

    def test_partial_multi_archive_download_leaves_nothing(self, tmp_path):
        """All-or-nothing staging: when the second archive of a
        multi-archive dataset fails, NO dataset dir may appear (a
        half-extracted dir would suppress retries and crash the
        loader on the missing side files)."""
        import tarfile

        from fedml_tpu.data.download import download_dataset

        src = tmp_path / "stackoverflow_train.h5"
        src.write_bytes(b"not really h5 but extractable")
        tar_path = tmp_path / "so.tar.bz2"
        with tarfile.open(tar_path, "w:bz2") as tf:
            tf.add(str(src), arcname="stackoverflow_train.h5")
        cache = tmp_path / "cache"
        ok = download_dataset(
            "stackoverflow_lr", str(cache),
            urls=(f"file://{tar_path}", "http://127.0.0.1:9/missing.tar.bz2"),
        )
        assert ok is False
        assert not os.path.exists(cache / "stackoverflow")
        assert not os.path.exists(cache / "stackoverflow_lr")
        assert not any(p.name.startswith(".staging") for p in cache.iterdir())

    def test_stackoverflow_tasks_share_one_extraction(self, tmp_path):
        """Both SO tasks symlink onto one extracted dir — the multi-GB
        archive is never unpacked twice."""
        import tarfile

        from fedml_tpu.data.download import download_dataset

        src = tmp_path / "stackoverflow_train.h5"
        src.write_bytes(b"payload")
        tar_path = tmp_path / "so.tar.bz2"
        with tarfile.open(tar_path, "w:bz2") as tf:
            tf.add(str(src), arcname="stackoverflow_train.h5")
        cache = tmp_path / "cache"
        assert download_dataset(
            "stackoverflow_nwp", str(cache), urls=(f"file://{tar_path}",)
        )
        assert download_dataset(
            "stackoverflow_lr", str(cache), urls=(f"file://{tar_path}",)
        )
        assert (cache / "stackoverflow" / "stackoverflow_train.h5").is_file()
        assert os.path.islink(cache / "stackoverflow_nwp")
        assert os.path.islink(cache / "stackoverflow_lr")
        assert (cache / "stackoverflow_lr" / "stackoverflow_train.h5").is_file()

    def test_loader_attempts_download_only_when_asked(self, tmp_path, monkeypatch):
        calls = []

        def fake_download(name, cache_dir):
            calls.append(cache_dir)
            return False

        import fedml_tpu.data.download as dl

        monkeypatch.setattr(dl, "download_dataset", fake_download)
        args = make_args(
            dataset="mnist",
            data_cache_dir=str(tmp_path),
            client_num_in_total=2,
            client_num_per_round=2,
            synthetic_train_size=64,
            synthetic_test_size=32,
            model="lr",
            batch_size=8,
        )
        load(args)
        assert calls == []  # download defaults to off
        args.download = True
        load(args)
        assert calls == [str(tmp_path)]


class TestRealDigits:
    @pytest.mark.slow
    def test_materialized_fixture_is_real_format(self, tmp_path):
        root = materialize_real_digits(str(tmp_path), n_users=20, seed=1)
        assert root is not None and leaf_available(root)
        blob = json.load(open(os.path.join(root, "train", "all_data_0.json")))
        assert set(blob) == {"users", "num_samples", "user_data"}
        u0 = blob["user_data"][blob["users"][0]]
        assert len(u0["x"][0]) == 784  # MNIST LEAF layout
        assert blob["users"] == json.load(
            open(os.path.join(root, "test", "all_data_0.json"))
        )["users"]  # same user set in both splits (read_data assumption)

    @pytest.mark.slow
    def test_single_sample_users_load(self, tmp_path):
        # regression: a user with 1 sample writes an empty test entry
        # ((0,)-shaped x) which used to crash np.concatenate in load()
        materialize_real_digits(str(tmp_path), n_users=100, seed=1)
        args = make_args(
            dataset="mnist", data_cache_dir=str(tmp_path),
            client_num_in_total=100, client_num_per_round=10,
            model="lr", batch_size=10,
        )
        ds = load(args)
        assert ds.client_num == 100

    @pytest.mark.slow
    def test_subset_marker_written(self, tmp_path):
        root = materialize_real_digits(str(tmp_path), n_users=10)
        blob = json.load(open(os.path.join(root, "_source.json")))
        assert blob["is_mnist"] is False and blob["real_data"] is True

    @pytest.mark.slow
    def test_learning_trajectory_on_real_data(self, tmp_path, caplog):
        """FedAvg+LR on the real digits climbs well past chance within
        25 rounds, through the normal load() path, with NO synthetic
        stand-in fallback."""
        materialize_real_digits(str(tmp_path), n_users=20, seed=0)
        args = make_args(
            dataset="mnist",
            data_cache_dir=str(tmp_path),
            partition_method="hetero",
            partition_alpha=0.5,
            model="lr",
            client_num_in_total=20,
            client_num_per_round=10,
            comm_round=25,
            epochs=1,
            batch_size=10,
            learning_rate=0.03,
            frequency_of_the_test=5,
        )
        from fedml_tpu import models
        from fedml_tpu.simulation import FedAvgAPI

        with caplog.at_level(logging.WARNING):
            args = fedml_tpu.init(args)
            dataset = load(args)
        assert "synthetic stand-in" not in caplog.text
        assert dataset.client_num == 20

        model = models.create(args, dataset.class_num)
        api = FedAvgAPI(args, None, dataset, model)
        final = api.train()
        accs = [h["test_acc"] for h in api.history]
        assert final["test_acc"] > 0.6  # far past 10-class chance
        assert accs[-1] > accs[0]  # genuinely learning
