"""S-FedAvg / HS-FedAvg defenses, FedGAN, and TurboAggregate secure agg."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu import models
from fedml_tpu.core.secure_agg import (
    FIELD_PRIME,
    TurboAggregateProtocol,
    additive_share,
    dequantize,
    lagrange_coeffs,
    modular_inv,
    quantize,
    shamir_reconstruct,
    shamir_share,
)
from fedml_tpu.data import load
from fedml_tpu.simulation.defenses import HSFedAvgAPI, SFedAvgAPI, make_hs_normalizer
from fedml_tpu.simulation.fedavg_api import FedAvgAPI
from fedml_tpu.simulation.fedgan import FedGANAPI
from fedml_tpu.simulation.turboaggregate import TurboAggregateAPI


def _small_args(make, **kw):
    base = dict(
        dataset="mnist",
        synthetic_train_size=400,
        synthetic_test_size=120,
        model="lr",
        partition_method="homo",
        client_num_in_total=4,
        client_num_per_round=4,
        comm_round=2,
        epochs=1,
        batch_size=25,
        learning_rate=0.1,
        momentum=0.0,
        weight_decay=0.0,
        frequency_of_the_test=1,
    )
    base.update(kw)
    return make(**base)


class TestSecureAggPrimitives:
    def test_modular_inverse(self):
        rng = np.random.default_rng(0)
        a = rng.integers(1, FIELD_PRIME, size=(64,), dtype=np.int64)
        inv = modular_inv(a)
        assert np.all(np.mod(a * inv, FIELD_PRIME) == 1)

    def test_lagrange_interpolation_recovers_poly(self):
        # f(x) = 3 + 5x + 7x^2 over the field; interpolate through 3 pts
        p = FIELD_PRIME
        f = lambda x: (3 + 5 * x + 7 * x * x) % p
        beta = [1, 2, 3]
        alpha = [0, 10]
        U = lagrange_coeffs(alpha, beta, p)
        vals = np.array([f(b) for b in beta], dtype=np.int64)
        got = np.mod(U @ vals, p)
        assert got[0] == f(0) and got[1] == f(10)

    def test_shamir_share_reconstruct(self):
        rng = np.random.default_rng(1)
        x = rng.integers(0, FIELD_PRIME, size=(17,), dtype=np.int64)
        shares = shamir_share(x, n=5, t=2, rng=rng)
        # any t+1 = 3 shares reconstruct
        got = shamir_reconstruct(shares[[0, 2, 4]], points=[1, 3, 5])
        assert np.array_equal(got, x)
        got2 = shamir_reconstruct(shares[[1, 2, 3]], points=[2, 3, 4])
        assert np.array_equal(got2, x)

    def test_additive_shares_sum(self):
        rng = np.random.default_rng(2)
        x = rng.integers(0, FIELD_PRIME, size=(33,), dtype=np.int64)
        sh = additive_share(x, 4, rng)
        assert np.array_equal(np.mod(sh.sum(axis=0), FIELD_PRIME), x)
        # individual shares look nothing like x
        assert not np.array_equal(sh[0], x)

    def test_quantize_roundtrip(self):
        x = np.array([-1.5, 0.0, 0.25, 3.75, -0.000015])
        q = quantize(x, 2.0**16)
        back = dequantize(q, 2.0**16)
        assert np.allclose(back, x, atol=1.0 / 2**16)

    def test_secure_weighted_sum_matches_plain(self):
        rng = np.random.default_rng(3)
        n, dim = 8, 101
        updates = [rng.normal(size=(dim,)) for _ in range(n)]
        w = rng.dirichlet(np.ones(n))
        proto = TurboAggregateProtocol(n_clients=n, n_groups=3, seed=0)
        got = proto.secure_weighted_sum(updates, w)
        want = sum(wi * ui for wi, ui in zip(w, updates))
        assert np.allclose(got, want, atol=n * 1.0 / 2**16)

    def test_single_client_protocol(self):
        proto = TurboAggregateProtocol(n_clients=1, n_groups=4, seed=0)
        x = np.array([1.5, -2.0, 0.0])
        got = proto.secure_weighted_sum([x], np.array([1.0]))
        assert np.allclose(got, x, atol=1e-4)


class TestTurboAggregateAPI:
    def test_matches_fedavg_within_quant_error(self, args_factory):
        args = _small_args(args_factory, comm_round=1)
        dataset = load(args)
        model = models.create(args, dataset.class_num)
        plain = FedAvgAPI(args, None, dataset, model)
        plain.train()
        args2 = _small_args(args_factory, comm_round=1)
        secure = TurboAggregateAPI(args2, None, dataset, model)
        secure.train()
        for a, b in zip(
            jax.tree.leaves(plain.global_params), jax.tree.leaves(secure.global_params)
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)


class TestSFedAvg:
    def test_smoke_and_reputation_update(self, args_factory):
        args = _small_args(args_factory, comm_round=2, sfedavg_alpha=0.5, sfedavg_beta=0.5)
        dataset = load(args)
        model = models.create(args, dataset.class_num)
        api = SFedAvgAPI(args, None, dataset, model)
        stats = api.train()
        assert np.isfinite(stats["test_acc"])
        assert len(api.sv_history) == 2
        # phi moved off its uniform init
        assert np.std(api.phi) > 0

    def test_poisoned_client_scores_lower(self, args_factory):
        args = _small_args(
            args_factory,
            comm_round=3,
            client_num_in_total=4,
            client_num_per_round=4,
            learning_rate=0.3,
            sfedavg_alpha=0.0,
            sfedavg_beta=1.0,
            valid_batches=4,
        )
        dataset = load(args)
        # corrupt client 0: rotate every label
        y = np.asarray(dataset.packed_train.y)
        y0 = y.copy()
        y0[0] = (y0[0] + 1) % dataset.class_num
        dataset = dataclasses.replace(
            dataset,
            packed_train=dataset.packed_train.replace(y=jnp.asarray(y0)),
        )
        model = models.create(args, dataset.class_num)
        api = SFedAvgAPI(args, None, dataset, model)
        api.train()
        others = [api.phi[i] for i in range(1, 4)]
        assert api.phi[0] < np.mean(others)

    def test_reputation_survives_resume(self, args_factory, tmp_path):
        kw = dict(comm_round=2, checkpoint_freq=1, checkpoint_dir=str(tmp_path / "ck"))
        args = _small_args(args_factory, **kw)
        dataset = load(args)
        model = models.create(args, dataset.class_num)
        api = SFedAvgAPI(args, None, dataset, model)
        api.train()
        phi_after = api.phi.copy()
        # a fresh API restores reputation from the checkpoint
        api2 = SFedAvgAPI(_small_args(args_factory, **kw), None, dataset, model)
        ckpt, start = api2._maybe_restore()
        ckpt.close()
        assert start == 2
        np.testing.assert_allclose(api2.phi, phi_after)


class TestHSFedAvg:
    def test_normalizer_equalizes_dc_amplitude(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(6, 8, 8, 1)).astype(np.float32) + 2.0)
        mask = jnp.ones((6,), jnp.float32)
        norm = make_hs_normalizer(8, 8, L=0.0, momentum=0.1)
        x2, amp = norm(x, mask, jnp.zeros((8, 8, 1)))
        # DC amplitude (|sum of pixels|) is now identical across images
        dc = np.abs(np.asarray(x2).sum(axis=(1, 2, 3)))
        assert np.allclose(dc, dc[0], rtol=1e-4)
        # first call seeds the running amplitude from the batch mean
        fft = np.fft.fft2(np.asarray(x), axes=(1, 2))
        assert np.allclose(np.asarray(amp), np.abs(fft).mean(axis=0), rtol=1e-4)

    def test_normalizer_leaves_padding_untouched(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(4, 8, 8, 1)).astype(np.float32))
        mask = jnp.asarray([1.0, 1.0, 0.0, 0.0])
        norm = make_hs_normalizer(8, 8, L=0.0, momentum=0.1)
        x2, _ = norm(x, mask, jnp.zeros((8, 8, 1)))
        np.testing.assert_array_equal(np.asarray(x2[2:]), np.asarray(x[2:]))

    @pytest.mark.slow  # re-tiered by measurement (>4s fast-gate budget)
    def test_api_trains(self, args_factory):
        args = _small_args(args_factory, comm_round=2, model="cnn")
        dataset = load(args)
        model = models.create(args, dataset.class_num)
        api = HSFedAvgAPI(args, None, dataset, model)
        stats = api.train()
        assert np.isfinite(stats["test_acc"])
        # running amplitude spectrum is live server state
        assert float(jnp.abs(api.server_state).sum()) > 0


class TestFedGAN:
    @pytest.mark.slow  # re-tiered by measurement (>4s fast-gate budget)
    def test_trains_and_reports(self, args_factory):
        args = _small_args(
            args_factory,
            comm_round=2,
            client_num_in_total=4,
            client_num_per_round=2,
            batch_size=16,
            synthetic_train_size=128,
            synthetic_test_size=32,
        )
        dataset = load(args)
        api = FedGANAPI(args, None, dataset)
        stats = api.train()
        assert np.isfinite(stats["d_loss"]) and np.isfinite(stats["g_loss"])
        assert 0.0 <= stats["disc_acc"] <= 1.0
        assert len(api.history) == 2
