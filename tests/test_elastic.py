"""Elastic membership (beyond the reference): mid-run join and leave.

The reference blocks round 0 until every configured client appears and
has no membership changes after that (fedml_server_manager.py:95-119).
With args.elastic_membership the federation starts at quorum
(client_num_per_round online), a late client joins and trains from the
next broadcast, and an OFFLINE leave mid-round never stalls a round.
"""

import threading
import time

import pytest

import fedml_tpu
from fedml_tpu import constants, models
from fedml_tpu.cross_silo import Client, Server
from fedml_tpu.data import load


def _mk(make, run_id, **kw):
    base = dict(
        training_type="cross_silo",
        dataset="mnist",
        synthetic_train_size=300,
        synthetic_test_size=60,
        model="lr",
        client_num_in_total=3,
        client_num_per_round=2,
        comm_round=10,
        epochs=1,
        batch_size=16,
        learning_rate=0.1,
        frequency_of_the_test=5,
        shuffle=False,
        backend="LOCAL",
        run_id=run_id,
        elastic_membership=True,
    )
    base.update(kw)
    return make(**base)


def _build(args_factory, run_id, rank, **kw):
    a = _mk(args_factory, run_id, **kw)
    a.rank = rank
    a = fedml_tpu.init(a)
    ds = load(a)
    m = models.create(a, ds.class_num)
    return a, ds, m


class TestElasticJoin:
    def test_late_client_joins_and_trains(self, args_factory):
        a0, ds0, m0 = _build(args_factory, "elastic_join", 0)
        server = Server(a0, None, ds0, m0)

        clients = []
        for r in (1, 2, 3):
            a, ds, m = _build(args_factory, "elastic_join", r)
            clients.append(Client(a, None, ds, m))

        # instrument the late client so participation is observable
        late = clients[2]
        late_calls = []
        orig_train = late.trainer.train
        late.trainer.train = lambda p, r: (late_calls.append(r), orig_train(p, r))[1]

        # join is gated on an OBSERVED event (first round completed),
        # not wall clock, and the early clients pace the rounds so the
        # joiner's ONLINE always lands mid-federation
        first_round_done = threading.Event()
        orig_finish = server.manager._finish_round

        def finish_hook():
            first_round_done.set()
            orig_finish()

        server.manager._finish_round = finish_hook
        for c in clients[:2]:
            orig = c.trainer.train
            c.trainer.train = (
                lambda p, r, _o=orig: (time.sleep(0.2), _o(p, r))[1]
            )

        def run_late():
            assert first_round_done.wait(timeout=120)
            late.run()

        threads = [
            threading.Thread(target=clients[0].run, daemon=True),
            threading.Thread(target=clients[1].run, daemon=True),
            threading.Thread(target=run_late, daemon=True),
        ]
        for t in threads:
            t.start()
        server.run()
        for t in threads:
            t.join(timeout=60)
        assert server.manager.round_idx == 10
        assert server.manager.joins == 1
        # the joiner was selected and trained at least once (10 rounds,
        # 2-of-3 selection after it joins: miss-every-round prob ~ 1e-4)
        assert len(late_calls) >= 1
        assert not any(t.is_alive() for t in threads), "clients hung"

    def test_nonelastic_ignores_unknown_rank(self, args_factory):
        from fedml_tpu.cross_silo.horizontal.fedml_server_manager import (
            FedMLServerManager,
        )
        from fedml_tpu.cross_silo.horizontal.fedml_aggregator import FedMLAggregator
        from fedml_tpu.core.message import Message

        a = _mk(args_factory, "ne1", elastic_membership=False,
                client_num_per_round=2)
        a = fedml_tpu.init(a)
        ds = load(a)
        m = models.create(a, ds.class_num)
        mgr = FedMLServerManager(
            a, FedMLAggregator(a, m), rank=0, size=3, backend="LOCAL"
        )
        msg = Message(constants.MSG_TYPE_C2S_CLIENT_STATUS, 99, 0)
        msg.add_params(
            constants.MSG_ARG_KEY_CLIENT_STATUS, constants.CLIENT_STATUS_ONLINE
        )
        mgr.handle_message_client_status_update(msg)
        assert not mgr.is_initialized
        assert 99 not in mgr.client_online_status


class TestElasticLeave:
    def test_leaver_does_not_stall_round(self, args_factory):
        a0, ds0, m0 = _build(
            args_factory, "elastic_leave", 0,
            client_num_per_round=3, comm_round=4,
        )
        server = Server(a0, None, ds0, m0)
        clients = []
        for r in (1, 2, 3):
            a, ds, m = _build(
                args_factory, "elastic_leave", r,
                client_num_per_round=3, comm_round=4,
            )
            clients.append(Client(a, None, ds, m))

        # client 2 trains round 0 then leaves instead of training again
        leaver = clients[1]
        orig = leaver.manager._train_and_send

        def train_or_leave(msg):
            if int(msg.get(constants.MSG_ARG_KEY_ROUND_INDEX, 0)) == 0:
                orig(msg)
            else:
                leaver.manager.leave()

        leaver.manager._train_and_send = train_or_leave

        threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
        for t in threads:
            t.start()
        server.run()
        for t in threads:
            t.join(timeout=60)
        assert server.manager.round_idx == 4  # never stalled
        assert server.manager.leaves == 1
        assert not any(t.is_alive() for t in threads), "clients hung"
