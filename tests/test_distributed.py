"""training_type: distributed (distributed.py) on the 8-device CPU mesh.

The user-reachable surface for the parallel subsystems: mesh from the
YAML, one jitted LM train step over it. Oracles: every mesh mode
produces the same numerics as the single-device program (sharded modes
exactly; sp/pp within fp tolerance of the dense/sequential oracle),
and the mode/mesh validation refuses bad configs loudly.
"""

import jax
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu import data, models
from fedml_tpu.distributed import DistributedTrainer, _resolve_mesh

# only the fast validation tests ride the smoke tier; the mode oracles
# train full trajectories (~6 min on the virtual mesh)


def _args(args_factory, **kw):
    base = dict(
        training_type="distributed",
        dataset="shakespeare",
        synthetic_train_size=64,
        synthetic_test_size=16,
        model="transformer",
        vocab_size=64,
        seq_len=16,
        num_layers=2,
        num_heads=4,
        embed_dim=32,
        client_num_in_total=1,
        client_num_per_round=1,
        comm_round=1,
        epochs=2,
        batch_size=8,
        learning_rate=0.1,
        frequency_of_the_test=1,
        run_id="distributed_test",
    )
    base.update(kw)
    return args_factory(**base)


_DENSE_BASELINE = {}


def _dense_baseline(args_factory, **kw):
    """Memoized single-device trajectories shared across oracles
    (identical config -> identical stats; each run costs minutes)."""
    key = tuple(sorted(kw.items()))
    if key not in _DENSE_BASELINE:
        _, stats = _run(args_factory, mesh_shape={"dp": 1}, **kw)
        _DENSE_BASELINE[key] = stats
    return _DENSE_BASELINE[key]


def _run(args_factory, **kw):
    args = fedml_tpu.init(_args(args_factory, **kw))
    ds = data.load(args)
    model = models.create(args, ds.class_num)
    trainer = DistributedTrainer(args, None, ds, model)
    stats = trainer.run()
    return trainer, stats


@pytest.mark.smoke
class TestMeshResolution:
    def test_default_is_all_dp(self, args_factory):
        mesh = _resolve_mesh(_args(args_factory))
        assert dict(mesh.shape) == {"dp": len(jax.devices())}

    def test_unknown_axis_rejected(self, args_factory):
        with pytest.raises(ValueError, match="unknown"):
            _resolve_mesh(_args(args_factory, mesh_shape={"zz": 8}))

    def test_sp_pp_compose_only_with_dp(self, args_factory):
        # dp x pp and dp x sp are valid meshes now
        assert dict(
            _resolve_mesh(
                _args(args_factory, mesh_shape={"dp": 2, "pp": 4})
            ).shape
        ) == {"dp": 2, "pp": 4}
        assert dict(
            _resolve_mesh(
                _args(args_factory, mesh_shape={"dp": 2, "sp": 4})
            ).shape
        ) == {"dp": 2, "sp": 4}
        # but tp/ep and sp+pp still refuse
        with pytest.raises(ValueError, match="composes only with 'dp'"):
            _resolve_mesh(_args(args_factory, mesh_shape={"sp": 4, "tp": 2}))
        with pytest.raises(ValueError, match="composes only with 'dp'"):
            _resolve_mesh(_args(args_factory, mesh_shape={"sp": 2, "pp": 4}))
        with pytest.raises(ValueError, match="composes only with 'dp'"):
            _resolve_mesh(_args(args_factory, mesh_shape={"pp": 4, "ep": 2}))

    def test_too_many_devices_rejected(self, args_factory):
        with pytest.raises(ValueError, match="devices"):
            _resolve_mesh(_args(args_factory, mesh_shape={"dp": 4096}))


# full tier only (re-tiered by measurement, round 6): each mode run
# trains a transformer for multiple epochs — 20-110s apiece on a
# 1-core box, far past the 4s fast-gate budget
@pytest.mark.slow
class TestModes:
    def test_dp_matches_single_device(self, args_factory):
        _, single = _run(args_factory, mesh_shape={"dp": 1})
        _, dp8 = _run(args_factory, mesh_shape={"dp": 8})
        # SPMD is semantics-preserving but not bitwise (sharded matmul
        # reduction order differs); over 2 epochs of steps the drift
        # compounds — trajectory tolerance, same as the other modes
        np.testing.assert_allclose(
            dp8["train_loss"], single["train_loss"], rtol=2e-2
        )
        np.testing.assert_allclose(
            dp8["test_loss"], single["test_loss"], rtol=2e-2
        )

    def test_dp_tp_ep_moe(self, args_factory):
        _, single = _run(
            args_factory, model="moe_transformer", num_experts=4,
            mesh_shape={"dp": 1},
        )
        trainer, sharded = _run(
            args_factory, model="moe_transformer", num_experts=4,
            mesh_shape={"dp": 2, "tp": 2, "ep": 2},
        )
        assert trainer.mode == "sharded"
        # expert stacks genuinely sharded
        wi = trainer.params["Block_1"]["SwitchFFN_0"]["wi"]
        assert wi.addressable_shards[0].data.shape[0] == wi.shape[0] // 2
        # trajectory comparison: hundreds of optimizer steps compound
        # fp reassociation from the tp/ep reduction orders — exact
        # single-step equivalence is tested in test_moe/test_tensor_parallel
        np.testing.assert_allclose(
            sharded["train_loss"], single["train_loss"], rtol=2e-2
        )

    def test_sequence_parallel_ring(self, args_factory):
        dense = _dense_baseline(args_factory)
        trainer, sp = _run(args_factory, mesh_shape={"sp": 8})
        assert trainer.mode == "sequence"
        # ring attention is exact up to fp reassociation; over a full
        # training trajectory the drift compounds (exact single-step
        # equivalence lives in test_longcontext)
        np.testing.assert_allclose(
            sp["train_loss"], dense["train_loss"], rtol=5e-2
        )
        np.testing.assert_allclose(sp["test_acc"], dense["test_acc"], atol=0.05)

    def test_sequence_parallel_ulysses(self, args_factory):
        """Ulysses all-to-all re-shards [T/n, H] -> [T, H/n]; needs
        heads % sp == 0, so sp=4 on the 8-device host (mesh uses a
        device subset)."""
        dense = _dense_baseline(args_factory)
        trainer, sp = _run(
            args_factory, mesh_shape={"sp": 4}, sp_strategy="ulysses"
        )
        assert trainer.mode == "sequence"
        # the strategy knob genuinely reached the attention builder
        # (a silently-dropped knob would fall back to ring and still
        # pass the loss oracle)
        assert trainer.model.module.attn_fn is not None
        np.testing.assert_allclose(
            sp["train_loss"], dense["train_loss"], rtol=5e-2
        )
        np.testing.assert_allclose(sp["test_acc"], dense["test_acc"], atol=0.05)

    def test_bad_sp_strategy_rejected(self, args_factory):
        with pytest.raises(ValueError, match="bogus"):
            _run(args_factory, mesh_shape={"sp": 4}, sp_strategy="bogus")

    def test_pipeline(self, args_factory):
        seq = _dense_baseline(args_factory, num_layers=4)
        trainer, pp = _run(args_factory, num_layers=4, mesh_shape={"pp": 4})
        assert trainer.mode == "pipeline"
        # trajectory tolerance (loose: ~16 sgd steps at lr .1 amplify
        # fp reassociation chaotically); exact forward/grad equivalence
        # is test_pipeline's department. Both must have actually
        # learned from the ~4.5 random-init loss.
        np.testing.assert_allclose(pp["train_loss"], seq["train_loss"], rtol=0.15)
        # learned-bar: well off the ~4.6 random-init loss (T=16 data since
        # seq_len drives the stand-in length; 2 epochs land ~1.7)
        assert pp["train_loss"] < 2.5 and seq["train_loss"] < 2.5

    def test_dp_sp_composition(self, args_factory):
        """Batch over dp x tokens over sp: each dp replica runs its own
        ring collectives; numerics track the single-device program."""
        dense = _dense_baseline(args_factory)
        trainer, dpsp = _run(args_factory, mesh_shape={"dp": 2, "sp": 4})
        assert trainer.mode == "sequence"
        x = trainer._place_data(trainer.dataset.train_data_global).x
        # data genuinely sharded on both axes
        assert x.addressable_shards[0].data.shape[1] == x.shape[1] // 2
        assert x.addressable_shards[0].data.shape[2] == x.shape[2] // 4
        np.testing.assert_allclose(
            dpsp["train_loss"], dense["train_loss"], rtol=5e-2
        )
        np.testing.assert_allclose(
            dpsp["test_acc"], dense["test_acc"], atol=0.05
        )

    def test_dp_pp_composition(self, args_factory):
        """GPipe microbatching inside each dp replica."""
        seq = _dense_baseline(args_factory, num_layers=4)
        trainer, dppp = _run(
            args_factory, num_layers=4, mesh_shape={"dp": 2, "pp": 4}
        )
        assert trainer.mode == "pipeline"
        x = trainer._place_data(trainer.dataset.train_data_global).x
        assert x.addressable_shards[0].data.shape[1] == x.shape[1] // 2
        np.testing.assert_allclose(
            dppp["train_loss"], seq["train_loss"], rtol=0.15
        )
        assert dppp["train_loss"] < 2.5 and seq["train_loss"] < 2.5

    # -- cross-regime equivalence (VERDICT r4 next #7) -----------------
    # MULTICHIP_r04 showed dp x sp and dp x pp landing identical losses;
    # this pins that as an oracle: same seed + same data => same loss
    # across mesh regimes. ONE optimizer step (1 batch, 1 epoch) so fp
    # reassociation cannot compound and the tolerance stays tight —
    # a collective-layout regression (wrong psum axis, dropped shard,
    # misrouted microbatch) moves the loss far beyond 1e-3.

    _ONE_STEP = {}

    def _one_step_loss(self, args_factory, mesh_shape):
        key = tuple(sorted(mesh_shape.items()))
        if key not in self._ONE_STEP:
            _, stats = _run(
                args_factory,
                num_layers=4,
                epochs=1,
                synthetic_train_size=8,
                batch_size=8,
                mesh_shape=mesh_shape,
            )
            self._ONE_STEP[key] = stats["train_loss"]
        return self._ONE_STEP[key]

    @pytest.mark.parametrize(
        "mesh_shape",
        [{"dp": 2, "sp": 4}, {"dp": 2, "pp": 4}],
        ids=["dpxsp", "dpxpp"],
    )
    def test_cross_regime_one_step_equivalence(self, args_factory, mesh_shape):
        anchor = self._one_step_loss(args_factory, {"dp": 8})
        loss = self._one_step_loss(args_factory, mesh_shape)
        np.testing.assert_allclose(loss, anchor, rtol=1e-3)

    def test_pipeline_layer_mismatch_rejected(self, args_factory):
        with pytest.raises(ValueError, match="num_layers"):
            _run(args_factory, num_layers=3, mesh_shape={"pp": 4})

    def test_sp_needs_pluggable_attention(self, args_factory):
        with pytest.raises(ValueError, match="attention"):
            _run(
                args_factory, model="rnn", dataset="shakespeare",
                mesh_shape={"sp": 8},
            )

    def test_grad_accumulation_matches_unchunked(self, args_factory):
        """Count-weighted accumulation is the exact full-batch masked
        mean — only fp reassociation separates the trajectories."""
        whole = _dense_baseline(args_factory, epochs=1)
        _, chunked = _run(
            args_factory, mesh_shape={"dp": 1}, epochs=1, grad_accum_steps=4
        )
        np.testing.assert_allclose(
            chunked["train_loss"], whole["train_loss"], rtol=1e-3
        )

    def test_grad_accumulation_divisibility(self, args_factory):
        with pytest.raises(ValueError, match="grad_accum_steps"):
            _run(args_factory, mesh_shape={"dp": 1}, epochs=1,
                 grad_accum_steps=3)

    def test_cosine_lr_schedule_shapes_training(self, args_factory):
        """A decaying schedule must genuinely reach the optimizer."""
        from fedml_tpu.core.optimizers import resolve_learning_rate

        a = _args(args_factory, lr_schedule="cosine", lr_total_steps=16,
                  warmup_steps=4)
        sched = resolve_learning_rate(a)
        assert callable(sched)
        assert float(sched(0)) < 1e-6  # warmup starts at ~0
        assert abs(float(sched(4)) - 0.1) < 1e-6  # peak at warmup end
        assert float(sched(16)) < 1e-3  # decayed away
        with pytest.raises(ValueError, match="lr_total_steps"):
            resolve_learning_rate(_args(args_factory, lr_schedule="cosine"))
        with pytest.raises(ValueError, match="lr_schedule"):
            resolve_learning_rate(_args(args_factory, lr_schedule="bogus"))

        const = _dense_baseline(args_factory, epochs=1)
        _, cos = _run(
            args_factory, mesh_shape={"dp": 1}, epochs=1,
            lr_schedule="cosine", lr_total_steps=16, warmup_steps=4,
        )
        assert abs(cos["train_loss"] - const["train_loss"]) > 1e-6

    def test_shuffle_changes_trajectory_deterministically(self, args_factory):
        """args.shuffle reorders examples per epoch (epoch-indexed rng:
        reruns and resumes replay identical permutations)."""
        shuffled = _dense_baseline(args_factory, epochs=1)  # shuffle=True default
        _, again = _run(args_factory, mesh_shape={"dp": 1}, epochs=1)
        np.testing.assert_allclose(
            again["train_loss"], shuffled["train_loss"], rtol=1e-6
        )  # deterministic across reruns
        _, ordered = _run(
            args_factory, mesh_shape={"dp": 1}, epochs=1, shuffle=False
        )
        assert abs(ordered["train_loss"] - shuffled["train_loss"]) > 1e-6

    def test_moe_aux_loss_shapes_training(self, args_factory):
        """The Switch aux loss must actually reach the objective: the
        same MoE run with aux weight 0 vs 1.0 lands on different
        params (a silently-dropped aux would make them identical)."""
        kw = dict(model="moe_transformer", num_experts=4,
                  mesh_shape={"dp": 1}, epochs=1)
        _, off = _run(args_factory, moe_aux_weight=0.0, **kw)
        _, on = _run(args_factory, moe_aux_weight=1.0, **kw)
        assert abs(on["train_loss"] - off["train_loss"]) > 1e-6

    def test_bf16(self, args_factory):
        _, stats = _run(args_factory, mesh_shape={"dp": 8}, dtype="bfloat16")
        assert np.isfinite(stats["train_loss"])
        assert stats["tokens_per_sec"] > 0


@pytest.mark.slow
class TestCheckpointResume:
    def test_resume_matches_uninterrupted(self, args_factory, tmp_path):
        """Train 2 epochs with checkpoints, 'crash', construct a fresh
        trainer pointed at the same dir with epochs=4: final loss must
        match an uninterrupted 4-epoch run (same data order, no
        shuffle -> identical trajectory)."""
        ckpt = str(tmp_path / "ckpt")
        _, full = _run(args_factory, epochs=4, mesh_shape={"dp": 8})
        _run(
            args_factory, epochs=2, mesh_shape={"dp": 8},
            checkpoint_dir=ckpt, checkpoint_freq=1,
        )
        _, resumed = _run(
            args_factory, epochs=4, mesh_shape={"dp": 8},
            checkpoint_dir=ckpt, checkpoint_freq=1,
        )
        assert resumed["epoch"] == 3
        np.testing.assert_allclose(
            resumed["train_loss"], full["train_loss"], rtol=1e-5
        )

    def test_resume_with_stateful_optimizer(self, args_factory, tmp_path):
        """Adam's mu/nu are identically shaped — a positional restore
        would swap them silently; the name-based restore must not."""
        kw = dict(
            mesh_shape={"dp": 8}, client_optimizer="adam",
            learning_rate=0.01,
        )
        ckpt = str(tmp_path / "ckpt")
        _, full = _run(args_factory, epochs=4, **kw)
        _run(args_factory, epochs=2, checkpoint_dir=ckpt,
             checkpoint_freq=1, **kw)
        _, resumed = _run(args_factory, epochs=4, checkpoint_dir=ckpt,
                          checkpoint_freq=1, **kw)
        np.testing.assert_allclose(
            resumed["train_loss"], full["train_loss"], rtol=1e-5
        )

    def test_completed_run_does_not_retrain(self, args_factory, tmp_path):
        ckpt = str(tmp_path / "ckpt")
        _run(
            args_factory, epochs=2, mesh_shape={"dp": 8},
            checkpoint_dir=ckpt, checkpoint_freq=1,
        )
        _, again = _run(
            args_factory, epochs=2, mesh_shape={"dp": 8},
            checkpoint_dir=ckpt, checkpoint_freq=1,
        )
        assert "train_loss" not in again  # eval-only terminal path
        assert "test_acc" in again


@pytest.mark.slow
class TestOneLine:
    def test_run_distributed_entry(self, args_factory, monkeypatch):
        args = _args(args_factory, mesh_shape={"dp": 2})
        stats = fedml_tpu.run_distributed(args)
        assert "train_loss" in stats and "test_acc" in stats
