"""Multi-controller distributed platform: 2 OS processes x 4 virtual
CPU devices each run ONE dp=8 LM training as a single SPMD program
(tests/dist_mp_worker.py), and the result matches the same config on a
single 8-device controller.

This is the multi-host seam of the distributed trainer — data and
params are placed with parallel.mesh.place_global, so each process
materializes only its addressable shards.
"""

import os
import socket
import subprocess
import sys

import jax
import numpy as np

import fedml_tpu
from fedml_tpu import models
from fedml_tpu.data import load
from fedml_tpu.distributed import DistributedTrainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "dist_mp_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestMultiProcessDistributed:
    def test_two_process_dp_matches_single_controller(
        self, tmp_path, args_factory
    ):
        out = str(tmp_path / "dist_params.npz")
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + ":" + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4"
        )
        port = _free_port()
        procs = [
            subprocess.Popen(
                [
                    sys.executable, WORKER,
                    "--proc_rank", str(r),
                    "--n_proc", "2",
                    "--coordinator", f"127.0.0.1:{port}",
                    "--out", out,
                ],
                env=env,
            )
            for r in (0, 1)
        ]
        try:
            rcs = [p.wait(timeout=600) for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        assert rcs == [0, 0], f"dist worker exit codes {rcs}"
        assert os.path.exists(out)

        args = args_factory(
            training_type="distributed",
            dataset="shakespeare",
            synthetic_train_size=64,
            synthetic_test_size=16,
            model="transformer",
            seq_len=16,
            num_layers=2,
            num_heads=4,
            embed_dim=32,
            client_num_in_total=1,
            client_num_per_round=1,
            comm_round=1,
            epochs=2,
            batch_size=8,
            learning_rate=0.1,
            frequency_of_the_test=1,
            mesh_shape={"dp": 8},
            run_id="dist_mp_ref",
        )
        args = fedml_tpu.init(args)
        ds = load(args)
        model = models.create(args, ds.class_num)
        trainer = DistributedTrainer(args, None, ds, model)
        stats = trainer.run()

        got = np.load(out)
        want = jax.tree.leaves(trainer.params)
        # trajectory tolerances (same rationale as test_distributed):
        # cross-process collectives reassociate reductions differently
        # than the single-controller program, compounding over epochs
        np.testing.assert_allclose(
            float(got["train_loss"]), stats["train_loss"], rtol=2e-2,
            err_msg="2-process train_loss != single-controller",
        )
        assert float(got["train_loss"]) < 1.5  # actually learned
        for i, w in enumerate(want):
            np.testing.assert_allclose(
                got[f"p{i}"], np.asarray(w), atol=2e-2,
                err_msg=f"leaf {i}: 2-process distributed != single-controller",
            )
