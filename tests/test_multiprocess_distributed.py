"""Multi-controller distributed platform: 2 OS processes x 4 virtual
CPU devices each run ONE dp=8 LM training as a single SPMD program
(tests/dist_mp_worker.py), and the result matches the same config on a
single 8-device controller.

This is the multi-host seam of the distributed trainer — data and
params are placed with parallel.mesh.place_global, so each process
materializes only its addressable shards.
"""

import os
import socket
import subprocess
import sys

import jax
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu import models
from fedml_tpu.data import load
from fedml_tpu.distributed import DistributedTrainer

# full tier only: multiprocess collectives are unsupported by this
# jaxlib's CPU backend, and the worlds are well over the 4s fast-gate
# budget
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "dist_mp_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + ":" + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    )
    return env


def _spawn_pair(extra_args):
    port = _free_port()
    return [
        subprocess.Popen(
            [
                sys.executable, WORKER,
                "--proc_rank", str(r),
                "--n_proc", "2",
                "--coordinator", f"127.0.0.1:{port}",
            ]
            + extra_args,
            env=_worker_env(),
        )
        for r in (0, 1)
    ]


def _wait_pair(procs):
    try:
        return [p.wait(timeout=600) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


class TestMultiProcessDistributed:
    def test_two_process_dp_matches_single_controller(
        self, tmp_path, args_factory
    ):
        out = str(tmp_path / "dist_params.npz")
        rcs = _wait_pair(_spawn_pair(["--out", out]))
        assert rcs == [0, 0], f"dist worker exit codes {rcs}"
        assert os.path.exists(out)

        args = args_factory(
            training_type="distributed",
            dataset="shakespeare",
            synthetic_train_size=64,
            synthetic_test_size=16,
            model="transformer",
            seq_len=16,
            num_layers=2,
            num_heads=4,
            embed_dim=32,
            client_num_in_total=1,
            client_num_per_round=1,
            comm_round=1,
            epochs=2,
            batch_size=8,
            learning_rate=0.1,
            frequency_of_the_test=1,
            mesh_shape={"dp": 8},
            run_id="dist_mp_ref",
        )
        args = fedml_tpu.init(args)
        ds = load(args)
        model = models.create(args, ds.class_num)
        trainer = DistributedTrainer(args, None, ds, model)
        stats = trainer.run()

        got = np.load(out)
        want = jax.tree.leaves(trainer.params)
        # trajectory tolerances (same rationale as test_distributed):
        # cross-process collectives reassociate reductions differently
        # than the single-controller program, compounding over epochs
        np.testing.assert_allclose(
            float(got["train_loss"]), stats["train_loss"], rtol=2e-2,
            err_msg="2-process train_loss != single-controller",
        )
        assert float(got["train_loss"]) < 2.5  # well off ~4.6 random init
        for i, w in enumerate(want):
            np.testing.assert_allclose(
                got[f"p{i}"], np.asarray(w), atol=2e-2,
                err_msg=f"leaf {i}: 2-process distributed != single-controller",
            )

    def test_kill_midrun_and_resume_matches_uninterrupted(
        self, tmp_path, args_factory
    ):
        """Multi-controller fault tolerance (sharded orbax checkpoint):
        both workers are hard-killed after the epoch-1 checkpoint of a
        4-epoch run; a relaunch resumes at epoch 2 and finishes with
        the same trajectory as an uninterrupted run (shuffle streams
        are epoch-indexed, so the resumed permutations replay exactly).
        The uninterrupted reference is the single-controller program —
        the first test already pins 2-process == single-controller."""
        ckpt = str(tmp_path / "mp_ckpt")
        out_resumed = str(tmp_path / "resumed.npz")

        # crash run: die right after the epoch-1 checkpoint
        rcs = _wait_pair(
            _spawn_pair(
                ["--epochs", "4", "--ckpt_dir", ckpt,
                 "--die_after_epoch", "1"]
            )
        )
        assert rcs == [3, 3], f"crash run exit codes {rcs}"

        # relaunch: must resume at epoch 2 and complete
        rcs = _wait_pair(
            _spawn_pair(
                ["--epochs", "4", "--ckpt_dir", ckpt, "--out", out_resumed]
            )
        )
        assert rcs == [0, 0], f"resumed run exit codes {rcs}"

        # uninterrupted single-controller reference (same config)
        args = args_factory(
            training_type="distributed",
            dataset="shakespeare",
            synthetic_train_size=64,
            synthetic_test_size=16,
            model="transformer",
            seq_len=16,
            num_layers=2,
            num_heads=4,
            embed_dim=32,
            client_num_in_total=1,
            client_num_per_round=1,
            comm_round=1,
            epochs=4,
            batch_size=8,
            learning_rate=0.1,
            frequency_of_the_test=1,
            mesh_shape={"dp": 8},
            run_id="dist_mp_resume_ref",
        )
        args = fedml_tpu.init(args)
        ds = load(args)
        model = models.create(args, ds.class_num)
        trainer = DistributedTrainer(args, None, ds, model)
        stats = trainer.run()

        resumed = np.load(out_resumed)
        assert float(resumed["start_epoch"]) == 2.0  # genuinely resumed
        np.testing.assert_allclose(
            float(resumed["train_loss"]), stats["train_loss"], rtol=2e-2,
        )
        want = jax.tree.leaves(trainer.params)
        for i, w in enumerate(want):
            # 4 epochs of cross-process vs single-controller reduction
            # reassociation drift ~3e-2 at convergence (loss ~0.024);
            # 6e-2 is 2x the observed max
            np.testing.assert_allclose(
                resumed[f"p{i}"], np.asarray(w), atol=6e-2,
                err_msg=f"leaf {i}: resumed != uninterrupted",
            )
